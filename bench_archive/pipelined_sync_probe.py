"""One-off r5: per-batch pipelined resolve cost on the live tunnel, with
eager D2H issue in place.  Emulates the e2e resolver pattern: submit batch,
advance chain, sync verdicts later — N batches in flight."""
import asyncio
import time

import jax
import numpy as np

jax.config.update("jax_enable_x64", True)

from foundationdb_tpu.bench.workload import MakoWorkload
from foundationdb_tpu.ops.backends import make_conflict_backend, resolve_begin
from foundationdb_tpu.runtime import Knobs

dev = jax.devices()[0]
print("device:", dev)

knobs = Knobs().override(
    RESOLVER_CONFLICT_BACKEND="tpu", RESOLVER_BATCH_TXNS=64,
    RESOLVER_RANGES_PER_TXN=2, CONFLICT_RING_CAPACITY=1 << 14,
    KEY_ENCODE_BYTES=32, CONFLICT_WINDOW_SLOTS=1024)

wl = MakoWorkload(n_keys=100_000, seed=42)
batches, versions = wl.make_batches(256, 64)
backend = make_conflict_backend(knobs, device=dev)

# warm compile
for txns, v in zip(batches[:4], versions[:4]):
    backend.resolve(txns, v)


async def pipelined(bs, vs, inflight):
    t0 = time.perf_counter()
    pending = []
    out = []
    for txns, v in zip(bs, vs):
        if len(pending) >= inflight:
            out.append(await pending.pop(0))
        pending.append(resolve_begin(backend, txns, v))
    for p in pending:
        out.append(await p)
    return time.perf_counter() - t0, out

for inflight in (4, 16, 64):
    el, out = asyncio.run(pipelined(batches[4:], versions[4:], inflight))
    n = len(batches) - 4
    print(f"inflight={inflight}: {el:.3f}s for {n} batches -> "
          f"{el/n*1e3:.2f}ms/batch, {n*64/el:.0f} txns/s")
