"""Phase-level timing of the grouped resolver path on the live device.

Where does the grouped bench's time go?  Encode, submit (dispatch), and
sync phases measured separately, plus overlap behavior of K=64 groups.
"""

from __future__ import annotations

import time

import numpy as np


def main():
    import jax
    jax.config.update("jax_enable_x64", True)

    dev = jax.devices()[0]

    from foundationdb_tpu.bench.workload import MakoWorkload
    from foundationdb_tpu.ops.backends import make_conflict_backend
    from foundationdb_tpu.runtime import Knobs

    B, GROUP = 64, 64
    NB = 1024
    wl = MakoWorkload(n_keys=1_000_000, seed=42)
    batches, versions = wl.make_batches(NB, B)

    knobs = Knobs().override(
        RESOLVER_BATCH_TXNS=B, RESOLVER_RANGES_PER_TXN=4,
        CONFLICT_RING_CAPACITY=1 << 19, KEY_ENCODE_BYTES=32,
        RESOLVER_CONFLICT_BACKEND="tpu")
    backend = make_conflict_backend(knobs, device=dev)

    # warm: compile K=1 + K=64
    backend.resolve(batches[0], versions[0] - 20_000_000)
    ebs0 = backend._encode_chunks([t for b in batches[:GROUP] for t in b])
    backend.cs.resolve_group_submit(ebs0, [versions[0] - 19_000_000] * len(ebs0))

    # fresh cs state
    backend = make_conflict_backend(knobs, device=dev)
    backend.resolve(batches[0], versions[0] - 20_000_000)  # K=1 compile for new cs... cached

    # phase 1: encode everything
    t0 = time.perf_counter()
    groups = []
    for start in range(0, NB, GROUP):
        ebs = []
        for b in batches[start:start + GROUP]:
            ebs.extend(backend._encode_chunks(b))
        groups.append((ebs, list(versions[start:start + GROUP])))
    t_enc = time.perf_counter() - t0
    print(f"encode {NB} batches: {t_enc*1e3:8.1f}ms ({t_enc/NB*1e3:.3f} ms/batch)")

    # phase 2: submit all groups (async dispatch)
    t0 = time.perf_counter()
    pend = [backend.cs.resolve_group_submit(ebs, cvs) for ebs, cvs in groups]
    t_sub = time.perf_counter() - t0
    print(f"submit {len(groups)} groups:  {t_sub*1e3:8.1f}ms")

    # phase 3: sync all verdicts
    t0 = time.perf_counter()
    hosts = [np.asarray(v) for v in pend]
    t_sync = time.perf_counter() - t0
    print(f"sync  {len(groups)} groups:  {t_sync*1e3:8.1f}ms")
    total = t_enc + t_sub + t_sync
    txns = NB * B
    print(f"total: {total*1e3:.1f}ms -> {txns/total/1000:.1f}k txns/s")

    # again (steady state, no compile effects)
    t0 = time.perf_counter()
    pend = [backend.cs.resolve_group_submit(ebs, cvs) for ebs, cvs in groups]
    hosts = [np.asarray(v) for v in pend]
    total = time.perf_counter() - t0
    print(f"round 2 submit+sync: {total*1e3:.1f}ms -> {txns/total/1000:.1f}k txns/s "
          f"(encode excluded)")

    # sync one group at a time right after its submit (serialized style)
    t0 = time.perf_counter()
    for ebs, cvs in groups[:4]:
        v = backend.cs.resolve_group_submit(ebs, cvs)
        np.asarray(v)
    t = time.perf_counter() - t0
    print(f"serialized 4 groups: {t*1e3:.1f}ms ({t/4*1e3:.1f} ms/group)")


if __name__ == "__main__":
    main()
