"""Round 4: actual cj.resolve_step is still ~67ms while an inline copy of
the same math is 0.18ms.  Fresh process per mode:

  r1  cj.resolve_step, inputs pre-device, cv created once
  r2  cj.resolve_step, jnp.asarray + jnp.int64 per call (backend style)
  r3  jax.jit(cj.resolve_core) no donate, pre-device inputs
  r4  inline copy of resolve_core body (control, expect fast)
  r5  r3 but module int8 constants replaced by inline ones via monkeypatch
"""

from __future__ import annotations

import subprocess
import sys
import time

import numpy as np

MODES = ["r1", "r2", "r3", "r4", "r5"]


def run_mode(mode: str) -> None:
    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    dev = jax.devices()[0]

    from foundationdb_tpu.bench.workload import MakoWorkload
    from foundationdb_tpu.ops import conflict_jax as cj
    from foundationdb_tpu.ops.batch import encode_batch, TxnRequest
    from foundationdb_tpu.ops.backends import coalesce_ranges

    B, R, WIDTH, CAP, WIN = 64, 4, 32, 1 << 16, 4096
    wl = MakoWorkload(n_keys=1_000_000, seed=42)
    batches, versions = wl.make_batches(4, B)
    txns = [TxnRequest(coalesce_ranges(t.read_ranges, R),
                       coalesce_ranges(t.write_ranges, R), t.read_snapshot)
            for t in batches[0]]
    eb = encode_batch(txns, B, R, WIDTH)

    if mode == "r5":
        cj.COMMITTED, cj.CONFLICT, cj.TOO_OLD = (
            jnp.int8(0), jnp.int8(1), jnp.int8(2))

    state = jax.device_put(cj.init_state(CAP, WIDTH, 0), dev)
    rb = jax.device_put(jnp.asarray(eb.read_begin), dev)
    re_ = jax.device_put(jnp.asarray(eb.read_end), dev)
    wb = jax.device_put(jnp.asarray(eb.write_begin), dev)
    we = jax.device_put(jnp.asarray(eb.write_end), dev)
    sn = jax.device_put(jnp.asarray(eb.read_snapshot), dev)
    cv = jnp.int64(versions[0])

    ts = []
    if mode in ("r1", "r2"):
        st = state
        for i in range(6):
            t0 = time.perf_counter()
            if mode == "r1":
                st, v = cj.resolve_step(st, rb, re_, wb, we, sn, cv,
                                        width=WIDTH, window=WIN)
            else:
                e = eb
                st, v = cj.resolve_step(
                    st, jnp.asarray(e.read_begin), jnp.asarray(e.read_end),
                    jnp.asarray(e.write_begin), jnp.asarray(e.write_end),
                    jnp.asarray(e.read_snapshot), jnp.int64(versions[i % 4]),
                    width=WIDTH, window=WIN)
            v.block_until_ready()
            ts.append(time.perf_counter() - t0)
    elif mode in ("r3", "r5"):
        j = jax.jit(cj.resolve_core, static_argnames=("width", "window"))
        st = state
        for i in range(6):
            t0 = time.perf_counter()
            st, v = j(st, rb, re_, wb, we, sn, cv, width=WIDTH, window=WIN)
            v.block_until_ready()
            ts.append(time.perf_counter() - t0)
    else:  # r4 inline control
        from jax import lax

        def core(state, rb, re_, wb, we, sn, cv):
            C = state.hver.shape[0] - 1
            Bl, Rl, L = rb.shape
            hb, he, hver = state.hb[:C], state.he[:C], state.hver[:C]
            too_old = sn < state.floor
            valid = sn >= 0
            idx = (state.ptr - WIN + jnp.arange(WIN)) % C
            v_edge = state.hver[(state.ptr - WIN - 1) % C]
            fast_ok = jnp.all(~valid | too_old | (sn >= v_edge))
            hist = lax.cond(
                fast_ok,
                lambda _: cj._hist_check(rb, re_, hb[idx], he[idx], hver[idx], sn, WIDTH),
                lambda _: cj._hist_check(rb, re_, hb, he, hver, sn, WIDTH), None)
            m = cj._overlap(rb[:, :, None, None, :], re_[:, :, None, None, :],
                            wb[None, None, :, :, :], we[None, None, :, :, :], WIDTH)
            M = m.any(axis=(1, 3)) & ~jnp.eye(Bl, dtype=bool)

            def body(committed, i):
                conf = hist[i] | (committed & M[i]).any()
                return committed.at[i].set(valid[i] & ~too_old[i] & ~conf), conf
            committed, conf = lax.scan(body, jnp.zeros(Bl, bool), jnp.arange(Bl))
            verdicts = jnp.where(~valid, cj.COMMITTED,
                                 jnp.where(too_old, cj.TOO_OLD,
                                           jnp.where(conf, cj.CONFLICT, cj.COMMITTED)))
            valid_w = wb[..., -1] != jnp.uint32(0xFFFFFFFF)
            ins = (committed[:, None] & valid_w).reshape(-1)
            k = jnp.cumsum(ins) - ins
            pos = jnp.where(ins, (state.ptr + k) % C, C).astype(jnp.int32)
            old = jnp.where(ins, state.hver[pos], jnp.int64(-1))
            floor2 = jnp.maximum(state.floor, jnp.max(old))
            wbf = jnp.where(ins[:, None], wb.reshape(Bl * Rl, L), jnp.uint32(0xFFFFFFFF))
            wef = jnp.where(ins[:, None], we.reshape(Bl * Rl, L), jnp.uint32(0xFFFFFFFF))
            hb2 = state.hb.at[pos].set(wbf)
            he2 = state.he.at[pos].set(wef)
            hver2 = state.hver.at[pos].set(jnp.where(ins, cv, jnp.int64(-1)))
            ptr2 = ((state.ptr + jnp.sum(ins)) % C).astype(jnp.int32)
            return cj.ConflictState(hb2, he2, hver2, ptr2, floor2), verdicts

        j = jax.jit(core)
        st = state
        for i in range(6):
            t0 = time.perf_counter()
            st, v = j(st, rb, re_, wb, we, sn, cv)
            v.block_until_ready()
            ts.append(time.perf_counter() - t0)

    one = jax.device_put(jnp.float32(1.0), dev)
    jt = jax.jit(lambda x: x + 1)
    jt(one).block_until_ready()
    tt = []
    for _ in range(5):
        t0 = time.perf_counter()
        jt(one).block_until_ready()
        tt.append(time.perf_counter() - t0)

    print(f"MODE {mode:4s} first={ts[0]*1e3:9.1f}ms med_rest={np.median(ts[1:])*1e3:8.3f}ms "
          f"trivial_after={np.median(tt)*1e3:8.3f}ms", flush=True)


def main():
    if sys.argv[1] == "--all":
        for m in MODES:
            r = subprocess.run([sys.executable, "-m",
                                "foundationdb_tpu.bench.profile_poison4", m],
                               capture_output=True, text=True, timeout=300)
            out = [l for l in r.stdout.splitlines() if l.startswith("MODE")]
            print(out[0] if out else f"MODE {m}: FAILED\n{r.stderr[-600:]}",
                  flush=True)
    else:
        run_mode(sys.argv[1])


if __name__ == "__main__":
    main()
