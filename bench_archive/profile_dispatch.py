"""Degraded-mode dispatch anatomy: what blocks inside resolve_group_submit?

After poisoning the session, time separately:
  1. np.stack host-side of a 64-batch group
  2. jnp.asarray (h2d) of the stacked arrays (~2.4MB)
  3. pure dispatch of resolve_many on pre-device inputs (no block)
  4. dispatch + block
  5. back-to-back dispatches (state chained) without sync
"""

from __future__ import annotations

import time

import numpy as np


def main():
    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    dev = jax.devices()[0]

    from foundationdb_tpu.bench.workload import MakoWorkload
    from foundationdb_tpu.ops import conflict_jax as cj
    from foundationdb_tpu.ops.batch import encode_batch
    from foundationdb_tpu.ops.backends import coalesce_ranges
    from foundationdb_tpu.ops.batch import TxnRequest

    B, R, WIDTH, K = 64, 4, 32, 64
    CAP = 1 << 19
    wl = MakoWorkload(n_keys=1_000_000, seed=42)
    batches, versions = wl.make_batches(K, B)

    def enc(txns):
        txns = [t if len(t.read_ranges) <= R and len(t.write_ranges) <= R
                else TxnRequest(coalesce_ranges(t.read_ranges, R),
                                coalesce_ranges(t.write_ranges, R),
                                t.read_snapshot) for t in txns]
        return encode_batch(txns, B, R, WIDTH)

    ebs = [enc(b) for b in batches]

    # poison
    one = jax.device_put(jnp.float32(1.0), dev)
    jt = jax.jit(lambda x: x + 1)
    _ = np.asarray(jt(one))
    t0 = time.perf_counter()
    jt(one).block_until_ready()
    print(f"RTT: {(time.perf_counter()-t0)*1e3:.1f}ms")

    # 1. host stack
    t0 = time.perf_counter()
    rb = np.stack([e.read_begin for e in ebs])
    re_ = np.stack([e.read_end for e in ebs])
    wb = np.stack([e.write_begin for e in ebs])
    we = np.stack([e.write_end for e in ebs])
    sn = np.stack([e.read_snapshot for e in ebs])
    cvs = np.array(versions, dtype=np.int64)
    print(f"1. np.stack group:        {(time.perf_counter()-t0)*1e3:8.1f}ms "
          f"({(rb.nbytes*4+sn.nbytes)/1e6:.1f}MB)")

    # 2. h2d
    t0 = time.perf_counter()
    drb = jax.device_put(rb, dev); dre = jax.device_put(re_, dev)
    dwb = jax.device_put(wb, dev); dwe = jax.device_put(we, dev)
    dsn = jax.device_put(sn, dev); dcv = jax.device_put(cvs, dev)
    jax.block_until_ready((drb, dre, dwb, dwe, dsn, dcv))
    print(f"2. h2d group (+sync):     {(time.perf_counter()-t0)*1e3:8.1f}ms")

    t0 = time.perf_counter()
    drb2 = jax.device_put(rb, dev)
    print(f"2b. h2d one array async:  {(time.perf_counter()-t0)*1e3:8.1f}ms")
    jax.block_until_ready(drb2)

    # 3. pure dispatch no block
    st = jax.device_put(cj.init_state(CAP, WIDTH, 0), dev)
    st, v = cj.resolve_many(st, drb, dre, dwb, dwe, dsn, dcv,
                            width=WIDTH, window=4096)
    v.block_until_ready()   # compile done
    t0 = time.perf_counter()
    st, v = cj.resolve_many(st, drb, dre, dwb, dwe, dsn, dcv,
                            width=WIDTH, window=4096)
    print(f"3. dispatch (no block):   {(time.perf_counter()-t0)*1e3:8.1f}ms")
    t0 = time.perf_counter()
    v.block_until_ready()
    print(f"4. then block:            {(time.perf_counter()-t0)*1e3:8.1f}ms")

    # 5. chained dispatches without sync
    t0 = time.perf_counter()
    vs = []
    for _ in range(4):
        st, v = cj.resolve_many(st, drb, dre, dwb, dwe, dsn, dcv,
                                width=WIDTH, window=4096)
        vs.append(v)
    t_disp = time.perf_counter() - t0
    t0 = time.perf_counter()
    jax.block_until_ready(vs)
    print(f"5. 4 chained dispatches:  {t_disp*1e3:8.1f}ms, block all: "
          f"{(time.perf_counter()-t0)*1e3:8.1f}ms")

    # 6. jnp.asarray-from-numpy inside the dispatch (backend style)
    t0 = time.perf_counter()
    st, v = cj.resolve_many(st, jnp.asarray(rb), jnp.asarray(re_),
                            jnp.asarray(wb), jnp.asarray(we),
                            jnp.asarray(sn), jnp.asarray(cvs),
                            width=WIDTH, window=4096)
    t_disp = time.perf_counter() - t0
    t0 = time.perf_counter()
    v.block_until_ready()
    print(f"6. asarray+dispatch:      {t_disp*1e3:8.1f}ms, block: "
          f"{(time.perf_counter()-t0)*1e3:8.1f}ms")


if __name__ == "__main__":
    main()
