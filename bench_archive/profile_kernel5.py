"""Decompose resolve_many device time: which kernel stage dominates?

Variants of the fused K-batch scan with stages knocked out, each timed on
the live device.  Stages: (1) window history check, (2) intra-batch
overlap matrix, (3) scalar bitmask commit chain, (4) slab append.
"""

from __future__ import annotations

import functools
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    jax.config.update("jax_enable_x64", True)
    dev = jax.devices()[0]
    print("device:", dev)

    from foundationdb_tpu.ops import conflict_jax as cj

    K, B, R, L = 64, 64, 2, 9
    CAP = 1 << 14
    WINDOW = 4096
    width = 32
    rng = np.random.default_rng(0)

    state = jax.device_put(cj.init_state(CAP, width), dev)
    rb = rng.integers(0, 2**32, (K, B, R, L), dtype=np.uint32)
    re = rb.copy()
    wb = rb.copy()
    we = rb.copy()
    sn = np.arange(K * B, dtype=np.int64).reshape(K, B)
    cv = np.arange(1, K + 1, dtype=np.int64) * 100

    def run_many(core_fn, st, tag):
        fn = jax.jit(functools.partial(core_fn, width=width, window=WINDOW))

        def scan_fn(s, x):
            rb_, re_, wb_, we_, sn_, cv_ = x
            return core_fn(s, rb_, re_, wb_, we_, sn_, cv_,
                           width=width, window=WINDOW)

        many = jax.jit(lambda s, *xs: lax.scan(scan_fn, s, xs))
        args = [jax.device_put(a, dev) for a in (rb, re, wb, we, sn, cv)]
        out = many(st, *args)
        jax.block_until_ready(out)
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            out = many(st, *args)
            jax.block_until_ready(out)
            ts.append(time.perf_counter() - t0)
        print(f"{tag:28s} {min(ts)*1e3:7.1f} ms/group  "
              f"({min(ts)/K*1e3:5.2f} ms/batch)")
        return min(ts)

    full = run_many(cj.resolve_core, state, "full")

    # knockout variants
    def make_variant(no_hist=False, no_intra=False, no_chain=False,
                     no_slab=False):
        def core(st, read_begin, read_end, write_begin, write_end, snap,
                 commit_version, *, width, window):
            C = st.hver.shape[0] // 2
            B_, R_, L_ = read_begin.shape
            S_ = B_ * R_
            i32 = jnp.int32
            too_old = snap < st.floor
            valid = snap >= 0
            if no_hist:
                hist_conflict = jnp.zeros(B_, bool)
            else:
                start = ((st.ptr - window) % C).astype(i32)
                hbW = lax.dynamic_slice(st.hb, (i32(0), start), (L_, window))
                heW = lax.dynamic_slice(st.he, (i32(0), start), (L_, window))
                hvW = lax.dynamic_slice(st.hver, (start,), (window,))
                hist_conflict = cj._hist_check_T(read_begin, read_end, hbW,
                                                 heW, hvW, snap, width)
            if no_intra:
                M = jnp.zeros((B_, B_), bool)
            else:
                m = cj._overlap(read_begin[:, :, None, None, :],
                                read_end[:, :, None, None, :],
                                write_begin[None, None, :, :, :],
                                write_end[None, None, :, :, :], width)
                M = m.any(axis=(1, 3)) & ~jnp.eye(B_, dtype=bool)
            ok = valid & ~too_old
            if no_chain:
                conf_vec = hist_conflict | M.any(axis=1)
                committed = ok & ~conf_vec
            else:
                nw = (B_ + 31) // 32
                Bpad = nw * 32
                Mp = jnp.pad(M, ((0, 0), (0, Bpad - B_)))
                packed = jnp.sum(
                    Mp.reshape(B_, nw, 32).astype(jnp.uint32)
                    << jnp.arange(32, dtype=jnp.uint32)[None, None, :],
                    axis=-1)
                cw = [jnp.uint32(0)] * nw
                confw = [jnp.uint32(0)] * nw
                for i in range(B_):
                    hit = cw[0] & packed[i, 0]
                    for w in range(1, nw):
                        hit = hit | (cw[w] & packed[i, w])
                    conf = hist_conflict[i] | (hit != jnp.uint32(0))
                    commit = ok[i] & ~conf
                    wi, bi = divmod(i, 32)
                    bit = jnp.uint32(1 << bi)
                    cw[wi] = cw[wi] | jnp.where(commit, bit, jnp.uint32(0))
                    confw[wi] = confw[wi] | jnp.where(conf, bit,
                                                      jnp.uint32(0))
                shifts = jnp.arange(32, dtype=jnp.uint32)
                conf_vec = jnp.concatenate(
                    [(w >> shifts) & jnp.uint32(1)
                     for w in confw])[:B_].astype(bool)
                committed = ok & ~conf_vec
            verdicts = jnp.where(~valid, cj.COMMITTED,
                                 jnp.where(too_old, cj.TOO_OLD,
                                           jnp.where(conf_vec, cj.CONFLICT,
                                                     cj.COMMITTED)))
            if no_slab:
                return st, verdicts
            is_pad = commit_version < 0
            p = st.ptr
            old_b = lax.dynamic_slice(st.hb, (i32(0), p), (L_, S_))
            old_e = lax.dynamic_slice(st.he, (i32(0), p), (L_, S_))
            old_v = lax.dynamic_slice(st.hver, (p,), (S_,))
            valid_w = write_begin[..., -1] != jnp.uint32(cj.SENTINEL_LANE)
            ins = (committed[:, None] & valid_w).reshape(S_)
            new_b = jnp.where(ins[:, None], write_begin.reshape(S_, L_),
                              jnp.uint32(cj.SENTINEL_LANE)).T
            new_e = jnp.where(ins[:, None], write_end.reshape(S_, L_),
                              jnp.uint32(cj.SENTINEL_LANE)).T
            new_v = jnp.broadcast_to(
                jnp.asarray(commit_version, st.hver.dtype), (S_,))
            slab_b = jnp.where(is_pad, old_b, new_b)
            slab_e = jnp.where(is_pad, old_e, new_e)
            slab_v = jnp.where(is_pad, old_v, new_v)
            floor2 = jnp.where(is_pad, st.floor,
                               jnp.maximum(st.floor, jnp.max(old_v)))
            hb2 = lax.dynamic_update_slice(st.hb, slab_b, (i32(0), p))
            hb2 = lax.dynamic_update_slice(hb2, slab_b, (i32(0), p + C))
            he2 = lax.dynamic_update_slice(st.he, slab_e, (i32(0), p))
            he2 = lax.dynamic_update_slice(he2, slab_e, (i32(0), p + C))
            hv2 = lax.dynamic_update_slice(st.hver, slab_v, (p,))
            hv2 = lax.dynamic_update_slice(hv2, slab_v, (p + C,))
            ptr2 = ((p + jnp.where(is_pad, 0, S_)) % C).astype(i32)
            return cj.ConflictState(hb2, he2, hv2, ptr2, floor2), verdicts
        return core

    run_many(make_variant(no_hist=True), state, "no window check")
    run_many(make_variant(no_intra=True), state, "no intra-batch matrix")
    run_many(make_variant(no_chain=True), state, "no scalar chain")
    run_many(make_variant(no_slab=True), state, "no slab append")
    run_many(make_variant(no_hist=True, no_intra=True, no_chain=True),
             state, "slab only")
    run_many(make_variant(no_hist=True, no_intra=True, no_chain=True,
                          no_slab=True), state, "empty (scan overhead)")


if __name__ == "__main__":
    main()
