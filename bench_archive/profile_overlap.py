"""Measure whether the axon tunnel overlaps work in degraded (post-readback)
mode — the real RTT is ~64ms; throughput depends on pipelining.

One fresh process.  First poison the session with a readback, then:
  1. 16 independent dispatches, one block_until_ready at end  -> dispatch pipelining
  2. compute 16 arrays, then 16 sequential np.asarray         -> serialized readbacks?
  3. same but copy_to_host_async all 16 first                 -> async readback overlap
  4. 16 np.asarray from 8 threads                             -> threaded overlap
  5. one kernel returning a CONCAT of the 16 results, 1 readback -> fusion amortization
  6. chained dependent dispatches (state threading) x16, 1 readback at end
"""

from __future__ import annotations

import concurrent.futures
import time

import numpy as np


def main():
    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    dev = jax.devices()[0]
    xs = [jax.device_put(jnp.ones((256, 256), jnp.float32) * i, dev)
          for i in range(16)]
    f = jax.jit(lambda x: (x @ x).sum(axis=0))
    f(xs[0]).block_until_ready()

    # poison: one readback
    t0 = time.perf_counter()
    _ = np.asarray(f(xs[0]))
    print(f"poison readback: {(time.perf_counter()-t0)*1e3:.1f}ms")
    one = jax.device_put(jnp.float32(1.0), dev)
    jt = jax.jit(lambda x: x + 1)
    jt(one).block_until_ready()
    t0 = time.perf_counter()
    jt(one).block_until_ready()
    print(f"trivial sync (degraded): {(time.perf_counter()-t0)*1e3:.1f}ms")

    # 1. independent dispatches, one sync
    t0 = time.perf_counter()
    outs = [f(x) for x in xs]
    outs[-1].block_until_ready()
    t1 = time.perf_counter()
    jax.block_until_ready(outs)
    print(f"1. 16 dispatch + 1 block: {(t1-t0)*1e3:.1f}ms; all block: "
          f"{(time.perf_counter()-t0)*1e3:.1f}ms")

    # 2. sequential readbacks
    outs = [f(x) for x in xs]
    jax.block_until_ready(outs)
    t0 = time.perf_counter()
    _ = [np.asarray(o) for o in outs]
    print(f"2. 16 sequential np.asarray: {(time.perf_counter()-t0)*1e3:.1f}ms")

    # 3. async copy then fetch
    outs = [f(x) for x in xs]
    jax.block_until_ready(outs)
    t0 = time.perf_counter()
    for o in outs:
        try:
            o.copy_to_host_async()
        except Exception as e:
            print("copy_to_host_async failed:", e)
            break
    _ = [np.asarray(o) for o in outs]
    print(f"3. async-copy + fetch 16:   {(time.perf_counter()-t0)*1e3:.1f}ms")

    # 4. threaded readbacks
    outs = [f(x) for x in xs]
    jax.block_until_ready(outs)
    t0 = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(8) as ex:
        list(ex.map(np.asarray, outs))
    print(f"4. threaded(8) 16 asarray:  {(time.perf_counter()-t0)*1e3:.1f}ms")

    # 5. fused output, one readback
    g = jax.jit(lambda *xs: jnp.stack([(x @ x).sum(axis=0) for x in xs]))
    g(*xs).block_until_ready()
    t0 = time.perf_counter()
    _ = np.asarray(g(*xs))
    print(f"5. fused 16->1 readback:    {(time.perf_counter()-t0)*1e3:.1f}ms")

    # 6. dependent chain, single sync
    h = jax.jit(lambda s, x: s + (x @ x).sum(axis=0))
    s = jax.device_put(jnp.zeros(256, jnp.float32), dev)
    h(s, xs[0]).block_until_ready()
    t0 = time.perf_counter()
    for x in xs:
        s = h(s, x)
    _ = np.asarray(s)
    print(f"6. 16-chain + 1 readback:   {(time.perf_counter()-t0)*1e3:.1f}ms")

    # 6b. repeat to see steady-state
    t0 = time.perf_counter()
    for x in xs:
        s = h(s, x)
    _ = np.asarray(s)
    print(f"6b. again:                  {(time.perf_counter()-t0)*1e3:.1f}ms")


if __name__ == "__main__":
    main()
