"""Round 3: confirm which verdict construction avoids the int8-in-scan poison.

Modes (fresh process each, CAP=65536, window=4096, donation — i.e. the real
resolve_step shape):
  v1 int32 verdict chain inside scan
  v2 scan returns conf bool; int8 where-chain vectorized OUTSIDE scan
  v3 like v2 but int32 outside
  v4 real resolve_core as shipped (control — expect poisoned)
  v5 v2-style patched resolve_core at full config incl. scatter+donate
"""

from __future__ import annotations

import subprocess
import sys
import time

import numpy as np

MODES = ["v1", "v2", "v3", "v4", "v5"]


def run_mode(mode: str) -> None:
    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from jax import lax

    dev = jax.devices()[0]

    from foundationdb_tpu.bench.workload import MakoWorkload
    from foundationdb_tpu.ops import conflict_jax as cj
    from foundationdb_tpu.ops.batch import encode_batch, TxnRequest
    from foundationdb_tpu.ops.backends import coalesce_ranges

    B, R, WIDTH, CAP, WIN = 64, 4, 32, 1 << 16, 4096
    wl = MakoWorkload(n_keys=1_000_000, seed=42)
    batches, versions = wl.make_batches(4, B)
    txns = [TxnRequest(coalesce_ranges(t.read_ranges, R),
                       coalesce_ranges(t.write_ranges, R), t.read_snapshot)
            for t in batches[0]]
    eb = encode_batch(txns, B, R, WIDTH)

    state = jax.device_put(cj.init_state(CAP, WIDTH, 0), dev)
    rb = jax.device_put(jnp.asarray(eb.read_begin), dev)
    re_ = jax.device_put(jnp.asarray(eb.read_end), dev)
    wb = jax.device_put(jnp.asarray(eb.write_begin), dev)
    we = jax.device_put(jnp.asarray(eb.write_end), dev)
    sn = jax.device_put(jnp.asarray(eb.read_snapshot), dev)
    cv = jnp.int64(versions[0])
    L = rb.shape[-1]

    def core_patched(state, rb, re_, wb, we, sn, cv, verdict_mode):
        C = state.hver.shape[0] - 1
        hb, he, hver = state.hb[:C], state.he[:C], state.hver[:C]
        too_old = sn < state.floor
        valid = sn >= 0
        idx = (state.ptr - WIN + jnp.arange(WIN)) % C
        v_edge = state.hver[(state.ptr - WIN - 1) % C]
        fast_ok = jnp.all(~valid | too_old | (sn >= v_edge))
        hist = lax.cond(
            fast_ok,
            lambda _: cj._hist_check(rb, re_, hb[idx], he[idx], hver[idx], sn, WIDTH),
            lambda _: cj._hist_check(rb, re_, hb, he, hver, sn, WIDTH), None)
        m = cj._overlap(rb[:, :, None, None, :], re_[:, :, None, None, :],
                        wb[None, None, :, :, :], we[None, None, :, :, :], WIDTH)
        M = m.any(axis=(1, 3)) & ~jnp.eye(B, dtype=bool)

        if verdict_mode == "v1":
            def body(committed, i):
                conf = hist[i] | (committed & M[i]).any()
                commit_i = valid[i] & ~too_old[i] & ~conf
                verdict = jnp.where(~valid[i], jnp.int32(0),
                                    jnp.where(too_old[i], jnp.int32(2),
                                              jnp.where(conf, jnp.int32(1),
                                                        jnp.int32(0))))
                return committed.at[i].set(commit_i), verdict
            committed, verdicts = lax.scan(body, jnp.zeros(B, bool), jnp.arange(B))
        else:
            def body(committed, i):
                conf = hist[i] | (committed & M[i]).any()
                return committed.at[i].set(valid[i] & ~too_old[i] & ~conf), conf
            committed, conf = lax.scan(body, jnp.zeros(B, bool), jnp.arange(B))
            dt = jnp.int8 if verdict_mode == "v2" else jnp.int32
            verdicts = jnp.where(~valid, dt(0),
                                 jnp.where(too_old, dt(2),
                                           jnp.where(conf, dt(1), dt(0))))

        valid_w = wb[..., -1] != jnp.uint32(0xFFFFFFFF)
        ins = (committed[:, None] & valid_w).reshape(-1)
        k = jnp.cumsum(ins) - ins
        pos = jnp.where(ins, (state.ptr + k) % C, C).astype(jnp.int32)
        old = jnp.where(ins, state.hver[pos], jnp.int64(-1))
        floor2 = jnp.maximum(state.floor, jnp.max(old))
        wbf = jnp.where(ins[:, None], wb.reshape(B * R, L), jnp.uint32(0xFFFFFFFF))
        wef = jnp.where(ins[:, None], we.reshape(B * R, L), jnp.uint32(0xFFFFFFFF))
        hb2 = state.hb.at[pos].set(wbf)
        he2 = state.he.at[pos].set(wef)
        hver2 = state.hver.at[pos].set(jnp.where(ins, cv, jnp.int64(-1)))
        ptr2 = ((state.ptr + jnp.sum(ins)) % C).astype(jnp.int32)
        return cj.ConflictState(hb2, he2, hver2, ptr2, floor2), verdicts

    if mode == "v4":
        j = jax.jit(cj.resolve_core, static_argnames=("width", "window"))
        arga = (state, rb, re_, wb, we, sn, cv)
        kw = {"width": WIDTH, "window": WIN}
    else:
        vm = {"v1": "v1", "v2": "v2", "v3": "v3", "v5": "v2"}[mode]
        donate = (0,) if mode == "v5" else ()
        j = jax.jit(lambda s, a, b, c, d, e, f: core_patched(s, a, b, c, d, e, f, vm),
                    donate_argnums=donate)
        arga = (state, rb, re_, wb, we, sn, cv)
        kw = {}

    t0 = time.perf_counter()
    out = j(*arga, **kw)
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0
    ts = []
    st = out[0]
    for _ in range(5):
        t0 = time.perf_counter()
        out = j(st, *arga[1:], **kw)
        jax.block_until_ready(out)
        st = out[0]
        ts.append(time.perf_counter() - t0)

    one = jax.device_put(jnp.float32(1.0), dev)
    jt = jax.jit(lambda x: x + 1)
    jt(one).block_until_ready()
    tt = []
    for _ in range(5):
        t0 = time.perf_counter()
        jt(one).block_until_ready()
        tt.append(time.perf_counter() - t0)

    print(f"MODE {mode:4s} kernel_med={np.median(ts)*1e3:8.3f}ms "
          f"trivial_after={np.median(tt)*1e3:8.3f}ms compile={compile_s:.1f}s",
          flush=True)


def main():
    if sys.argv[1] == "--all":
        for m in MODES:
            r = subprocess.run([sys.executable, "-m",
                                "foundationdb_tpu.bench.profile_poison3", m],
                               capture_output=True, text=True, timeout=300)
            out = [l for l in r.stdout.splitlines() if l.startswith("MODE")]
            print(out[0] if out else f"MODE {m}: FAILED\n{r.stderr[-600:]}",
                  flush=True)
    else:
        run_mode(sys.argv[1])


if __name__ == "__main__":
    main()
