"""Profile the resolver kernel's dispatch pipeline on the live device.

Isolates the round-2 mystery (~70ms per resolve_step on TPU, pipelining
gains nothing) into its parts:

  1. bare dispatch+sync RTT of a trivial op        -> tunnel per-call floor
  2. host->device transfer of one encoded batch    -> transfer cost
  3. resolve_step execute (fast window path)       -> kernel compute
  4. resolve_step execute (full-ring path)         -> slow-path compute
  5. K-fused scan prototype                        -> amortization headroom
  6. int32-version variant of the hist check       -> int64 emulation tax

Run: python -m foundationdb_tpu.bench.profile_resolver [--cpu]
Prints one timing line per experiment; safe to run over the axon tunnel
(single process, never killed mid-op by itself).
"""

from __future__ import annotations

import argparse
import time

import numpy as np


def timeit(fn, n=20, warmup=3):
    for _ in range(warmup):
        fn()
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    ts = np.array(ts) * 1e3
    return float(np.median(ts)), float(np.min(ts)), float(np.max(ts))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--n", type=int, default=20)
    args = ap.parse_args()

    import jax

    jax.config.update("jax_enable_x64", True)
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    from jax import lax

    dev = jax.devices()[0]
    print(f"device: {dev} platform={dev.platform}")

    from foundationdb_tpu.bench.workload import MakoWorkload
    from foundationdb_tpu.ops import conflict_jax as cj
    from foundationdb_tpu.ops.batch import encode_batch, TxnRequest
    from foundationdb_tpu.ops.backends import coalesce_ranges

    B, R, WIDTH, CAP, WIN = 64, 4, 32, 1 << 16, 4096
    wl = MakoWorkload(n_keys=1_000_000, seed=42)
    batches, versions = wl.make_batches(64, B)

    def enc(txns):
        txns = [TxnRequest(coalesce_ranges(t.read_ranges, R),
                           coalesce_ranges(t.write_ranges, R),
                           t.read_snapshot) for t in txns]
        return encode_batch(txns, B, R, WIDTH)

    ebs = [enc(t) for t in batches]

    # --- 1. bare dispatch RTT
    one = jax.device_put(jnp.float32(1.0), dev)
    f_triv = jax.jit(lambda x: x + 1, device=dev)
    f_triv(one).block_until_ready()
    med, mn, mx = timeit(lambda: f_triv(one).block_until_ready(), args.n)
    print(f"1. trivial dispatch+sync:        med={med:8.3f}ms min={mn:8.3f} max={mx:8.3f}")

    # 1b. dispatch without sync
    med, mn, mx = timeit(lambda: f_triv(one), args.n)
    print(f"1b. trivial dispatch (async):    med={med:8.3f}ms min={mn:8.3f} max={mx:8.3f}")

    # --- 2. transfer one encoded batch
    eb = ebs[0]
    def xfer():
        a = jax.device_put(eb.read_begin, dev)
        b = jax.device_put(eb.read_end, dev)
        c = jax.device_put(eb.write_begin, dev)
        d = jax.device_put(eb.write_end, dev)
        e = jax.device_put(eb.read_snapshot, dev)
        jax.block_until_ready((a, b, c, d, e))
    med, mn, mx = timeit(xfer, args.n)
    print(f"2. h2d transfer 1 batch:         med={med:8.3f}ms min={mn:8.3f} max={mx:8.3f}")

    # --- 3/4. resolve_step fast vs full
    for name, win in (("fast window", WIN), ("full ring  ", 0)):
        state = jax.device_put(cj.init_state(CAP, WIDTH, 0), dev)
        # warm compile
        st = state
        st, v = cj.resolve_step(st, jnp.asarray(ebs[0].read_begin),
                                jnp.asarray(ebs[0].read_end),
                                jnp.asarray(ebs[0].write_begin),
                                jnp.asarray(ebs[0].write_end),
                                jnp.asarray(ebs[0].read_snapshot),
                                jnp.int64(versions[0]), width=WIDTH, window=win)
        v.block_until_ready()
        holder = {"st": st}
        idx = {"i": 1}
        def step():
            i = idx["i"] % len(ebs)
            idx["i"] += 1
            e = ebs[i]
            holder["st"], vv = cj.resolve_step(
                holder["st"], jnp.asarray(e.read_begin), jnp.asarray(e.read_end),
                jnp.asarray(e.write_begin), jnp.asarray(e.write_end),
                jnp.asarray(e.read_snapshot), jnp.int64(versions[i]),
                width=WIDTH, window=win)
            vv.block_until_ready()
        med, mn, mx = timeit(step, args.n)
        print(f"3. resolve_step {name}:     med={med:8.3f}ms min={mn:8.3f} max={mx:8.3f}")

    # --- 5. K-fused scan prototype: stack K batches, scan on device
    for K in (8, 64):
        ks = (ebs * ((K // len(ebs)) + 1))[:K]
        rb = jnp.asarray(np.stack([e.read_begin for e in ks]))
        re_ = jnp.asarray(np.stack([e.read_end for e in ks]))
        wb = jnp.asarray(np.stack([e.write_begin for e in ks]))
        we = jnp.asarray(np.stack([e.write_end for e in ks]))
        sn = jnp.asarray(np.stack([e.read_snapshot for e in ks]))
        cv = jnp.asarray(np.array(versions[:1] * K, dtype=np.int64))

        def many(state, rb, re_, wb, we, sn, cv):
            def body(st, x):
                st2, v = cj.resolve_core(st, *x[:5], x[5], width=WIDTH, window=WIN)
                return st2, v
            return lax.scan(body, state, (rb, re_, wb, we, sn, cv))

        manyj = jax.jit(many, donate_argnums=(0,), device=dev)
        state = jax.device_put(cj.init_state(CAP, WIDTH, 0), dev)
        t0 = time.perf_counter()
        st, v = manyj(state, rb, re_, wb, we, sn, cv)
        v.block_until_ready()
        compile_s = time.perf_counter() - t0
        holder = {"st": st}
        def stepk():
            holder["st"], vv = manyj(holder["st"], rb, re_, wb, we, sn, cv)
            vv.block_until_ready()
        med, mn, mx = timeit(stepk, max(5, args.n // 2))
        print(f"5. K={K:3d} fused scan:           med={med:8.3f}ms min={mn:8.3f} max={mx:8.3f}"
              f"  ({med/K:7.3f} ms/batch, compile {compile_s:.1f}s)")

    # --- 6. int64 vs int32 hist-version compare tax
    hver64 = jax.device_put(jnp.arange(CAP, dtype=jnp.int64), dev)
    hver32 = jax.device_put(jnp.arange(CAP, dtype=jnp.int32), dev)
    snap64 = jax.device_put(jnp.arange(B, dtype=jnp.int64), dev)
    snap32 = jax.device_put(jnp.arange(B, dtype=jnp.int32), dev)
    f64 = jax.jit(lambda h, s: (h[None, None, :] > s[:, None, None]).sum(), device=dev)
    f32 = jax.jit(lambda h, s: (h[None, None, :] > s[:, None, None]).sum(), device=dev)
    f64(hver64, snap64).block_until_ready()
    f32(hver32, snap32).block_until_ready()
    med, _, _ = timeit(lambda: f64(hver64, snap64).block_until_ready(), args.n)
    print(f"6. int64 compare [B,1,C]:        med={med:8.3f}ms")
    med, _, _ = timeit(lambda: f32(hver32, snap32).block_until_ready(), args.n)
    print(f"6. int32 compare [B,1,C]:        med={med:8.3f}ms")

    return 0


if __name__ == "__main__":
    raise SystemExit(main())
