"""Component-level profile of the fused lane-major kernel (degraded mode).

All components run inside a K=64 lax.scan to mirror the real kernel.
  c1  window dynamic_slice only
  c2  + hist compare, int64 hver
  c3  + hist compare, int32 hver (version deltas)
  c4  intra matrix, transposed [B,R,BR]
  c4b intra matrix, original [B,R,B,R]
  c5  inner scan alone (unroll 8)
  c6  append-insert (2 dynamic_update_slice) + floor max
  c7  FULL kernel: append-insert, always-window (no cond), int32 hver
  c8  c7 + lax.cond fallback
"""

from __future__ import annotations

import functools
import time

import numpy as np


def main():
    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from jax import lax

    dev = jax.devices()[0]

    from foundationdb_tpu.bench.workload import MakoWorkload
    from foundationdb_tpu.ops.batch import encode_batch, TxnRequest
    from foundationdb_tpu.ops.backends import coalesce_ranges

    B, R, WIDTH, WIN = 64, 4, 32, 4096
    SLAB = B * R                      # slots consumed per batch
    CAP = 1 << 16                     # ring slots
    K = 64
    wl = MakoWorkload(n_keys=1_000_000, seed=42)
    batches, versions = wl.make_batches(K, B)

    def enc(txns):
        txns = [TxnRequest(coalesce_ranges(t.read_ranges, R),
                           coalesce_ranges(t.write_ranges, R),
                           t.read_snapshot) for t in txns]
        return encode_batch(txns, B, R, WIDTH)

    ebs = [enc(t) for t in batches]
    L = ebs[0].read_begin.shape[-1]

    one = jax.device_put(jnp.float32(1.0), dev)
    jt = jax.jit(lambda x: x + 1)
    _ = np.asarray(jt(one))
    rtts = []
    for _ in range(5):
        t0 = time.perf_counter()
        jt(one).block_until_ready()
        rtts.append(time.perf_counter() - t0)
    rtt = float(np.median(rtts))
    print(f"RTT: {rtt*1e3:.1f}ms  L={L}")

    rb = jax.device_put(jnp.asarray(np.stack([e.read_begin for e in ebs])), dev)
    re_ = jax.device_put(jnp.asarray(np.stack([e.read_end for e in ebs])), dev)
    wb = jax.device_put(jnp.asarray(np.stack([e.write_begin for e in ebs])), dev)
    we = jax.device_put(jnp.asarray(np.stack([e.write_end for e in ebs])), dev)
    sn64 = jax.device_put(jnp.asarray(np.stack([e.read_snapshot for e in ebs])), dev)
    sn32 = jax.device_put(jnp.asarray(
        np.stack([e.read_snapshot for e in ebs]).astype(np.int32)), dev)
    cvs = jax.device_put(jnp.asarray(np.array(versions, dtype=np.int64)), dev)
    cvs32 = jax.device_put(jnp.asarray(np.array(versions, dtype=np.int32)), dev)

    hbT = jax.device_put(jnp.full((L, 2 * CAP), 0xFFFFFFFF, jnp.uint32), dev)
    heT = jax.device_put(jnp.full((L, 2 * CAP), 0xFFFFFFFF, jnp.uint32), dev)
    hv64 = jax.device_put(jnp.full((2 * CAP,), -1, jnp.int64), dev)
    hv32 = jax.device_put(jnp.full((2 * CAP,), -1, jnp.int32), dev)

    def cmp_T(a, bT, W, width):
        lt = jnp.zeros((a.shape[0], a.shape[1], W), bool)
        eq = jnp.ones_like(lt)
        for l in range(L):
            al = a[:, :, l:l + 1]
            bl = bT[l][None, None, :]
            lt = lt | (eq & (al < bl))
            eq = eq & (al == bl)
        both = (a[:, :, -1:] == width + 1) & (bT[-1][None, None, :] == width + 1)
        return lt | (eq & both)

    def cmp_T_rev(aT, b, W, width):
        lt = jnp.zeros((b.shape[0], b.shape[1], W), bool)
        eq = jnp.ones_like(lt)
        for l in range(L):
            al = aT[l][None, None, :]
            bl = b[:, :, l:l + 1]
            lt = lt | (eq & (al < bl))
            eq = eq & (al == bl)
        both = (aT[-1][None, None, :] == width + 1) & (b[:, :, -1:] == width + 1)
        return lt | (eq & both)

    def run(name, body, carry_fn, xs):
        @functools.partial(jax.jit, donate_argnums=(0,))
        def f(carry, xs):
            return lax.scan(body, carry, xs)
        c = jax.device_put(carry_fn(), dev)
        t0 = time.perf_counter()
        c, y = f(c, xs)
        jax.block_until_ready(y)
        comp = time.perf_counter() - t0
        ts = []
        for _ in range(3):
            c2, y = f(c, xs)
            jax.block_until_ready(y)
            c = c2
            t0b = time.perf_counter()
            c2, y = f(c, xs)
            jax.block_until_ready(y)
            c = c2
            ts.append(time.perf_counter() - t0b)
        t = float(np.median(ts))
        print(f"{name:36s} {t*1e3:8.1f}ms exec~{(t-rtt)/K*1e3:6.3f}ms/batch "
              f"(compile {comp:.0f}s)")

    i32 = jnp.int32

    # c1: window slice only
    def c1(carry, x):
        ptr = carry
        start = ((ptr - WIN) % CAP).astype(i32)
        hbW = lax.dynamic_slice(hbT, (i32(0), start), (L, WIN))
        return ptr + SLAB, hbW[0, 0]
    run("c1 window slice", c1, lambda: jnp.int32(0), jnp.arange(K))

    # c2/c3: slice + hist compare
    def mk_hist(hv, sn):
        def c2(carry, x):
            ptr = carry
            rbi, rei, sni = x
            start = ((ptr - WIN) % CAP).astype(i32)
            hbW = lax.dynamic_slice(hbT, (i32(0), start), (L, WIN))
            heW = lax.dynamic_slice(heT, (i32(0), start), (L, WIN))
            hvW = lax.dynamic_slice(hv, (start,), (WIN,))
            hit = cmp_T(rbi, heW, WIN, WIDTH) & cmp_T_rev(hbW, rei, WIN, WIDTH)
            newer = hvW[None, None, :] > sni[:, None, None]
            return ptr + SLAB, (hit & newer).any(axis=(1, 2))
        return c2
    run("c2 slice+hist int64", mk_hist(hv64, sn64), lambda: jnp.int32(0), (rb, re_, sn64))
    run("c3 slice+hist int32", mk_hist(hv32, sn32), lambda: jnp.int32(0), (rb, re_, sn32))

    # c4: intra transposed
    def c4(carry, x):
        rbi, rei, wbi, wei = x
        wbT = wbi.reshape(SLAB, L).T
        weT = wei.reshape(SLAB, L).T
        hitM = cmp_T(rbi, weT, SLAB, WIDTH) & cmp_T_rev(wbT, rei, SLAB, WIDTH)
        M = hitM.reshape(B, R, B, R).any(axis=(1, 3)) & ~jnp.eye(B, dtype=bool)
        return carry, M[0]
    run("c4 intra transposed", c4, lambda: jnp.int32(0), (rb, re_, wb, we))

    # c4b: intra original layout
    def lex_lt(a, b):
        lt = jnp.zeros(jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1]), bool)
        eq = jnp.ones_like(lt)
        for l in range(L):
            al, bl = a[..., l], b[..., l]
            lt = lt | (eq & (al < bl))
            eq = eq & (al == bl)
        both = (a[..., -1] == WIDTH + 1) & (b[..., -1] == WIDTH + 1)
        return lt | (eq & both)

    def c4b(carry, x):
        rbi, rei, wbi, wei = x
        m = (lex_lt(rbi[:, :, None, None, :], wei[None, None, :, :, :])
             & lex_lt(wbi[None, None, :, :, :], rei[:, :, None, None, :]))
        M = m.any(axis=(1, 3)) & ~jnp.eye(B, dtype=bool)
        return carry, M[0]
    run("c4b intra original", c4b, lambda: jnp.int32(0), (rb, re_, wb, we))

    # c5: inner scan
    Ms = jax.device_put(jnp.zeros((K, B, B), bool), dev)
    hists = jax.device_put(jnp.zeros((K, B), bool), dev)
    def c5(carry, x):
        M, hist = x
        def ib(committed, i):
            conf = hist[i] | (committed & M[i]).any()
            return committed.at[i].set(~conf), conf
        committed, conf = lax.scan(ib, jnp.zeros(B, bool), jnp.arange(B), unroll=8)
        return carry, conf
    run("c5 inner scan u8", c5, lambda: jnp.int32(0), (Ms, hists))

    # c6: append insert
    def c6(carry, x):
        hbT_, hv_, ptr, floor = carry
        wbi, cv = x
        wslab = wbi.reshape(SLAB, L).T
        vslab = jnp.full((SLAB,), 0, jnp.int64) + cv
        old = lax.dynamic_slice(hv_, ((ptr % CAP).astype(i32),), (SLAB,))
        floor2 = jnp.maximum(floor, jnp.max(old))
        p = (ptr % CAP).astype(i32)
        hbT2 = lax.dynamic_update_slice(hbT_, wslab, (i32(0), p))
        hbT2 = lax.dynamic_update_slice(hbT2, wslab, (i32(0), p + CAP))
        hv2 = lax.dynamic_update_slice(hv_, vslab, (p,))
        hv2 = lax.dynamic_update_slice(hv2, vslab, (p + CAP,))
        return (hbT2, hv2, ptr + SLAB, floor2), floor2
    def mk_ring64():
        return (jnp.full((L, 2 * CAP), 0xFFFFFFFF, jnp.uint32),
                jnp.full((2 * CAP,), -1, jnp.int64),
                jnp.int32(0), jnp.int64(0))
    run("c6 append insert", c6, mk_ring64, (wb, cvs))

    # c7: full kernel, always-window, int32 hver
    def full_body(use_cond):
        def body(carry, x):
            hbT_, heT_, hv_, ptr, floor = carry
            rbi, rei, wbi, wei, sni, cv = x
            too_old = sni < floor
            valid = sni >= 0
            start = ((ptr - WIN) % CAP).astype(i32)
            hbW = lax.dynamic_slice(hbT_, (i32(0), start), (L, WIN))
            heW = lax.dynamic_slice(heT_, (i32(0), start), (L, WIN))
            hvW = lax.dynamic_slice(hv_, (start,), (WIN,))

            def hist_of(hb_, he_, hv__, W):
                hit = cmp_T(rbi, he_, W, WIDTH) & cmp_T_rev(hb_, rei, W, WIDTH)
                newer = hv__[None, None, :] > sni[:, None, None]
                return (hit & newer).any(axis=(1, 2))

            if use_cond:
                v_edge = hv_[((ptr - WIN - 1) % CAP).astype(i32)]
                fast_ok = jnp.all(~valid | too_old | (sni >= v_edge))
                hist = lax.cond(
                    fast_ok,
                    lambda _: hist_of(hbW, heW, hvW, WIN),
                    lambda _: hist_of(hbT_[:, :CAP], heT_[:, :CAP], hv_[:CAP], CAP),
                    None)
            else:
                hist = hist_of(hbW, heW, hvW, WIN)

            wbT = wbi.reshape(SLAB, L).T
            weT = wei.reshape(SLAB, L).T
            hitM = cmp_T(rbi, weT, SLAB, WIDTH) & cmp_T_rev(wbT, rei, SLAB, WIDTH)
            M = hitM.reshape(B, R, B, R).any(axis=(1, 3)) & ~jnp.eye(B, dtype=bool)

            def ib(committed, i):
                conf = hist[i] | (committed & M[i]).any()
                return committed.at[i].set(valid[i] & ~too_old[i] & ~conf), conf
            committed, conf = lax.scan(ib, jnp.zeros(B, bool), jnp.arange(B),
                                       unroll=8)
            verdicts = jnp.where(~valid, np.int8(0),
                                 jnp.where(too_old, np.int8(2),
                                           jnp.where(conf, np.int8(1), np.int8(0))))

            insm = (committed[:, None] & (wbi[..., -1] != jnp.uint32(0xFFFFFFFF))).reshape(-1)
            wslab_b = jnp.where(insm[:, None], wbi.reshape(SLAB, L),
                                jnp.uint32(0xFFFFFFFF)).T
            wslab_e = jnp.where(insm[:, None], wei.reshape(SLAB, L),
                                jnp.uint32(0xFFFFFFFF)).T
            vslab = jnp.where(insm, cv, jnp.asarray(-1, hv_.dtype))
            p = (ptr % CAP).astype(i32)
            old = lax.dynamic_slice(hv_, (p,), (SLAB,))
            floor2 = jnp.maximum(floor, jnp.max(old))
            hbT2 = lax.dynamic_update_slice(hbT_, wslab_b, (i32(0), p))
            hbT2 = lax.dynamic_update_slice(hbT2, wslab_b, (i32(0), p + CAP))
            heT2 = lax.dynamic_update_slice(heT_, wslab_e, (i32(0), p))
            heT2 = lax.dynamic_update_slice(heT2, wslab_e, (i32(0), p + CAP))
            hv2 = lax.dynamic_update_slice(hv_, vslab, (p,))
            hv2 = lax.dynamic_update_slice(hv2, vslab, (p + CAP,))
            ptr2 = ((ptr + SLAB) % CAP).astype(i32)
            return (hbT2, heT2, hv2, ptr2, floor2), verdicts
        return body

    def mk_full32():
        return (jnp.full((L, 2 * CAP), 0xFFFFFFFF, jnp.uint32),
                jnp.full((L, 2 * CAP), 0xFFFFFFFF, jnp.uint32),
                jnp.full((2 * CAP,), -1, jnp.int32),
                jnp.int32(0), jnp.int32(0))
    run("c7 FULL window-only int32", full_body(False), mk_full32,
        (rb, re_, wb, we, sn32, cvs32))
    run("c8 FULL + cond int32", full_body(True), mk_full32,
        (rb, re_, wb, we, sn32, cvs32))


if __name__ == "__main__":
    main()
