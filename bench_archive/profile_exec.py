"""True device exec time per resolve batch, measured in degraded mode.

Poison the session first (one readback) so every block_until_ready is a
real round trip; exec = measured - trivial RTT.  Times:
  - resolve_step single batch (window fast path)
  - fused scan over K batches, K = 16/64/256
  - transposed-layout hist-check prototype [L,C] lane-major, K=64
"""

from __future__ import annotations

import functools
import time

import numpy as np


def main():
    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from jax import lax

    dev = jax.devices()[0]

    from foundationdb_tpu.bench.workload import MakoWorkload
    from foundationdb_tpu.ops import conflict_jax as cj
    from foundationdb_tpu.ops.batch import encode_batch, TxnRequest
    from foundationdb_tpu.ops.backends import coalesce_ranges

    B, R, WIDTH, CAP, WIN = 64, 4, 32, 1 << 16, 4096
    wl = MakoWorkload(n_keys=1_000_000, seed=42)
    batches, versions = wl.make_batches(256, B)

    def enc(txns):
        txns = [TxnRequest(coalesce_ranges(t.read_ranges, R),
                           coalesce_ranges(t.write_ranges, R),
                           t.read_snapshot) for t in txns]
        return encode_batch(txns, B, R, WIDTH)

    ebs = [enc(t) for t in batches]
    L = ebs[0].read_begin.shape[-1]

    # poison -> degraded mode
    one = jax.device_put(jnp.float32(1.0), dev)
    jt = jax.jit(lambda x: x + 1)
    _ = np.asarray(jt(one))
    rtts = []
    for _ in range(5):
        t0 = time.perf_counter()
        jt(one).block_until_ready()
        rtts.append(time.perf_counter() - t0)
    rtt = float(np.median(rtts))
    print(f"RTT (trivial sync): {rtt*1e3:.1f}ms")

    def timed(fn, n=5):
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts))

    # single batch
    state = jax.device_put(cj.init_state(CAP, WIDTH, 0), dev)
    args0 = [jax.device_put(jnp.asarray(a), dev) for a in
             (ebs[0].read_begin, ebs[0].read_end, ebs[0].write_begin,
              ebs[0].write_end, ebs[0].read_snapshot)]
    cv = jnp.int64(versions[0])
    holder = {"st": state}
    def step():
        holder["st"], v = cj.resolve_step(holder["st"], *args0, cv,
                                          width=WIDTH, window=WIN)
        v.block_until_ready()
    step()
    t = timed(step)
    print(f"resolve_step 1 batch: {t*1e3:7.2f}ms -> exec ~{(t-rtt)*1e3:6.2f}ms")

    # fused scan
    for K in (16, 64, 256):
        ks = ebs[:K]
        rb = jax.device_put(jnp.asarray(np.stack([e.read_begin for e in ks])), dev)
        re_ = jax.device_put(jnp.asarray(np.stack([e.read_end for e in ks])), dev)
        wb = jax.device_put(jnp.asarray(np.stack([e.write_begin for e in ks])), dev)
        we = jax.device_put(jnp.asarray(np.stack([e.write_end for e in ks])), dev)
        sn = jax.device_put(jnp.asarray(np.stack([e.read_snapshot for e in ks])), dev)
        cvs = jax.device_put(jnp.asarray(np.array(versions[:K], dtype=np.int64)), dev)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def many(state, rb, re_, wb, we, sn, cvs):
            def body(st, x):
                st2, v = cj.resolve_core(st, *x[:5], x[5], width=WIDTH, window=WIN)
                return st2, v
            return lax.scan(body, state, (rb, re_, wb, we, sn, cvs))

        st = jax.device_put(cj.init_state(CAP, WIDTH, 0), dev)
        st, v = many(st, rb, re_, wb, we, sn, cvs)
        v.block_until_ready()
        holder = {"st": st}
        def stepk():
            holder["st"], vv = many(holder["st"], rb, re_, wb, we, sn, cvs)
            vv.block_until_ready()
        t = timed(stepk, 3)
        ex = (t - rtt) / K * 1e3
        print(f"fused K={K:3d}: {t*1e3:8.1f}ms -> exec ~{ex:6.3f}ms/batch "
              f"-> ceiling ~{64_000/ex/1000 if ex>0 else 0:8.1f}k txns/s")

    # transposed-layout hist prototype: hb/he as [L, C], reads [B,R,L]
    K = 64
    hbT = jax.device_put(jnp.full((L, WIN), 0x7FFFFFFF, jnp.int32), dev)
    heT = jax.device_put(jnp.full((L, WIN), 0x7FFFFFFF, jnp.int32), dev)
    hverT = jax.device_put(jnp.zeros((WIN,), jnp.int32), dev)
    rbK = jax.device_put(jnp.asarray(
        np.stack([e.read_begin for e in ebs[:K]]).astype(np.int32)), dev)
    reK = jax.device_put(jnp.asarray(
        np.stack([e.read_end for e in ebs[:K]]).astype(np.int32)), dev)
    snK = jax.device_put(jnp.asarray(
        np.stack([e.read_snapshot for e in ebs[:K]]).astype(np.int32)), dev)

    def lex_lt_T(a, b):  # a [B,R,L] vs b [L,W] -> [B,R,W]
        lt = jnp.zeros(a.shape[:2] + (b.shape[-1],), bool)
        eq = jnp.ones_like(lt)
        for l in range(a.shape[-1]):
            al = a[:, :, l:l+1]
            bl = b[l][None, None, :]
            lt = lt | (eq & (al < bl))
            eq = eq & (al == bl)
        return lt

    @jax.jit
    def histT(rb, re_, sn, hbT, heT, hverT):
        def body(_, x):
            rbi, rei, sni = x
            hit = lex_lt_T(rbi, heT) & ~lex_lt_T(rei, hbT)  # approx overlap
            newer = hverT[None, None, :] > sni[:, None, None]
            return _, (hit & newer).any(axis=(1, 2))
        return lax.scan(body, None, (rb, re_, sn))

    _, v = histT(rbK, reK, snK, hbT, heT, hverT)
    v.block_until_ready()
    def stepT():
        _, vv = histT(rbK, reK, snK, hbT, heT, hverT)
        vv.block_until_ready()
    t = timed(stepT, 3)
    print(f"histT K=64 [L,C] layout: {t*1e3:8.1f}ms -> ~{(t-rtt)/K*1e3:6.3f}ms/batch (hist only)")


if __name__ == "__main__":
    main()
