"""Bisect the pathological ~63ms dispatch seen in profile_resolver exp 6.

A 2-op kernel (compare [64]x[65536] + sum) costs 63ms while a trivial
scalar add costs 0.02ms.  Vary: array size, dtype, reduction, output
shape/location, donation — to find which property triggers the cliff.
"""

from __future__ import annotations

import time

import numpy as np


def timeit(fn, n=10, warmup=2):
    for _ in range(warmup):
        fn()
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts) * 1e3)


def main():
    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    dev = jax.devices()[0]
    print(f"device: {dev}")

    def bench(name, fn, *arrs):
        arrs = [jax.device_put(a, dev) for a in arrs]
        j = jax.jit(fn)
        j(*arrs).block_until_ready()
        print(f"{name:48s} {timeit(lambda: j(*arrs).block_until_ready()):9.3f}ms")

    for C in (1024, 8192, 65536):
        h = jnp.arange(C, dtype=jnp.int32)
        s = jnp.arange(64, dtype=jnp.int32)
        bench(f"cmp+sum int32 [64]x[{C}]",
              lambda h, s: (h[None, :] > s[:, None]).sum(), h, s)

    C = 65536
    h32 = jnp.arange(C, dtype=jnp.int32)
    hf = jnp.arange(C, dtype=jnp.float32)
    s32 = jnp.arange(64, dtype=jnp.int32)
    sf = jnp.arange(64, dtype=jnp.float32)

    bench("cmp+sum float32 [64]x[65536]",
          lambda h, s: (h[None, :] > s[:, None]).sum(), hf, sf)
    bench("cmp+any int32 [64]x[65536]",
          lambda h, s: (h[None, :] > s[:, None]).any(), h32, s32)
    bench("cmp only -> [64,65536] bool out",
          lambda h, s: h[None, :] > s[:, None], h32, s32)
    bench("cmp+reduce axis1 -> [64] out",
          lambda h, s: (h[None, :] > s[:, None]).any(axis=1), h32, s32)
    bench("sum [65536] alone", lambda h: h.sum(), h32)
    bench("sum [65536] f32 alone", lambda h: h.sum(), hf)
    bench("add [65536] -> [65536]", lambda h: h + 1, h32)
    bench("add [64,65536] -> same", lambda h: h + 1,
          jnp.zeros((64, 65536), jnp.int32))
    bench("matmul 1024x1024 f32", lambda a: a @ a,
          jnp.ones((1024, 1024), jnp.float32))
    bench("matmul 1024 bf16", lambda a: a @ a,
          jnp.ones((1024, 1024), jnp.bfloat16))
    # scalar output vs array output
    bench("scalar out: sum [64] f32", lambda s: s.sum(), sf)
    # x64-affected: int64 arrays
    h64 = jnp.arange(C, dtype=jnp.int64)
    bench("sum [65536] int64 alone", lambda h: h.sum(), h64)


if __name__ == "__main__":
    main()
