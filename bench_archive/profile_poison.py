"""Find which op combination in resolve_core poisons the axon dispatch path.

After resolve_core runs once, EVERY subsequent dispatch (even x+1) takes
~70ms for the rest of the process (profile_decompose exp I).  Each mode
here runs in a FRESH process: build a candidate kernel, run it 3x, then
time a trivial op.  If the trivial op is slow, that mode contains the
poison.

Usage: python -m foundationdb_tpu.bench.profile_poison MODE
       python -m foundationdb_tpu.bench.profile_poison --all   (spawns children)
"""

from __future__ import annotations

import subprocess
import sys
import time

import numpy as np

MODES = [
    "nostate",      # hist+intra+innerscan only, no state outputs
    "noscatter",    # + floor/ptr math, no ring scatter
    "noint64",      # full kernel but hver/versions as int32
    "nocond",       # full kernel, window=0 (no lax.cond)
    "nofloor",      # full kernel minus the floor=max(old) reduction
    "full",         # resolve_core as shipped
    "smallcap",     # full kernel, CAP=1024
    "donate",       # full kernel + donation
]


def run_mode(mode: str) -> None:
    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from jax import lax

    dev = jax.devices()[0]

    from foundationdb_tpu.bench.workload import MakoWorkload
    from foundationdb_tpu.ops import conflict_jax as cj
    from foundationdb_tpu.ops.batch import encode_batch, TxnRequest
    from foundationdb_tpu.ops.backends import coalesce_ranges

    B, R, WIDTH = 64, 4, 32
    CAP = 1024 if mode == "smallcap" else 1 << 16
    WIN = 0 if mode in ("nocond",) else 4096
    if WIN >= CAP:
        WIN = 0
    wl = MakoWorkload(n_keys=1_000_000, seed=42)
    batches, versions = wl.make_batches(4, B)
    txns = [TxnRequest(coalesce_ranges(t.read_ranges, R),
                       coalesce_ranges(t.write_ranges, R), t.read_snapshot)
            for t in batches[0]]
    eb = encode_batch(txns, B, R, WIDTH)

    state = jax.device_put(cj.init_state(CAP, WIDTH, 0), dev)
    if mode == "noint64":
        state = state._replace(hver=state.hver.astype(jnp.int32),
                               floor=state.floor.astype(jnp.int32))
    rb = jax.device_put(jnp.asarray(eb.read_begin), dev)
    re_ = jax.device_put(jnp.asarray(eb.read_end), dev)
    wb = jax.device_put(jnp.asarray(eb.write_begin), dev)
    we = jax.device_put(jnp.asarray(eb.write_end), dev)
    sn0 = jnp.asarray(eb.read_snapshot)
    sn = jax.device_put(sn0.astype(jnp.int32) if mode == "noint64" else sn0, dev)
    cv = (jnp.int32 if mode == "noint64" else jnp.int64)(versions[0])

    L = rb.shape[-1]

    def kernel(state, rb, re_, wb, we, sn, cv):
        C = state.hver.shape[0] - 1
        hb, he, hver = state.hb[:C], state.he[:C], state.hver[:C]
        too_old = sn < state.floor
        valid = sn >= 0
        if WIN:
            idx = (state.ptr - WIN + jnp.arange(WIN)) % C
            v_edge = state.hver[(state.ptr - WIN - 1) % C]
            fast_ok = jnp.all(~valid | too_old | (sn >= v_edge))
            hist = lax.cond(
                fast_ok,
                lambda _: cj._hist_check(rb, re_, hb[idx], he[idx], hver[idx], sn, WIDTH),
                lambda _: cj._hist_check(rb, re_, hb, he, hver, sn, WIDTH),
                None)
        else:
            hist = cj._hist_check(rb, re_, hb, he, hver, sn, WIDTH)
        m = cj._overlap(rb[:, :, None, None, :], re_[:, :, None, None, :],
                        wb[None, None, :, :, :], we[None, None, :, :, :], WIDTH)
        M = m.any(axis=(1, 3)) & ~jnp.eye(B, dtype=bool)

        def body(committed, i):
            conf = hist[i] | (committed & M[i]).any()
            commit_i = valid[i] & ~too_old[i] & ~conf
            verdict = jnp.where(~valid[i], cj.COMMITTED,
                                jnp.where(too_old[i], cj.TOO_OLD,
                                          jnp.where(conf, cj.CONFLICT, cj.COMMITTED)))
            return committed.at[i].set(commit_i), verdict

        committed, verdicts = lax.scan(body, jnp.zeros(B, bool), jnp.arange(B))
        if mode == "nostate":
            return verdicts

        valid_w = wb[..., -1] != jnp.uint32(0xFFFFFFFF)
        ins = (committed[:, None] & valid_w).reshape(-1)
        k = jnp.cumsum(ins) - ins
        pos = jnp.where(ins, (state.ptr + k) % C, C).astype(jnp.int32)
        if mode == "noscatter":
            ptr2 = ((state.ptr + jnp.sum(ins)) % C).astype(jnp.int32)
            return state._replace(ptr=ptr2), verdicts
        old = jnp.where(ins, state.hver[pos], jnp.asarray(-1, state.hver.dtype))
        if mode == "nofloor":
            floor2 = state.floor
        else:
            floor2 = jnp.maximum(state.floor, jnp.max(old))
        wbf = jnp.where(ins[:, None], wb.reshape(B * R, L), jnp.uint32(0xFFFFFFFF))
        wef = jnp.where(ins[:, None], we.reshape(B * R, L), jnp.uint32(0xFFFFFFFF))
        hb2 = state.hb.at[pos].set(wbf)
        he2 = state.he.at[pos].set(wef)
        hver2 = state.hver.at[pos].set(
            jnp.where(ins, cv, jnp.asarray(-1, state.hver.dtype)))
        ptr2 = ((state.ptr + jnp.sum(ins)) % C).astype(jnp.int32)
        return cj.ConflictState(hb2, he2, hver2, ptr2, floor2), verdicts

    donate = (0,) if mode == "donate" else ()
    j = jax.jit(kernel, donate_argnums=donate)

    t0 = time.perf_counter()
    out = j(state, rb, re_, wb, we, sn, cv)
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0

    ts = []
    for _ in range(3):
        if mode == "donate":
            state = jax.device_put(cj.init_state(CAP, WIDTH, 0), dev)
        t0 = time.perf_counter()
        out = j(state, rb, re_, wb, we, sn, cv)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)

    # the tell: trivial op afterwards
    one = jax.device_put(jnp.float32(1.0), dev)
    jt = jax.jit(lambda x: x + 1)
    jt(one).block_until_ready()
    tt = []
    for _ in range(5):
        t0 = time.perf_counter()
        jt(one).block_until_ready()
        tt.append(time.perf_counter() - t0)

    print(f"MODE {mode:10s} kernel_med={np.median(ts)*1e3:8.3f}ms "
          f"trivial_after={np.median(tt)*1e3:8.3f}ms compile={compile_s:.1f}s",
          flush=True)


def main():
    if sys.argv[1] == "--all":
        for m in MODES:
            r = subprocess.run([sys.executable, "-m",
                                "foundationdb_tpu.bench.profile_poison", m],
                               capture_output=True, text=True, timeout=300)
            out = [l for l in r.stdout.splitlines() if l.startswith("MODE")]
            print(out[0] if out else f"MODE {m}: FAILED\n{r.stderr[-500:]}",
                  flush=True)
    else:
        run_mode(sys.argv[1])


if __name__ == "__main__":
    main()
