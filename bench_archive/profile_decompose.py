"""Decompose resolve_core: which stage costs ~67ms on the axon TPU?

Stages timed as separate jits with real encoded-batch inputs:
  A. _hist_check on window slice [B,R,W,L]
  B. window gather hb[idx] (dynamic gather mod ptr)
  C. intra-batch overlap matrix [B,R,B,R]
  D. inner lax.scan commit resolution (64 steps)
  E. ring scatter insert
  F. full resolve_core, no donation
  G. full resolve_core, donated
  H. resolve_core without the lax.cond (window=0 full ring)
  I. interaction: does running G slow down a subsequent trivial op?
"""

from __future__ import annotations

import time

import numpy as np


def timeit(fn, n=10, warmup=2):
    for _ in range(warmup):
        fn()
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts) * 1e3)


def main():
    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from jax import lax

    dev = jax.devices()[0]
    print(f"device: {dev}")

    from foundationdb_tpu.bench.workload import MakoWorkload
    from foundationdb_tpu.ops import conflict_jax as cj
    from foundationdb_tpu.ops.batch import encode_batch, TxnRequest
    from foundationdb_tpu.ops.backends import coalesce_ranges

    B, R, WIDTH, CAP, WIN = 64, 4, 32, 1 << 16, 4096
    wl = MakoWorkload(n_keys=1_000_000, seed=42)
    batches, versions = wl.make_batches(8, B)
    txns = [TxnRequest(coalesce_ranges(t.read_ranges, R),
                       coalesce_ranges(t.write_ranges, R), t.read_snapshot)
            for t in batches[0]]
    eb = encode_batch(txns, B, R, WIDTH)

    state = jax.device_put(cj.init_state(CAP, WIDTH, 0), dev)
    rb = jax.device_put(jnp.asarray(eb.read_begin), dev)
    re_ = jax.device_put(jnp.asarray(eb.read_end), dev)
    wb = jax.device_put(jnp.asarray(eb.write_begin), dev)
    we = jax.device_put(jnp.asarray(eb.write_end), dev)
    sn = jax.device_put(jnp.asarray(eb.read_snapshot), dev)
    cv = jnp.int64(versions[0])

    def bench(name, j, *a, **kw):
        out = j(*a, **kw)
        jax.block_until_ready(out)
        t = timeit(lambda: jax.block_until_ready(j(*a, **kw)))
        print(f"{name:44s} {t:9.3f}ms")
        return out

    L = rb.shape[-1]

    # A. hist check on a static window slice
    hbw = state.hb[:WIN]
    hew = state.he[:WIN]
    hvw = state.hver[:WIN]
    jA = jax.jit(lambda rb, re_, hb, he, hv, sn:
                 cj._hist_check(rb, re_, hb, he, hv, sn, WIDTH))
    bench("A hist_check [B,R,4096,L] static", jA, rb, re_, hbw, hew, hvw, sn)

    # B. dynamic window gather
    def gather(state):
        idx = (state.ptr - WIN + jnp.arange(WIN)) % CAP
        return state.hb[idx], state.he[idx], state.hver[idx]
    jB = jax.jit(gather)
    bench("B window gather hb[idx]", jB, state)

    # B2. gather + hist check fused
    def gh(state, rb, re_, sn):
        hb, he, hv = gather(state)
        return cj._hist_check(rb, re_, hb, he, hv, sn, WIDTH)
    bench("B2 gather+hist_check fused", jax.jit(gh), state, rb, re_, sn)

    # C. intra-batch matrix
    def intra(rb, re_, wb, we):
        m = cj._overlap(rb[:, :, None, None, :], re_[:, :, None, None, :],
                        wb[None, None, :, :, :], we[None, None, :, :, :], WIDTH)
        return m.any(axis=(1, 3)) & ~jnp.eye(B, dtype=bool)
    M = bench("C intra-batch [B,R,B,R] matrix", jax.jit(intra), rb, re_, wb, we)

    # D. inner scan
    hist = jnp.zeros(B, bool)
    valid = jnp.ones(B, bool)
    too_old = jnp.zeros(B, bool)
    def inner(hist, M, valid, too_old):
        def body(committed, i):
            conf = hist[i] | (committed & M[i]).any()
            commit_i = valid[i] & ~too_old[i] & ~conf
            return committed.at[i].set(commit_i), conf
        return lax.scan(body, jnp.zeros(B, bool), jnp.arange(B))
    bench("D inner scan 64 steps", jax.jit(inner), hist, M, valid, too_old)

    # E. ring scatter
    committed = jnp.ones(B, bool)
    def scat(state, wb, we, committed, cv):
        valid_w = wb[..., -1] != jnp.uint32(0xFFFFFFFF)
        ins = (committed[:, None] & valid_w).reshape(-1)
        k = jnp.cumsum(ins) - ins
        pos = jnp.where(ins, (state.ptr + k) % CAP, CAP).astype(jnp.int32)
        wbf = jnp.where(ins[:, None], wb.reshape(B * R, L), jnp.uint32(0xFFFFFFFF))
        hb2 = state.hb.at[pos].set(wbf)
        hver2 = state.hver.at[pos].set(jnp.where(ins, cv, jnp.int64(-1)))
        return hb2, hver2
    bench("E ring scatter", jax.jit(scat), state, wb, we, committed, cv)

    # F. full resolve_core, NOT donated
    jF = jax.jit(cj.resolve_core, static_argnames=("width", "window"))
    bench("F resolve_core no-donate window", jF, state, rb, re_, wb, we, sn, cv,
          width=WIDTH, window=WIN)
    bench("H resolve_core no-donate window=0", jF, state, rb, re_, wb, we, sn, cv,
          width=WIDTH, window=0)

    # G. donated (fresh state each call so donation is legal)
    states = [jax.device_put(cj.init_state(CAP, WIDTH, 0), dev) for _ in range(14)]
    jG = jax.jit(cj.resolve_core, static_argnames=("width", "window"),
                 donate_argnums=(0,))
    jax.block_until_ready(jG(states.pop(), rb, re_, wb, we, sn, cv,
                             width=WIDTH, window=WIN))
    ts = []
    for _ in range(12):
        st = states.pop()
        t0 = time.perf_counter()
        jax.block_until_ready(jG(st, rb, re_, wb, we, sn, cv,
                                 width=WIDTH, window=WIN))
        ts.append(time.perf_counter() - t0)
    print(f"{'G resolve_core donated':44s} {np.median(ts)*1e3:9.3f}ms")

    # I. trivial op after the heavy kernel
    one = jax.device_put(jnp.float32(1.0), dev)
    jt = jax.jit(lambda x: x + 1)
    print(f"{'I trivial after heavy':44s} "
          f"{timeit(lambda: jt(one).block_until_ready()):9.3f}ms")


if __name__ == "__main__":
    main()
