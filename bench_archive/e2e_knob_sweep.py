"""One-off r5: e2e knob sweep on the live tunnel — shallow concurrent
batches (post eager-D2H fix) vs the r4 deep-batch config."""
import asyncio
import sys
import time

import jax

jax.config.update("jax_enable_x64", True)

from foundationdb_tpu.bench.e2e import run_e2e
from foundationdb_tpu.runtime import Knobs

dev = jax.devices()[0]
print("device:", dev, file=sys.stderr)

CONFIGS = {
    "r4-deep": dict(COMMIT_BATCH_INTERVAL=0.05, GRV_BATCH_INTERVAL=0.01,
                    RESOLVER_BATCH_TXNS=256),
    "shallow-8ms": dict(COMMIT_BATCH_INTERVAL=0.008, GRV_BATCH_INTERVAL=0.005,
                        RESOLVER_BATCH_TXNS=64),
    "shallow-5ms": dict(COMMIT_BATCH_INTERVAL=0.005, GRV_BATCH_INTERVAL=0.005,
                        RESOLVER_BATCH_TXNS=64),
    # pinned single-chunk batches: every dispatch is the K=1 kernel, no
    # mid-measurement compiles for new K buckets
    "pinned-8ms": dict(COMMIT_BATCH_INTERVAL=0.008, GRV_BATCH_INTERVAL=0.005,
                       RESOLVER_BATCH_TXNS=64, COMMIT_BATCH_COUNT_LIMIT=64),
    "pinned-5ms": dict(COMMIT_BATCH_INTERVAL=0.005, GRV_BATCH_INTERVAL=0.005,
                       RESOLVER_BATCH_TXNS=64, COMMIT_BATCH_COUNT_LIMIT=64),
}

which = sys.argv[1] if len(sys.argv) > 1 else "shallow-8ms"
n_clients = int(sys.argv[2]) if len(sys.argv) > 2 else 256
cfg = CONFIGS[which]
knobs = Knobs().override(RESOLVER_CONFLICT_BACKEND="tpu", **cfg)
t0 = time.time()
out = asyncio.run(run_e2e(knobs, duration_s=5.0, n_clients=n_clients,
                          device=dev, warmup_s=12.0))
print(which, n_clients, {k: round(v, 1) if isinstance(v, float) else v
                         for k, v in out.items()})
