"""Round 6: the fixed kernel is fast standalone (poison5 b) but bench.py
still sees ~80ms/batch.  Bisect the bench's own path, fresh process per mode:

  m1  make_conflict_backend("tpu", device) -> backend.resolve serial x10
  m2  m1 but run the cpp backend phase first (bench order)
  m3  m1 but with bench's warmup-then-fresh-backend dance
  m4  raw JaxConflictSet.resolve_encoded (no EncodedConflictBackend wrap)
  m5  m1 but cv passed as python int each call (no jnp.int64 wrapper)
"""

from __future__ import annotations

import subprocess
import sys
import time

import numpy as np

MODES = ["m4", "m5", "m1", "m3", "m2"]


def run_mode(mode: str) -> None:
    import jax
    jax.config.update("jax_enable_x64", True)

    import jax.numpy as jnp

    dev = jax.devices()[0]
    one = jax.device_put(jnp.float32(1.0), dev)
    jt = jax.jit(lambda x: x + 1)
    jt(one).block_until_ready()

    from foundationdb_tpu.bench.workload import MakoWorkload
    from foundationdb_tpu.ops.backends import make_conflict_backend
    from foundationdb_tpu.runtime import Knobs

    wl = MakoWorkload(n_keys=100_000, seed=42)
    batches, versions = wl.make_batches(12, 64)
    warm_batches, warm_versions = wl.make_batches(
        8, 64, start_version=versions[-1] + 10_000_000)

    knobs = Knobs().override(
        RESOLVER_BATCH_TXNS=64, RESOLVER_RANGES_PER_TXN=4,
        CONFLICT_RING_CAPACITY=1 << 16, KEY_ENCODE_BYTES=32,
        RESOLVER_CONFLICT_BACKEND="tpu")

    if mode == "m2":
        cppb = make_conflict_backend(knobs.override(RESOLVER_CONFLICT_BACKEND="cpp"))
        for txns, v in zip(warm_batches, warm_versions):
            cppb.resolve(txns, v)

    if mode == "m4":
        from foundationdb_tpu.ops.conflict_jax import JaxConflictSet
        from foundationdb_tpu.ops.batch import encode_batch, TxnRequest
        from foundationdb_tpu.ops.backends import coalesce_ranges
        cs = JaxConflictSet(1 << 16, 32, device=dev, window=4096)
        ebs = []
        for txns in batches:
            txns = [TxnRequest(coalesce_ranges(t.read_ranges, 4),
                               coalesce_ranges(t.write_ranges, 4),
                               t.read_snapshot) for t in txns]
            ebs.append(encode_batch(txns, 64, 4, 32))
        # warm
        cs.resolve_encoded(ebs[0], versions[0] - 20_000_000)
        ts = []
        for eb, v in zip(ebs[1:], versions[1:]):
            t0 = time.perf_counter()
            cs.resolve_encoded(eb, v)
            ts.append(time.perf_counter() - t0)
    else:
        backend = make_conflict_backend(knobs, device=dev)
        for txns, v in zip(warm_batches, warm_versions):
            backend.resolve(txns, v)
        if mode == "m3":
            backend = make_conflict_backend(knobs, device=dev)
        ts = []
        for txns, v in zip(batches, versions):
            t0 = time.perf_counter()
            backend.resolve(txns, int(v) if mode == "m5" else v)
            ts.append(time.perf_counter() - t0)

    tt = []
    for _ in range(5):
        t0 = time.perf_counter()
        jt(one).block_until_ready()
        tt.append(time.perf_counter() - t0)

    print(f"MODE {mode:2s} first={ts[0]*1e3:9.1f}ms med_rest={np.median(ts[1:])*1e3:8.3f}ms "
          f"trivial_after={np.median(tt)*1e3:8.3f}ms", flush=True)


def main():
    if sys.argv[1] == "--all":
        for m in MODES:
            r = subprocess.run([sys.executable, "-m",
                                "foundationdb_tpu.bench.profile_poison6", m],
                               capture_output=True, text=True, timeout=300)
            out = [l for l in r.stdout.splitlines() if l.startswith("MODE")]
            print(out[0] if out else f"MODE {m}: FAILED\n{r.stderr[-600:]}",
                  flush=True)
    else:
        run_mode(sys.argv[1])


if __name__ == "__main__":
    main()
