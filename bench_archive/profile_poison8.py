"""Round 8: is device->host readback of small int8 arrays the poison?

Fresh process per mode; each does 10 timed d2h readbacks of a [64] array
of the given dtype (produced by a tiny jit), then times trivial dispatches.

  t_i32, t_bool, t_u8, t_i16, t_i8, t_i8big (4096), t_i8once (1 readback)
"""

from __future__ import annotations

import subprocess
import sys
import time

import numpy as np

MODES = ["t_i32", "t_bool", "t_u8", "t_i16", "t_i8", "t_i8big", "t_i8once"]


def run_mode(mode: str) -> None:
    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    dev = jax.devices()[0]
    one = jax.device_put(jnp.float32(1.0), dev)
    jt = jax.jit(lambda x: x + 1)
    jt(one).block_until_ready()

    dt = {"t_i32": jnp.int32, "t_bool": jnp.bool_, "t_u8": jnp.uint8,
          "t_i16": jnp.int16, "t_i8": jnp.int8, "t_i8big": jnp.int8,
          "t_i8once": jnp.int8}[mode]
    n = 4096 if mode == "t_i8big" else 64
    src = jax.device_put(jnp.zeros(n, jnp.int32), dev)
    f = jax.jit(lambda x: (x + 1).astype(dt))
    out = f(src)
    out.block_until_ready()

    reps = 1 if mode == "t_i8once" else 10
    ts = []
    for _ in range(reps):
        out = f(src)
        t0 = time.perf_counter()
        _ = np.asarray(out)
        ts.append(time.perf_counter() - t0)

    tt = []
    for _ in range(5):
        t0 = time.perf_counter()
        jt(one).block_until_ready()
        tt.append(time.perf_counter() - t0)

    print(f"MODE {mode:8s} d2h_med={np.median(ts)*1e3:8.3f}ms "
          f"trivial_after={np.median(tt)*1e3:8.3f}ms", flush=True)


def main():
    if sys.argv[1] == "--all":
        for m in MODES:
            r = subprocess.run([sys.executable, "-m",
                                "foundationdb_tpu.bench.profile_poison8", m],
                               capture_output=True, text=True, timeout=300)
            out = [l for l in r.stdout.splitlines() if l.startswith("MODE")]
            print(out[0] if out else f"MODE {m}: FAILED\n{r.stderr[-600:]}",
                  flush=True)
    else:
        run_mode(sys.argv[1])


if __name__ == "__main__":
    main()
