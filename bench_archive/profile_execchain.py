"""Split the grouped bench's ~1ms/batch into exec vs transfer.

Bench-identical config (R=2, K=64, CAP=2^18, window=4096, 16 groups).
  A. all inputs PRE-TRANSFERRED: chain 16 resolve_many_packed, block once
     -> pure exec chain
  B. transfer-only: device_put all 16 packed groups, block
  C. full interleaved (transfer k+1 while exec k) like the real backend
"""

from __future__ import annotations

import functools
import time

import numpy as np


def main():
    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    dev = jax.devices()[0]

    from foundationdb_tpu.bench.workload import MakoWorkload
    from foundationdb_tpu.ops import conflict_jax as cj
    from foundationdb_tpu.ops.batch import encode_batch

    B, R, WIDTH, K, NG = 64, 2, 32, 64, 16
    CAP = int(__import__('os').environ.get('CAP', 1 << 18))
    WIN = 4096
    wl = MakoWorkload(n_keys=1_000_000, seed=42)
    batches, versions = wl.make_batches(NG * K, B)
    L = 9

    # pack groups host-side
    n = K * B * R * L
    packs = []
    for g in range(NG):
        ebs = [encode_batch(b, B, R, WIDTH) for b in batches[g * K:(g + 1) * K]]
        pu32 = np.empty(4 * n, dtype=np.uint32)
        for f, field in enumerate(("read_begin", "read_end", "write_begin", "write_end")):
            dst = pu32[f * n:(f + 1) * n].reshape(K, B, R, L)
            for i, e in enumerate(ebs):
                dst[i] = getattr(e, field)
        pi64 = np.empty(K * B + K, dtype=np.int64)
        for i, e in enumerate(ebs):
            pi64[i * B:(i + 1) * B] = e.read_snapshot
        pi64[K * B:] = versions[g * K:(g + 1) * K]
        packs.append((pu32, pi64))
    print(f"group payload: {(packs[0][0].nbytes + packs[0][1].nbytes)/1e6:.2f}MB")

    # degrade session
    one = jax.device_put(jnp.float32(1.0), dev)
    jt = jax.jit(lambda x: x + 1)
    _ = np.asarray(jt(one))

    shape = (K, B, R, L)
    st = jax.device_put(cj.init_state(CAP, WIDTH, 0), dev)
    # compile
    d0 = (jax.device_put(packs[0][0], dev), jax.device_put(packs[0][1], dev))
    st, v = cj.resolve_many_packed(st, *d0, shape=shape, width=WIDTH, window=WIN)
    v.block_until_ready()

    # B. transfer only
    t0 = time.perf_counter()
    dev_packs = [(jax.device_put(a, dev), jax.device_put(b, dev))
                 for a, b in packs]
    jax.block_until_ready(dev_packs)
    t_xfer = time.perf_counter() - t0
    print(f"B. transfer 16 groups:   {t_xfer*1e3:7.0f}ms "
          f"({NG*(packs[0][0].nbytes+packs[0][1].nbytes)/t_xfer/1e6:.0f} MB/s)")

    # A. pure exec chain on pre-device inputs
    t0 = time.perf_counter()
    vs = []
    for dp in dev_packs:
        st, v = cj.resolve_many_packed(st, *dp, shape=shape, width=WIDTH,
                                       window=WIN)
        vs.append(v)
    jax.block_until_ready(vs)
    t_exec = time.perf_counter() - t0
    print(f"A. exec chain 16 groups: {t_exec*1e3:7.0f}ms "
          f"({t_exec/NG/K*1e3:.3f} ms/batch)")

    # C. interleaved like the backend
    st = jax.device_put(cj.init_state(CAP, WIDTH, 0), dev)
    t0 = time.perf_counter()
    vs = []
    for a, b in packs:
        da, db = jax.device_put(a, dev), jax.device_put(b, dev)
        st, v = cj.resolve_many_packed(st, da, db, shape=shape, width=WIDTH,
                                       window=WIN)
        try:
            v.copy_to_host_async()
        except Exception:
            pass
        vs.append(v)
    hosts = [np.asarray(v) for v in vs]
    t_full = time.perf_counter() - t0
    txns = NG * K * B
    print(f"C. interleaved full:     {t_full*1e3:7.0f}ms "
          f"-> {txns/t_full/1000:.0f}k txns/s")


if __name__ == "__main__":
    main()
