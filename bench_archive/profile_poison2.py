"""Round 2 of poison bisection (fresh process per mode).

profile_poison showed the ~70ms session poison occurs even with
no scatter / no int64 / no cond / CAP=1024.  Candidates left: the
combination hist+intra+scan, or simply *compiling anything slow*.

Modes (all CAP=1024, window=0 unless said):
  compileonly — lower+compile the full kernel, NEVER execute; then trivial
  bigcompile  — compile+run an unrelated 5s-compile fn (chain of matmuls)
  p1 hist     — _hist_check only
  p2 intra    — overlap matrix only
  p3 histintra— both, no scan
  p4 scan     — hist+intra+lax.scan(committed)
  p5 verdict  — p4 + int8 verdict chain (== nostate@smallcap)
"""

from __future__ import annotations

import subprocess
import sys
import time

import numpy as np

MODES = ["compileonly", "bigcompile", "p1", "p2", "p3", "p4", "p5"]


def run_mode(mode: str) -> None:
    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from jax import lax

    dev = jax.devices()[0]

    from foundationdb_tpu.bench.workload import MakoWorkload
    from foundationdb_tpu.ops import conflict_jax as cj
    from foundationdb_tpu.ops.batch import encode_batch, TxnRequest
    from foundationdb_tpu.ops.backends import coalesce_ranges

    B, R, WIDTH, CAP = 64, 4, 32, 1024
    wl = MakoWorkload(n_keys=1_000_000, seed=42)
    batches, versions = wl.make_batches(4, B)
    txns = [TxnRequest(coalesce_ranges(t.read_ranges, R),
                       coalesce_ranges(t.write_ranges, R), t.read_snapshot)
            for t in batches[0]]
    eb = encode_batch(txns, B, R, WIDTH)

    state = jax.device_put(cj.init_state(CAP, WIDTH, 0), dev)
    rb = jax.device_put(jnp.asarray(eb.read_begin), dev)
    re_ = jax.device_put(jnp.asarray(eb.read_end), dev)
    wb = jax.device_put(jnp.asarray(eb.write_begin), dev)
    we = jax.device_put(jnp.asarray(eb.write_end), dev)
    sn = jax.device_put(jnp.asarray(eb.read_snapshot), dev)

    hb, he, hver = state.hb[:CAP], state.he[:CAP], state.hver[:CAP]
    too_old = sn < state.floor
    valid = sn >= 0

    def khist(rb, re_, hb, he, hver, sn):
        return cj._hist_check(rb, re_, hb, he, hver, sn, WIDTH)

    def kintra(rb, re_, wb, we):
        m = cj._overlap(rb[:, :, None, None, :], re_[:, :, None, None, :],
                        wb[None, None, :, :, :], we[None, None, :, :, :], WIDTH)
        return m.any(axis=(1, 3)) & ~jnp.eye(B, dtype=bool)

    def kboth(rb, re_, wb, we, hb, he, hver, sn):
        return khist(rb, re_, hb, he, hver, sn), kintra(rb, re_, wb, we)

    def kscan(rb, re_, wb, we, hb, he, hver, sn, valid, too_old):
        hist, M = kboth(rb, re_, wb, we, hb, he, hver, sn)
        def body(committed, i):
            conf = hist[i] | (committed & M[i]).any()
            return committed.at[i].set(valid[i] & ~too_old[i] & ~conf), conf
        return lax.scan(body, jnp.zeros(B, bool), jnp.arange(B))

    def kverd(rb, re_, wb, we, hb, he, hver, sn, valid, too_old):
        hist, M = kboth(rb, re_, wb, we, hb, he, hver, sn)
        def body(committed, i):
            conf = hist[i] | (committed & M[i]).any()
            commit_i = valid[i] & ~too_old[i] & ~conf
            verdict = jnp.where(~valid[i], cj.COMMITTED,
                                jnp.where(too_old[i], cj.TOO_OLD,
                                          jnp.where(conf, cj.CONFLICT, cj.COMMITTED)))
            return committed.at[i].set(commit_i), verdict
        return lax.scan(body, jnp.zeros(B, bool), jnp.arange(B))

    compile_s = 0.0
    if mode == "compileonly":
        t0 = time.perf_counter()
        jax.jit(kverd).lower(rb, re_, wb, we, hb, he, hver, sn,
                             valid, too_old).compile()
        compile_s = time.perf_counter() - t0
        ts = [0.0]
    elif mode == "bigcompile":
        def chain(a):
            for _ in range(200):
                a = jnp.tanh(a @ a) + a
            return a
        a = jnp.ones((256, 256), jnp.float32)
        jc = jax.jit(chain)
        t0 = time.perf_counter()
        jc(a).block_until_ready()
        compile_s = time.perf_counter() - t0
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            jc(a).block_until_ready()
            ts.append(time.perf_counter() - t0)
    else:
        fn, arga = {
            "p1": (khist, (rb, re_, hb, he, hver, sn)),
            "p2": (kintra, (rb, re_, wb, we)),
            "p3": (kboth, (rb, re_, wb, we, hb, he, hver, sn)),
            "p4": (kscan, (rb, re_, wb, we, hb, he, hver, sn, valid, too_old)),
            "p5": (kverd, (rb, re_, wb, we, hb, he, hver, sn, valid, too_old)),
        }[mode]
        j = jax.jit(fn)
        t0 = time.perf_counter()
        jax.block_until_ready(j(*arga))
        compile_s = time.perf_counter() - t0
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(j(*arga))
            ts.append(time.perf_counter() - t0)

    one = jax.device_put(jnp.float32(1.0), dev)
    jt = jax.jit(lambda x: x + 1)
    jt(one).block_until_ready()
    tt = []
    for _ in range(5):
        t0 = time.perf_counter()
        jt(one).block_until_ready()
        tt.append(time.perf_counter() - t0)

    print(f"MODE {mode:12s} kernel_med={np.median(ts)*1e3:8.3f}ms "
          f"trivial_after={np.median(tt)*1e3:8.3f}ms compile={compile_s:.1f}s",
          flush=True)


def main():
    if sys.argv[1] == "--all":
        for m in MODES:
            r = subprocess.run([sys.executable, "-m",
                                "foundationdb_tpu.bench.profile_poison2", m],
                               capture_output=True, text=True, timeout=300)
            out = [l for l in r.stdout.splitlines() if l.startswith("MODE")]
            print(out[0] if out else f"MODE {m}: FAILED\n{r.stderr[-500:]}",
                  flush=True)
    else:
        run_mode(sys.argv[1])


if __name__ == "__main__":
    main()
