"""One-off: measure tunnel RTT, sync cost, and pipelined sync throughput.

Informs the r5 e2e redesign: how much does each device->host verdict sync
cost when N dispatches are in flight?  Run on the live axon tunnel.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)
dev = jax.devices()[0]
print("device:", dev, dev.platform)

# --- 1. bare RTT: tiny transfer + sync, repeated
xs = []
for _ in range(12):
    t0 = time.perf_counter()
    np.asarray(jax.device_put(np.int32(1), dev))
    xs.append(time.perf_counter() - t0)
print(f"tiny put+get RTT: min {min(xs)*1e3:.1f}ms p50 {sorted(xs)[len(xs)//2]*1e3:.1f}ms")

# --- 2. jitted nop dispatch + sync (dispatch->result readback)
@jax.jit
def nop(x):
    return x + 1

x = jax.device_put(jnp.zeros((64,), jnp.int32), dev)
nop(x).block_until_ready()      # compile
xs = []
for _ in range(12):
    t0 = time.perf_counter()
    np.asarray(nop(x))
    xs.append(time.perf_counter() - t0)
print(f"nop dispatch+sync: min {min(xs)*1e3:.1f}ms")

# --- 3. pipelined syncs: N dispatches queued, sync each in order
for n in (8, 32, 128):
    t0 = time.perf_counter()
    outs = [nop(x) for _ in range(n)]
    for o in outs:
        np.asarray(o)
    el = time.perf_counter() - t0
    print(f"pipelined x{n}: total {el*1e3:.1f}ms -> {el/n*1e3:.2f}ms/sync")

# --- 4. chained compute, single sync (device compute isolation)
@jax.jit
def chain(x):
    for _ in range(4):
        x = x * 2 + 1
    return x

big = jax.device_put(jnp.zeros((1 << 14,), jnp.int64), dev)
chain(big).block_until_ready()
for n in (32, 128):
    t0 = time.perf_counter()
    y = big
    for _ in range(n):
        y = chain(y)
    y.block_until_ready()
    el = time.perf_counter() - t0
    print(f"chained x{n} single sync: total {el*1e3:.1f}ms -> {el/n*1e3:.3f}ms/dispatch")

# --- 5. H2D transfer bandwidth-ish: 1MB put + tiny compute + sync
mb = np.zeros((1 << 18,), np.int32)  # 1MiB
xs = []
for _ in range(6):
    t0 = time.perf_counter()
    nop_big = jax.device_put(mb, dev)
    nop_big.block_until_ready()
    xs.append(time.perf_counter() - t0)
print(f"1MiB device_put: min {min(xs)*1e3:.1f}ms")
