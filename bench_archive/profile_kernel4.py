"""Can the inner greedy commit-resolution go faster than 0.137ms/batch?

Variants (inside K=64 scan, degraded mode):
  v_vec   current: lax.scan over [64]-bool vector carry (baseline)
  v_bits  fully-unrolled scalar bitmask chain: committed packed in 2 uint32
          scalars, M rows packed [64] uint32 lo/hi, 64 static steps
  v_fori  same bitmask but lax.fori_loop with dynamic row index
  + FULL kernel with v_bits inner
"""

from __future__ import annotations

import functools
import time

import numpy as np


def main():
    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from jax import lax

    dev = jax.devices()[0]
    B, K = 64, 64

    one = jax.device_put(jnp.float32(1.0), dev)
    jt = jax.jit(lambda x: x + 1)
    _ = np.asarray(jt(one))
    rtts = []
    for _ in range(5):
        t0 = time.perf_counter()
        jt(one).block_until_ready()
        rtts.append(time.perf_counter() - t0)
    rtt = float(np.median(rtts))
    print(f"RTT: {rtt*1e3:.1f}ms")

    rng = np.random.default_rng(0)
    Ms = jax.device_put(jnp.asarray(rng.random((K, B, B)) < 0.05), dev)
    hists = jax.device_put(jnp.asarray(rng.random((K, B)) < 0.2), dev)
    valids = jax.device_put(jnp.ones((K, B), bool), dev)
    too_olds = jax.device_put(jnp.zeros((K, B), bool), dev)

    def run(name, body, xs):
        @jax.jit
        def f(xs):
            return lax.scan(body, jnp.int32(0), xs)
        _, y = f(xs)
        jax.block_until_ready(y)
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            _, y = f(xs)
            jax.block_until_ready(y)
            ts.append(time.perf_counter() - t0)
        t = float(np.median(ts))
        print(f"{name:22s} {t*1e3:8.1f}ms exec~{(t-rtt)/K*1e3:7.4f}ms/batch")
        return np.asarray(y)

    # baseline vector scan
    def v_vec(carry, x):
        M, hist, valid, too_old = x
        def ib(committed, i):
            conf = hist[i] | (committed & M[i]).any()
            return committed.at[i].set(valid[i] & ~too_old[i] & ~conf), conf
        committed, conf = lax.scan(ib, jnp.zeros(B, bool), jnp.arange(B), unroll=8)
        return carry, conf
    ref = run("v_vec (scan u8)", v_vec, (Ms, hists, valids, too_olds))

    # packed scalar bitmask, fully unrolled
    pw_lo = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))

    def pack64(bits):  # [.., 64] bool -> (lo, hi) uint32
        lo = jnp.sum(bits[..., :32].astype(jnp.uint32) * pw_lo, axis=-1)
        hi = jnp.sum(bits[..., 32:].astype(jnp.uint32) * pw_lo, axis=-1)
        return lo, hi

    def v_bits(carry, x):
        M, hist, valid, too_old = x
        Mlo, Mhi = pack64(M)                     # [64] uint32 each
        ok = valid & ~too_old
        c_lo = jnp.uint32(0)
        c_hi = jnp.uint32(0)
        confs = []
        for i in range(B):
            hit = (c_lo & Mlo[i]) | (c_hi & Mhi[i])
            conf = hist[i] | (hit != 0)
            commit = ok[i] & ~conf
            if i < 32:
                c_lo = c_lo | jnp.where(commit, jnp.uint32(1 << i), jnp.uint32(0))
            else:
                c_hi = c_hi | jnp.where(commit, jnp.uint32(1 << (i - 32)), jnp.uint32(0))
            confs.append(conf)
        return carry, jnp.stack(confs)
    out = run("v_bits (unrolled)", v_bits, (Ms, hists, valids, too_olds))
    print("  parity v_bits:", bool((out == ref).all()))

    # fori_loop bitmask
    def v_fori(carry, x):
        M, hist, valid, too_old = x
        Mlo, Mhi = pack64(M)
        ok = valid & ~too_old

        def ib(i, st):
            c_lo, c_hi, confbits_lo, confbits_hi = st
            hit = (c_lo & Mlo[i]) | (c_hi & Mhi[i])
            conf = hist[i] | (hit != 0)
            commit = ok[i] & ~conf
            ilt = (i < 32)
            sh_lo = jnp.where(ilt, i, 0).astype(jnp.uint32)
            sh_hi = jnp.where(ilt, 0, i - 32).astype(jnp.uint32)
            bit_lo = jnp.where(ilt, jnp.uint32(1) << sh_lo, jnp.uint32(0))
            bit_hi = jnp.where(ilt, jnp.uint32(0), jnp.uint32(1) << sh_hi)
            c_lo = c_lo | jnp.where(commit, bit_lo, jnp.uint32(0))
            c_hi = c_hi | jnp.where(commit, bit_hi, jnp.uint32(0))
            confbits_lo = confbits_lo | jnp.where(conf, bit_lo, jnp.uint32(0))
            confbits_hi = confbits_hi | jnp.where(conf, bit_hi, jnp.uint32(0))
            return c_lo, c_hi, confbits_lo, confbits_hi

        z = jnp.uint32(0)
        _, _, cb_lo, cb_hi = lax.fori_loop(0, B, ib, (z, z, z, z))
        conf = jnp.concatenate([
            (cb_lo >> jnp.arange(32, dtype=jnp.uint32)) & 1,
            (cb_hi >> jnp.arange(32, dtype=jnp.uint32)) & 1]).astype(bool)
        return carry, conf
    out = run("v_fori (bitmask)", v_fori, (Ms, hists, valids, too_olds))
    print("  parity v_fori:", bool((out == ref).all()))


if __name__ == "__main__":
    main()
