"""Prototype the layout-optimized fused resolver kernel and measure exec.

Design under test (vs current conflict_jax.resolve_core):
  - ring stored lane-major [L, 2C] (doubled so any window is contiguous);
    scatter writes each committed range twice (pos, pos+C)
  - window read = lax.dynamic_slice (no gather)
  - hist compare loops L in Python (8 unrolled [B,R,W]-shaped ops, W minor)
  - fused scan over K batches, per-batch commit versions
  - inner commit-resolution scan with unroll
Reports exec/batch in degraded mode for K in {16, 64}, unroll in {1, 8, 64}.
"""

from __future__ import annotations

import functools
import time

import numpy as np


def main():
    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from jax import lax

    dev = jax.devices()[0]

    from foundationdb_tpu.bench.workload import MakoWorkload
    from foundationdb_tpu.ops.batch import encode_batch, TxnRequest
    from foundationdb_tpu.ops.backends import coalesce_ranges

    B, R, WIDTH, CAP, WIN = 64, 4, 32, 1 << 16, 4096
    wl = MakoWorkload(n_keys=1_000_000, seed=42)
    batches, versions = wl.make_batches(64, B)

    def enc(txns):
        txns = [TxnRequest(coalesce_ranges(t.read_ranges, R),
                           coalesce_ranges(t.write_ranges, R),
                           t.read_snapshot) for t in txns]
        return encode_batch(txns, B, R, WIDTH)

    ebs = [enc(t) for t in batches]
    L = ebs[0].read_begin.shape[-1]

    one = jax.device_put(jnp.float32(1.0), dev)
    jt = jax.jit(lambda x: x + 1)
    _ = np.asarray(jt(one))
    rtts = []
    for _ in range(5):
        t0 = time.perf_counter()
        jt(one).block_until_ready()
        rtts.append(time.perf_counter() - t0)
    rtt = float(np.median(rtts))
    print(f"RTT: {rtt*1e3:.1f}ms  (L={L})")

    # --- state: hbT/heT [L, 2C] uint32, hver [2C] int64, ptr, floor
    def init():
        return (jnp.full((L, 2 * CAP), 0xFFFFFFFF, jnp.uint32),
                jnp.full((L, 2 * CAP), 0xFFFFFFFF, jnp.uint32),
                jnp.full((2 * CAP,), -1, jnp.int64),
                jnp.int32(0), jnp.int64(0))

    def lex_lt_T(a, bT, W):
        # a [B,R,L] vs bT [L,W] -> strict lex <  [B,R,W]
        lt = jnp.zeros((a.shape[0], a.shape[1], W), bool)
        eq = jnp.ones_like(lt)
        for l in range(L):
            al = a[:, :, l:l + 1]
            bl = bT[l][None, None, :]
            lt = lt | (eq & (al < bl))
            eq = eq & (al == bl)
        return lt, eq

    def possibly_lt_T(a, bT, W, width):
        lt, eq = lex_lt_T(a, bT, W)
        both_trunc = (a[:, :, -1:] == width + 1) & (bT[-1][None, None, :] == width + 1)
        return lt | (eq & both_trunc)

    def overlap_T(ab, ae, bbT, beT, W, width):
        # interval overlap of read [ab,ae] vs history [bbT,beT]
        return possibly_lt_T(ab, beT, W, width) & possibly_lt_T_rev(bbT, ae, W, width)

    def possibly_lt_T_rev(aT, b, W, width):
        # aT [L,W] < b [B,R,L] -> [B,R,W]
        lt = jnp.zeros((b.shape[0], b.shape[1], W), bool)
        eq = jnp.ones_like(lt)
        for l in range(L):
            al = aT[l][None, None, :]
            bl = b[:, :, l:l + 1]
            lt = lt | (eq & (al < bl))
            eq = eq & (al == bl)
        both_trunc = (aT[-1][None, None, :] == width + 1) & (b[:, :, -1:] == width + 1)
        return lt | (eq & both_trunc)

    def make_many(K, unroll):
        def body(st, x):
            hbT, heT, hver, ptr, floor = st
            rb, re_, wb, we, sn, cv = x
            too_old = sn < floor
            valid = sn >= 0
            start = ((ptr - WIN) % CAP).astype(jnp.int32)
            hbW = lax.dynamic_slice(hbT, (jnp.int32(0), start), (L, WIN))
            heW = lax.dynamic_slice(heT, (jnp.int32(0), start), (L, WIN))
            hvW = lax.dynamic_slice(hver, (start,), (WIN,))
            v_edge = hver[(ptr - WIN - 1) % CAP]
            fast_ok = jnp.all(~valid | too_old | (sn >= v_edge))

            def hist_of(hbT_, heT_, hv_, W):
                hit = overlap_T(rb, re_, hbT_, heT_, W, WIDTH)
                newer = hv_[None, None, :] > sn[:, None, None]
                return (hit & newer).any(axis=(1, 2))

            hist = lax.cond(
                fast_ok,
                lambda _: hist_of(hbW, heW, hvW, WIN),
                lambda _: hist_of(hbT[:, :CAP], heT[:, :CAP], hver[:CAP], CAP),
                None)

            # intra-batch matrix via transposed writes [L, B*R]
            wbT = wb.reshape(B * R, L).T
            weT = we.reshape(B * R, L).T
            hitM = overlap_T(rb, re_, wbT, weT, B * R, WIDTH)  # [B,R,B*R]
            M = hitM.reshape(B, R, B, R).any(axis=(1, 3)) & ~jnp.eye(B, dtype=bool)

            def ibody(committed, i):
                conf = hist[i] | (committed & M[i]).any()
                return committed.at[i].set(valid[i] & ~too_old[i] & ~conf), conf
            committed, conf = lax.scan(ibody, jnp.zeros(B, bool), jnp.arange(B),
                                       unroll=unroll)
            verdicts = jnp.where(~valid, np.int8(0),
                                 jnp.where(too_old, np.int8(2),
                                           jnp.where(conf, np.int8(1), np.int8(0))))

            valid_w = wb[..., -1] != jnp.uint32(0xFFFFFFFF)
            ins = (committed[:, None] & valid_w).reshape(-1)
            k = jnp.cumsum(ins) - ins
            pos = jnp.where(ins, (ptr + k) % CAP, 2 * CAP - 1).astype(jnp.int32)
            old = jnp.where(ins, hver[pos], jnp.int64(-1))
            floor2 = jnp.maximum(floor, jnp.max(old))
            wbf = jnp.where(ins[:, None], wb.reshape(B * R, L), jnp.uint32(0xFFFFFFFF)).T
            wef = jnp.where(ins[:, None], we.reshape(B * R, L), jnp.uint32(0xFFFFFFFF)).T
            pos2 = jnp.where(ins, pos + CAP, 2 * CAP - 1).astype(jnp.int32)
            cvv = jnp.where(ins, cv, jnp.int64(-1))
            hbT2 = hbT.at[:, pos].set(wbf).at[:, pos2].set(wbf)
            heT2 = heT.at[:, pos].set(wef).at[:, pos2].set(wef)
            hver2 = hver.at[pos].set(cvv).at[pos2].set(cvv)
            ptr2 = ((ptr + jnp.sum(ins)) % CAP).astype(jnp.int32)
            return (hbT2, heT2, hver2, ptr2, floor2), verdicts

        @functools.partial(jax.jit, donate_argnums=(0,))
        def many(st, rb, re_, wb, we, sn, cvs):
            return lax.scan(body, st, (rb, re_, wb, we, sn, cvs))
        return many

    for K in (16, 64):
        ks = ebs[:K]
        rb = jax.device_put(jnp.asarray(np.stack([e.read_begin for e in ks])), dev)
        re_ = jax.device_put(jnp.asarray(np.stack([e.read_end for e in ks])), dev)
        wb = jax.device_put(jnp.asarray(np.stack([e.write_begin for e in ks])), dev)
        we = jax.device_put(jnp.asarray(np.stack([e.write_end for e in ks])), dev)
        sn = jax.device_put(jnp.asarray(np.stack([e.read_snapshot for e in ks])), dev)
        cvs = jax.device_put(jnp.asarray(np.array(versions[:K], dtype=np.int64)), dev)
        for unroll in (1, 8, 64):
            many = make_many(K, unroll)
            st = jax.device_put(init(), dev)
            t0 = time.perf_counter()
            st, v = many(st, rb, re_, wb, we, sn, cvs)
            v.block_until_ready()
            comp = time.perf_counter() - t0
            ts = []
            for _ in range(3):
                t0 = time.perf_counter()
                st, v = many(st, rb, re_, wb, we, sn, cvs)
                v.block_until_ready()
                ts.append(time.perf_counter() - t0)
            t = float(np.median(ts))
            ex = (t - rtt) / K * 1e3
            print(f"K={K:3d} unroll={unroll:2d}: {t*1e3:8.1f}ms exec~{ex:6.3f}ms/batch "
                  f"ceiling~{64/ex:7.1f}k txns/s (compile {comp:.0f}s)")


if __name__ == "__main__":
    main()
