"""One-off r5: kernel-stage config sweep on the live tunnel with the
canonical (hot/cold) ring.  Sweeps ring capacity / window / GROUP /
INFLIGHT around the r4 operating point."""
import sys
import time

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

from bench import measure_grouped
from foundationdb_tpu.bench.workload import MakoWorkload
from foundationdb_tpu.ops.backends import make_conflict_backend
from foundationdb_tpu.ops.batch import wire_from_txns
from foundationdb_tpu.runtime import Knobs

dev = jax.devices()[0]
N_BATCHES = 4096

wl = MakoWorkload(n_keys=1_000_000, seed=42)
batches, versions = wl.make_batches(N_BATCHES, 64)
wires = [wire_from_txns(b) for b in batches]

CONFIGS = [
    # (cap_pow, window, group, inflight)
    (14, 1024, 128, 8),      # r4 operating point
    (16, 1024, 128, 8),      # big ring now affordable?
    (14, 512, 128, 8),
    (14, 1024, 256, 8),
    (16, 1024, 256, 8),
    (14, 2048, 128, 8),
    (14, 1024, 128, 16),
]
for cap_pow, window, group, inflight in CONFIGS:
    knobs = Knobs().override(
        RESOLVER_CONFLICT_BACKEND="tpu", RESOLVER_BATCH_TXNS=64,
        RESOLVER_RANGES_PER_TXN=2, CONFLICT_RING_CAPACITY=1 << cap_pow,
        KEY_ENCODE_BYTES=32, CONFLICT_WINDOW_SLOTS=window)
    backend = make_conflict_backend(knobs, device=dev)
    warm_b, warm_v = wl.make_batches(4 + group, 64,
                                     start_version=versions[-1] + 10_000_000)
    warm_w = [wire_from_txns(b) for b in warm_b]
    for txns, v in zip(warm_b[:4], warm_v[:4]):
        backend.resolve(txns, v)
    measure_grouped(backend, warm_w[4:], warm_v[4:], group=group,
                    inflight=inflight)
    if backend.reset_ring(0):
        measure_grouped(backend, wires, versions, group=group,
                        inflight=inflight)
        backend.reset_ring(0)
    best = None
    for _ in range(3):
        el, verdicts = measure_grouped(backend, wires, versions, group=group,
                                       inflight=inflight)
        if best is None or el < best[0]:
            best = (el, verdicts)
        backend.reset_ring(0)
    el, verdicts = best
    flat = np.array([x for vs in verdicts for x in vs])
    commits = int((flat == 0).sum())
    print(f"cap=2^{cap_pow} win={window} K={group} if={inflight}: "
          f"{el:.3f}s, {commits/el:,.0f} commits/s, "
          f"{el/N_BATCHES*1e6:.0f}us/batch", flush=True)
