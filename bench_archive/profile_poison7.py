"""Round 7: only input prep differs between fast (poison5-b) and slow (m4).

All modes loop cj.resolve_step 10x, fresh process each, varying input prep:
  s1  all inputs device_put once; cv jnp.int64 once          (expect fast)
  s2  arrays once; cv = jnp.int64(v) fresh per call
  s3  arrays jnp.asarray per call; cv once
  s4  arrays jax.device_put(.., dev) per call; cv once
  s5  arrays jnp.asarray + cv jnp.int64 per call             (backend path)
  s6  like s5 but int(v) -> np.int64 host scalar passed directly (no wrap)
"""

from __future__ import annotations

import subprocess
import sys
import time

import numpy as np

MODES = ["s1", "s2", "s3", "s4", "s5", "s6"]


def run_mode(mode: str) -> None:
    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    dev = jax.devices()[0]
    one = jax.device_put(jnp.float32(1.0), dev)
    jt = jax.jit(lambda x: x + 1)
    jt(one).block_until_ready()

    from foundationdb_tpu.bench.workload import MakoWorkload
    from foundationdb_tpu.ops import conflict_jax as cj
    from foundationdb_tpu.ops.batch import encode_batch, TxnRequest
    from foundationdb_tpu.ops.backends import coalesce_ranges

    B, R, WIDTH, CAP, WIN = 64, 4, 32, 1 << 16, 4096
    wl = MakoWorkload(n_keys=100_000, seed=42)
    batches, versions = wl.make_batches(12, B)
    txns = [TxnRequest(coalesce_ranges(t.read_ranges, R),
                       coalesce_ranges(t.write_ranges, R), t.read_snapshot)
            for t in batches[0]]
    eb = encode_batch(txns, B, R, WIDTH)

    st = jax.device_put(cj.init_state(CAP, WIDTH, 0), dev)
    rb0 = jax.device_put(jnp.asarray(eb.read_begin), dev)
    re0 = jax.device_put(jnp.asarray(eb.read_end), dev)
    wb0 = jax.device_put(jnp.asarray(eb.write_begin), dev)
    we0 = jax.device_put(jnp.asarray(eb.write_end), dev)
    sn0 = jax.device_put(jnp.asarray(eb.read_snapshot), dev)
    cv0 = jnp.int64(versions[0])

    # warm compile
    st, v = cj.resolve_step(st, rb0, re0, wb0, we0, sn0, cv0,
                            width=WIDTH, window=WIN)
    v.block_until_ready()

    ts = []
    for i in range(1, 11):
        t0 = time.perf_counter()
        if mode == "s1":
            a = (rb0, re0, wb0, we0, sn0, cv0)
        elif mode == "s2":
            a = (rb0, re0, wb0, we0, sn0, jnp.int64(versions[i]))
        elif mode == "s3":
            a = (jnp.asarray(eb.read_begin), jnp.asarray(eb.read_end),
                 jnp.asarray(eb.write_begin), jnp.asarray(eb.write_end),
                 jnp.asarray(eb.read_snapshot), cv0)
        elif mode == "s4":
            a = (jax.device_put(eb.read_begin, dev), jax.device_put(eb.read_end, dev),
                 jax.device_put(eb.write_begin, dev), jax.device_put(eb.write_end, dev),
                 jax.device_put(eb.read_snapshot, dev), cv0)
        elif mode == "s5":
            a = (jnp.asarray(eb.read_begin), jnp.asarray(eb.read_end),
                 jnp.asarray(eb.write_begin), jnp.asarray(eb.write_end),
                 jnp.asarray(eb.read_snapshot), jnp.int64(versions[i]))
        else:  # s6
            a = (rb0, re0, wb0, we0, sn0, np.int64(versions[i]))
        st, v = cj.resolve_step(st, *a, width=WIDTH, window=WIN)
        v.block_until_ready()
        ts.append(time.perf_counter() - t0)

    tt = []
    for _ in range(5):
        t0 = time.perf_counter()
        jt(one).block_until_ready()
        tt.append(time.perf_counter() - t0)

    print(f"MODE {mode:2s} med={np.median(ts)*1e3:8.3f}ms "
          f"trivial_after={np.median(tt)*1e3:8.3f}ms", flush=True)


def main():
    if sys.argv[1] == "--all":
        for m in MODES:
            r = subprocess.run([sys.executable, "-m",
                                "foundationdb_tpu.bench.profile_poison7", m],
                               capture_output=True, text=True, timeout=300)
            out = [l for l in r.stdout.splitlines() if l.startswith("MODE")]
            print(out[0] if out else f"MODE {m}: FAILED\n{r.stderr[-600:]}",
                  flush=True)
    else:
        run_mode(sys.argv[1])


if __name__ == "__main__":
    main()
