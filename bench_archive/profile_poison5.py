"""Round 5: pin down why the PATCHED resolve_core is still slow.

Suspect: the module-level concrete int8 device arrays (COMMITTED/CONFLICT/
TOO_OLD) captured as jit constants.  In-trace-created jnp.int8(0) was fast
(poison3 v2/v5), module constants slow (poison4 r4/r5).

Fresh process per mode, run fast-expected first:
  d  inline patched kernel, in-trace jnp.int8 constants (v5 replica control)
  b  cj.resolve_step but constants monkeypatched to np.int8 HOST scalars
  c  cj.resolve_step but constants monkeypatched to np.int32 host scalars
  a  cj.resolve_step as-is (module jnp.int8 device constants) — expect slow
"""

from __future__ import annotations

import subprocess
import sys
import time

import numpy as np

MODES = ["d", "b", "c", "a"]


def run_mode(mode: str) -> None:
    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from jax import lax

    dev = jax.devices()[0]

    # trivial-op baseline BEFORE anything heavy
    one = jax.device_put(jnp.float32(1.0), dev)
    jt = jax.jit(lambda x: x + 1)
    jt(one).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(5):
        jt(one).block_until_ready()
    pre_trivial = (time.perf_counter() - t0) / 5 * 1e3

    from foundationdb_tpu.bench.workload import MakoWorkload
    from foundationdb_tpu.ops import conflict_jax as cj
    from foundationdb_tpu.ops.batch import encode_batch, TxnRequest
    from foundationdb_tpu.ops.backends import coalesce_ranges

    B, R, WIDTH, CAP, WIN = 64, 4, 32, 1 << 16, 4096
    wl = MakoWorkload(n_keys=1_000_000, seed=42)
    batches, versions = wl.make_batches(4, B)
    txns = [TxnRequest(coalesce_ranges(t.read_ranges, R),
                       coalesce_ranges(t.write_ranges, R), t.read_snapshot)
            for t in batches[0]]
    eb = encode_batch(txns, B, R, WIDTH)

    if mode == "b":
        cj.COMMITTED, cj.CONFLICT, cj.TOO_OLD = (
            np.int8(0), np.int8(1), np.int8(2))
    elif mode == "c":
        cj.COMMITTED, cj.CONFLICT, cj.TOO_OLD = (
            np.int32(0), np.int32(1), np.int32(2))

    state = jax.device_put(cj.init_state(CAP, WIDTH, 0), dev)
    rb = jax.device_put(jnp.asarray(eb.read_begin), dev)
    re_ = jax.device_put(jnp.asarray(eb.read_end), dev)
    wb = jax.device_put(jnp.asarray(eb.write_begin), dev)
    we = jax.device_put(jnp.asarray(eb.write_end), dev)
    sn = jax.device_put(jnp.asarray(eb.read_snapshot), dev)
    cv = jnp.int64(versions[0])

    ts = []
    if mode == "d":
        def core(state, rb, re_, wb, we, sn, cv):
            C = state.hver.shape[0] - 1
            Bl, Rl, L = rb.shape
            hb, he, hver = state.hb[:C], state.he[:C], state.hver[:C]
            too_old = sn < state.floor
            valid = sn >= 0
            idx = (state.ptr - WIN + jnp.arange(WIN)) % C
            v_edge = state.hver[(state.ptr - WIN - 1) % C]
            fast_ok = jnp.all(~valid | too_old | (sn >= v_edge))
            hist = lax.cond(
                fast_ok,
                lambda _: cj._hist_check(rb, re_, hb[idx], he[idx], hver[idx], sn, WIDTH),
                lambda _: cj._hist_check(rb, re_, hb, he, hver, sn, WIDTH), None)
            m = cj._overlap(rb[:, :, None, None, :], re_[:, :, None, None, :],
                            wb[None, None, :, :, :], we[None, None, :, :, :], WIDTH)
            M = m.any(axis=(1, 3)) & ~jnp.eye(Bl, dtype=bool)

            def body(committed, i):
                conf = hist[i] | (committed & M[i]).any()
                return committed.at[i].set(valid[i] & ~too_old[i] & ~conf), conf
            committed, conf = lax.scan(body, jnp.zeros(Bl, bool), jnp.arange(Bl))
            dt = jnp.int8
            verdicts = jnp.where(~valid, dt(0),
                                 jnp.where(too_old, dt(2),
                                           jnp.where(conf, dt(1), dt(0))))
            valid_w = wb[..., -1] != jnp.uint32(0xFFFFFFFF)
            ins = (committed[:, None] & valid_w).reshape(-1)
            k = jnp.cumsum(ins) - ins
            pos = jnp.where(ins, (state.ptr + k) % C, C).astype(jnp.int32)
            old = jnp.where(ins, state.hver[pos], jnp.int64(-1))
            floor2 = jnp.maximum(state.floor, jnp.max(old))
            wbf = jnp.where(ins[:, None], wb.reshape(Bl * Rl, L), jnp.uint32(0xFFFFFFFF))
            wef = jnp.where(ins[:, None], we.reshape(Bl * Rl, L), jnp.uint32(0xFFFFFFFF))
            hb2 = state.hb.at[pos].set(wbf)
            he2 = state.he.at[pos].set(wef)
            hver2 = state.hver.at[pos].set(jnp.where(ins, cv, jnp.int64(-1)))
            ptr2 = ((state.ptr + jnp.sum(ins)) % C).astype(jnp.int32)
            return cj.ConflictState(hb2, he2, hver2, ptr2, floor2), verdicts

        j = jax.jit(core)
        st = state
        for i in range(6):
            t0 = time.perf_counter()
            st, v = j(st, rb, re_, wb, we, sn, cv)
            v.block_until_ready()
            ts.append(time.perf_counter() - t0)
    else:
        st = state
        for i in range(6):
            t0 = time.perf_counter()
            st, v = cj.resolve_step(st, rb, re_, wb, we, sn, cv,
                                    width=WIDTH, window=WIN)
            v.block_until_ready()
            ts.append(time.perf_counter() - t0)

    tt = []
    for _ in range(5):
        t0 = time.perf_counter()
        jt(one).block_until_ready()
        tt.append(time.perf_counter() - t0)

    print(f"MODE {mode:2s} pre_trivial={pre_trivial:7.3f}ms first={ts[0]*1e3:9.1f}ms "
          f"med_rest={np.median(ts[1:])*1e3:8.3f}ms "
          f"trivial_after={np.median(tt)*1e3:8.3f}ms", flush=True)


def main():
    if sys.argv[1] == "--all":
        for m in MODES:
            r = subprocess.run([sys.executable, "-m",
                                "foundationdb_tpu.bench.profile_poison5", m],
                               capture_output=True, text=True, timeout=300)
            out = [l for l in r.stdout.splitlines() if l.startswith("MODE")]
            print(out[0] if out else f"MODE {m}: FAILED\n{r.stderr[-600:]}",
                  flush=True)
    else:
        run_mode(sys.argv[1])


if __name__ == "__main__":
    main()
