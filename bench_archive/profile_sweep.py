"""Sweep grouped-resolver parameters on the live device: GROUP x INFLIGHT x R.

Uses the exact bench driver (measure_grouped) over 1024 mako batches.
mako txns carry 2 reads + 2 writes, so R=2 halves transfer volume and
kernel rows vs the default R=4.
"""

from __future__ import annotations

import sys
import time

import numpy as np


def main():
    import jax
    jax.config.update("jax_enable_x64", True)
    dev = jax.devices()[0]

    sys.path.insert(0, "/root/repo")
    from bench import measure_grouped
    from foundationdb_tpu.bench.workload import MakoWorkload
    from foundationdb_tpu.ops.backends import make_conflict_backend
    from foundationdb_tpu.runtime import Knobs

    B, NB = 64, 1024
    wl = MakoWorkload(n_keys=1_000_000, seed=42)
    batches, versions = wl.make_batches(NB, B)

    for R in (2, 4):
        knobs = Knobs().override(
            RESOLVER_BATCH_TXNS=B, RESOLVER_RANGES_PER_TXN=R,
            CONFLICT_RING_CAPACITY=NB * B * R * 2, KEY_ENCODE_BYTES=32,
            CONFLICT_WINDOW_SLOTS=B * R * 16,
            RESOLVER_CONFLICT_BACKEND="tpu")
        for GROUP in (64, 128, 256):
            for INFLIGHT in (8, 32):
                backend = make_conflict_backend(knobs, device=dev)
                # warm compile on a throwaway run
                wb, wv = wl.make_batches(GROUP, B,
                                         start_version=versions[-1] + 10**7)
                measure_grouped(backend, wb, wv, group=GROUP, inflight=INFLIGHT)
                backend = make_conflict_backend(knobs, device=dev)
                el, verd = measure_grouped(backend, batches, versions,
                                           group=GROUP, inflight=INFLIGHT)
                flat = np.array([x for vs in verd for x in vs])
                commits = int((flat == 0).sum())
                print(f"R={R} GROUP={GROUP:3d} INFLIGHT={INFLIGHT:2d}: "
                      f"{el*1e3:7.0f}ms -> {len(flat)/el/1000:7.1f}k txns/s, "
                      f"{commits/el/1000:7.1f}k commits/s", flush=True)


if __name__ == "__main__":
    main()
