"""The device commit pipeline — persistent on-device resolver state with
donated-buffer pipelined dispatch (ISSUE 6, SURVEY §7 hard part 3).

The resolver's conflict history lives on device for the whole resolver
generation: ``JaxConflictSet`` holds the lane-major ring as donated
device buffers (``donate_argnums`` on every resolve jit), so a dispatch
updates it in place and the state NEVER round-trips to host.  What r08
measured is that the kernel itself is fast but every per-call dispatch
pays full host work + transfer + readback serially; this pipeline is the
missing piece: a host-side queue in front of the device that

- **enqueues** proxy batches as they arrive (strict version order —
  submission order is queue order, kept by the single FIFO pump task);
- **fuses** queued batches into one ``resolve_many`` dispatch per pump
  turn (encode via the existing ``DictEncoder``: u32 endpoint ids + one
  fused transfer buffer, not lane arrays);
- **pipelines** a bounded number of dispatches: with depth 2, group
  N+1's encode+transfer runs on the host while group N's kernel runs on
  device and group N-1's verdicts read back on the sync worker thread —
  the JAX dispatch queue serializes the device side, so chained donated
  states keep strict order for free;
- **compacts** the ring across batches: the MAX_WRITE_TRANSACTION_LIFE
  ``oldest_version`` floor advances between dispatches with the same
  one-group lag the serial path used (a floor update is itself a tiny
  device op on the same stream, so ordering is preserved);
- **drains or discards** at shutdown: ``close()`` awaits in-flight
  verdicts (benches and smokes drain; the production lifecycle —
  ``Resolver.stop()`` on role teardown — passes ``discard=True`` so
  queued batches fail with ResolverFailed instead of resolving against
  a ring the next generation won't trust, matching the reference's
  kill-the-role recovery discipline).

Verdict parity: the pipeline reorders NOTHING — batches reach
``resolve_group_begin`` in enqueue order and the fused kernel threads
the ring through the group per batch (per-batch too-old floors, see
ops/conflict_jax.resolve_many_core), so verdicts are bit-identical to a
chained serial resolve and to the deterministic CPU twin
(ops/conflict_np.py).  tools/perf_smoke.py --stage resolve asserts this
in situ at tier-1 cost.

The pipeline works over ANY encoded backend: the numpy twin syncs
inline (and under SimEventLoop no thread is ever used — the sim
determinism gate), the jax backend takes the donated-buffer device
path.  The exact cpp baseline resolves host-side per batch and gains
nothing from queueing; the resolver keeps it on the direct path.
"""

from __future__ import annotations

import asyncio

from ..ops.backends import resolve_group_begin
from ..runtime.errors import ResolverFailed
from ..runtime.knobs import Knobs
from ..runtime.latency_probe import StageStats
from ..runtime.span import SpanSink


class _Item:
    __slots__ = ("txns", "version", "fut", "ctx", "barrier")

    def __init__(self, txns, version, fut, ctx, barrier):
        self.txns = txns
        self.version = version
        self.fut = fut
        self.ctx = ctx
        self.barrier = barrier


class GroupSizeStats:
    """Group-fusion depth as a real role metric (ISSUE 18 satellite):
    a MetricsRegistry ``Histogram`` replaces the ad-hoc capped list, so
    the distribution shows up in cluster.lag / ``metrics_tool summary``
    like every other role series.  The trace Histogram clears itself on
    every log interval, so the running count/total/max (which the
    FusedGroupMean gauge and the benches read) live here, outside it.
    A bounded sample buffer keeps the old list-ish read surface
    (iteration in benches and tests) alive."""

    _SAMPLE_CAP = 65536

    __slots__ = ("hist", "count", "total", "max", "samples")

    def __init__(self) -> None:
        from ..runtime.trace import Histogram
        self.hist = Histogram("ResolverDevice", "GroupSize", unit="batches")
        self.count = 0
        self.total = 0
        self.max = 0
        self.samples: list[int] = []

    def append(self, n: int) -> None:
        self.hist.sample(n)
        self.count += 1
        self.total += n
        if n > self.max:
            self.max = n
        if len(self.samples) < self._SAMPLE_CAP:
            self.samples.append(n)

    def clear(self) -> None:
        self.hist.clear()
        self.count = 0
        self.total = 0
        self.max = 0
        self.samples.clear()

    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def __len__(self) -> int:
        return self.count

    def __iter__(self):
        return iter(self.samples)


def supports_pipeline(backend) -> bool:
    """True when ``backend`` can ride the pipeline (encoded backends with
    a group-submit path).  The cpp interval map resolves host-side per
    batch — queueing it adds latency for nothing — so it reports False
    and the resolver keeps the direct dispatch (graceful fallback)."""
    return hasattr(backend, "resolve_group_begin")


class DevicePipeline:
    """Host-side front of the device resolver: enqueue → fuse → dispatch
    → readback, a bounded number of dispatches in flight."""

    def __init__(self, backend, knobs: Knobs, on_poison=None,
                 epoch_begin_version: int = 0) -> None:
        assert supports_pipeline(backend)
        self.backend = backend
        self.knobs = knobs
        self.depth = max(1, knobs.RESOLVER_PIPELINE_DEPTH)
        self.group_max = max(1, knobs.RESOLVER_GROUP_MAX)
        self._window = knobs.MAX_WRITE_TRANSACTION_LIFE_VERSIONS
        self._on_poison = on_poison
        self._pending: list[_Item] = []
        self._pump_task: asyncio.Task | None = None
        self._inflight: list[asyncio.Task] = []
        self._last_version = epoch_begin_version
        self._poisoned: BaseException | None = None
        self._closed = False
        # --- observability (rolled up as cluster.resolver_device) ---
        self.spans = SpanSink("ResolverDevice")
        self.stages = StageStats("DevicePipeline", cap=4096)
        self.enqueued = 0          # batches accepted
        self.dispatches = 0        # fused device dispatches issued
        self.batches_dispatched = 0
        self.readbacks = 0         # dispatches whose verdicts synced back
        self.queue_peak = 0
        self.inflight_peak = 0
        self.group_sizes = GroupSizeStats()
        self._dispatch_s = 0.0     # host time in encode+transfer+dispatch
        self._overlap_s = 0.0      # ...of which with >= 1 dispatch in flight

    # --- submission ---

    def submit(self, txns, version: int, span_ctx=None,
               barrier: bool = False) -> asyncio.Future:
        """Enqueue one proxy batch; returns a future of its verdict list.
        ``barrier`` (state-txn batches) ends the fused group at this
        batch, so its verdicts never wait on later batches' kernels.
        The caller owns version ordering (the resolver's version chain
        gates submission); the pipeline preserves enqueue order."""
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        if self._poisoned is not None or self._closed:
            fut.set_exception(ResolverFailed())
            return fut
        self._pending.append(_Item(txns, version, fut, span_ctx, barrier))
        self.enqueued += 1
        self.queue_peak = max(self.queue_peak, len(self._pending))
        self.spans.event("CommitDebug", span_ctx,
                         "ResolverDevice.enqueue",
                         Version=version, QueueDepth=len(self._pending))
        if self._pump_task is None or self._pump_task.done():
            from ..runtime.span import no_span
            # the pump outlives this request: mask its span so later
            # groups aren't attributed to this transaction
            with no_span():
                self._pump_task = loop.create_task(
                    self._pump(), name="resolver-device-pipeline")
        return fut

    async def resolve(self, txns, version: int) -> list[int]:
        """Submit one batch and await its verdicts (the serial
        convenience used by parity checks and latency probes)."""
        return await self.submit(txns, version)

    # --- the pump: one task, FIFO, bounded in-flight dispatches ---

    def _reap(self) -> None:
        """Drop completed readback tasks: _inflight must mean device work
        genuinely outstanding — the depth gate, the overlap accounting,
        and the metrics all key on it, and a done task lingering from an
        earlier burst would count a dispatch as overlapped against a
        kernel that already finished."""
        if any(t.done() for t in self._inflight):
            self._inflight = [t for t in self._inflight if not t.done()]

    async def _pump(self) -> None:
        loop = asyncio.get_running_loop()
        group: list[_Item] = []
        try:
            while self._pending:
                self._reap()
                while len(self._inflight) >= self.depth:
                    await asyncio.wait({self._inflight[0]})
                    self._reap()
                if self._poisoned is not None or not self._pending:
                    # a readback that failed while we were parked at the
                    # depth gate poisoned the pipeline and drained the
                    # queue — nothing left to dispatch
                    break
                group = []
                while self._pending and len(group) < self.group_max:
                    item = self._pending.pop(0)
                    group.append(item)
                    if item.barrier:
                        break
                # ring compaction: slide the too-old floor as of the
                # PREVIOUS dispatch (one-group lag, exactly the serial
                # path's discipline) — a device-side op on the same
                # stream, so it lands between kernels in order
                floor = self._last_version - self._window
                if floor > 0:
                    self.backend.set_oldest_version(floor)
                self._last_version = group[-1].version
                t0 = loop.time()
                overlapped = bool(self._inflight)
                finish = resolve_group_begin(
                    self.backend, [it.txns for it in group],
                    [it.version for it in group])
                dt = loop.time() - t0
                self.stages.record("dispatch", dt)
                self._dispatch_s += dt
                if overlapped:
                    self._overlap_s += dt
                self.dispatches += 1
                self.batches_dispatched += len(group)
                self.group_sizes.append(len(group))
                self.spans.event("CommitDebug", group[0].ctx,
                                 "ResolverDevice.dispatch",
                                 Version=group[-1].version,
                                 Batches=len(group),
                                 InFlight=len(self._inflight) + 1,
                                 Overlapped=overlapped)
                task = loop.create_task(self._readback(group, finish),
                                        name="resolver-device-readback")
                self._inflight.append(task)
                self.inflight_peak = max(self.inflight_peak,
                                         len(self._inflight))
                group = []
        except asyncio.CancelledError:
            for it in group:
                if not it.fut.done():
                    it.fut.set_exception(ResolverFailed())
            raise
        except BaseException as e:  # noqa: BLE001 — submission failure
            self._poison(e)
            for it in group:        # popped but not dispatched
                if not it.fut.done():
                    it.fut.set_exception(ResolverFailed())
            raise

    async def _readback(self, group: list[_Item], finish) -> None:
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        try:
            rows = await finish
        except asyncio.CancelledError:
            for it in group:
                if not it.fut.done():
                    it.fut.set_exception(ResolverFailed())
            raise
        except BaseException as e:  # noqa: BLE001 — sync failure
            self._poison(e)
            for it in group:
                if not it.fut.done():
                    it.fut.set_exception(ResolverFailed())
            return
        self.stages.record("readback", loop.time() - t0)
        self.readbacks += 1
        self.spans.event("CommitDebug", group[0].ctx,
                         "ResolverDevice.readback",
                         Version=group[-1].version, Batches=len(group))
        for it, verdicts in zip(group, rows):
            if not it.fut.done():
                it.fut.set_result(verdicts)

    # --- lifecycle ---

    @property
    def poisoned(self) -> BaseException | None:
        return self._poisoned

    def _poison(self, e: BaseException) -> None:
        """Fail-stop: device history may be partially mutated (some group
        dispatched, some not) — no later verdict can be trusted.  Queued
        batches fail immediately instead of hanging; the owner (the
        resolver role) is told so it poisons its version chain too."""
        if self._poisoned is not None:
            return
        self._poisoned = e
        pending, self._pending = self._pending, []
        for it in pending:
            if not it.fut.done():
                it.fut.set_exception(ResolverFailed())
        if self._on_poison is not None:
            self._on_poison(e)

    async def drain(self) -> None:
        """Wait until every enqueued batch has verdicts (or failed)."""
        while self._pending or self._inflight \
                or (self._pump_task is not None
                    and not self._pump_task.done()):
            tasks = set(self._inflight)
            if self._pump_task is not None and not self._pump_task.done():
                tasks.add(self._pump_task)
            if not tasks:
                break
            try:
                await asyncio.wait(tasks)
            except asyncio.CancelledError:
                raise
            self._inflight = [t for t in self._inflight if not t.done()]

    async def close(self, discard: bool = False) -> None:
        """Generation end: drain in-flight work then stop accepting.
        ``discard`` skips the drain (rollback path — recovery replaces
        the role, so queued batches fail with ResolverFailed instead of
        being resolved against a ring the next generation won't trust)."""
        self._closed = True
        if discard:
            self._poison(ResolverFailed())
            for t in list(self._inflight):
                t.cancel()
        else:
            try:
                await self.drain()
            except asyncio.CancelledError:
                pass
        for t in [self._pump_task, *self._inflight]:
            if t is not None and not t.done():
                t.cancel()
                try:
                    await t
                except (asyncio.CancelledError, ResolverFailed):
                    pass
                except BaseException:  # noqa: BLE001 — already poisoned
                    pass
        self._inflight = []
        self._pump_task = None

    # --- observability ---

    def reset_stats(self) -> None:
        """Zero the dispatch/overlap accounting (NOT the queue state):
        benches call this at measuring start so warmup compile stalls —
        which land inside the first dispatches' host time — don't skew
        the steady-state per-batch numbers."""
        self.stages = StageStats("DevicePipeline", cap=4096)
        self.enqueued = 0
        self.dispatches = 0
        self.batches_dispatched = 0
        self.readbacks = 0
        self._reap()
        self.queue_peak = len(self._pending)
        self.inflight_peak = len(self._inflight)
        self.group_sizes.clear()
        self._dispatch_s = 0.0
        self._overlap_s = 0.0
        if hasattr(self.backend, "readback_bytes"):
            self.backend.readback_bytes = 0
            self.backend.readback_txns = 0

    def metrics(self) -> dict:
        """Counters for the resolver's metrics() → cluster.resolver_device
        rollup: queue/in-flight depth, dispatch shape, and where dispatch
        host time went (overlap ratio ~1.0 = encode+transfer fully hidden
        behind in-flight kernels; ~0.0 = serial)."""
        self._reap()
        s = self.stages.summary()
        disp = s.get("dispatch", {})
        sync = s.get("readback", {})
        n = max(1, self.batches_dispatched)
        return {
            "device_pipeline": 1,
            "device_pipeline_depth": self.depth,
            "device_enqueued": self.enqueued,
            "device_dispatches": self.dispatches,
            "device_batches_dispatched": self.batches_dispatched,
            "device_readbacks": self.readbacks,
            "device_queue_depth": len(self._pending),
            "device_queue_peak": self.queue_peak,
            "device_inflight": len(self._inflight),
            "device_inflight_peak": self.inflight_peak,
            "device_group_mean": round(
                self.batches_dispatched / max(1, self.dispatches), 2),
            "device_group_max": self.group_sizes.max,
            # verdict readback volume (ISSUE 18): what the host actually
            # synced — the bitmask reduction's bytes/txn win reads here
            "device_readback_bytes": getattr(self.backend,
                                             "readback_bytes", 0),
            "device_readback_txns": getattr(self.backend,
                                            "readback_txns", 0),
            "device_dispatch_us_per_batch": round(
                self._dispatch_s / n * 1e6, 1),
            "device_dispatch_p99_ms": disp.get("p99_ms", 0.0),
            "device_readback_p99_ms": sync.get("p99_ms", 0.0),
            "device_overlap_ratio": round(
                self._overlap_s / self._dispatch_s, 3)
            if self._dispatch_s > 0 else 0.0,
            "device_poisoned": int(self._poisoned is not None),
            # namespaced: the resolver spreads this dict into ITS
            # metrics(), whose own SpanSink publishes the bare
            # spans_emitted/dropped keys — colliding would clobber the
            # role's span accounting in the cluster.tracing rollup
            **{"device_" + k: v for k, v in self.spans.counters().items()},
        }
