"""Device-resident subsystems (ISSUE 6).

``pipeline``   — the resolver's device commit pipeline: persistent
                 on-device ConflictState in donated buffers, host-side
                 batch queueing, fused pipelined dispatch.
``read_serve`` — device gather path for point-read serving: a mirror of
                 the storage engine's PackedKeyIndex key prefixes served
                 by one vectorized searchsorted per batch.
"""
