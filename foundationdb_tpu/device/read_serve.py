"""Device gather path for point-read serving (ISSUE 6; the PR 5
follow-up "TPU gather kernel for point serving").

``get_values``' missing-key pass — the sorted keys the MVCC window does
not resolve — is exactly a batched sorted-probe over the storage
engine's key space, and ``PackedKeyIndex`` already keeps that key space
as two sorted runs with keycode-u64 prefixes (storage/key_index.py).
This module mirrors the BASE run's u64 prefixes as a device array and
answers a whole batch with ONE vectorized ``searchsorted`` pair
(left/right bounds) on device, the same pack-keys-into-lanes discipline
the resolver kernel uses.  The host then only refines inside the
(usually single-element) equal-prefix band and gathers values for the
keys that exist — no per-key descent over the big run.

Freshness contract: the mirror is stamped with the index ``gen`` counter
(bumped whenever the base run mutates: merges, discards).  A batch
arriving with a stale mirror is served by the ENGINE path — identical
results, tested — and triggers a re-upload so the next batch is fresh;
the pending overlay (keys inserted since the last merge) is always
probed host-side, so the mirror only ever needs to track merges, not
every insert.  The re-upload happens inline on that first stale batch:
its host half is the index's own cached ``_prefixes()`` array — the
same once-per-merge encode the numpy bound path already pays — and
``jax.device_put`` returns before the transfer completes, so only the
prefix (re)encode can land on the serving path, once per merge.  Batches below ``STORAGE_DEVICE_READ_MIN_BATCH`` skip the
device entirely (a lone probe's dispatch overhead beats any gather win —
the same threshold reasoning as PackedKeyIndex.ranges_keys).

Results are BYTE-IDENTICAL to ``engine.get_batch`` by construction: the
device only locates candidate bands; membership is decided by the same
bisect refinement the host index uses, and values come from the same
engine storage.

Two engine shapes share the mirror (ISSUE 11).  ``membership`` mode
(MemoryKVStore): the mirrored run is the engine's full key index — the
device locates the key's band, the host decides membership and gathers
the value.  ``blocks`` mode (LSMKVStore): the mirrored run is the
engine's MERGED SPARSE INDEX (every sorted run's block first-keys in one
sorted KeyRun, ``lsm.LsmSparseIndex``) — the one device searchsorted
locates the candidate data block in EVERY run at once (the prefix-max
table turns the merged position into per-run block indices), and the
host finishes with ``engine.get_batch_located`` (memtable first, block
decode + bisect, newest-run-wins).  This is where the vectorized gather
finally replaces a real per-run sorted-probe descent (ROADMAP item 1
(e)) instead of racing a dict lookup.
"""

from __future__ import annotations

import bisect

import numpy as np

from ..runtime.knobs import Knobs


def _jax_ready() -> bool:
    """The mirror needs uint64 device arrays: jax importable with x64 on
    (without x64 jnp silently truncates u64 to u32 — a wrong-band bug,
    not a slowdown — so this gate is correctness, not convenience)."""
    try:
        import jax
        return bool(jax.config.jax_enable_x64)
    except Exception:   # noqa: BLE001 — no jax: engine path only
        return False


class DeviceKeyDirectory:
    """Device mirror of one PackedKeyIndex base run's u64 prefixes."""

    def __init__(self, index, device=None) -> None:
        self._index = index
        self._device = device
        self._pfx_dev = None
        self._gen = -1          # index.gen the mirror was built at
        self._jfn = None        # jitted fused searchsorted pair
        self.uploads = 0
        self.uploaded_keys = 0

    @property
    def fresh(self) -> bool:
        return self._pfx_dev is not None and self._gen == self._index.gen

    def refresh(self) -> None:
        """Re-upload the base run's prefixes (called on merge/discard
        staleness, not per batch).  Runs inline: the prefix array is the
        index's shared once-per-merge cache and device_put returns
        before the transfer completes (see the module docstring)."""
        import jax
        pfx = self._index.base_prefixes()
        self._gen = self._index.gen
        self._pfx_dev = jax.device_put(pfx, self._device) \
            if self._device is not None else jax.device_put(pfx)
        self.uploads += 1
        self.uploaded_keys += int(pfx.shape[0])

    def lookup(self, keys: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
        """One device dispatch for the whole batch: (lo, hi) candidate
        bands over the base run per key.  Caller must hold ``fresh``.

        The searchsorted pair runs as ONE jitted call with the probe
        vector padded to a power-of-two bucket: eager per-op dispatch
        costs ~1-3ms per call on a host-CPU backend (measured — it
        inverted the multiget edge on the lsm read smoke), while the
        fused jit is ~100µs with one compile per (mirror length,
        bucket) pair; padding probes with u64-max keeps varying batch
        sizes on a handful of compiled shapes, the resolver's bucket
        discipline."""
        import jax
        from ..ops.keycode import encode_prefix_u64
        if self._jfn is None:
            import jax.numpy as jnp
            self._jfn = jax.jit(lambda pfx, probes: (
                jnp.searchsorted(pfx, probes, side="left"),
                jnp.searchsorted(pfx, probes, side="right")))
        probes = encode_prefix_u64(keys)
        n = len(probes)
        bucket = 1 << max(0, (n - 1).bit_length())
        if bucket > n:
            probes = np.concatenate(
                [probes, np.full(bucket - n, np.uint64(0xFFFFFFFFFFFFFFFF),
                                 dtype=np.uint64)])
        los, his = self._jfn(self._pfx_dev, probes)
        return np.asarray(los)[:n], np.asarray(his)[:n]


class DeviceReadServer:
    """Per-storage-server device read path over the engine's key index.

    ``get_batch(keys)`` returns the same list ``engine.get_batch`` would,
    or None to tell the caller to take the engine path (below threshold,
    stale mirror, engine without a packed index, no usable jax)."""

    def __init__(self, engine, knobs: Knobs, device=None) -> None:
        self.engine = engine
        self.knobs = knobs
        self.min_batch = max(1, knobs.STORAGE_DEVICE_READ_MIN_BATCH)
        index = getattr(engine, "packed_index", None)
        # how the host finishes a device-located batch: "membership"
        # (full key index + engine.get) or "blocks" (merged sparse
        # directory + engine.get_batch_located) — see module docstring
        self._mode = getattr(index, "device_mode", "membership")
        self._dir = None
        if index is not None and knobs.STORAGE_DEVICE_READ_SERVE \
                and _jax_ready():
            self._dir = DeviceKeyDirectory(index, device)
        # --- observability (storage metrics → status rollup) ---
        self.served_batches = 0
        self.served_keys = 0
        self.fallbacks = 0      # batches routed to the engine path

    @property
    def active(self) -> bool:
        return self._dir is not None

    def get_batch(self, keys: list[bytes]):
        if self._dir is None or len(keys) < self.min_batch:
            if self._dir is not None:
                self.fallbacks += 1
            return None
        index = self._dir._index
        if not self._dir.fresh:
            # stale mirror: serve THIS batch off the engine, refresh so
            # the next one rides the device (refresh on merge, not per
            # batch — steady-state reads never pay an upload)
            self.fallbacks += 1
            self._dir.refresh()
            return None
        base = index.base_run()
        if not len(base):
            # nothing mirrored yet (empty index / no sorted runs):
            # the engine path answers without a device dispatch
            self.fallbacks += 1
            return None
        los, his = self._dir.lookup(keys)
        if self._mode == "blocks":
            # merged sparse directory: the band refines to the exact
            # bisect_right position, whose prefix-max row names the
            # candidate block in every run; the engine finishes host-side
            pos = [base.bisect_right(k, int(lo), int(hi))
                   for k, lo, hi in zip(keys, los, his)]
            out = self.engine.get_batch_located(keys, pos)
        else:
            pending = index.pending_run()
            get = self.engine.get
            out = []
            for k, lo, hi in zip(keys, los, his):
                lo, hi = int(lo), int(hi)
                present = False
                if lo < hi:
                    i = bisect.bisect_left(base, k, lo, hi)
                    present = i < hi and base[i] == k
                if not present and pending:
                    j = bisect.bisect_left(pending, k)
                    present = j < len(pending) and pending[j] == k
                out.append(get(k) if present else None)
        self.served_batches += 1
        self.served_keys += len(keys)
        return out

    def metrics(self) -> dict:
        d = self._dir
        return {
            "device_read_active": int(self.active),
            "device_read_batches": self.served_batches,
            "device_read_keys": self.served_keys,
            "device_read_fallbacks": self.fallbacks,
            "device_read_uploads": d.uploads if d is not None else 0,
            "device_read_uploaded_keys":
                d.uploaded_keys if d is not None else 0,
        }
