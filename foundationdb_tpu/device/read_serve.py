"""Device gather path for point-read serving (ISSUE 6; the PR 5
follow-up "TPU gather kernel for point serving").

``get_values``' missing-key pass — the sorted keys the MVCC window does
not resolve — is exactly a batched sorted-probe over the storage
engine's key space, and ``PackedKeyIndex`` already keeps that key space
as two sorted runs with keycode-u64 prefixes (storage/key_index.py).
This module mirrors the BASE run's u64 prefixes as a device array and
answers a whole batch with ONE vectorized ``searchsorted`` pair
(left/right bounds) on device, the same pack-keys-into-lanes discipline
the resolver kernel uses.  The host then only refines inside the
(usually single-element) equal-prefix band and gathers values for the
keys that exist — no per-key descent over the big run.

Freshness contract: the mirror is stamped with the index ``gen`` counter
(bumped whenever the base run mutates: merges, discards).  A batch
arriving with a stale mirror is served by the ENGINE path — identical
results, tested — and triggers a re-upload so the next batch is fresh;
the pending overlay (keys inserted since the last merge) is always
probed host-side, so the mirror only ever needs to track merges, not
every insert.  The re-upload happens inline on that first stale batch:
its host half is the index's own cached ``_prefixes()`` array — the
same once-per-merge encode the numpy bound path already pays — and
``jax.device_put`` returns before the transfer completes, so only the
prefix (re)encode can land on the serving path, once per merge.  Batches below ``STORAGE_DEVICE_READ_MIN_BATCH`` skip the
device entirely (a lone probe's dispatch overhead beats any gather win —
the same threshold reasoning as PackedKeyIndex.ranges_keys).

Results are BYTE-IDENTICAL to ``engine.get_batch`` by construction: the
device only locates candidate bands; membership is decided by the same
bisect refinement the host index uses, and values come from the same
engine storage.

Two engine shapes share the mirror (ISSUE 11).  ``membership`` mode
(MemoryKVStore): the mirrored run is the engine's full key index — the
device locates the key's band, the host decides membership and gathers
the value.  ``blocks`` mode (LSMKVStore): the mirrored run is the
engine's MERGED SPARSE INDEX (every sorted run's block first-keys in one
sorted KeyRun, ``lsm.LsmSparseIndex``) — the one device searchsorted
locates the candidate data block in EVERY run at once (the prefix-max
table turns the merged position into per-run block indices), and the
host finishes with ``engine.get_batch_located`` (memtable first, block
decode + bisect, newest-run-wins).  This is where the vectorized gather
finally replaces a real per-run sorted-probe descent (ROADMAP item 1
(e)) instead of racing a dict lookup.
"""

from __future__ import annotations

import bisect

import numpy as np

from ..runtime.knobs import Knobs


def _jax_ready() -> bool:
    """The mirror needs uint64 device arrays: jax importable with x64 on
    (without x64 jnp silently truncates u64 to u32 — a wrong-band bug,
    not a slowdown — so this gate is correctness, not convenience)."""
    try:
        import jax
        return bool(jax.config.jax_enable_x64)
    except Exception:   # noqa: BLE001 — no jax: engine path only
        return False


class DeviceKeyDirectory:
    """Device mirror of one PackedKeyIndex base run's u64 prefixes."""

    def __init__(self, index, device=None) -> None:
        self._index = index
        self._device = device
        self._pfx_dev = None
        self._gen = -1          # index.gen the mirror was built at
        self._jfn = None        # jitted fused searchsorted pair
        self.uploads = 0
        self.uploaded_keys = 0

    @property
    def fresh(self) -> bool:
        return self._pfx_dev is not None and self._gen == self._index.gen

    def refresh(self) -> None:
        """Re-upload the base run's prefixes (called on merge/discard
        staleness, not per batch).  Runs inline: the prefix array is the
        index's shared once-per-merge cache and device_put returns
        before the transfer completes (see the module docstring)."""
        import jax
        pfx = self._index.base_prefixes()
        self._gen = self._index.gen
        self._pfx_dev = jax.device_put(pfx, self._device) \
            if self._device is not None else jax.device_put(pfx)
        self.uploads += 1
        self.uploaded_keys += int(pfx.shape[0])

    def lookup(self, keys: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
        """One device dispatch for the whole batch: (lo, hi) candidate
        bands over the base run per key.  Caller must hold ``fresh``.

        The searchsorted pair runs as ONE jitted call with the probe
        vector padded to a power-of-two bucket: eager per-op dispatch
        costs ~1-3ms per call on a host-CPU backend (measured — it
        inverted the multiget edge on the lsm read smoke), while the
        fused jit is ~100µs with one compile per (mirror length,
        bucket) pair; padding probes with u64-max keeps varying batch
        sizes on a handful of compiled shapes, the resolver's bucket
        discipline."""
        import jax
        from ..ops.keycode import encode_prefix_u64
        if self._jfn is None:
            import jax.numpy as jnp
            self._jfn = jax.jit(lambda pfx, probes: (
                jnp.searchsorted(pfx, probes, side="left"),
                jnp.searchsorted(pfx, probes, side="right")))
        probes = encode_prefix_u64(keys)
        n = len(probes)
        bucket = 1 << max(0, (n - 1).bit_length())
        if bucket > n:
            probes = np.concatenate(
                [probes, np.full(bucket - n, np.uint64(0xFFFFFFFFFFFFFFFF),
                                 dtype=np.uint64)])
        los, his = self._jfn(self._pfx_dev, probes)
        return np.asarray(los)[:n], np.asarray(his)[:n]


_U64_MAX = np.uint64(0xFFFFFFFFFFFFFFFF)


class ShardedDeviceDirectory:
    """Per-chip sharded mirror (ISSUE 18, ROADMAP 1 (d)): the base run's
    u64 prefixes split across ``jax.devices()`` by key range — one shard
    per chip when the chips exist, round-robin shard replicas on one
    chip otherwise (the CPU tier-1 shape, forced multi-device via
    ``--xla_force_host_platform_device_count``).

    Duck-types ``DeviceKeyDirectory`` (fresh/refresh/lookup + upload
    counters) so ``DeviceReadServer`` treats either as the mirror.  Two
    things the monolithic mirror cannot do:

    - **Partial refresh.**  Shard boundaries are PREFIX values, so after
      a base mutation the per-shard slices are recomputed by one
      searchsorted over the new prefix array, and only the shards whose
      key range intersects the index's ``changed_since`` spans
      re-upload — a localized merge re-ships 1/S of the mirror instead
      of all of it.  An unaccounted gen gap (change log trimmed) falls
      back to a full re-split.
    - **Cross-shard batched gathers.**  A batch's probes route host-side
      by the boundary table (one searchsorted), every touched shard's
      searchsorted pair dispatches back-to-back (jax dispatch is async,
      so the per-shard kernels overlap), and the host joins the global
      (lo, hi) bands by adding each shard's base offset.

    Boundary invariant: every shard starts at the FIRST element of an
    equal-prefix run (searchsorted-left of the boundary prefix), so a
    probe routed to shard s resolves the same global band the monolithic
    searchsorted would — elements before the shard are strictly below
    its bound, elements after are at or above the next bound.
    """

    def __init__(self, index, n_shards: int, devices=None) -> None:
        self._index = index
        self.n_shards = max(2, int(n_shards))
        if devices is None:
            try:
                import jax
                devices = list(jax.devices())
            except Exception:   # noqa: BLE001 — default placement
                devices = [None]
        self._devices = devices or [None]
        self._gen = -1
        self._bounds: np.ndarray | None = None   # [S] lower prefix bound
        self._offsets: np.ndarray | None = None  # [S+1] base-run offsets
        self._shard_dev: list = [None] * self.n_shards
        self._jfn = None
        self.uploads = 0            # refresh() calls (twin-compatible)
        self.uploaded_keys = 0      # prefixes actually re-shipped
        self.shard_refreshes = 0    # per-shard uploads (S per full split)
        self.full_splits = 0        # refreshes that re-split everything
        self.gathers = 0            # per-shard device dispatches

    @property
    def fresh(self) -> bool:
        return self._bounds is not None and self._gen == self._index.gen

    def _put(self, arr: np.ndarray, s: int):
        import jax
        dev = self._devices[s % len(self._devices)]
        return jax.device_put(arr, dev) if dev is not None \
            else jax.device_put(arr)

    def _split_all(self, pfx: np.ndarray) -> None:
        """Full re-split: equal-share cuts snapped left to equal-prefix
        run starts, every shard re-uploaded to its device."""
        n = int(pfx.shape[0])
        S = self.n_shards
        cuts = [min(n, round(n * s / S)) for s in range(S)]
        offs = [0] * (S + 1)
        offs[S] = n
        bounds = np.zeros(S, dtype=np.uint64)
        for s in range(1, S):
            c = cuts[s]
            b = pfx[c] if c < n else _U64_MAX
            offs[s] = int(np.searchsorted(pfx, b, side="left"))
            bounds[s] = b
        self._offsets = np.asarray(offs, dtype=np.int64)
        self._bounds = bounds
        for s in range(S):
            seg = pfx[offs[s]:offs[s + 1]]
            self._shard_dev[s] = self._put(seg, s)
            self.shard_refreshes += 1
            self.uploaded_keys += int(seg.shape[0])
        self.full_splits += 1

    def refresh(self) -> None:
        """Rebuild freshness after a base mutation.  Partial when the
        index's change log accounts for every gen bump since the last
        upload: offsets recompute against the fixed prefix boundaries
        and only intersecting shards re-ship."""
        pfx = self._index.base_prefixes()
        spans = self._index.changed_since(self._gen) \
            if self._bounds is not None else None
        self.uploads += 1
        self._gen = self._index.gen
        if spans is None:
            self._split_all(pfx)
            return
        n = int(pfx.shape[0])
        S = self.n_shards
        offs = np.empty(S + 1, dtype=np.int64)
        offs[:S] = np.searchsorted(pfx, self._bounds, side="left")
        offs[0] = 0
        offs[S] = n
        self._offsets = offs
        if not spans:
            return
        from ..ops.keycode import encode_prefix_u64
        span_p = encode_prefix_u64([k for lo_hi in spans for k in lo_hi])
        for s in range(S):
            lo_b = self._bounds[s]
            hi_b = self._bounds[s + 1] if s + 1 < S else _U64_MAX
            touched = any(
                not (span_p[2 * i + 1] < lo_b
                     or (s + 1 < S and span_p[2 * i] >= hi_b))
                for i in range(len(spans)))
            if not touched:
                continue
            seg = pfx[int(offs[s]):int(offs[s + 1])]
            self._shard_dev[s] = self._put(seg, s)
            self.shard_refreshes += 1
            self.uploaded_keys += int(seg.shape[0])

    def lookup(self, keys: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
        """Global (lo, hi) candidate bands for the whole batch: probes
        route to shards host-side, every touched shard dispatches its
        jitted searchsorted pair (async — the transfers and kernels
        overlap across chips), and the host joins with shard offsets."""
        import jax
        from ..ops.keycode import encode_prefix_u64
        if self._jfn is None:
            import jax.numpy as jnp
            self._jfn = jax.jit(lambda pfx, probes: (
                jnp.searchsorted(pfx, probes, side="left"),
                jnp.searchsorted(pfx, probes, side="right")))
        probes = encode_prefix_u64(keys)
        n = len(probes)
        sid = np.clip(
            np.searchsorted(self._bounds, probes, side="right") - 1,
            0, self.n_shards - 1)
        los = np.zeros(n, dtype=np.int64)
        his = np.zeros(n, dtype=np.int64)
        launched = []
        for s in np.unique(sid):
            mask = sid == s
            sub = probes[mask]
            m = len(sub)
            bucket = 1 << max(0, (m - 1).bit_length())
            if bucket > m:
                sub = np.concatenate(
                    [sub, np.full(bucket - m, _U64_MAX, dtype=np.uint64)])
            lo_d, hi_d = self._jfn(self._shard_dev[int(s)], sub)
            self.gathers += 1
            launched.append((int(s), mask, m, lo_d, hi_d))
        for s, mask, m, lo_d, hi_d in launched:
            off = int(self._offsets[s])
            los[mask] = np.asarray(lo_d)[:m] + off
            his[mask] = np.asarray(hi_d)[:m] + off
        return los, his


class DeviceReadServer:
    """Per-storage-server device read path over the engine's key index.

    ``get_batch(keys)`` returns the same list ``engine.get_batch`` would,
    or None to tell the caller to take the engine path (below threshold,
    stale mirror, engine without a packed index, no usable jax).

    ``version_fn`` (the hosting server's applied-version tip) turns the
    boolean stale/fresh flip into a staleness GAUGE: metrics report how
    many versions the mirror's last refresh trails the engine tip, so a
    mirror quietly serving off an old upload shows up as a rising
    number, not a flag nobody polls (ISSUE 18 satellite)."""

    def __init__(self, engine, knobs: Knobs, device=None,
                 version_fn=None) -> None:
        self.engine = engine
        self.knobs = knobs
        self.min_batch = max(1, knobs.STORAGE_DEVICE_READ_MIN_BATCH)
        index = getattr(engine, "packed_index", None)
        # how the host finishes a device-located batch: "membership"
        # (full key index + engine.get) or "blocks" (merged sparse
        # directory + engine.get_batch_located) — see module docstring
        self._mode = getattr(index, "device_mode", "membership")
        self._dir = None
        self._sharded = False
        if index is not None and knobs.STORAGE_DEVICE_READ_SERVE \
                and _jax_ready():
            shards = int(getattr(knobs, "STORAGE_DEVICE_READ_SHARDS", 0))
            if shards >= 2:
                self._dir = ShardedDeviceDirectory(
                    index, shards,
                    devices=[device] if device is not None else None)
                self._sharded = True
            else:
                self._dir = DeviceKeyDirectory(index, device)
        # --- observability (storage metrics → status rollup) ---
        self.version_fn = version_fn
        self.last_refresh_version = 0
        self.served_batches = 0
        self.served_keys = 0
        self.fallbacks = 0      # batches routed to the engine path

    @property
    def active(self) -> bool:
        return self._dir is not None

    def _refresh(self) -> None:
        self._dir.refresh()
        if self.version_fn is not None:
            self.last_refresh_version = self.version_fn()

    def get_batch(self, keys: list[bytes]):
        if self._dir is None or len(keys) < self.min_batch:
            if self._dir is not None:
                self.fallbacks += 1
            return None
        index = self._dir._index
        if not self._dir.fresh:
            if self._sharded:
                # sharded mirror: a stale shard refreshes PARTIALLY
                # (only the shards the mutation's key span touched
                # re-ship) and THIS batch still serves off the device —
                # device_put returns before the transfer completes, so
                # the serving path pays the re-slice, not the copy
                self._refresh()
            else:
                # stale mirror: serve THIS batch off the engine, refresh
                # so the next one rides the device (refresh on merge,
                # not per batch — steady-state reads never pay an upload)
                self.fallbacks += 1
                self._refresh()
                return None
        base = index.base_run()
        if not len(base):
            # nothing mirrored yet (empty index / no sorted runs):
            # the engine path answers without a device dispatch
            self.fallbacks += 1
            return None
        los, his = self._dir.lookup(keys)
        if self._mode == "blocks":
            # merged sparse directory: the band refines to the exact
            # bisect_right position, whose prefix-max row names the
            # candidate block in every run; the engine finishes host-side
            pos = [base.bisect_right(k, int(lo), int(hi))
                   for k, lo, hi in zip(keys, los, his)]
            out = self.engine.get_batch_located(keys, pos)
        else:
            pending = index.pending_run()
            get = self.engine.get
            out = []
            for k, lo, hi in zip(keys, los, his):
                lo, hi = int(lo), int(hi)
                present = False
                if lo < hi:
                    i = bisect.bisect_left(base, k, lo, hi)
                    present = i < hi and base[i] == k
                if not present and pending:
                    j = bisect.bisect_left(pending, k)
                    present = j < len(pending) and pending[j] == k
                out.append(get(k) if present else None)
        self.served_batches += 1
        self.served_keys += len(keys)
        return out

    def staleness_versions(self) -> int:
        """Versions the mirror's last refresh trails the engine tip —
        0 while fresh (a fresh mirror plus host-probed pending overlay
        serves current data regardless of when it last uploaded)."""
        if self._dir is None or self.version_fn is None \
                or self._dir.fresh:
            return 0
        return max(0, int(self.version_fn()) - self.last_refresh_version)

    def metrics(self) -> dict:
        d = self._dir
        out = {
            "device_read_active": int(self.active),
            "device_read_batches": self.served_batches,
            "device_read_keys": self.served_keys,
            "device_read_fallbacks": self.fallbacks,
            "device_read_uploads": d.uploads if d is not None else 0,
            "device_read_uploaded_keys":
                d.uploaded_keys if d is not None else 0,
            "device_read_staleness_versions": self.staleness_versions(),
        }
        if self._sharded:
            out["device_read_shards"] = d.n_shards
            out["device_read_shard_refreshes"] = d.shard_refreshes
            out["device_read_full_splits"] = d.full_splits
            out["device_read_gathers"] = d.gathers
        return out
