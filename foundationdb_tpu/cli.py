"""fdbcli analog — interactive/one-shot cluster shell.

Reference: REF:fdbcli/fdbcli.actor.cpp — get/set/clear/getrange/status
against a live cluster found through the cluster file.

    python -m foundationdb_tpu.cli -C fdb.cluster --exec "set k v; get k"
"""

from __future__ import annotations

import argparse
import asyncio
import shlex
import sys

from .client.transaction import Transaction
from .core.cluster_client import RecoveredClusterView, fetch_cluster_state
from .core.cluster_file import ClusterFile
from .rpc.stubs import CoordinatorClient
from .rpc.tcp_transport import TcpTransport
from .rpc.transport import (NetworkAddress, WLTOKEN_COORDINATOR,
                            WLTOKEN_FIRST_AVAILABLE)
from .runtime.errors import FdbError
from .runtime.knobs import Knobs

BASE = WLTOKEN_FIRST_AVAILABLE


class _CliDatabase:
    """Database facade over the CLI's retry loop (refresh-aware)."""

    def __init__(self, cli: "Cli") -> None:
        self._cli = cli

    @property
    def view(self):
        return self._cli.view

    @property
    def coordinators(self):
        return self._cli.coordinators

    def create_transaction(self):
        from .client.transaction import Transaction
        return Transaction(self._cli.view)

    async def run(self, fn, max_retries=None):
        return await self._cli.run_txn(fn)

    async def get(self, key):
        return await self.run(lambda tr: tr.get(key))

    async def set(self, key, value):
        async def go(tr):
            tr.set(key, value)
        await self.run(go)


class Cli:
    def __init__(self, knobs: Knobs, view: RecoveredClusterView,
                 coordinators: list, coordinator_factory=None,
                 cluster_file_path: str | None = None) -> None:
        self.knobs = knobs
        self.view = view
        self.coordinators = coordinators
        self.coordinator_factory = coordinator_factory
        self.cluster_file_path = cluster_file_path

    async def refresh(self) -> None:
        from .runtime.errors import CoordinatorsChanged
        try:
            self.view.update(await fetch_cluster_state(self.coordinators))
        except CoordinatorsChanged as e:
            # the quorum moved (changeQuorum): follow the forward pointer
            addrs = getattr(e, "moved_to", None)
            if addrs is None or self.coordinator_factory is None:
                raise
            self._repoint(addrs)
            self.view.update(await fetch_cluster_state(self.coordinators))

    def _repoint(self, addrs: list) -> None:
        self.coordinators = self.coordinator_factory(addrs)
        if self.cluster_file_path:
            ClusterFile.repoint(self.cluster_file_path, addrs)

    async def run_txn(self, fn):
        tr = Transaction(self.view)
        refreshed_for: set[int] = set()
        while True:
            try:
                out = await fn(tr)
                await tr.commit()
                return out
            except FdbError as e:
                try:
                    await tr.on_error(e)
                except FdbError:
                    # one refresh per distinct non-retryable code covers
                    # stale-view errors; a repeat of any already-refreshed
                    # code is real (e.g. database_locked) and must
                    # surface, not spin
                    if e.code in refreshed_for:
                        raise
                    refreshed_for.add(e.code)
                # EVERY retry follows recoveries: a retryable error
                # (endpoint_not_found, connection_failed) against a
                # stale view would otherwise loop forever dialing the
                # previous epoch's dead endpoints (the
                # _RefreshingTransaction contract)
                await self.refresh()
                tr = Transaction(self.view)

    async def execute(self, line: str) -> str:
        try:
            return await self._execute(line)
        except FdbError as e:
            return f"ERROR: {e.name} ({e.code})"

    async def _execute(self, line: str) -> str:
        parts = shlex.split(line)
        if not parts:
            return ""
        cmd, *args = parts
        if cmd == "get":
            v = await self.run_txn(lambda tr: tr.get(args[0].encode()))
            return f"`{args[0]}' is `{v.decode(errors='replace')}'" if v is not None \
                else f"`{args[0]}': not found"
        if cmd == "set":
            async def do(tr):
                tr.set(args[0].encode(), args[1].encode())
            await self.run_txn(do)
            return "Committed"
        if cmd == "clear":
            async def do(tr):
                tr.clear(args[0].encode())
            await self.run_txn(do)
            return "Committed"
        if cmd == "getrange":
            begin = args[0].encode()
            end = args[1].encode() if len(args) > 1 else b"\xff"
            limit = int(args[2]) if len(args) > 2 else 25

            async def do(tr):
                return await tr.get_range(begin, end, limit=limit)
            rows = await self.run_txn(do)
            return "\n".join(f"`{k.decode(errors='replace')}' is "
                             f"`{v.decode(errors='replace')}'" for k, v in rows) \
                or "<empty>"
        if cmd == "backup" or cmd == "restore":
            from .backup import BackupAgent
            from .runtime.files import RealFileSystem
            agent = BackupAgent(_CliDatabase(self), RealFileSystem(),
                                args[0] if args else "fdb-backup")
            if cmd == "backup":
                m = await agent.backup()
                return f"Backup complete: {m.rows} rows at version {m.version}"
            to_version = int(args[1]) if len(args) > 1 else None
            m = await agent.restore(to_version=to_version)
            return f"Restore complete: {m.rows} rows (snapshot version {m.version})"
        if cmd == "lock":
            from .core.management import (DatabaseLockedByOther,
                                          lock_database)
            import os as _os
            uid = args[0].encode() if args else _os.urandom(8).hex().encode()
            try:
                await lock_database(_CliDatabase(self), uid)
            except DatabaseLockedByOther:
                return "ERROR: locked under a different uid"
            return f"Database locked (uid {uid.decode()})"
        if cmd == "unlock":
            if not args:
                return "ERROR: unlock <uid>"
            from .core.management import (DatabaseLockedByOther,
                                          unlock_database)
            try:
                await unlock_database(_CliDatabase(self), args[0].encode())
            except DatabaseLockedByOther:
                return "ERROR: locked under a different uid"
            return "Database unlocked"
        if cmd == "dr":
            # fdbdr analog: dr start <dest_cluster_file> | dr status |
            # dr switch | dr abort.  The stream runs for the life of this
            # CLI session (the reference runs a separate dr_agent daemon;
            # here the session hosts it).
            from .backup.dr import DRAgent
            sub = args[0] if args else "status"
            if sub == "start":
                if len(args) < 2:
                    return "ERROR: dr start <dest_cluster_file>"
                cur = getattr(self, "_dr", None)
                if cur is not None and cur._task is not None \
                        and not cur._task.done():
                    return ("ERROR: a DR is already running in this "
                            "session (dr abort/switch first)")
                dest = await open_cli(args[1], self.knobs)
                self._dr = DRAgent(_CliDatabase(self), _CliDatabase(dest))
                v0 = await self._dr.start()
                return f"DR started (snapshot version {v0})"
            dr = getattr(self, "_dr", None)
            if dr is None:
                return "ERROR: no DR running in this session"
            if sub == "status":
                st = await dr.status()
                return (f"running: {st['running']}  applied: "
                        f"{st['applied_through']}  lag: "
                        f"{st['lag_versions']} versions")
            if sub == "switch":
                vd = await dr.switchover()
                return (f"Switchover complete at version {vd}: destination "
                        f"is primary; source locked")
            if sub == "abort":
                await dr.abort()
                return "DR aborted (destination keeps its prefix)"
            return f"ERROR: unknown dr subcommand `{sub}'"
        if cmd in ("exclude", "include"):
            # through the special-key space (REF: fdbcli drives exclusion
            # via \xff\xff/management/excluded/ since 6.3)
            from .client.special_keys import ExcludedServersModule
            prefix = ExcludedServersModule.prefix

            async def do(tr):
                tr.special_key_space_enable_writes = True
                for a in args:
                    if cmd == "exclude":
                        tr.set(prefix + a.encode(), b"1")
                    else:
                        tr.clear(prefix + a.encode())
            await self.run_txn(do)
            return f"Servers {cmd}d (takes effect at the next recovery)"
        if cmd == "configure":
            from .core.system_data import conf_key, validate_conf

            async def do(tr):
                for part in args:
                    name, _, val = part.partition("=")
                    tr.set(conf_key(name), validate_conf(name, val))
            await self.run_txn(do)
            return "Configuration changed (takes effect at the next recovery)"
        if cmd == "coordinators":
            # coordinators ip:port[,ip:port...] — changeQuorum
            # (REF:fdbclient/ManagementAPI.actor.cpp::changeQuorum)
            if not args:
                return "coordinators: " + ",".join(
                    f"{c._address.ip}:{c._address.port}"
                    if hasattr(c, "_address") else repr(c)
                    for c in self.coordinators)
            if self.coordinator_factory is None:
                return "ERROR: this cli session cannot change coordinators"
            from .core.coordination import change_coordinators
            raw = ",".join(args).split(",")
            addrs = []
            for part in raw:
                ip, _, port = part.strip().rpartition(":")
                if not ip or not port.isdigit():
                    return f"ERROR: bad coordinator address `{part}'"
                a = [ip, int(port)]
                if a in addrs:
                    # a duplicate would let one process vote twice,
                    # silently collapsing the advertised fault tolerance
                    return f"ERROR: duplicate coordinator address `{part}'"
                addrs.append(a)
            if len(addrs) % 2 == 0:
                return "ERROR: coordinator count must be odd"
            new_stubs = self.coordinator_factory(addrs)
            # loop-clock mover id: unique enough for generation tie-breaks,
            # deterministic under the simulator
            mover = int(asyncio.get_running_loop().time() * 1e6) & 0xFFFFFF
            await change_coordinators(self.coordinators, new_stubs, addrs,
                                      self.knobs, mover_id=mover)
            self._repoint(addrs)
            return "Coordinators changed"
        if cmd == "status" and args and args[0] == "json":
            import json as _json

            from .core.status import cluster_status
            # refresh first: follows a coordinator change (repoint) the
            # same way the plain `status` command does
            await self.refresh()
            doc = await cluster_status(self.knobs, self.view.transport,
                                       self.coordinators)
            return _json.dumps(doc, indent=2, default=str)
        if cmd == "status":
            await self.refresh()
            st = await fetch_cluster_state(self.coordinators)
            lines = [f"epoch: {st['epoch']}",
                     f"recovery_version: {st['recovery_version']}",
                     f"sequencer: {st['sequencer']['addr']}",
                     f"tlogs: {st['log_cfg'][-1]['tlogs']}",
                     f"resolvers: {[r['addr'] for r in st['resolvers']]}",
                     f"storage: {[s['addr'] for s in st['storage']]}",
                     f"commit_proxies: {[p['addr'] for p in st['commit_proxies']]}",
                     f"grv_proxies: {[p['addr'] for p in st['grv_proxies']]}"]
            return "\n".join(lines)
        return f"ERROR: unknown command `{cmd}'"


async def open_cli(cluster_file: str, knobs: Knobs,
                   timeout: float = 30.0, tls=None) -> Cli:
    cf = ClusterFile.load(cluster_file)
    t = TcpTransport(NetworkAddress("127.0.0.1", 0), tls=tls)

    from .rpc.stubs import make_coordinator_stubs

    def coord_factory(addrs):
        return make_coordinator_stubs(addrs, transport=t)

    coords = coord_factory(cf.coordinators)
    deadline = asyncio.get_running_loop().time() + timeout
    while True:
        try:
            state = await fetch_cluster_state(coords)
            break
        except (FdbError, OSError):
            if asyncio.get_running_loop().time() > deadline:
                raise
            await asyncio.sleep(0.5)
    return Cli(knobs, RecoveredClusterView(knobs, t, state), coords,
               coordinator_factory=coord_factory,
               cluster_file_path=cluster_file)


async def amain(args) -> int:
    knobs = Knobs()
    tls = None
    if args.tls_cert:
        from .rpc.tcp_transport import TlsConfig
        tls = TlsConfig(args.tls_cert, args.tls_key, args.tls_ca)
    cli = await open_cli(args.cluster_file, knobs, tls=tls)
    if args.exec:
        for line in args.exec.split(";"):
            out = await cli.execute(line.strip())
            if out:
                print(out)
        return 0
    print("fdbtpu cli — commands: get set clear getrange status exit")
    loop = asyncio.get_running_loop()
    while True:
        line = await loop.run_in_executor(None, lambda: input("fdbtpu> "))
        if line.strip() in ("exit", "quit"):
            return 0
        try:
            out = await cli.execute(line)
        except Exception as e:      # noqa: BLE001 — shell keeps going
            out = f"ERROR: {e!r}"
        if out:
            print(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="foundationdb_tpu.cli")
    ap.add_argument("-C", "--cluster-file", required=True)
    ap.add_argument("--exec", default="", help="semicolon-separated commands")
    ap.add_argument("--tls-cert", default="")
    ap.add_argument("--tls-key", default="")
    ap.add_argument("--tls-ca", default="")
    args = ap.parse_args(argv if argv is not None else sys.argv[1:])
    return asyncio.run(amain(args))


if __name__ == "__main__":
    raise SystemExit(main())
