"""Spec-file-driven simulation tests, including restart/upgrade specs.

Reference: REF:tests/fast/*.toml + REF:tests/restarting/ — the reference
defines simulation tests as declarative spec files (workload lists +
knobs), and its *restarting* tier runs a test in two halves: part 1
against the old binary, then the cluster is stopped, restarted under a
NEW binary/protocol version, and part 2 must find everything intact.

A spec here is TOML:

    [config]
    machines = 5
    replication = 2
    durableStorage = true
    buggify = false

    [[test]]                    # phase 1 workloads (run concurrently)
    testName = "Cycle"
    nodeCount = 10

    [restart]                   # optional: the restarting/upgrade step
    protocolBump = true         # restart as a "new binary"

    [[restart.test]]            # phase 2, after the restart
    testName = "ConsistencyCheck"

With a ``[restart]`` section the runner: quiesces phase 1, snapshots the
whole committed keyspace, power-kills EVERY machine (unsynced writes
lost), restarts them under a bumped PROTOCOL_VERSION, verifies the
snapshot readable byte-for-byte through a NEW client AND through the
multi-version client created BEFORE the upgrade (which must re-resolve
across the protocol change, while a pinned single-version view raises
cluster_version_changed), then runs phase 2.
"""

from __future__ import annotations

try:                            # tomllib is stdlib only from py3.11
    import tomllib
except ModuleNotFoundError:     # py3.10: the same parser's PyPI name
    try:
        import tomli as tomllib
    except ModuleNotFoundError:
        tomllib = None          # last resort: the minimal parser below

from ..core.cluster_controller import ClusterConfigSpec
from ..runtime.buggify import enable_buggify
from ..runtime.errors import FdbError
from ..runtime.knobs import Knobs
from ..runtime.trace import TraceEvent
from ..workloads.workload import run_workloads_on


def load_spec(path: str) -> dict:
    with open(path, "rb") as f:
        blob = f.read()
    if tomllib is not None:
        return tomllib.loads(blob.decode("utf-8"))
    return _parse_spec_toml(blob.decode("utf-8"))


def _parse_value(s: str):
    s = s.strip()
    if s.startswith("[") and s.endswith("]"):
        inner = s[1:-1].strip()
        return [_parse_value(x) for x in _split_top(inner)] if inner else []
    if s in ("true", "false"):
        return s == "true"
    if len(s) >= 2 and s[0] in "\"'" and s[-1] == s[0]:
        return s[1:-1]
    try:
        return int(s)
    except ValueError:
        return float(s)


def _strip_comment(s: str) -> str:
    """Cut a trailing ``# comment`` outside quotes (quote-aware, so a
    '#' inside a quoted string or an array of strings survives)."""
    quote = None
    for i, ch in enumerate(s):
        if quote:
            if ch == quote:
                quote = None
        elif ch in "\"'":
            quote = ch
        elif ch == "#":
            return s[:i]
    return s


def _split_top(s: str) -> list[str]:
    """Split an inline array body on top-level commas."""
    out, depth, cur, quote = [], 0, [], None
    for ch in s:
        if quote:
            cur.append(ch)
            if ch == quote:
                quote = None
            continue
        if ch in "\"'":
            quote = ch
            cur.append(ch)
        elif ch == "[":
            depth += 1
            cur.append(ch)
        elif ch == "]":
            depth -= 1
            cur.append(ch)
        elif ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur and "".join(cur).strip():
        out.append("".join(cur))
    return out


def _parse_spec_toml(text: str) -> dict:
    """Minimal TOML-subset parser covering the sim spec files: comments,
    ``[table]`` / ``[[array.of.tables]]`` headers with dotted names, and
    ``key = value`` where value is a string, int, float, bool, or an
    inline array of those.  Used only when neither tomllib nor tomli is
    importable (old interpreter, bare image)."""
    root: dict = {}
    cur = root

    def descend(parts: list[str]) -> dict:
        node = root
        for p in parts:
            nxt = node.get(p)
            if isinstance(nxt, list):
                node = nxt[-1]
            elif isinstance(nxt, dict):
                node = nxt
            else:
                node = node.setdefault(p, {})
        return node

    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("[["):
            parts = line[2:line.index("]]")].strip().split(".")
            parent = descend(parts[:-1])
            parent.setdefault(parts[-1], [])
            cur = {}
            parent[parts[-1]].append(cur)
        elif line.startswith("["):
            parts = line[1:line.index("]")].strip().split(".")
            parent = descend(parts[:-1])
            cur = parent.setdefault(parts[-1], {})
        else:
            key, _, val = line.partition("=")
            cur[key.strip()] = _parse_value(_strip_comment(val).strip())
    return root


async def run_spec(spec: dict, seed: int = 0,
                   buggify_override: bool | None = None) -> dict:
    """Run one spec against a fresh SimulatedCluster; returns a result
    dict with per-phase workload results + restart continuity info.
    ``buggify_override`` (the CLI's --no-buggify) beats the spec file —
    triage runs must be able to isolate a failure from buggify noise."""
    from .cluster_sim import SimulatedCluster

    cfg = spec.get("config", {})
    buggify = bool(cfg.get("buggify", True)) \
        if buggify_override is None else buggify_override
    knobs = Knobs().override(BUGGIFY_ENABLED=buggify,
                             **cfg.get("knobs", {}))
    # buggify is a process-global flag: restore it on exit, or one spec
    # run leaves fault injection armed for every later sim in the same
    # process (surfaced as replica-lag flakes in unrelated suite tests)
    from ..runtime.buggify import buggify_enabled
    prev_buggify = buggify_enabled()
    enable_buggify(buggify)
    sim = None
    try:
        n = int(cfg.get("machines", 6))
        sim = SimulatedCluster(
            knobs, n_machines=n,
            durable_storage=bool(cfg.get("durableStorage", False)),
            dcids=cfg.get("dcids"),
            spec=ClusterConfigSpec(
                min_workers=n,
                replication=int(cfg.get("replication", 2)),
                logs=int(cfg.get("logs", 2)),
                regions=[dict(r) for r in cfg["regions"]]
                if cfg.get("regions") else None))
        await sim.start()
        state1 = await sim.wait_epoch(1)
        db = await sim.database()

        def _phase_specs(tests: list[dict]) -> list[dict]:
            out = []
            for t in tests:
                t = dict(t)
                t["sim"] = sim      # chaos workloads opt-in to the handle
                out.append(t)
            return out

        results: dict = {"seed": seed}
        results["phase1"] = await run_workloads_on(
            db, _phase_specs(spec.get("test", [])),
            client_count=int(cfg.get("clients", 2)))

        restart = spec.get("restart")
        if restart is not None:
            results["restart"] = await _run_restart(sim, db, restart, state1)
            if restart.get("test"):
                db2 = await sim.database()
                results["phase2"] = await run_workloads_on(
                    db2, _phase_specs(restart["test"]),
                    client_count=int(cfg.get("clients", 2)))
        return results
    finally:
        # teardown runs on the failure path too (a workload assertion
        # must not leak cluster tasks), and must not mask it
        if sim is not None:
            try:
                await sim.stop()
            except Exception:  # noqa: BLE001
                TraceEvent("SpecSimStopFailed", severity=30).log()
        enable_buggify(prev_buggify)


async def _snapshot(db) -> list[tuple[bytes, bytes]]:
    tr = db.create_transaction()
    while True:
        try:
            rows = await tr.get_range(b"", b"\xff", limit=0)
            return [(bytes(a), bytes(b)) for a, b in rows]
        except Exception as e:  # noqa: BLE001 — follow recoveries
            await tr.on_error(e)


async def _run_restart(sim, old_db, restart: dict, state1: dict) -> dict:
    """The restarting/upgrade step: snapshot, whole-cluster power loss,
    restart under a bumped protocol, prove continuity."""
    from ..client.multiversion import (MultiVersionDatabase,
                                       selected_api_version, api_version)
    before = await _snapshot(old_db)
    # the multi-version client is created against the OLD cluster and
    # must survive the upgrade by re-resolving
    if selected_api_version() is None:
        api_version(710)
    mv = MultiVersionDatabase("native", old_db)

    epoch0 = (await sim.wait_state(lambda s: True))["epoch"]
    for m in sim.machines:
        await m.kill()
    if restart.get("protocolBump", True):
        sim.knobs = sim.knobs.override(
            PROTOCOL_VERSION=sim.knobs.PROTOCOL_VERSION + 1)
    for m in sim.machines:
        await m.start()
    state2 = await sim.wait_state(
        lambda s: s["epoch"] > epoch0
        and s.get("protocol") == sim.knobs.PROTOCOL_VERSION)

    out = {"old_protocol": state1.get("protocol"),
           "new_protocol": state2.get("protocol"),
           "rows": len(before)}

    # a NEW client of the new "binary" reads everything back
    db2 = await sim.database()
    after = await _snapshot(db2)
    if after != before:
        missing = len({k for k, _ in before} - {k for k, _ in after})
        raise AssertionError(
            f"restart lost/changed data: {len(before)} rows before, "
            f"{len(after)} after ({missing} missing)")

    if restart.get("protocolBump", True):
        # the PINNED old view must refuse the upgraded cluster...
        try:
            await old_db.refresh()
            raise AssertionError(
                "pinned single-version client accepted an upgraded "
                "cluster (expected cluster_version_changed)")
        except FdbError as e:
            if e.code != 1039:
                raise
        # ...while the multi-version client re-resolves and keeps going
        async def probe(tr):
            return await tr.get(before[0][0]) if before else None
        got = await mv.run(probe)
        if before:
            assert bytes(got) == before[0][1], "mv client read stale data"
        out["mv_client_switched"] = True
    return out
