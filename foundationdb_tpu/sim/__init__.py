"""Machine-level simulation: simulated cluster + fault workloads + seed farm.

Reference: REF:fdbserver/SimulatedCluster.actor.cpp + workloads/ — the
whole-cluster crucible: machines with lossy filesystems and a shared
deterministic network get killed, rebooted, clogged and partitioned while
invariant workloads run; any divergence is a real bug at some seed.
"""

from .cluster_sim import RefreshingDatabase, SimMachine, SimulatedCluster

__all__ = ["SimMachine", "SimulatedCluster", "RefreshingDatabase"]
