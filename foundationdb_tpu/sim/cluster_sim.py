"""SimulatedCluster — machines, kills, reboots on the deterministic net.

Reference: REF:fdbserver/SimulatedCluster.actor.cpp — a simulated machine
is an IP (every process transport on it), a lossy filesystem and the
fdbserver process (here: ClusterHost, plus a durable Coordinator when the
machine is in the quorum).  Killing a machine drops every packet to/from
its IP AND its filesystem's unsynced writes — the crash semantics FDB's
recovery is proved against; rebooting brings up a fresh process over the
surviving disk state.

Storage machines are excluded from attrition by callers until
DataDistribution can re-replicate lost replicas (the reference's
MachineAttrition honors the same constraint via protectedAddresses).
"""

from __future__ import annotations

import asyncio
import itertools

from ..core.cluster_controller import ClusterConfigSpec
from ..core.cluster_client import (RecoveredClusterView,
                                   RefreshingDatabase, fetch_cluster_state)
from ..core.cluster_host import ClusterHost
from ..core.coordination import Coordinator
from ..rpc.sim_transport import SimNetwork, SimTransport
from ..rpc.stubs import CoordinatorClient, serve_role
from ..rpc.transport import (NetworkAddress, WLTOKEN_COORDINATOR,
                             WLTOKEN_FIRST_AVAILABLE)
from ..runtime.errors import FdbError
from ..runtime.files import DiskFaultProfile, SimFileSystem
from ..runtime.knobs import Knobs
from ..runtime.trace import TraceEvent

BASE = WLTOKEN_FIRST_AVAILABLE
SERVER_PORT = 5100


class SimMachine:
    """One machine: IP + lossy filesystem + (coordinator?) + ClusterHost."""

    def __init__(self, sim: "SimulatedCluster", index: int,
                 coordinator: bool) -> None:
        self.sim = sim
        self.index = index
        self.ip = f"10.1.0.{index + 1}"
        self.is_coordinator = coordinator
        # hostile-disk model (ISSUE 12): every machine carries a
        # DiskFaultProfile — disarmed by default (zero rng draws, so
        # same-seed traces with faults off stay bit-identical).  Knob
        # SIM_DISK_FAULTS arms it at boot from a per-machine split of
        # the sim rng; DiskFaultWorkload arms it mid-run.
        self.fault_profile = DiskFaultProfile()
        self.fs = SimFileSystem(profile=self.fault_profile)
        self.fs.health.configure(sim.knobs.DISK_HEALTH_HALFLIFE_S,
                                 sim.knobs.DISK_DEGRADED_LATENCY_MS)
        if sim.knobs.SIM_DISK_FAULTS:
            from ..runtime.rng import deterministic_random
            self.fault_profile.arm_from_knobs(
                sim.knobs, deterministic_random().split())
        self.addr = NetworkAddress(self.ip, SERVER_PORT)
        self.host: ClusterHost | None = None
        self.coordinator: Coordinator | None = None
        self.alive = False
        self._ports = itertools.count(5200)
        self._boots = 0

    def _client_transport(self) -> SimTransport:
        return SimTransport(self.sim.net,
                            NetworkAddress(self.ip, next(self._ports)))

    async def start(self) -> None:
        """Boot (or reboot) the machine's process.  With a fault profile
        armed, boot-time disk reads can fail (injected IoError) — the
        supervisor loop retries like a respawning fdbserver would,
        bounded so real corruption (DiskCorrupt) still fails the boot
        loudly after a few attempts."""
        attempt = 0
        while True:
            try:
                return await self._start_once()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — supervisor respawn
                attempt += 1
                from ..runtime.errors import DiskCorrupt
                if isinstance(e, DiskCorrupt) or attempt >= 20:
                    raise
                TraceEvent("SimMachineBootError", severity=30) \
                    .detail("IP", self.ip).detail("Attempt", attempt) \
                    .detail("Error", repr(e)[:120]).log()
                await asyncio.sleep(0.25)

    async def _start_once(self) -> None:
        self.sim.net.reboot_ip(self.ip)
        transport = SimTransport(self.sim.net, self.addr)  # replaces listener
        # EVERY machine serves a coordination register (idle unless its
        # address is in the connection string) so `coordinators` can move
        # the quorum onto any machine — like fdbserver, where any process
        # can host coordination when the connection string names it
        self.coordinator = await Coordinator.open(
            self.sim.knobs, self.fs, "coordination-0.fdq")
        serve_role(transport, "coordinator", self.coordinator,
                   WLTOKEN_COORDINATOR)

        from ..rpc.stubs import make_coordinator_stubs

        def coord_factory(addrs):
            return make_coordinator_stubs(
                addrs, transport_factory=self._client_transport)

        coord_stubs = coord_factory(self.sim.coord_addrs)
        # host ids must differ across boots or coordinators could confuse
        # two incarnations in the same election
        host_id = self.index + 100 * self._boots
        self._boots += 1
        locality = {}
        if self.sim.dcids is not None:
            locality["dcid"] = self.sim.dcids[self.index]
        self.host = ClusterHost(
            host_id, self.sim.knobs, transport, self._client_transport,
            BASE, coord_stubs, self.sim.spec,
            fs=self.fs if self.sim.durable_storage else None,
            data_dir="data", locality=locality,
            coordinator_factory=coord_factory)
        self.host.start()
        self.alive = True

    async def kill(self) -> None:
        """Machine crash: network dark + unsynced writes lost + process
        coroutines stopped."""
        TraceEvent("SimMachineKill").detail("IP", self.ip).log()
        self.sim.net.kill_ip(self.ip)
        self.fs.kill_unsynced()
        self.alive = False
        if self.host is not None:
            await self.host.stop()
            self.host = None
        self.coordinator = None

    async def reboot(self) -> None:
        TraceEvent("SimMachineReboot").detail("IP", self.ip).log()
        await self.start()


class SimulatedCluster:
    """The machine fleet + shared network + client helpers."""

    def __init__(self, knobs: Knobs | None = None, n_machines: int = 6,
                 n_coordinators: int = 3,
                 spec: ClusterConfigSpec | None = None,
                 durable_storage: bool = False,
                 dcids: list[str] | None = None) -> None:
        self.durable_storage = durable_storage
        # per-machine datacenter ids (multi-region topologies); rides
        # worker registration as locality
        assert dcids is None or len(dcids) == n_machines
        self.dcids = dcids
        # sim-scale resolver shapes: the numpy conflict twin scans the
        # whole ever-written ring per batch, and append-slab rings consume
        # B*R slots per batch — production-sized shapes (64x8 over 2^16
        # slots) cost ~seconds of real time per resolve in simulation
        self.knobs = (knobs or Knobs()).override(
            RESOLVER_BATCH_TXNS=16, RESOLVER_RANGES_PER_TXN=4,
            CONFLICT_RING_CAPACITY=1 << 12, KEY_ENCODE_BYTES=16)
        self.net = SimNetwork(self.knobs)
        self.spec = spec or ClusterConfigSpec(
            min_workers=n_machines, replication=2)
        self.machines = [SimMachine(self, i, i < n_coordinators)
                         for i in range(n_machines)]
        self.coord_addrs = [m.addr for m in self.machines[:n_coordinators]]
        self._client_ports = itertools.count(7000)

    async def start(self) -> None:
        for m in self.machines:
            await m.start()

    async def stop(self) -> None:
        for m in self.machines:
            if m.host is not None:
                await m.host.stop()

    # --- clients ---

    def client_transport(self) -> SimTransport:
        p = next(self._client_ports)
        return SimTransport(self.net, NetworkAddress("10.9.0.1", p))

    def coordinator_stubs(self, transport=None):
        t = transport or self.client_transport()
        return [CoordinatorClient(t, a, WLTOKEN_COORDINATOR)
                for a in self.coord_addrs]

    async def wait_epoch(self, n: int, poll: float = 0.25) -> dict:
        return await self.wait_state(lambda s: s.get("epoch", 0) >= n, poll)

    async def wait_state(self, pred, poll: float = 0.25) -> dict:
        """Poll the coordinators until the published cluster state
        satisfies ``pred`` (e.g. a live move's seq bump)."""
        stubs = self.coordinator_stubs()
        while True:
            try:
                state = await fetch_cluster_state(stubs)
                if pred(state):
                    return state
            except FdbError:
                pass
            await asyncio.sleep(poll)

    async def database(self) -> "RefreshingDatabase":
        t = self.client_transport()
        stubs = self.coordinator_stubs(t)
        state = await fetch_cluster_state(stubs)
        view = RecoveredClusterView(self.knobs, t, state)
        return RefreshingDatabase(view, stubs)

    async def kill_dc(self, dcid: str) -> list:
        """Region loss: kill every live machine whose locality is dcid."""
        victims = [m for m in self.machines
                   if self.dcids is not None and m.alive
                   and self.dcids[m.index] == dcid]
        for m in victims:
            await m.kill()
        return victims

    # --- fault targeting ---

    def leader_cc(self):
        """The live ClusterController, if any machine currently leads."""
        for m in self.machines:
            if m.alive and m.host is not None and m.host.cc is not None:
                return m.host.cc
        return None

    def leader_dd(self):
        """The live DataDistributor, if any machine currently leads."""
        for m in self.machines:
            if m.alive and m.host is not None \
                    and getattr(m.host, "dd", None) is not None:
                return m.host.dd
        return None

    def leader_scrubber(self):
        """The live ConsistencyScrubber, if any machine currently
        leads with SCRUB_ENABLED (ISSUE 17)."""
        for m in self.machines:
            if m.alive and m.host is not None \
                    and getattr(m.host, "scrubber", None) is not None:
                return m.host.scrubber
        return None

    def storage_objects(self) -> list:
        """Every live in-process StorageServer object (scrub tests
        reach these to inject test-only corruption on ONE replica)."""
        out = []
        for m in self.machines:
            if m.alive and m.host is not None:
                for role, obj in m.host.worker.roles.values():
                    if role == "storage":
                        out.append(obj)
        return out

    async def txn_only_machines(self) -> list[SimMachine]:
        """Machines whose kill exercises recovery: hosting at least one
        txn-subsystem role, but no storage replica (re-replication needs
        DataDistribution) and not a coordinator.  The elected controller's
        machine may be included — CC failover is part of what attrition
        tests."""
        state = await self.wait_epoch(1)
        storage_ips = {s["worker"][0] for s in state["storage"]}
        role_ips = {state["sequencer"]["addr"][0]}
        role_ips |= {a[0] for a in state["log_cfg"][-1]["tlogs"]}
        role_ips |= {r["addr"][0] for r in state["resolvers"]}
        role_ips |= {p["addr"][0]
                     for p in state["commit_proxies"] + state["grv_proxies"]}
        if state.get("ratekeeper"):
            role_ips.add(state["ratekeeper"]["addr"][0])
        # coordinator protection derives from the CURRENT quorum — a
        # changeQuorum mid-run moves it, and the boot-time per-machine
        # flag would protect a retired member while exposing a new one
        coord_ips = {a.ip for a in self.coord_addrs}
        return [m for m in self.machines
                if m.ip not in coord_ips and m.ip not in storage_ips
                and m.ip in role_ips]
