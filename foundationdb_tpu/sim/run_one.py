"""One simulation run: the analog of ``fdbserver -r simulation -s <seed>``.

Boots a SimulatedCluster, runs Cycle + Serializability concurrently with
MachineAttrition + RandomClogging under BUGGIFY, checks invariants, exits
0 on success.  The seed farm (tools/seed_farm.py) fans these out.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from ..core.cluster_controller import ClusterConfigSpec
from ..runtime.buggify import enable_buggify
from ..runtime.knobs import Knobs
from ..runtime.simloop import run_simulation
from ..workloads.workload import run_workloads_on
from .cluster_sim import SimulatedCluster


async def simulate(seed: int, kills: int, buggify: bool,
                   faults: str | None = None) -> dict:
    knobs = Knobs().override(BUGGIFY_ENABLED=buggify, DD_ENABLED=True)
    durable = False
    if faults == "disk":
        # hostile-disk profile (ISSUE 12): every machine's fault profile
        # armed from boot AND durable storage so torn/corrupt kills bite
        # every durable surface (engines, WALs, TLog queues, spill side
        # files) — the seed farm's `--faults disk` profile.  The MVCC
        # window stays at its default: tightening it (200k versions)
        # trips a PRE-EXISTING ambiguous-commit resurrection under the
        # durable chaos mix (seed 3 reproduces on the pre-fault tree
        # with zero injection — ROADMAP item 6 follow-up (e)), which is
        # a real bug this profile surfaced but not one this PR fixes.
        knobs = knobs.override(SIM_DISK_FAULTS=True)
        durable = True
    enable_buggify(buggify)
    sim = SimulatedCluster(knobs, n_machines=7, durable_storage=durable,
                           spec=ClusterConfigSpec(min_workers=7,
                                                  replication=2))
    await sim.start()
    await sim.wait_epoch(1)
    db = await sim.database()
    specs = [
        {"testName": "Cycle", "nodeCount": 12, "transactionsPerClient": 30},
        {"testName": "Serializability", "numOps": 40},
        {"testName": "AtomicOps", "addsPerClient": 15},
        {"testName": "ConflictRange", "nodeCount": 8, "opsPerClient": 15},
        {"testName": "Increment", "incrementsPerClient": 10},
        {"testName": "VersionStamp", "stampsPerClient": 8},
        {"testName": "Watches", "rounds": 3, "strictFires": False},
        {"testName": "ApiCorrectness", "keyCount": 16,
         "transactionsPerClient": 10, "opsPerTransaction": 6},
        {"testName": "Sideband", "messages": 8},
        {"testName": "BankTransfer", "accounts": 8,
         "transfersPerClient": 8, "scanEvery": 4},
        # r5 additions: API-contract fuzzers + operational invariants
        {"testName": "WriteDuringRead", "rounds": 4, "opsPerRound": 15},
        {"testName": "FuzzApiCorrectness", "calls": 50},
        {"testName": "SelectorCorrectness", "keys": 12, "probes": 25},
        {"testName": "Storefront", "orders": 10},
        {"testName": "SpecialKeySpaceCorrectness", "rounds": 2},
        # change-feed completeness under the whole chaos mix (ISSUE 4):
        # exactly-once, exact-version, in-order delivery while machines
        # die, ranges move and BUGGIFY fires
        {"testName": "ChangeFeed", "transactionsPerClient": 10,
         "popAfter": 6},
        {"testName": "LowLatency", "seconds": 6.0, "maxLatency": 30.0},
        # (the r5 "DD+swizzle causal failures" turned out to be the API
        # fuzzer's unscoped clear_range wiping other workloads' keys —
        # fixed by endpoint validation + mutation scoping; DD live moves
        # run in the default mix again)
        {"testName": "RandomMoveKeys", "sim": sim, "moves": 1,
         "secondsBetweenMoves": 3.0},
        {"testName": "ConfigureDatabase", "sim": sim, "rounds": 2,
         "secondsBetweenChanges": 2.5},
        {"testName": "MachineAttrition", "sim": sim, "machinesToKill": kills},
        {"testName": "Swizzle", "sim": sim, "rounds": 1,
         "secondsBefore": 6.0},
        {"testName": "RandomClogging", "sim": sim, "testDuration": 8.0},
        # hostile disks ride the default chaos mix (ISSUE 12): live-op
        # IO errors + stalls for the first stretch, kill-time torn/
        # corrupt writes for every attrition/swizzle kill — so every
        # future PR's durable code faces torn and corrupt disks by
        # default (the coordinator state files in this mix; every
        # engine/WAL/side-file too under --faults disk)
        {"testName": "DiskFault", "sim": sim, "testDuration": 10.0},
        {"testName": "ConsistencyCheck"},
    ]
    results = await run_workloads_on(db, specs, client_count=2)
    await sim.stop()
    return results


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--kills", type=int, default=2)
    ap.add_argument("--no-buggify", action="store_true")
    ap.add_argument("--faults", choices=("disk",),
                    help="arm a fault profile: 'disk' = hostile disks "
                    "from boot on a DURABLE cluster (torn/corrupt/"
                    "erroring/slow; ISSUE 12)")
    ap.add_argument("--spec", help="run a TOML test spec (tests/specs/*) "
                    "instead of the built-in chaos mix")
    args = ap.parse_args(argv)
    try:
        if args.spec:
            from .spec import load_spec, run_spec
            results = run_simulation(
                run_spec(load_spec(args.spec), seed=args.seed,
                         buggify_override=False if args.no_buggify
                         else None),
                seed=args.seed)
        else:
            results = run_simulation(
                simulate(args.seed, args.kills, not args.no_buggify,
                         faults=args.faults),
                seed=args.seed)
    except BaseException as e:  # noqa: BLE001 — the signature IS the output
        print(json.dumps({"seed": args.seed, "ok": False,
                          "error": f"{type(e).__name__}: {e}"[:300]}))
        return 1
    print(json.dumps({"seed": args.seed, "ok": True, "results": results}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
