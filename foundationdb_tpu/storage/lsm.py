"""LSM key-value engine: memtable + WAL + sorted-run files + compaction.

Reference: the disk-backed IKeyValueStore engines —
REF:fdbserver/VersionedBTree.actor.cpp (Redwood) and
REF:fdbserver/KeyValueStoreRocksDB.actor.cpp — behind the same
IKeyValueStore surface as kv_store.MemoryKVStore.  Where the memory
engine caps the database at RAM and rewrites O(db) snapshots, this engine
keeps only the memtable in RAM:

- writes land in the WAL (DiskQueue, fsync per commit) + memtable;
- a full memtable flushes to an immutable sorted-run file (data blocks +
  a sparse index block + footer), newest-first;
- reads consult memtable then runs newest→oldest through a small LRU
  block cache (sync block reads — the page-cache path);
- too many runs trigger a merge compaction into one run (tombstones
  elided at the bottom level);
- the MANIFEST names the live runs + engine metadata; every state change
  (flush/compact) writes MANIFEST atomically after the new files are
  durable, so a crash at any point recovers to a consistent run set.
"""

from __future__ import annotations

import bisect
import heapq
from collections import OrderedDict
from typing import Iterator

from ..rpc.wire import decode, encode
from .disk_queue import DiskQueue
from .key_runs import KeyRun
from .kv_store import OP_CLEAR, OP_SET

_TOMBSTONE = None          # value None in runs marks a deletion
_BLOCK_BYTES = 1 << 16
_MEMTABLE_BYTES = 1 << 22  # flush threshold (4MB)
_MAX_RUNS = 6              # compact when exceeded
_MEM_RUN_ROWS = 2048       # memtable rows per bulk run (range_runs)
_CACHE_BLOCKS = 256        # LRU block cache entries (~16MB)
_FOOTER = b"LSM1"


class _Run:
    """One immutable sorted-run file: block-sparse index in RAM, data
    blocks read on demand through the shared cache."""

    def __init__(self, fs, path: str, cache: "_BlockCache") -> None:
        self.path = path
        self._f = fs.open(path)
        self._cache = cache
        size = self._f.size()
        foot = self._f.read_sync(size - 12, 12)
        if foot[8:] != _FOOTER:
            # runs are named by a manifest written only AFTER the run
            # file synced, so a bad footer is never a torn flush — it is
            # corruption of committed data, raised loudly (ISSUE 12)
            from ..runtime.errors import DiskCorrupt
            raise DiskCorrupt(f"bad sorted-run footer in committed run "
                              f"{path}")
        idx_off = int.from_bytes(foot[:8], "little")
        self.index = decode(self._f.read_sync(idx_off, size - 12 - idx_off))
        # index: list of [first_key, offset, length].  The sparse index
        # (block first keys) is a COLUMNAR KeyRun (storage/key_runs.py,
        # ISSUE 11): one blob + bounds + cached u64 prefixes — the same
        # layout PackedKeyIndex's base run uses, deduplicating the
        # searchsorted-over-prefixes discipline this file had grown its
        # own copy of (the old first_keys list + _fk_pfx pair)
        self.first_keys = KeyRun.from_keys([bytes(e[0]) for e in self.index])

    def _block(self, i: int) -> list:
        key = (self.path, i)
        blk = self._cache.get(key)
        if blk is None:
            _, off, ln = self.index[i]
            blk = decode(self._f.read_sync(off, ln))
            self._cache.put(key, blk)
        return blk

    def get(self, key: bytes) -> tuple[bool, bytes | None]:
        """(found, value-or-tombstone)."""
        i = self.first_keys.bisect_right(key) - 1
        if i < 0:
            return False, None
        blk = self._block(i)
        keys = [bytes(e[0]) for e in blk]
        j = bisect.bisect_left(keys, key)
        if j < len(keys) and keys[j] == key:
            v = blk[j][1]
            return True, (bytes(v) if v is not None else None)
        return False, None

    def get_batch_into(self, keys: list[bytes], idxs: list[int],
                       out: list) -> list[int]:
        """Probe ``keys[i] for i in idxs`` (idxs ascending over sorted
        keys) against this run, writing hits — including tombstones —
        into ``out``; returns the still-unresolved indices for the next
        (older) run.  The block per probe resolves in ONE vectorized
        ``searchsorted`` over the sparse index's cached u64 prefixes
        (``KeyRun.batch_bisect`` — the shared home of the
        PackedKeyIndex bound-batch discipline), a bisect refining
        inside the equal-prefix band; each touched block is then
        decoded exactly once per batch."""
        fk = self.first_keys
        if not fk:
            return idxs
        blocks = [b - 1 for b in
                  fk.batch_bisect([keys[i] for i in idxs], side="right",
                                  sorted_keys=True)]
        remaining: list[int] = []
        cur = -1
        bkeys: list[bytes] = []
        blk: list = []
        for i, b in zip(idxs, blocks):
            if b < 0:
                remaining.append(i)
                continue
            if b != cur:        # idxs sorted => blocks non-decreasing
                cur = b
                blk = self._block(b)
                bkeys = [bytes(e[0]) for e in blk]
            j = bisect.bisect_left(bkeys, keys[i])
            if j < len(bkeys) and bkeys[j] == keys[i]:
                v = blk[j][1]
                out[i] = bytes(v) if v is not None else None
            else:
                remaining.append(i)
        return remaining

    def iter_range(self, begin: bytes, end: bytes,
                   reverse: bool = False) -> Iterator[tuple[bytes, bytes | None]]:
        lo = max(0, self.first_keys.bisect_right(begin) - 1)
        hi = self.first_keys.bisect_left(end)
        blocks = range(lo, min(hi + 1, len(self.index)))
        if reverse:
            blocks = reversed(blocks)
        for i in blocks:
            blk = self._block(i)
            entries = reversed(blk) if reverse else blk
            for k, v in entries:
                k = bytes(k)
                if k < begin or k >= end:
                    continue
                yield k, (bytes(v) if v is not None else None)

    def range_blocks(self, begin: bytes,
                     end: bytes) -> Iterator[list]:
        """Forward block RUNS of [begin, end): each touched block
        decoded once, the boundary blocks trimmed by bisect, interior
        blocks sliced wholesale — the searchsorted-over-sorted-index
        discipline of ``get_batch_into`` generalized from point probes
        to interval extraction (ISSUE 9).  Rows include tombstones
        (value None): the engine-level newest-wins merge needs them."""
        fk = self.first_keys
        if not fk:
            return
        first = lambda e: e[0]  # noqa: E731 — bisect key
        lo = max(0, fk.bisect_right(begin) - 1)
        stop = max(fk.bisect_left(end), lo + 1)
        for i in range(lo, stop):
            # the decoder already hands back bytes keys/values, so rows
            # pass through with NO per-row re-materialization: interior
            # blocks yield the cached block list itself (read-only by
            # contract), boundary blocks yield one slice
            blk = self._block(i)
            if i == lo or i == stop - 1:
                s = (bisect.bisect_left(blk, begin, key=first)
                     if i == lo else 0)
                t = (bisect.bisect_left(blk, end, key=first)
                     if i == stop - 1 else len(blk))
                if s >= t:
                    continue
                yield blk[s:t] if (s or t < len(blk)) else blk
            else:
                yield blk


class _BlockCache:
    def __init__(self, capacity: int) -> None:
        self.capacity = capacity
        self._d: OrderedDict = OrderedDict()

    def get(self, key):
        blk = self._d.get(key)
        if blk is not None:
            self._d.move_to_end(key)
        return blk

    def put(self, key, blk) -> None:
        self._d[key] = blk
        self._d.move_to_end(key)
        while len(self._d) > self.capacity:
            self._d.popitem(last=False)

    def drop_file(self, path: str) -> None:
        for k in [k for k in self._d if k[0] == path]:
            del self._d[k]


class LsmSparseIndex:
    """Merged block directory over every sorted run — the lsm engine's
    ``packed_index`` (ISSUE 11, ROADMAP item 1 (e)).

    The per-run sparse indexes (block first keys) merge into ONE sorted
    ``KeyRun`` with parallel (run, block) back-pointer columns and a
    per-run prefix-max table, so a probe key's candidate block in EVERY
    run falls out of a single sorted-array bound:

        pos = bisect_right(merged, key)
        candidate block of run r = blockmax[pos][r]
          (== bisect_right(run_r.first_keys, key) - 1, by construction)

    That single sorted u64-prefix array is exactly the shape the device
    read mirror consumes (device/read_serve.py): one vectorized
    ``searchsorted`` per ``get_values`` batch locates the candidate
    block in every run at once, replacing the per-run host searchsorted
    descent — the surface where the device gather finally sits over a
    real probe structure instead of MemoryKVStore's O(1) dict.

    ``gen`` bumps whenever the run SET changes (open/flush/compact);
    memtable writes never stale it — the memtable is probed host-side
    by ``get_batch_located``, the lsm twin of the pending-overlay
    contract the PackedKeyIndex mirror already has."""

    device_mode = "blocks"      # host refinement the device mirror needs

    __slots__ = ("_store", "gen", "_cache")

    def __init__(self, store: "LSMKVStore") -> None:
        self._store = store
        self.gen = 0
        self._cache: tuple | None = None    # (merged KeyRun, blockmax)

    def bump(self) -> None:
        self.gen += 1
        self._cache = None

    def _ensure(self) -> tuple:
        if self._cache is None:
            import numpy as np
            runs = self._store._runs
            entries: list[tuple[bytes, int, int]] = []
            for r_i, run in enumerate(runs):
                fk = run.first_keys
                entries.extend((fk.key(b_i), r_i, b_i)
                               for b_i in range(len(fk)))
            entries.sort()
            merged = KeyRun.from_keys([e[0] for e in entries])
            n, nr = len(entries), len(runs)
            blockmax = np.full((n + 1, max(nr, 1)), -1, dtype=np.int64)
            if n and nr:
                run_of = np.fromiter((e[1] for e in entries),
                                     dtype=np.int64, count=n)
                block_of = np.fromiter((e[2] for e in entries),
                                       dtype=np.int64, count=n)
                for r in range(nr):
                    col = np.where(run_of == r, block_of, -1)
                    # blocks within a run appear in ascending order, so
                    # the running max IS the newest block at-or-before
                    np.maximum.accumulate(col, out=col)
                    blockmax[1:, r] = col
            self._cache = (merged, blockmax)
        return self._cache

    # --- the device-mirror surface (DeviceKeyDirectory contract) ---

    def base_run(self) -> KeyRun:
        return self._ensure()[0]

    def pending_run(self) -> list[bytes]:
        return []               # the memtable is handled host-side

    def base_prefixes(self):
        return self._ensure()[0].prefixes()


class LSMKVStore:
    """IKeyValueStore-compatible LSM engine (see kv_store.MemoryKVStore
    for the surface contract)."""

    def __init__(self, fs, prefix: str) -> None:
        self.fs = fs
        self.prefix = prefix
        self.meta: dict = {}
        self._mem: dict[bytes, bytes | None] = {}   # None = tombstone
        self._mem_index: list[bytes] = []
        self._mem_bytes = 0
        self._runs: list[_Run] = []                 # newest first
        self._cache = _BlockCache(_CACHE_BLOCKS)
        self._sparse = LsmSparseIndex(self)
        self._wal: DiskQueue | None = None
        self._wal_file = None
        self._gen = 0
        self._wal_gen = 0
        # the dual-slot manifest persist (rpc/wire.SlottedBlob); open()
        # replaces it with the loaded/armed instance
        from ..rpc.wire import SlottedBlob
        self._man_sb = SlottedBlob(fs, prefix,
                                   (".MANIFEST.a", ".MANIFEST.b"))

    # --- lifecycle ---

    @classmethod
    async def _load_manifest(cls, fs, prefix: str
                             ) -> tuple[dict | None, int, "SlottedBlob"]:
        """Newest valid manifest from the shared dual-slot helper
        (rpc/wire.py ``SlottedBlob`` — ONE audited corruption policy,
        ISSUE 13 / ROADMAP 6 (f)), falling back to the two pre-helper
        slot formats: the ISSUE-12 crc-framed dict-with-seq slots, and
        the original rewritten-in-place single file (which a torn kill
        could destroy outright).  Returns (manifest, slots seen, the
        armed helper for subsequent saves)."""
        from ..rpc.wire import SlottedBlob, unframe
        sb = SlottedBlob(fs, prefix, (".MANIFEST.a", ".MANIFEST.b"))
        payload, found = await sb.load()
        if payload is not None:
            return decode(payload), found, sb
        best = None
        for suffix in (".MANIFEST.a", ".MANIFEST.b"):
            f = fs.open(prefix + suffix)
            blob = await f.read(0, f.size())
            await f.close()
            if not blob:
                continue
            try:
                man = decode(unframe(blob))
            except Exception:  # noqa: BLE001 — torn slot: other one wins
                continue
            if best is None or man.get("seq", 0) > best.get("seq", 0):
                best = man
        if best is not None:
            # keep the slot alternation continuous across the envelope
            # migration: the next save must NOT target the only valid
            # old-format slot
            sb.seed(best.get("seq", 0))
            return best, found, sb
        legacy = fs.open(prefix + ".MANIFEST")
        blob = await legacy.read(0, legacy.size())
        await legacy.close()
        if blob:
            found += 1
            try:
                return decode(blob), found, sb
            except Exception:  # noqa: BLE001 — caller decides torn/corrupt
                pass
        return None, found, sb

    @classmethod
    async def open(cls, fs, prefix: str) -> "LSMKVStore":
        kv = cls(fs, prefix)
        man, slots_seen, kv._man_sb = await cls._load_manifest(fs, prefix)
        if man is not None:
            kv.meta = man["meta"]
            kv._gen = man["gen"]
            kv._wal_gen = man.get("wal_gen", 0)
            for path in man["runs"]:
                kv._runs.append(_Run(fs, str(path), kv._cache))
            kv._sparse.bump()
        kv._wal_file = fs.open(prefix + ".wal")
        kv._wal, frames = await DiskQueue.open(kv._wal_file)
        recs = [decode(frame) for frame, _end in frames]
        if man is None and slots_seen:
            # manifest slots exist but none decodes.  A kill tearing the
            # FIRST-ever manifest write is legitimate (the WAL was not
            # yet popped, so gen-0 frames rebuild everything); but WAL
            # frames at gen > 0 — or committed runs with no WAL at all —
            # prove a synced manifest once existed and was popped
            # against: recovering without it would silently resurrect a
            # partial ancient state (ISSUE 12)
            gens = [r["gen"] for r in recs]
            has_runs = bool(fs.listdir(prefix + ".run."))
            if (gens and min(gens) > 0) or (has_runs and not gens):
                from ..runtime.errors import DiskCorrupt
                raise DiskCorrupt(
                    f"no readable MANIFEST among {slots_seen} slots for "
                    f"{prefix} while committed runs/WAL generations "
                    f"reference one — the committed run set is damaged, "
                    f"refusing silent recovery")
        for rec in recs:
            if rec["gen"] < kv._wal_gen:
                continue        # folded into a flushed run already
            kv._apply_mem(rec["ops"])
            kv.meta = rec["meta"]
        kv._mem_index = sorted(kv._mem)
        return kv

    async def close(self) -> None:
        if self._wal_file is not None:
            await self._wal_file.close()

    def __len__(self) -> int:
        n = 0
        for _ in self.range(b"", b"\xff\xff\xff\xff"):
            n += 1
        return n

    # --- reads ---

    @property
    def packed_index(self) -> LsmSparseIndex:
        """The merged sparse-index directory — the capability probe the
        device read path keys on (device/read_serve.py, ISSUE 11)."""
        return self._sparse

    def get(self, key: bytes) -> bytes | None:
        if key in self._mem:
            return self._mem[key]
        for run in self._runs:
            found, v = run.get(key)
            if found:
                return v
        return None

    def get_batch(self, keys: list[bytes]) -> list[bytes | None]:
        """Batched point reads over SORTED keys (the multiget engine
        fall-through): one memtable dict pass, then each run probed
        once via its vectorized sparse-index search — every touched
        data block decodes once per batch instead of once per key."""
        out: list[bytes | None] = [None] * len(keys)
        mem = self._mem
        pending: list[int] = []
        for i, k in enumerate(keys):
            if k in mem:
                out[i] = mem[k]     # value or tombstone (None): resolved
            else:
                pending.append(i)
        for run in self._runs:
            if not pending:
                break
            pending = run.get_batch_into(keys, pending, out)
        return out

    def get_batch_located(self, keys: list[bytes],
                          pos: list[int]) -> list[bytes | None]:
        """Finish a device-located batch (ISSUE 11): ``pos[i]`` is the
        bisect_right of ``keys[i]`` over the merged sparse directory
        (``packed_index.base_run()``) — computed by the device mirror's
        vectorized searchsorted.  The host half probes the memtable
        first, then each run's candidate block newest→oldest, resolving
        tombstones newest-wins — result identical to ``get_batch`` on
        the same keys by construction (the directory's prefix-max table
        reproduces exactly each run's ``bisect_right(first_keys) - 1``
        block choice), and tested."""
        _merged, blockmax = self._sparse._ensure()
        out: list[bytes | None] = [None] * len(keys)
        mem = self._mem
        runs = self._runs
        bkeys_cache: dict[tuple[int, int], list[bytes]] = {}
        for i, k in enumerate(keys):
            if k in mem:
                out[i] = mem[k]
                continue
            row = blockmax[pos[i]]
            for r_i in range(len(runs)):
                b = int(row[r_i])
                if b < 0:
                    continue
                ck = (r_i, b)
                bkeys = bkeys_cache.get(ck)
                blk = runs[r_i]._block(b)
                if bkeys is None:
                    bkeys = [bytes(e[0]) for e in blk]
                    bkeys_cache[ck] = bkeys
                j = bisect.bisect_left(bkeys, k)
                if j < len(bkeys) and bkeys[j] == k:
                    v = blk[j][1]
                    out[i] = bytes(v) if v is not None else None
                    break
        return out

    def range(self, begin: bytes, end: bytes,
              reverse: bool = False) -> Iterator[tuple[bytes, bytes]]:
        """Newest-wins k-way merge of memtable + runs, tombstones elided."""
        sources: list[Iterator[tuple[bytes, bytes | None]]] = []

        def mem_iter():
            lo = bisect.bisect_left(self._mem_index, begin)
            hi = bisect.bisect_left(self._mem_index, end)
            keys = self._mem_index[lo:hi]
            if reverse:
                keys = list(reversed(keys))
            for k in keys:
                yield k, self._mem[k]

        sources.append(mem_iter())
        sources.extend(r.iter_range(begin, end, reverse) for r in self._runs)
        yield from _merge(sources, reverse)

    def _mem_runs(self, begin: bytes, end: bytes) -> Iterator[list]:
        """Memtable rows of [begin, end) as bulk runs, tombstones kept."""
        lo = bisect.bisect_left(self._mem_index, begin)
        hi = bisect.bisect_left(self._mem_index, end)
        mem = self._mem
        for i in range(lo, hi, _MEM_RUN_ROWS):
            yield [(k, mem[k])
                   for k in self._mem_index[i:min(i + _MEM_RUN_ROWS, hi)]]

    def range_runs(self, begin: bytes,
                   end: bytes) -> Iterator[list]:
        """Forward scan of [begin, end) as bulk row RUNS: newest-wins
        across memtable + sorted runs with tombstones elided, flattened
        output byte-identical to ``range(..., reverse=False)``.  Rows
        are (key, value) SEQUENCES — tuples or the block decoder's
        2-item lists — and runs may alias cached block storage:
        consumers index and slice, never mutate or type-match.

        A range held by ONE source (the post-compaction common case)
        streams its block runs straight through.  Overlapping sources
        merge SEGMENT-wise: each round cuts at the smallest buffered
        tail key — so no source decodes blocks past what the consumer
        needs — and resolves the segment with one C-speed sort + linear
        dedup (newest source first) instead of a per-row heap."""
        sources = [self._mem_runs(begin, end)]
        sources += [r.range_blocks(begin, end) for r in self._runs]
        # newest first: position in ``bufs`` is the win priority on
        # duplicate keys (memtable beats every run, newer runs beat
        # older); filtering exhausted sources preserves relative order
        bufs: list[list] = []
        for src in sources:
            rows = next(src, None)
            if rows:
                bufs.append([rows, src])
        first = lambda r: r[0]  # noqa: E731 — bisect key
        while bufs:
            if len(bufs) == 1:
                rows, src = bufs[0]
                while rows is not None:
                    live = [e for e in rows if e[1] is not None]
                    if live:
                        yield live
                    rows = next(src, None)
                return
            pivot = min(rows[-1][0] for rows, _src in bufs)
            seg: list[list] = []
            for entry in bufs:
                rows, src = entry
                if rows[-1][0] <= pivot:
                    part = rows
                    entry[0] = next(src, None)
                else:
                    cut = bisect.bisect_right(rows, pivot, key=first)
                    part = rows[:cut]
                    entry[0] = rows[cut:]
                if part:
                    seg.append(part)
            bufs = [entry for entry in bufs if entry[0]]
            if not seg:
                continue
            if len(seg) > 1:
                # span-disjoint parts (sequential flushes stripe the
                # keyspace, so segments usually interleave WITHOUT
                # overlapping) concatenate in span order — no sort, no
                # per-row dedup
                order = sorted(range(len(seg)), key=lambda i: seg[i][0][0])
                if all(seg[order[i]][-1][0] < seg[order[i + 1]][0][0]
                       for i in range(len(order) - 1)):
                    for i in order:
                        live = [e for e in seg[i] if e[1] is not None]
                        if live:
                            yield live
                    continue
                # overlapping parts: (key, priority, value) triples —
                # one sort resolves order AND newest-wins (priority
                # breaks key ties; a key appears at most once per
                # source, so values are never compared)
                merged: list[tuple] = []
                for prio, part in enumerate(seg):
                    merged += [(k, prio, v) for k, v in part]
                merged.sort()
                out: list[tuple[bytes, bytes]] = []
                last = None
                for k, _prio, v in merged:
                    if k == last:
                        continue
                    last = k
                    if v is not None:
                        out.append((k, v))
                if out:
                    yield out
                continue
            live = [e for e in seg[0] if e[1] is not None]
            if live:
                yield live

    # --- writes ---

    def _apply_mem(self, ops: list[tuple[int, bytes, bytes]]) -> None:
        for op, p1, p2 in ops:
            if op == OP_SET:
                old = self._mem.get(p1)
                self._mem[p1] = p2
                self._mem_bytes += len(p1) + len(p2) - (len(old) if old else 0)
            else:
                # a clear becomes per-key tombstones over every key known
                # ANYWHERE (memtable or runs) in [p1, p2): point lookups
                # must see the deletion without a range check
                for k, _ in list(self.range(p1, p2)):
                    self._mem[k] = _TOMBSTONE
                for k in [k for k in self._mem if p1 <= k < p2]:
                    self._mem[k] = _TOMBSTONE

    async def commit(self, ops, meta: dict) -> None:
        if not isinstance(ops, list):
            # PackedOps slice from the durability ring: this engine's WAL
            # frames stay tuple-shaped, so materialize the slice once
            ops = [(op, p1, p2) for op, p1, p2 in ops]
        rec = encode({"gen": self._gen, "ops": ops, "meta": meta})
        await self._wal.push(rec)
        await self._wal.commit()
        self._apply_mem(ops)
        self.meta = meta
        self._mem_index = sorted(self._mem)
        if self._mem_bytes > _MEMTABLE_BYTES:
            await self._flush()
        if len(self._runs) > _MAX_RUNS:
            await self._compact()

    # --- flush / compaction ---

    async def _write_run(self, items: Iterator[tuple[bytes, bytes | None]],
                         drop_tombstones: bool) -> str | None:
        self._gen += 1
        path = f"{self.prefix}.run.{self._gen:08d}"
        f = self.fs.open(path)
        await f.truncate(0)
        off = 0
        index = []
        block: list = []
        bbytes = 0

        async def emit():
            nonlocal off, block, bbytes
            if not block:
                return
            blob = encode(block)
            index.append([block[0][0], off, len(blob)])
            await f.write(off, blob)
            off += len(blob)
            block = []
            bbytes = 0

        wrote = False
        for k, v in items:
            if v is None and drop_tombstones:
                continue
            wrote = True
            block.append([k, v])
            bbytes += len(k) + (len(v) if v else 0)
            if bbytes >= _BLOCK_BYTES:
                await emit()
        await emit()
        if not wrote:
            await f.close()
            self.fs.remove(path)
            return None
        idx = encode(index)
        await f.write(off, idx)
        await f.write(off + len(idx), off.to_bytes(8, "little") + _FOOTER)
        await f.sync()
        await f.close()
        return path

    async def _write_manifest(self) -> None:
        """One save through the shared dual-slot helper (ISSUE 13): the
        slot not being written always holds the previous valid manifest,
        so a kill tearing this write can never lose the committed run
        set, and a failed (retried) write re-targets the same slot."""
        await self._man_sb.save(encode({
            "gen": self._gen, "wal_gen": self._wal_gen, "meta": self.meta,
            "runs": [r.path for r in self._runs]}))

    async def _flush(self) -> None:
        def items():
            for k in self._mem_index:
                yield k, self._mem[k]

        path = await self._write_run(items(), drop_tombstones=not self._runs)
        if path is not None:
            self._runs.insert(0, _Run(self.fs, path, self._cache))
            self._sparse.bump()
        # WAL records below the new gen are folded into the run
        self._wal_gen = self._gen
        await self._write_manifest()
        await self._wal.pop_to(self._wal.end_offset)
        self._mem.clear()
        self._mem_index = []
        self._mem_bytes = 0

    async def _compact(self) -> None:
        """Merge every run into one (tombstones drop at the bottom)."""
        old = list(self._runs)
        merged = _merge([r.iter_range(b"", b"\xff\xff\xff\xff")
                         for r in old], reverse=False, keep_tombstones=False)
        path = await self._write_run(merged, drop_tombstones=True)
        self._runs = [_Run(self.fs, path, self._cache)] if path else []
        self._sparse.bump()
        await self._write_manifest()
        for r in old:
            self._cache.drop_file(r.path)
            self.fs.remove(r.path)


def _merge(sources, reverse: bool, keep_tombstones: bool = False):
    """K-way merge, earlier sources win on equal keys; tombstones elided
    from the output unless kept (compaction intermediate)."""
    heap = []
    for si, it in enumerate(sources):
        it = iter(it)
        first = next(it, None)
        if first is not None:
            k = first[0]
            heap.append(((_rk(k) if reverse else k), si, first, it))
    heapq.heapify(heap)
    last_key = None
    while heap:
        _, si, (k, v), it = heapq.heappop(heap)
        nxt = next(it, None)
        if nxt is not None:
            heapq.heappush(heap, ((_rk(nxt[0]) if reverse else nxt[0]),
                                  si, nxt, it))
        if k == last_key:
            continue            # an older source's version of the same key
        last_key = k
        if v is None and not keep_tombstones:
            continue
        yield k, v


class _rk(bytes):
    """Reversed byte ordering for descending merges."""
    __slots__ = ()

    def __lt__(self, other):    # type: ignore[override]
        return bytes.__gt__(self, other)
