"""LSM key-value engine: memtable + WAL + sorted-run files + compaction.

Reference: the disk-backed IKeyValueStore engines —
REF:fdbserver/VersionedBTree.actor.cpp (Redwood) and
REF:fdbserver/KeyValueStoreRocksDB.actor.cpp — behind the same
IKeyValueStore surface as kv_store.MemoryKVStore.  Where the memory
engine caps the database at RAM and rewrites O(db) snapshots, this engine
keeps only the memtable in RAM:

- writes land in the WAL (DiskQueue, fsync per commit) + memtable;
- a full memtable flushes to an immutable sorted-run file (data blocks +
  a sparse index block + footer), newest-first;
- reads consult memtable then runs newest→oldest through a small LRU
  block cache (sync block reads — the page-cache path);
- compaction keeps the run count bounded.  Two disciplines live behind
  knob ``LSM_LEVELED_COMPACTION`` (ISSUE 14, the STORAGE_MVCC_COLUMNAR
  pattern — the monolithic twin kept verbatim for the A/B):

  * LEVELED (default): L0 holds the overlapping flush runs; L1+ hold
    key-range-DISJOINT partitioned runs.  A background compactor task
    picks the fullest level by debt score, merges one input set (the
    oldest L0 suffix, or one over-full level's largest partition) with
    only the OVERLAPPING next-level partitions, and rewrites just that
    slice — write amplification drops from O(keyspace) per cycle to
    O(overlap), and ``commit()`` never awaits a merge: it only nudges
    the compactor.  Merges are budget-sliced (knob
    ``LSM_COMPACT_SLICE_BYTES`` of input per event-loop yield), the
    common 2-source slice goes vectorized through
    ``KeyRun.run_positions`` + np.insert column stitches over the
    decoded blocks (the ISSUE-13 segment pair-merge discipline), and
    the heapq k-way merge is retained for k>2 fan-ins.  Tombstones
    drop only when the output level is the deepest non-empty one.

  * MONOLITHIC (knob off): every run merges into ONE from ``commit()``
    past ``_MAX_RUNS`` — the pre-ISSUE-14 behavior, verbatim.

- the MANIFEST names the live runs + per-run LEVEL (old manifests load
  as all-L0, so a pre-leveled disk upgrades in place and either mode
  opens the other's state) + engine metadata; every state change
  (flush/compact) writes MANIFEST atomically after the new files are
  durable, so a crash at any point — including mid-compaction, in
  either direction — recovers to a consistent run set.  Run files the
  manifest does not name (a kill between run write and manifest, or
  between manifest and input removal) are swept at open.
"""

from __future__ import annotations

import asyncio
import bisect
import heapq
import time
from collections import OrderedDict
from typing import Iterator

from ..rpc.wire import decode, encode
from .disk_queue import DiskQueue
from .key_runs import KeyRun
from .kv_store import OP_CLEAR, OP_SET

_TOMBSTONE = None          # value None in runs marks a deletion
_BLOCK_BYTES = 1 << 16
_MEMTABLE_BYTES = 1 << 22  # flush threshold (4MB)
_MAX_RUNS = 6              # compact when exceeded (monolithic mode) /
#                            L0 run-count trigger (leveled mode) — ONE
#                            constant so the monkeypatched test/smoke
#                            thresholds drive both twins identically
_MEM_RUN_ROWS = 2048       # memtable rows per bulk run (range_runs)
_CACHE_BLOCKS = 256        # LRU block cache entries (~16MB)
_FOOTER = b"LSM1"
_L0_MERGE_MAX = 16         # L0 runs one compaction folds at most
_L0_MERGE_MAX_BYTES = 64 << 20  # ...and at most this many input bytes
#                            (ISSUE 18 satellite / ROADMAP 5(h)): a burst
#                            of fat L0 runs otherwise wedges the single
#                            compactor in one giant merge while debt at
#                            deeper levels starves; the pick stays the
#                            contiguous OLDEST suffix (shadowing safety),
#                            just a shorter one, and always takes >= 1 run
_COMPACT_RETRY_S = 0.5     # backoff after a failed (IoError) compaction
_COMPACT_MAX_RETRIES = 20  # consecutive NON-IoError failures before the
#                            compactor poisons the store: transient disk
#                            errors retry forever (gray failure owns a
#                            persistently bad disk), a DETERMINISTIC bug
#                            must surface loudly, not livelock forever


def _close_sync(f) -> None:
    """Best-effort close from a sync context (run-construction failure
    cleanup): both file types' ``close()`` coroutines contain no awaits,
    so one send() drives them to completion; anything else is dropped —
    this path only exists to keep error retries from leaking fds."""
    try:
        f.close().send(None)
    except StopIteration:
        pass
    except Exception:  # noqa: BLE001 — cleanup best-effort
        pass


class _Run:
    """One immutable sorted-run file: block-sparse index in RAM, data
    blocks read on demand through the shared cache."""

    def __init__(self, fs, path: str, cache: "_BlockCache") -> None:
        self.path = path
        self._f = fs.open(path)
        self._cache = cache
        self.level = 0          # leveled-compaction home (0 = overlapping)
        self._last: bytes | None = None     # span cache (last_key())
        try:
            size = self._f.size()
            self.bytes = size   # file size — the level-fullness operand
            foot = self._f.read_sync(size - 12, 12)
            if foot[8:] != _FOOTER:
                # runs are named by a manifest written only AFTER the
                # run file synced, so a bad footer is never a torn flush
                # — it is corruption of committed data, raised loudly
                # (ISSUE 12)
                from ..runtime.errors import DiskCorrupt
                raise DiskCorrupt(f"bad sorted-run footer in committed "
                                  f"run {path}")
            idx_off = int.from_bytes(foot[:8], "little")
            self.index = decode(
                self._f.read_sync(idx_off, size - 12 - idx_off))
            # index: list of [first_key, offset, length].  The sparse
            # index (block first keys) is a COLUMNAR KeyRun
            # (storage/key_runs.py, ISSUE 11): one blob + bounds +
            # cached u64 prefixes — the same layout PackedKeyIndex's
            # base run uses, deduplicating the searchsorted-over-
            # prefixes discipline this file had grown its own copy of
            # (the old first_keys list + _fk_pfx pair)
            self.first_keys = KeyRun.from_keys(
                [bytes(e[0]) for e in self.index])
        except BaseException:
            # construction failure (IoError mid-read, corrupt footer):
            # release the fd — open()/compactor callers RETRY, and each
            # leaked handle on a real fs walks toward EMFILE
            f, self._f = self._f, None
            _close_sync(f)
            raise

    def _block(self, i: int) -> list:
        key = (self.path, i)
        blk = self._cache.get(key)
        if blk is None:
            _, off, ln = self.index[i]
            blk = decode(self._f.read_sync(off, ln))
            self._cache.put(key, blk)
        return blk

    async def close(self) -> None:
        """Release the run's file handle (idempotent) — called when the
        run is retired by a compaction or the store closes; a real fd
        left open on an unlinked file leaks until EMFILE."""
        f, self._f = self._f, None
        if f is not None:
            await f.close()

    # --- key span (the leveled compactor's overlap operands) ---

    def first_key(self) -> bytes:
        return self.first_keys.key(0)

    def last_key(self) -> bytes:
        """Largest key in the run (one cached block decode — the sparse
        index only names block FIRST keys)."""
        if self._last is None:
            self._last = bytes(self._block(len(self.index) - 1)[-1][0])
        return self._last

    def iter_blocks(self) -> Iterator[list]:
        """Every data block in key order — the compaction input stream
        (rows include tombstones).  Reads AROUND the shared LRU block
        cache on miss: each input block is consumed exactly once and
        its file is deleted right after the merge, so inserting them
        would only evict the read path's hot set."""
        for i in range(len(self.index)):
            blk = self._cache.get((self.path, i))
            if blk is None:
                _, off, ln = self.index[i]
                blk = decode(self._f.read_sync(off, ln))
            yield blk

    def get(self, key: bytes) -> tuple[bool, bytes | None]:
        """(found, value-or-tombstone)."""
        i = self.first_keys.bisect_right(key) - 1
        if i < 0:
            return False, None
        blk = self._block(i)
        keys = [bytes(e[0]) for e in blk]
        j = bisect.bisect_left(keys, key)
        if j < len(keys) and keys[j] == key:
            v = blk[j][1]
            return True, (bytes(v) if v is not None else None)
        return False, None

    def get_batch_into(self, keys: list[bytes], idxs: list[int],
                       out: list) -> list[int]:
        """Probe ``keys[i] for i in idxs`` (idxs ascending over sorted
        keys) against this run, writing hits — including tombstones —
        into ``out``; returns the still-unresolved indices for the next
        (older) run.  The block per probe resolves in ONE vectorized
        ``searchsorted`` over the sparse index's cached u64 prefixes
        (``KeyRun.batch_bisect`` — the shared home of the
        PackedKeyIndex bound-batch discipline), a bisect refining
        inside the equal-prefix band; each touched block is then
        decoded exactly once per batch."""
        fk = self.first_keys
        if not fk:
            return idxs
        blocks = [b - 1 for b in
                  fk.batch_bisect([keys[i] for i in idxs], side="right",
                                  sorted_keys=True)]
        remaining: list[int] = []
        cur = -1
        bkeys: list[bytes] = []
        blk: list = []
        for i, b in zip(idxs, blocks):
            if b < 0:
                remaining.append(i)
                continue
            if b != cur:        # idxs sorted => blocks non-decreasing
                cur = b
                blk = self._block(b)
                bkeys = [bytes(e[0]) for e in blk]
            j = bisect.bisect_left(bkeys, keys[i])
            if j < len(bkeys) and bkeys[j] == keys[i]:
                v = blk[j][1]
                out[i] = bytes(v) if v is not None else None
            else:
                remaining.append(i)
        return remaining

    def iter_range(self, begin: bytes, end: bytes,
                   reverse: bool = False) -> Iterator[tuple[bytes, bytes | None]]:
        lo = max(0, self.first_keys.bisect_right(begin) - 1)
        hi = self.first_keys.bisect_left(end)
        blocks = range(lo, min(hi + 1, len(self.index)))
        if reverse:
            blocks = reversed(blocks)
        for i in blocks:
            blk = self._block(i)
            entries = reversed(blk) if reverse else blk
            for k, v in entries:
                k = bytes(k)
                if k < begin or k >= end:
                    continue
                yield k, (bytes(v) if v is not None else None)

    def range_blocks(self, begin: bytes,
                     end: bytes) -> Iterator[list]:
        """Forward block RUNS of [begin, end): each touched block
        decoded once, the boundary blocks trimmed by bisect, interior
        blocks sliced wholesale — the searchsorted-over-sorted-index
        discipline of ``get_batch_into`` generalized from point probes
        to interval extraction (ISSUE 9).  Rows include tombstones
        (value None): the engine-level newest-wins merge needs them."""
        fk = self.first_keys
        if not fk:
            return
        first = lambda e: e[0]  # noqa: E731 — bisect key
        lo = max(0, fk.bisect_right(begin) - 1)
        stop = max(fk.bisect_left(end), lo + 1)
        for i in range(lo, stop):
            # the decoder already hands back bytes keys/values, so rows
            # pass through with NO per-row re-materialization: interior
            # blocks yield the cached block list itself (read-only by
            # contract), boundary blocks yield one slice
            blk = self._block(i)
            if i == lo or i == stop - 1:
                s = (bisect.bisect_left(blk, begin, key=first)
                     if i == lo else 0)
                t = (bisect.bisect_left(blk, end, key=first)
                     if i == stop - 1 else len(blk))
                if s >= t:
                    continue
                yield blk[s:t] if (s or t < len(blk)) else blk
            else:
                yield blk


class _BlockCache:
    def __init__(self, capacity: int) -> None:
        self.capacity = capacity
        self._d: OrderedDict = OrderedDict()

    def get(self, key):
        blk = self._d.get(key)
        if blk is not None:
            self._d.move_to_end(key)
        return blk

    def put(self, key, blk) -> None:
        self._d[key] = blk
        self._d.move_to_end(key)
        while len(self._d) > self.capacity:
            self._d.popitem(last=False)

    def drop_file(self, path: str) -> None:
        for k in [k for k in self._d if k[0] == path]:
            del self._d[k]


class LsmSparseIndex:
    """Merged block directory over every sorted run — the lsm engine's
    ``packed_index`` (ISSUE 11, ROADMAP item 1 (e)).

    The per-run sparse indexes (block first keys) merge into ONE sorted
    ``KeyRun`` with parallel (run, block) back-pointer columns and a
    per-run prefix-max table, so a probe key's candidate block in EVERY
    run falls out of a single sorted-array bound:

        pos = bisect_right(merged, key)
        candidate block of run r = blockmax[pos][r]
          (== bisect_right(run_r.first_keys, key) - 1, by construction)

    That single sorted u64-prefix array is exactly the shape the device
    read mirror consumes (device/read_serve.py): one vectorized
    ``searchsorted`` per ``get_values`` batch locates the candidate
    block in every run at once, replacing the per-run host searchsorted
    descent — the surface where the device gather finally sits over a
    real probe structure instead of MemoryKVStore's O(1) dict.

    ``gen`` bumps whenever the run SET changes (open/flush/compact);
    memtable writes never stale it — the memtable is probed host-side
    by ``get_batch_located``, the lsm twin of the pending-overlay
    contract the PackedKeyIndex mirror already has."""

    device_mode = "blocks"      # host refinement the device mirror needs

    __slots__ = ("_store", "gen", "_cache")

    def __init__(self, store: "LSMKVStore") -> None:
        self._store = store
        self.gen = 0
        self._cache: tuple | None = None    # (merged KeyRun, blockmax)

    def bump(self) -> None:
        self.gen += 1
        self._cache = None

    def _ensure(self) -> tuple:
        if self._cache is None:
            import numpy as np
            runs = self._store._runs
            entries: list[tuple[bytes, int, int]] = []
            for r_i, run in enumerate(runs):
                fk = run.first_keys
                entries.extend((fk.key(b_i), r_i, b_i)
                               for b_i in range(len(fk)))
            entries.sort()
            merged = KeyRun.from_keys([e[0] for e in entries])
            n, nr = len(entries), len(runs)
            blockmax = np.full((n + 1, max(nr, 1)), -1, dtype=np.int64)
            if n and nr:
                run_of = np.fromiter((e[1] for e in entries),
                                     dtype=np.int64, count=n)
                block_of = np.fromiter((e[2] for e in entries),
                                       dtype=np.int64, count=n)
                for r in range(nr):
                    col = np.where(run_of == r, block_of, -1)
                    # blocks within a run appear in ascending order, so
                    # the running max IS the newest block at-or-before
                    np.maximum.accumulate(col, out=col)
                    blockmax[1:, r] = col
            self._cache = (merged, blockmax)
        return self._cache

    # --- the device-mirror surface (DeviceKeyDirectory contract) ---

    def base_run(self) -> KeyRun:
        return self._ensure()[0]

    def pending_run(self) -> list[bytes]:
        return []               # the memtable is handled host-side

    def base_prefixes(self):
        return self._ensure()[0].prefixes()


class LSMKVStore:
    """IKeyValueStore-compatible LSM engine (see kv_store.MemoryKVStore
    for the surface contract)."""

    def __init__(self, fs, prefix: str, knobs=None) -> None:
        from ..runtime.knobs import KNOBS
        self.fs = fs
        self.prefix = prefix
        self.knobs = knobs if knobs is not None else KNOBS
        self.meta: dict = {}
        self._mem: dict[bytes, bytes | None] = {}   # None = tombstone
        self._mem_index: list[bytes] = []
        self._mem_bytes = 0
        # serving order, newest-wins by position: L0 newest-first, then
        # each deeper level's disjoint partitions.  ``_runs`` is the ONE
        # flattened list every read path (and the sparse index) walks;
        # ``_levels`` is the compactor's structured view of the same
        # runs — ``_rebuild_runs`` keeps them in lockstep.
        self._runs: list[_Run] = []                 # newest first
        self._levels: list[list[_Run]] = [[]]
        self._cache = _BlockCache(_CACHE_BLOCKS)
        self._sparse = LsmSparseIndex(self)
        self._wal: DiskQueue | None = None
        self._wal_file = None
        self._gen = 0
        self._wal_gen = 0
        # --- leveled background compaction (ISSUE 14) ---
        self._leveled = bool(self.knobs.LSM_LEVELED_COMPACTION)
        self._io_lock = asyncio.Lock()      # run-set install + MANIFEST
        self._compact_task: asyncio.Task | None = None
        self._compact_event = asyncio.Event()
        self._job_active = False
        self._poison: Exception | None = None   # DiskCorrupt from the
        #                                         compactor, re-raised
        #                                         loudly at next commit
        self._closed = False
        # write-amplification accounting: ingested = flushed run bytes,
        # rewritten = compaction output bytes (both modes count the
        # same way, so the A/B ratio is apples-to-apples)
        self.flush_bytes = 0
        self.compact_bytes = 0
        self.compactions = 0
        self._stall_s_max = 0.0     # commit-path compaction stalls
        self._stall_s_total = 0.0
        self._stalls = 0
        # the dual-slot manifest persist (rpc/wire.SlottedBlob); open()
        # replaces it with the loaded/armed instance
        from ..rpc.wire import SlottedBlob
        self._man_sb = SlottedBlob(fs, prefix,
                                   (".MANIFEST.a", ".MANIFEST.b"))

    # --- lifecycle ---

    @classmethod
    async def _load_manifest(cls, fs, prefix: str
                             ) -> tuple[dict | None, int, "SlottedBlob"]:
        """Newest valid manifest from the shared dual-slot helper
        (rpc/wire.py ``SlottedBlob`` — ONE audited corruption policy,
        ISSUE 13 / ROADMAP 6 (f)), falling back to the two pre-helper
        slot formats: the ISSUE-12 crc-framed dict-with-seq slots, and
        the original rewritten-in-place single file (which a torn kill
        could destroy outright).  Returns (manifest, slots seen, the
        armed helper for subsequent saves)."""
        from ..rpc.wire import SlottedBlob, unframe
        sb = SlottedBlob(fs, prefix, (".MANIFEST.a", ".MANIFEST.b"))
        payload, found = await sb.load()
        if payload is not None:
            return decode(payload), found, sb
        best = None
        for suffix in (".MANIFEST.a", ".MANIFEST.b"):
            f = fs.open(prefix + suffix)
            blob = await f.read(0, f.size())
            await f.close()
            if not blob:
                continue
            try:
                man = decode(unframe(blob))
            except Exception:  # noqa: BLE001 — torn slot: other one wins
                continue
            if best is None or man.get("seq", 0) > best.get("seq", 0):
                best = man
        if best is not None:
            # keep the slot alternation continuous across the envelope
            # migration: the next save must NOT target the only valid
            # old-format slot
            sb.seed(best.get("seq", 0))
            return best, found, sb
        legacy = fs.open(prefix + ".MANIFEST")
        blob = await legacy.read(0, legacy.size())
        await legacy.close()
        if blob:
            found += 1
            try:
                return decode(blob), found, sb
            except Exception:  # noqa: BLE001 — caller decides torn/corrupt
                pass
        return None, found, sb

    @classmethod
    async def open(cls, fs, prefix: str, knobs=None) -> "LSMKVStore":
        kv = cls(fs, prefix, knobs)
        try:
            return await kv._open_into(fs, prefix)
        except BaseException:
            # a failed open (IoError mid-read, DiskCorrupt) releases
            # every handle it acquired: the worker adoption path RETRIES
            # transient errors, and each leaked run/WAL fd on a real fs
            # walks toward EMFILE
            await kv.close()
            raise

    async def _open_into(self, fs, prefix: str) -> "LSMKVStore":
        kv, cls = self, type(self)
        man, slots_seen, kv._man_sb = await cls._load_manifest(fs, prefix)
        if man is not None:
            kv.meta = man["meta"]
            kv._gen = man["gen"]
            kv._wal_gen = man.get("wal_gen", 0)
            # per-run levels (ISSUE 14): manifests predating the leveled
            # compactor carry no "levels" — every run loads as L0
            # (overlapping), exactly the monolithic twin's shape, and
            # the compactor partitions it in place from there
            levels = man.get("levels") or [0] * len(man["runs"])
            for path, lvl in zip(man["runs"], levels):
                run = _Run(fs, str(path), kv._cache)
                run.level = int(lvl)
                kv._level(run.level).append(run)
            for lvl_runs in kv._levels[1:]:
                # disjoint levels serve in any order; keep them sorted
                # by span so overlap selection stays a clean scan
                lvl_runs.sort(key=lambda r: r.first_key())
            kv._rebuild_runs()
            kv._sparse.bump()
        kv._wal_file = fs.open(prefix + ".wal")
        kv._wal, frames = await DiskQueue.open(kv._wal_file)
        recs = [decode(frame) for frame, _end in frames]
        if man is None and slots_seen:
            # manifest slots exist but none decodes.  A kill tearing the
            # FIRST-ever manifest write is legitimate (the WAL was not
            # yet popped, so gen-0 frames rebuild everything); but WAL
            # frames at gen > 0 — or committed runs with no WAL at all —
            # prove a synced manifest once existed and was popped
            # against: recovering without it would silently resurrect a
            # partial ancient state (ISSUE 12)
            gens = [r["gen"] for r in recs]
            has_runs = bool(fs.listdir(prefix + ".run."))
            if (gens and min(gens) > 0) or (has_runs and not gens):
                from ..runtime.errors import DiskCorrupt
                raise DiskCorrupt(
                    f"no readable MANIFEST among {slots_seen} slots for "
                    f"{prefix} while committed runs/WAL generations "
                    f"reference one — the committed run set is damaged, "
                    f"refusing silent recovery")
        for rec in recs:
            if rec["gen"] < kv._wal_gen:
                continue        # folded into a flushed run already
            kv._apply_mem(rec["ops"])
            kv.meta = rec["meta"]
        kv._mem_index = sorted(kv._mem)
        if man is not None:
            # sweep run files the manifest does not name: a kill between
            # a compaction's run write and its manifest (new runs
            # orphaned) or between manifest and input removal (old runs
            # orphaned) leaves unnamed files — harmless to serving,
            # reclaimed here so either crash direction converges.  BOTH
            # modes sweep: the monolithic twin leaves the same orphans
            # at the same crash cuts, and a leveled-mode crash may be
            # reopened with the knob off (either mode opens the other's
            # MANIFEST)
            live = {r.path for r in kv._runs}
            for path in fs.listdir(prefix + ".run."):
                if path not in live:
                    fs.remove(path)
        if kv._leveled and kv._has_debt():
            # inherited run debt (ISSUE 14 satellite): a reopened store
            # starts compacting immediately instead of waiting for the
            # next commit to re-check the trigger
            kv._nudge()
        return kv

    async def close(self) -> None:
        self._closed = True
        t = self._compact_task
        if t is not None and not t.done():
            t.cancel()
            try:
                await t
            except asyncio.CancelledError:
                pass
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        if self._wal_file is not None:
            await self._wal_file.close()
            self._wal_file = None
        # the level view, not _runs: a failed open() cleans up runs
        # loaded before _rebuild_runs ever ran
        for lvl_runs in self._levels:
            for r in lvl_runs:
                try:
                    await r.close()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass

    def __len__(self) -> int:
        n = 0
        for _ in self.range(b"", b"\xff\xff\xff\xff"):
            n += 1
        return n

    # --- reads ---

    @property
    def packed_index(self) -> LsmSparseIndex:
        """The merged sparse-index directory — the capability probe the
        device read path keys on (device/read_serve.py, ISSUE 11)."""
        return self._sparse

    def get(self, key: bytes) -> bytes | None:
        if key in self._mem:
            return self._mem[key]
        for run in self._runs:
            found, v = run.get(key)
            if found:
                return v
        return None

    def get_batch(self, keys: list[bytes]) -> list[bytes | None]:
        """Batched point reads over SORTED keys (the multiget engine
        fall-through): one memtable dict pass, then each run probed
        once via its vectorized sparse-index search — every touched
        data block decodes once per batch instead of once per key."""
        out: list[bytes | None] = [None] * len(keys)
        mem = self._mem
        pending: list[int] = []
        for i, k in enumerate(keys):
            if k in mem:
                out[i] = mem[k]     # value or tombstone (None): resolved
            else:
                pending.append(i)
        for run in self._runs:
            if not pending:
                break
            pending = run.get_batch_into(keys, pending, out)
        return out

    def get_batch_located(self, keys: list[bytes],
                          pos: list[int]) -> list[bytes | None]:
        """Finish a device-located batch (ISSUE 11): ``pos[i]`` is the
        bisect_right of ``keys[i]`` over the merged sparse directory
        (``packed_index.base_run()``) — computed by the device mirror's
        vectorized searchsorted.  The host half probes the memtable
        first, then each run's candidate block newest→oldest, resolving
        tombstones newest-wins — result identical to ``get_batch`` on
        the same keys by construction (the directory's prefix-max table
        reproduces exactly each run's ``bisect_right(first_keys) - 1``
        block choice), and tested."""
        _merged, blockmax = self._sparse._ensure()
        out: list[bytes | None] = [None] * len(keys)
        mem = self._mem
        runs = self._runs
        bkeys_cache: dict[tuple[int, int], list[bytes]] = {}
        for i, k in enumerate(keys):
            if k in mem:
                out[i] = mem[k]
                continue
            row = blockmax[pos[i]]
            for r_i in range(len(runs)):
                b = int(row[r_i])
                if b < 0:
                    continue
                ck = (r_i, b)
                bkeys = bkeys_cache.get(ck)
                blk = runs[r_i]._block(b)
                if bkeys is None:
                    bkeys = [bytes(e[0]) for e in blk]
                    bkeys_cache[ck] = bkeys
                j = bisect.bisect_left(bkeys, k)
                if j < len(bkeys) and bkeys[j] == k:
                    v = blk[j][1]
                    out[i] = bytes(v) if v is not None else None
                    break
        return out

    def range(self, begin: bytes, end: bytes,
              reverse: bool = False) -> Iterator[tuple[bytes, bytes]]:
        """Newest-wins k-way merge of memtable + runs, tombstones elided."""
        sources: list[Iterator[tuple[bytes, bytes | None]]] = []

        def mem_iter():
            lo = bisect.bisect_left(self._mem_index, begin)
            hi = bisect.bisect_left(self._mem_index, end)
            keys = self._mem_index[lo:hi]
            if reverse:
                keys = list(reversed(keys))
            for k in keys:
                yield k, self._mem[k]

        sources.append(mem_iter())
        sources.extend(r.iter_range(begin, end, reverse) for r in self._runs)
        yield from _merge(sources, reverse)

    def _mem_runs(self, begin: bytes, end: bytes) -> Iterator[list]:
        """Memtable rows of [begin, end) as bulk runs, tombstones kept."""
        lo = bisect.bisect_left(self._mem_index, begin)
        hi = bisect.bisect_left(self._mem_index, end)
        mem = self._mem
        for i in range(lo, hi, _MEM_RUN_ROWS):
            yield [(k, mem[k])
                   for k in self._mem_index[i:min(i + _MEM_RUN_ROWS, hi)]]

    def range_runs(self, begin: bytes,
                   end: bytes) -> Iterator[list]:
        """Forward scan of [begin, end) as bulk row RUNS: newest-wins
        across memtable + sorted runs with tombstones elided, flattened
        output byte-identical to ``range(..., reverse=False)``.  Rows
        are (key, value) SEQUENCES — tuples or the block decoder's
        2-item lists — and runs may alias cached block storage:
        consumers index and slice, never mutate or type-match.

        A range held by ONE source (the post-compaction common case)
        streams its block runs straight through.  Overlapping sources
        merge SEGMENT-wise: each round cuts at the smallest buffered
        tail key — so no source decodes blocks past what the consumer
        needs — and resolves the segment with one C-speed sort + linear
        dedup (newest source first) instead of a per-row heap."""
        sources = [self._mem_runs(begin, end)]
        sources += [r.range_blocks(begin, end) for r in self._runs]
        # newest first: position in ``bufs`` is the win priority on
        # duplicate keys (memtable beats every run, newer runs beat
        # older); filtering exhausted sources preserves relative order
        bufs: list[list] = []
        for src in sources:
            rows = next(src, None)
            if rows:
                bufs.append([rows, src])
        first = lambda r: r[0]  # noqa: E731 — bisect key
        while bufs:
            if len(bufs) == 1:
                rows, src = bufs[0]
                while rows is not None:
                    live = [e for e in rows if e[1] is not None]
                    if live:
                        yield live
                    rows = next(src, None)
                return
            pivot = min(rows[-1][0] for rows, _src in bufs)
            seg: list[list] = []
            for entry in bufs:
                rows, src = entry
                if rows[-1][0] <= pivot:
                    part = rows
                    entry[0] = next(src, None)
                else:
                    cut = bisect.bisect_right(rows, pivot, key=first)
                    part = rows[:cut]
                    entry[0] = rows[cut:]
                if part:
                    seg.append(part)
            bufs = [entry for entry in bufs if entry[0]]
            if not seg:
                continue
            if len(seg) > 1:
                # span-disjoint parts (sequential flushes stripe the
                # keyspace, so segments usually interleave WITHOUT
                # overlapping) concatenate in span order — no sort, no
                # per-row dedup
                order = sorted(range(len(seg)), key=lambda i: seg[i][0][0])
                if all(seg[order[i]][-1][0] < seg[order[i + 1]][0][0]
                       for i in range(len(order) - 1)):
                    for i in order:
                        live = [e for e in seg[i] if e[1] is not None]
                        if live:
                            yield live
                    continue
                # overlapping parts: (key, priority, value) triples —
                # one sort resolves order AND newest-wins (priority
                # breaks key ties; a key appears at most once per
                # source, so values are never compared)
                merged: list[tuple] = []
                for prio, part in enumerate(seg):
                    merged += [(k, prio, v) for k, v in part]
                merged.sort()
                out: list[tuple[bytes, bytes]] = []
                last = None
                for k, _prio, v in merged:
                    if k == last:
                        continue
                    last = k
                    if v is not None:
                        out.append((k, v))
                if out:
                    yield out
                continue
            live = [e for e in seg[0] if e[1] is not None]
            if live:
                yield live

    # --- writes ---

    def _apply_mem(self, ops: list[tuple[int, bytes, bytes]]) -> None:
        for op, p1, p2 in ops:
            if op == OP_SET:
                old = self._mem.get(p1)
                self._mem[p1] = p2
                self._mem_bytes += len(p1) + len(p2) - (len(old) if old else 0)
            else:
                # a clear becomes per-key tombstones over every key known
                # ANYWHERE (memtable or runs) in [p1, p2): point lookups
                # must see the deletion without a range check
                for k, _ in list(self.range(p1, p2)):
                    self._mem[k] = _TOMBSTONE
                for k in [k for k in self._mem if p1 <= k < p2]:
                    self._mem[k] = _TOMBSTONE

    async def commit(self, ops, meta: dict) -> None:
        if self._poison is not None:
            # the background compactor hit committed-data corruption:
            # surface it LOUDLY on the commit path (ISSUE 12 discipline)
            # instead of serving on silently with compaction wedged
            raise self._poison
        if not isinstance(ops, list):
            # PackedOps slice from the durability ring: this engine's WAL
            # frames stay tuple-shaped, so materialize the slice once
            ops = [(op, p1, p2) for op, p1, p2 in ops]
        rec = encode({"gen": self._gen, "ops": ops, "meta": meta})
        await self._wal.push(rec)
        await self._wal.commit()
        self._apply_mem(ops)
        self.meta = meta
        self._mem_index = sorted(self._mem)
        if self._mem_bytes > _MEMTABLE_BYTES:
            await self._flush()
        if self._leveled:
            # never await a merge here: debt only NUDGES the background
            # compactor (checked every commit, not just after a flush
            # this commit triggered — the ISSUE 14 decoupled trigger)
            pending = self._has_debt()
            if pending:
                self._nudge()
            if pending or self._job_active:
                # one loop yield per commit while a merge is in flight:
                # a tight commit burst whose awaits never suspend (the
                # in-memory sim fs) would otherwise starve the
                # compactor outright — L0 then grows without bound and
                # every read/clear walks the pile.  The yield hands the
                # merge exactly one slice (LSM_COMPACT_SLICE_BYTES), so
                # this is ALSO the only compaction cost a commit can
                # ever see — bounded, and ~100x smaller than the
                # monolithic twin's inline merge-all (perf_smoke
                # --stage compact holds it at ≤20%)
                await asyncio.sleep(0)
        elif len(self._runs) > _MAX_RUNS:
            t0 = time.perf_counter()
            await self._compact()
            self._note_stall(time.perf_counter() - t0)

    # --- flush / compaction ---

    async def _write_run(self, items: Iterator[tuple[bytes, bytes | None]],
                         drop_tombstones: bool) -> str | None:
        """Single-run file write (flush / monolithic compaction): the
        ``_RunWriter`` streaming format with an unbounded partition
        target, so exactly one run emerges — ONE home for the on-disk
        run layout (block emit / index / footer / fsync)."""
        w = _RunWriter(self, 1 << 62)
        rows: list = []
        try:
            for row in items:
                rows.append(row)
                if len(rows) >= 4096:
                    await w.add_rows(rows, drop_tombstones)
                    rows = []
            if rows:
                await w.add_rows(rows, drop_tombstones)
            paths = await w.finish()
        except BaseException:
            await w.abort()
            raise
        return paths[0] if paths else None

    async def _write_manifest(self) -> None:
        """One save through the shared dual-slot helper (ISSUE 13): the
        slot not being written always holds the previous valid manifest,
        so a kill tearing this write can never lose the committed run
        set, and a failed (retried) write re-targets the same slot.
        Callers racing the background compactor hold ``_io_lock``."""
        await self._man_sb.save(encode({
            "gen": self._gen, "wal_gen": self._wal_gen, "meta": self.meta,
            "runs": [r.path for r in self._runs],
            "levels": [r.level for r in self._runs]}))

    async def _flush(self) -> None:
        def items():
            for k in self._mem_index:
                yield k, self._mem[k]

        path = await self._write_run(items(), drop_tombstones=not self._runs)
        # install + manifest under the io lock: the background
        # compactor's install is the only concurrent manifest writer,
        # and the SlottedBlob alternation must never interleave
        t0 = time.perf_counter()
        async with self._io_lock:
            wait = time.perf_counter() - t0
            if wait > 0.0005:
                self._note_stall(wait)      # leveled-mode commit stall:
                #                             waiting out an install
            if path is not None:
                run = _Run(self.fs, path, self._cache)
                run.level = 0
                self._levels[0].insert(0, run)
                self._rebuild_runs()
                self._sparse.bump()
                self.flush_bytes += run.bytes
            # WAL records below the new gen are folded into the run
            self._wal_gen = self._gen
            await self._write_manifest()
            await self._wal.pop_to(self._wal.end_offset)
        self._mem.clear()
        self._mem_index = []
        self._mem_bytes = 0

    async def _compact(self) -> None:
        """Monolithic compaction (knob off): merge every run into one
        (tombstones drop at the bottom) — the pre-ISSUE-14 behavior,
        awaited inline from commit(), kept verbatim as the A/B twin."""
        old = list(self._runs)
        merged = _merge([r.iter_range(b"", b"\xff\xff\xff\xff")
                         for r in old], reverse=False, keep_tombstones=False)
        path = await self._write_run(merged, drop_tombstones=True)
        if path:
            run = _Run(self.fs, path, self._cache)
            self.compact_bytes += run.bytes
            self._levels = [[run]]
        else:
            self._levels = [[]]
        self._rebuild_runs()
        self._sparse.bump()
        await self._write_manifest()
        self.compactions += 1
        for r in old:
            self._cache.drop_file(r.path)
            await r.close()
            self.fs.remove(r.path)

    # --- leveled background compaction (ISSUE 14) ---

    def _level(self, i: int) -> list:
        while len(self._levels) <= i:
            self._levels.append([])
        return self._levels[i]

    def _rebuild_runs(self) -> None:
        """Re-derive the flattened serving list from the level view
        (priority = position: L0 newest-first, then deeper levels)."""
        while len(self._levels) > 1 and not self._levels[-1]:
            self._levels.pop()
        self._runs = [r for lvl in self._levels for r in lvl]

    def _note_stall(self, dt: float) -> None:
        self._stalls += 1
        self._stall_s_total += dt
        if dt > self._stall_s_max:
            self._stall_s_max = dt

    def _level_cap(self, i: int) -> int:
        """Byte capacity of level i >= 1 before its fullness scores a
        compaction: the L0-equivalent budget times FANOUT**(i-1).  Reads
        the module constants at call time so monkeypatched test/smoke
        thresholds scale the whole geometry."""
        base = max(1, _MEMTABLE_BYTES * (_MAX_RUNS + 1))
        return base * (max(2, self.knobs.LSM_LEVEL_FANOUT) ** (i - 1))

    def _over_budget(self):
        """Yields (level, over_bytes, score) for every level past its
        budget — the ONE home of the compaction trigger condition:
        `_debt_bytes`/`_has_debt`/`_pick_job` all derive from it, so
        the commit-path trigger and the job selector can never
        disagree.  O(levels) arithmetic, no key spans, no block
        decodes."""
        l0 = self._levels[0]
        if len(l0) > _MAX_RUNS:
            yield (0, sum(r.bytes for r in l0[_MAX_RUNS:]),
                   len(l0) / _MAX_RUNS)
        for i in range(1, len(self._levels)):
            runs = self._levels[i]
            if not runs:
                continue
            cap = self._level_cap(i)
            size = sum(r.bytes for r in runs)
            if size > cap:
                yield i, size - cap, size / cap

    def _debt_bytes(self) -> int:
        """Bytes of run data sitting past its level's budget — the
        compactor's backlog (0 = idle)."""
        if not self._leveled:
            return (sum(r.bytes for r in self._runs)
                    if len(self._runs) > _MAX_RUNS else 0)
        return sum(over for _lvl, over, _score in self._over_budget())

    def _has_debt(self) -> bool:
        """Whether any level is past its budget — `_pick_job() is not
        None` at per-commit-trigger cost."""
        return self._leveled and \
            next(self._over_budget(), None) is not None

    def _pick_job(self):
        """The next compaction, by debt score (level fullness; the
        overlap bytes it implies are what the job then bounds itself
        to), or None when every level is inside budget.  Deterministic:
        no RNG, ties broken by level then run path, so same-seed sims
        replay the same schedule."""
        if not self._leveled:
            return None
        # max() keeps the FIRST maximal element: ties break to the
        # shallower level, like the strict-> scan it replaces
        best = max(self._over_budget(), key=lambda t: t[2], default=None)
        if best is None:
            return None
        lvl = best[0]
        l0 = self._levels[0]
        if lvl == 0:
            # the OLDEST L0 suffix (list is newest-first), bounded by
            # count AND cumulative bytes: the remaining newer runs keep
            # shadowing the output correctly
            n, acc = 0, 0
            for r in reversed(l0):          # oldest first
                if n >= _L0_MERGE_MAX:
                    break
                acc += r.bytes
                if n > 0 and acc > _L0_MERGE_MAX_BYTES:
                    break
                n += 1
            sel = list(l0[-n:])
        else:
            runs = self._levels[lvl]
            sel = [max(runs, key=lambda r: (r.bytes, r.path))]
        lo = min(r.first_key() for r in sel)
        hi = max(r.last_key() for r in sel)
        out = lvl + 1
        nxt = self._levels[out] if out < len(self._levels) else []
        overlap = [r for r in nxt
                   if not (r.last_key() < lo or hi < r.first_key())]
        # tombstones drop only at the DEEPEST non-empty level: nothing
        # below the output can hold an older shadowed version
        drop = not any(self._levels[j]
                       for j in range(out + 1, len(self._levels)))
        return sel, overlap, lvl, out, drop

    def _nudge(self) -> None:
        """Wake (spawning lazily) the background compactor — the only
        thing the commit path ever does about compaction debt."""
        if not self._leveled or self._poison is not None or self._closed:
            return
        if self._compact_task is None or self._compact_task.done():
            self._compact_task = asyncio.get_running_loop().create_task(
                self._compact_loop(), name=f"lsm-compact-{self.prefix}")
        self._compact_event.set()

    async def wait_compaction_idle(self) -> None:
        """Drain the compactor to a debt-free state (tests / smokes /
        benches — production never waits)."""
        if not self._leveled:
            return
        while True:
            if self._poison is not None:
                raise self._poison
            if self._closed:
                return      # nothing left to drain the debt — a closed
                #             store must not spin a waiter forever
            if not self._job_active and not self._has_debt():
                return
            self._nudge()
            await asyncio.sleep(0.01)

    async def _compact_loop(self) -> None:
        from ..runtime.errors import DiskCorrupt, IoError
        from ..runtime.trace import TraceEvent
        failures = 0
        while not self._closed:
            try:
                job = self._pick_job()
                if job is None:
                    self._compact_event.clear()
                    await self._compact_event.wait()
                    continue
                self._job_active = True
                try:
                    await self._run_job(*job)
                finally:
                    self._job_active = False
                failures = 0
            except asyncio.CancelledError:
                raise
            except DiskCorrupt as e:
                # committed-data corruption must be LOUD (ISSUE 12): the
                # compactor stops and the next commit re-raises
                self._poison = e
                TraceEvent("LsmCompactCorrupt", severity=40) \
                    .detail("Prefix", self.prefix).error(e).log()
                return
            except Exception as e:  # noqa: BLE001 — retry/poison below
                if isinstance(e, IoError):
                    # transient disk trouble: retry forever with backoff
                    # — a persistently bad disk is the PR-11 gray-failure
                    # machinery's job (degraded flag, DD avoidance), and
                    # a healed one must find a LIVE compactor, never a
                    # store poisoned by a long-gone outage.  The
                    # non-IoError count is NOT reset here (only a
                    # completed job resets it): interleaved disk faults
                    # must not defeat the deterministic-bug backstop
                    pass
                else:
                    failures += 1
                    if failures >= _COMPACT_MAX_RETRIES:
                        # a non-disk error failing every retry is a
                        # DETERMINISTIC bug: poison the store so the next
                        # commit raises it — debt silently growing while
                        # the loop spins at 2Hz is the one livelock shape
                        # this subsystem must never have
                        self._poison = e
                        TraceEvent("LsmCompactWedged", severity=40) \
                            .detail("Prefix", self.prefix) \
                            .detail("Failures", failures).error(e).log()
                        return
                TraceEvent("LsmCompactError", severity=30) \
                    .detail("Prefix", self.prefix).error(e).log()
                await asyncio.sleep(_COMPACT_RETRY_S)

    async def _run_job(self, sel: list, overlap: list, src_level: int,
                       out_level: int, drop: bool) -> None:
        """One compaction: merge ``sel`` (newer) with the overlapping
        next-level partitions, write partition-sized output runs, then
        install atomically — new runs fsync'd BEFORE the manifest names
        them, input files removed only AFTER, so a kill at any await
        recovers to a valid run set in either direction."""
        from ..runtime.trace import TraceEvent
        if len(sel) == 1 and not overlap:
            # trivial move (the RocksDB discipline): a single input run
            # disjoint with the ENTIRE output level just changes its
            # level field — zero bytes rewritten, one manifest write.
            # This is how a deep level absorbs spill from the one above
            # without the geometric rewrite the debt score would
            # otherwise keep charging.
            run = sel[0]
            async with self._io_lock:
                src = self._level(src_level)
                src[:] = [r for r in src if r is not run]
                run.level = out_level
                out = self._level(out_level)
                out.append(run)
                out.sort(key=lambda r: r.first_key())
                self._rebuild_runs()
                self._sparse.bump()
                await self._write_manifest()
            self.compactions += 1
            TraceEvent("LsmCompactMove").detail("Prefix", self.prefix) \
                .detail("Level", src_level).detail("OutLevel", out_level) \
                .detail("Bytes", run.bytes).log()
            return
        inputs = sel + overlap      # newest-first = win priority
        writer = _RunWriter(self, max(2 * _MEMTABLE_BYTES, 4 * _BLOCK_BYTES))
        budget = max(1, self.knobs.LSM_COMPACT_SLICE_BYTES)
        consumed = 0

        async def write(rows: list) -> None:
            nonlocal consumed
            consumed += await writer.add_rows(rows, drop)
            if consumed >= budget:
                # the slice budget: yield the loop so commits never
                # queue behind a long merge
                consumed = 0
                await asyncio.sleep(0)

        try:
            await self._merge_streams(inputs, write)
            paths = await writer.finish()
        except BaseException:
            await writer.abort()
            raise
        new_runs = []
        try:
            for p in paths:
                r = _Run(self.fs, p, self._cache)
                r.level = out_level
                new_runs.append(r)
        except BaseException:
            for r in new_runs:      # constructed runs hold open fds
                try:
                    await r.close()
                except Exception:  # noqa: BLE001 — cleanup best-effort
                    pass
            for p in paths:
                try:
                    self.fs.remove(p)
                except Exception:  # noqa: BLE001 — cleanup best-effort
                    pass
            raise
        async with self._io_lock:
            gone = {id(r) for r in inputs}
            src = self._level(src_level)
            src[:] = [r for r in src if id(r) not in gone]
            out = self._level(out_level)
            out[:] = [r for r in out if id(r) not in gone]
            out.extend(new_runs)
            out.sort(key=lambda r: r.first_key())
            self._rebuild_runs()
            self._sparse.bump()     # level changes stale the directory
            #                         exactly like run-set changes
            await self._write_manifest()
        self.compactions += 1
        self.compact_bytes += writer.bytes_written
        for r in inputs:
            self._cache.drop_file(r.path)
            try:
                await r.close()
                self.fs.remove(r.path)
            except Exception:  # noqa: BLE001 — orphan swept at next open
                pass
        TraceEvent("LsmCompact").detail("Prefix", self.prefix) \
            .detail("Level", src_level).detail("OutLevel", out_level) \
            .detail("Inputs", len(sel)).detail("Overlap", len(overlap)) \
            .detail("OutRuns", len(new_runs)) \
            .detail("Bytes", writer.bytes_written).log()

    async def _merge_streams(self, inputs: list, write) -> None:
        """Pivot-sliced newest-wins merge of whole input runs (the
        ``range_runs`` discipline over full block streams): each round
        cuts at the smallest buffered tail key; span-disjoint parts
        concatenate with NO merge work, the common 2-source slice goes
        vectorized (``_merge_pair_rows``), and k>2 fan-ins keep the
        heapq path.  Tombstones pass through — the writer owns the
        bottom-level drop."""
        first = lambda e: e[0]  # noqa: E731 — bisect key
        bufs: list[list] = []
        for run in inputs:
            it = run.iter_blocks()
            blk = next(it, None)
            if blk:
                bufs.append([blk, it])
        while bufs:
            if len(bufs) == 1:
                rows, src = bufs[0]
                while rows is not None:
                    await write(rows)
                    rows = next(src, None)
                return
            pivot = min(rows[-1][0] for rows, _src in bufs)
            seg: list[list] = []
            for entry in bufs:
                rows, src = entry
                if rows[-1][0] <= pivot:
                    part = rows
                    entry[0] = next(src, None)
                else:
                    cut = bisect.bisect_right(rows, pivot, key=first)
                    part = rows[:cut]
                    entry[0] = rows[cut:]
                if part:
                    seg.append(part)
            bufs = [entry for entry in bufs if entry[0]]
            if not seg:
                continue
            if len(seg) == 1:
                await write(seg[0])
                continue
            order = sorted(range(len(seg)), key=lambda i: seg[i][0][0])
            if all(seg[order[i]][-1][0] < seg[order[i + 1]][0][0]
                   for i in range(len(order) - 1)):
                # span-disjoint parts (striped flushes): emit in span
                # order, zero merge work
                for i in order:
                    await write(seg[i])
                continue
            if len(seg) == 2:
                await write(_merge_pair_rows(seg[0], seg[1]))
                continue
            await write(list(_merge([iter(p) for p in seg], reverse=False,
                                    keep_tombstones=True)))

    def metrics(self) -> dict:
        """Compaction observability (merged into the storage role's
        metrics and rolled up by status, ISSUE 14)."""
        return {
            "lsm_runs": len(self._runs),
            "lsm_levels": [len(lvl) for lvl in self._levels],
            "lsm_leveled": self._leveled,
            "lsm_ingest_bytes": self.flush_bytes,
            "lsm_compact_bytes": self.compact_bytes,
            "lsm_compactions": self.compactions,
            "lsm_write_amp": round(self.compact_bytes
                                   / max(1, self.flush_bytes), 3),
            "lsm_compact_debt_bytes": self._debt_bytes(),
            "lsm_compact_stall_ms": round(self._stall_s_max * 1e3, 3),
            "lsm_compact_stalls": self._stalls,
        }


class _RunWriter:
    """Streams merged rows into partition-sized sorted-run files — the
    ``_write_run`` block format, incremental: blocks emit at
    ``_BLOCK_BYTES``, a run closes (index + footer + fsync) past the
    partition target at a block boundary, so one compaction yields a
    span-ordered sequence of disjoint runs."""

    def __init__(self, store: "LSMKVStore", target_bytes: int) -> None:
        self.store = store
        self.target = max(1, target_bytes)
        self.f = None
        self.path: str | None = None
        self.off = 0
        self.index: list = []
        self.block: list = []
        self.bbytes = 0
        self.out: list[str] = []
        self.bytes_written = 0

    async def _open_run(self) -> None:
        s = self.store
        s._gen += 1
        self.path = f"{s.prefix}.run.{s._gen:08d}"
        self.f = s.fs.open(self.path)
        await self.f.truncate(0)
        self.off = 0
        self.index = []

    async def _emit_block(self) -> None:
        if not self.block:
            return
        blob = encode(self.block)
        self.index.append([self.block[0][0], self.off, len(blob)])
        await self.f.write(self.off, blob)
        self.off += len(blob)
        self.block = []
        self.bbytes = 0

    async def _close_run(self) -> None:
        await self._emit_block()
        if self.f is None:
            return
        f, path = self.f, self.path
        self.f = None
        self.path = None
        if not self.index:
            await f.close()
            self.store.fs.remove(path)
            return
        idx = encode(self.index)
        await f.write(self.off, idx)
        await f.write(self.off + len(idx),
                      self.off.to_bytes(8, "little") + _FOOTER)
        await f.sync()
        await f.close()
        self.bytes_written += self.off + len(idx) + 12
        self.out.append(path)

    async def add_rows(self, rows: list, drop_tombstones: bool) -> int:
        """Append merged rows (ascending keys, already deduplicated);
        returns the input bytes consumed (the slice-budget operand)."""
        nbytes = 0
        for e in rows:
            k, v = e[0], e[1]
            nbytes += len(k) + (len(v) if v is not None else 0)
            if v is None and drop_tombstones:
                continue
            if self.f is None:
                await self._open_run()
            self.block.append([k, v])
            self.bbytes += len(k) + (len(v) if v is not None else 0)
            if self.bbytes >= _BLOCK_BYTES:
                await self._emit_block()
                if self.off >= self.target:
                    await self._close_run()
        return nbytes

    async def finish(self) -> list[str]:
        await self._close_run()
        return self.out

    async def abort(self) -> None:
        """Best-effort cleanup of partial output (the job failed or was
        cancelled): unnamed files are also swept at next open."""
        f, path = self.f, self.path
        self.f = None
        self.path = None
        try:
            if f is not None:
                await f.close()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass
        for p in self.out + ([path] if path else []):
            try:
                self.store.fs.remove(p)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        self.out = []


def _merge_pair_rows(newer: list, older: list) -> list:
    """Vectorized 2-source merge slice (the ISSUE-13 segment pair-merge
    discipline applied to compaction): the two key columns resolve in
    ONE ``KeyRun.run_positions`` call, the merged key blob stitches via
    np.insert gathers (``merge_newest_wins``), and values follow one
    int source-index column — no per-row key comparisons at all."""
    ka = KeyRun.from_keys([bytes(r[0]) for r in older])
    kb = KeyRun.from_keys([bytes(r[0]) for r in newer])
    keys, src = ka.merge_newest_wins(kb)
    vals = [r[1] for r in older] + [r[1] for r in newer]
    return [(k, vals[s]) for k, s in zip(keys, src.tolist())]


def _merge(sources, reverse: bool, keep_tombstones: bool = False):
    """K-way merge, earlier sources win on equal keys; tombstones elided
    from the output unless kept (compaction intermediate)."""
    heap = []
    for si, it in enumerate(sources):
        it = iter(it)
        first = next(it, None)
        if first is not None:
            k = first[0]
            heap.append(((_rk(k) if reverse else k), si, first, it))
    heapq.heapify(heap)
    last_key = None
    while heap:
        _, si, (k, v), it = heapq.heappop(heap)
        nxt = next(it, None)
        if nxt is not None:
            heapq.heappush(heap, ((_rk(nxt[0]) if reverse else nxt[0]),
                                  si, nxt, it))
        if k == last_key:
            continue            # an older source's version of the same key
        last_key = k
        if v is None and not keep_tombstones:
            continue
        yield k, v


class _rk(bytes):
    """Reversed byte ordering for descending merges."""
    __slots__ = ()

    def __lt__(self, other):    # type: ignore[override]
        return bytes.__gt__(self, other)
