"""Persistent key-value engine: in-memory map + WAL + snapshot.

Reference: REF:fdbserver/KeyValueStoreMemory.actor.cpp — FDB's "memory"
engine holds the full map in RAM and makes it durable with an operation
log on a DiskQueue, periodically snapshotting the whole map so the log
can be truncated.  Same design here: commit() appends one encoded op
batch frame + fsync; recovery = load newest complete snapshot, replay
the WAL after it.  The engine also persists a small metadata dict
(durable version, tag, shard) the storage server needs to resume.

The IKeyValueStore surface (get/range/commit/meta) is engine-neutral:
a B-tree or LSM engine can replace this behind it (IKeyValueStore,
REF:fdbserver/IKeyValueStore.h).
"""

from __future__ import annotations

from typing import Iterator

from ..core.data import MutationBatch
from ..rpc.wire import decode, encode, frame as _frame, unframe as _unframe
from .disk_queue import DiskQueue
from .key_index import PackedKeyIndex
from .packed_ops import PackedOps

_SNAPSHOT_WAL_BYTES = 1 << 24   # rewrite snapshot when WAL exceeds 16MB
# rows per bulk run yielded by range_runs: big enough to amortize the
# per-run call, small enough that a limit-bounded scan never over-probes
RANGE_RUN_ROWS = 2048

OP_SET = 0
OP_CLEAR = 1


class MemoryKVStore:
    def __init__(self, fs, prefix: str) -> None:
        self.fs = fs
        self.prefix = prefix
        self._data: dict[bytes, bytes] = {}
        # PackedKeyIndex instead of the seed's flat bisect.insort list:
        # the engine sees the same batched workload as the MVCC window
        # (durability ticks, GC clears), so it gets the same structure —
        # amortized O(log n) inserts and ONE vectorized searchsorted for
        # a batch of clear bounds (ROADMAP open item b)
        self._index = PackedKeyIndex()
        self.meta: dict = {}
        self._wal: DiskQueue | None = None
        self._wal_file = None
        self._snap_gen = 0

    # --- lifecycle ---

    @classmethod
    async def open(cls, fs, prefix: str, knobs=None) -> "MemoryKVStore":
        # ``knobs`` accepted for engine-factory uniformity (the lsm
        # engine keys its compaction mode on it); unused here
        kv = cls(fs, prefix)
        # newest complete snapshot wins; exact "<prefix>.snap." match so
        # "storage-1" never picks up "storage-10"'s snapshots
        snap_paths = [p for p in fs.listdir(prefix)
                      if p.startswith(prefix + ".snap.")]
        loaded = None
        for path in sorted(snap_paths, reverse=True):
            f = fs.open(path)
            try:
                blob = await f.read(0, f.size())
                if not blob:
                    continue
                try:
                    payload = _unframe(blob)
                except ValueError:
                    payload = blob      # pre-frame snapshot: raw decode
                snap = decode(payload)
                kv._data = dict(snap["data"])
                kv.meta = snap["meta"]
                kv._snap_gen = snap["gen"]
                loaded = path
                break
            except Exception:
                continue    # torn snapshot: fall back to an older one
            finally:
                await f.close()
        kv._wal_file = fs.open(prefix + ".wal")
        kv._wal, frames = await DiskQueue.open(kv._wal_file)
        recs = [decode(frame) for frame, _end in frames]
        if snap_paths and loaded is None:
            # snapshot files exist but NONE decodes.  A kill tearing the
            # FIRST-ever snapshot write is a legitimate crash: the WAL
            # was not yet popped against it, so its surviving frames
            # carry generations BELOW the torn file's and rebuild the
            # whole state.  But frames at or past the newest snapshot
            # generation — or no frames at all — prove a snapshot once
            # synced and was popped against: recovering over an empty
            # map would silently resurrect a partial ancient state
            # (ISSUE 12; the lsm _load_manifest discipline)
            newest = max(int(p.rsplit(".", 1)[1]) for p in snap_paths)
            gens = [r["gen"] for r in recs]
            if not gens or min(gens) >= newest:
                from ..runtime.errors import DiskCorrupt
                raise DiskCorrupt(
                    f"no readable snapshot among {len(snap_paths)} "
                    f"on-disk snapshot files for {prefix} while the WAL "
                    f"references one — committed engine state is "
                    f"damaged, refusing to recover silently")
        kv._index.add_many(sorted(kv._data))
        for rec in recs:
            if rec["gen"] < kv._snap_gen:
                continue    # already folded into the snapshot
            if "pk" in rec:
                # packed frame (712 format): (types, bounds, blob)
                # segments straight back into the apply pass
                kv._apply(PackedOps([MutationBatch(*p) for p in rec["pk"]]))
            else:
                # pre-712 frame: the tuple-list op log
                kv._apply(rec["ops"])
            kv.meta = rec["meta"]
        return kv

    def _apply(self, ops) -> None:
        """ops: ordered (OP_SET, key, value) / (OP_CLEAR, begin, end) —
        any iterable of triples (a tuple list, or a ``PackedOps`` slice
        decoded lazily per op).

        Maintains data AND index together.  Fresh keys batch into one
        sorted overlay append; a run of consecutive clears (the
        durability loop's GC commit is exactly that) resolves every
        bound in ONE vectorized ``ranges_keys`` call instead of the
        seed's full-dict scan per clear."""
        data = self._data
        index = self._index
        fresh: list[bytes] = []
        clears: list[tuple[bytes, bytes]] = []

        def flush_clears() -> None:
            dead: set[bytes] = set()
            for keys in index.ranges_keys(clears):
                dead.update(keys)
            for k in dead:
                del data[k]
            index.discard_many(list(dead))
            clears.clear()

        for op, p1, p2 in ops:
            if op == OP_SET:
                if clears:
                    flush_clears()
                if p1 not in data:
                    fresh.append(p1)
                data[p1] = p2
            else:
                # clears must see fresh keys from this batch in the index
                if fresh:
                    index.add_many(fresh)
                    fresh = []
                clears.append((p1, p2))
        if clears:
            flush_clears()
        if fresh:
            index.add_many(fresh)

    # --- reads ---

    @property
    def packed_index(self):
        """The engine's PackedKeyIndex — the capability probe the device
        read path keys on (device/read_serve.py)."""
        return self._index

    def get(self, key: bytes) -> bytes | None:
        return self._data.get(key)

    def get_batch(self, keys: list[bytes]) -> list[bytes | None]:
        """Batched point reads (sorted keys by the wire contract; this
        engine's dict probe doesn't care)."""
        get = self._data.get
        return [get(k) for k in keys]

    def range(self, begin: bytes, end: bytes,
              reverse: bool = False) -> Iterator[tuple[bytes, bytes]]:
        keys = self._index.keys_in_range(begin, end)
        if reverse:
            keys = reversed(keys)
        for k in keys:
            v = self._data.get(k)
            if v is not None:
                yield k, v

    def range_runs(self, begin: bytes,
                   end: bytes) -> Iterator[list[tuple[bytes, bytes]]]:
        """Forward scan of [begin, end) as bulk row RUNS — the columnar
        range-read extraction (ISSUE 9).  The PackedKeyIndex resolves
        the whole interval in one bound query; values resolve per run
        (a C-speed list comprehension over the key slice), so a
        limit-bounded caller that stops consuming never probes the
        tail.  Flattened output is byte-identical to ``range``."""
        keys = self._index.keys_in_range(begin, end)
        data = self._data
        for i in range(0, len(keys), RANGE_RUN_ROWS):
            run = [(k, v) for k in keys[i:i + RANGE_RUN_ROWS]
                   if (v := data.get(k)) is not None]
            if run:
                yield run

    def __len__(self) -> int:
        return len(self._data)

    # --- writes ---

    async def commit(self, ops, meta: dict) -> None:
        """Durably apply one ordered op batch (the durability tick).

        A ``PackedOps`` slice rides into the WAL frame as its raw
        (types, bounds, blob) byte strings — the same objects the TLog
        pull handed the durability ring, zero-copy end to end; a plain
        tuple list (GC clears, engine tests) keeps the legacy frame
        shape."""
        if isinstance(ops, PackedOps):
            rec = encode({"gen": self._snap_gen, "pk": ops.wire_parts(),
                          "meta": meta})
        else:
            rec = encode({"gen": self._snap_gen, "ops": ops, "meta": meta})
        await self._wal.push(rec)
        await self._wal.commit()
        self._apply(ops)        # data + index together, clears batched
        self.meta = meta
        if self._wal.bytes_used > _SNAPSHOT_WAL_BYTES:
            await self._snapshot()

    async def _snapshot(self) -> None:
        self._snap_gen += 1
        path = f"{self.prefix}.snap.{self._snap_gen:08d}"
        f = self.fs.open(path)
        # crc-framed so a torn write from a kill FAILS the frame check
        # instead of decoding into garbage rows (the BackupContainer
        # frame discipline; ISSUE 12)
        blob = _frame(encode({"gen": self._snap_gen, "data": self._data,
                              "meta": self.meta}))
        await f.write(0, blob)
        await f.truncate(len(blob))
        await f.sync()
        await f.close()
        # restart the WAL: future records carry the new gen; old frames are
        # skipped on recovery via the gen check
        await self._wal.pop_to(self._wal.end_offset)
        # the new snapshot is durable: superseded generations are garbage
        for old in list(self.fs.listdir(self.prefix)):
            if old.startswith(self.prefix + ".snap.") and old != path:
                self.fs.remove(old)

    async def close(self) -> None:
        if self._wal_file is not None:
            await self._wal_file.close()
