"""MVCC versioned key-value map — the storage server's in-memory window.

Reference: REF:fdbserver/VersionedMap.h — upstream keeps a persistent
red-black tree (PTree) per version so the last ~5 seconds of versions are
all readable at once while TLog data ahead of the durable version is
replayed.  A persistent tree is the right call in C++ where structural
sharing saves copies; in Python the idiomatic equivalent is *per-key
version chains* over one sorted key index:

- ``_chains[key]`` is an append-only list of (version, value-or-None)
  in increasing version order (None = tombstone from a clear).
- ``_index`` is a sorted list of every key with a chain, for range scans.

Reads at version V binary-search each chain for the newest entry <= V.
Clears append tombstones to every covered live key — O(keys cleared),
same cost class as upstream's range insert into the PTree fringe.
Compaction (``forget_before``) folds chain prefixes below the new oldest
readable version; fully-dead keys leave the index.

This trades upstream's O(log n) snapshot-copy for chain append, which is
faster in CPython and keeps GC pressure flat; correctness properties
(exact-version reads, half-open ranges, tombstone semantics) are identical
and tested against a brute-force model.
"""

from __future__ import annotations

import bisect
from collections import deque
from typing import Iterator

from ..core.data import Version


class VersionedMap:
    def __init__(self) -> None:
        self._chains: dict[bytes, list[tuple[Version, bytes | None]]] = {}
        self._index: list[bytes] = []
        self.oldest_version: Version = 0   # reads below this raise at the role layer
        self.latest_version: Version = 0   # newest version any entry carries
        # every write/tombstone pushes (version, key) here; compaction
        # (forget_before / drop_before) pops entries at or below its
        # target and touches ONLY those keys — a full-map walk per GC
        # tick measured ~1s of event-loop stall per million keys on a
        # 1-cpu host (the r5 YCSB-at-1M-rows collapse).  A server uses
        # one consumer (engine-less -> forget, engine-backed -> drop);
        # rollback_after (recovery-rare) still walks everything.
        self._touched: deque[tuple[Version, bytes]] = deque()

    def __len__(self) -> int:
        return len(self._index)

    # --- writes (storage role applies mutations in version order) ---

    def set(self, version: Version, key: bytes, value: bytes) -> None:
        assert version >= self.latest_version, \
            f"mutations must arrive in version order " \
            f"(v={version} < latest={self.latest_version})"
        self.latest_version = version
        self._touched.append((version, key))
        chain = self._chains.get(key)
        if chain is None:
            self._chains[key] = [(version, value)]
            bisect.insort(self._index, key)
        elif chain[-1][0] == version:
            chain[-1] = (version, value)
        else:
            chain.append((version, value))

    def clear_range(self, version: Version, begin: bytes, end: bytes) -> None:
        assert version >= self.latest_version
        self.latest_version = version
        lo = bisect.bisect_left(self._index, begin)
        hi = bisect.bisect_left(self._index, end)
        for key in self._index[lo:hi]:
            chain = self._chains[key]
            if chain[-1][1] is not None:          # live at tip: tombstone it
                self._touched.append((version, key))
                if chain[-1][0] == version:
                    chain[-1] = (version, None)
                else:
                    chain.append((version, None))

    # --- reads ---

    def get(self, key: bytes, version: Version) -> bytes | None:
        found, value = self.get2(key, version)
        return value if found else None

    def get2(self, key: bytes, version: Version) -> tuple[bool, bytes | None]:
        """(found, value): found=False means this map has no entry at or
        below ``version`` — the caller falls through to the persistent
        engine (the PTree→IKeyValueStore read path of getValueQ,
        REF:fdbserver/storageserver.actor.cpp)."""
        chain = self._chains.get(key)
        if chain is None:
            return False, None
        i = bisect.bisect_right(chain, version, key=lambda e: e[0]) - 1
        if i < 0:
            return False, None
        return True, chain[i][1]

    def get_latest(self, key: bytes) -> bytes | None:
        chain = self._chains.get(key)
        return chain[-1][1] if chain else None

    def range_iter(self, begin: bytes, end: bytes, version: Version,
                   reverse: bool = False) -> Iterator[tuple[bytes, bytes]]:
        lo = bisect.bisect_left(self._index, begin)
        hi = bisect.bisect_left(self._index, end)
        keys = self._index[lo:hi]
        if reverse:
            keys = reversed(keys)
        for key in keys:
            v = self.get(key, version)
            if v is not None:
                yield key, v

    def range_read(self, begin: bytes, end: bytes, version: Version,
                   limit: int = 0, reverse: bool = False,
                   byte_limit: int = 0) -> tuple[list[tuple[bytes, bytes]], bool]:
        """Returns (kv pairs, more) where more=True means limits truncated."""
        out: list[tuple[bytes, bytes]] = []
        nbytes = 0
        it = self.range_iter(begin, end, version, reverse)
        for kv in it:
            out.append(kv)
            nbytes += len(kv[0]) + len(kv[1])
            if (limit and len(out) >= limit) or (byte_limit and nbytes >= byte_limit):
                # one probe to learn if anything remains
                more = next(it, None) is not None
                return out, more
        return out, False

    def overlay_iter(self, begin: bytes, end: bytes, version: Version,
                     reverse: bool = False):
        """Yield (key, found, value) for every key with a chain in range —
        including not-found and tombstone markers — for merging over an
        engine's range iterator."""
        lo = bisect.bisect_left(self._index, begin)
        hi = bisect.bisect_left(self._index, end)
        keys = self._index[lo:hi]
        if reverse:
            keys = reversed(keys)
        for key in keys:
            found, v = self.get2(key, version)
            yield key, found, v

    # --- compaction (setOldestVersion analog) ---

    def _pop_touched(self, version: Version) -> set[bytes]:
        """Keys with at least one entry at or below ``version`` — every
        such entry has a queued (version, key) record by construction."""
        keys: set[bytes] = set()
        q = self._touched
        while q and q[0][0] <= version:
            keys.add(q.popleft()[1])
        return keys

    def forget_before(self, version: Version) -> None:
        """Drop history below ``version``; reads at >= version unaffected.
        Touches only keys written at or below ``version`` (incremental)."""
        if version <= self.oldest_version:
            return
        self.oldest_version = version
        dead: list[bytes] = []
        for key in self._pop_touched(version):
            chain = self._chains.get(key)
            if chain is None:
                continue
            # newest entry <= version becomes the base; older ones go
            i = len(chain) - 1
            while i > 0 and chain[i][0] > version:
                i -= 1
            if i > 0:
                del chain[:i]
            if len(chain) == 1 and chain[0][1] is None and chain[0][0] <= version:
                dead.append(key)
        for key in dead:
            del self._chains[key]
            i = bisect.bisect_left(self._index, key)
            del self._index[i]

    def rollback_after(self, version: Version) -> None:
        """Discard every entry newer than ``version`` — the storage-server
        rollback at recovery (REF:fdbserver/storageserver.actor.cpp
        rollback): mutations the server applied from a log generation's
        unacked suffix were clamped out of the recovered history and must
        be un-applied before pulling from the new generation."""
        if version >= self.latest_version:
            return
        self.latest_version = version
        dead: list[bytes] = []
        for key, chain in self._chains.items():
            i = len(chain)
            while i > 0 and chain[i - 1][0] > version:
                i -= 1
            if i < len(chain):
                del chain[i:]
            if not chain:
                dead.append(key)
        for key in dead:
            del self._chains[key]
            i = bisect.bisect_left(self._index, key)
            del self._index[i]
        # purge queue records for the rolled-back suffix: a stale
        # higher-version record at the front would park _pop_touched (it
        # pops while monotonically <= target) and stall compaction for
        # every key queued behind it until versions climb past it again
        self._touched = deque(e for e in self._touched if e[0] <= version)

    def drop_before(self, version: Version) -> None:
        """Remove entries at or below ``version`` entirely (they are now
        durable in the engine); reads at those versions must fall through.
        Mirrors the PTree erase after makeVersionDurable.  Touches only
        keys written at or below ``version`` (incremental)."""
        if version <= self.oldest_version:
            return
        self.oldest_version = version
        dead: list[bytes] = []
        for key in self._pop_touched(version):
            chain = self._chains.get(key)
            if chain is None:
                continue
            i = 0
            while i < len(chain) and chain[i][0] <= version:
                i += 1
            if i > 0:
                del chain[:i]
            if not chain:
                dead.append(key)
        for key in dead:
            del self._chains[key]
            i = bisect.bisect_left(self._index, key)
            del self._index[i]
