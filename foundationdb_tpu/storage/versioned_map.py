"""MVCC versioned key-value map — the storage server's in-memory window.

Reference: REF:fdbserver/VersionedMap.h — upstream keeps a persistent
red-black tree (PTree) per version so the last ~5 seconds of versions are
all readable at once while TLog data ahead of the durable version is
replayed.  A persistent tree is the right call in C++ where structural
sharing saves copies; in Python the idiomatic equivalent is *per-key
version chains* over one sorted key index:

- ``_chains[key]`` is an append-only list of (version, value-or-None)
  in increasing version order (None = tombstone from a clear).
- ``_index`` is a PackedKeyIndex (storage/key_index.py) of every key
  with a chain, for range scans — two sorted runs merged lazily, so a
  fresh-key insert costs amortized O(log n) instead of the seed's O(n)
  ``bisect.insort`` memmove (the r5 YCSB-at-1M-rows bench collapse:
  O(n²) across a bulk load, ~900ms event-loop stalls per SlowTask).
  Since ISSUE 11 the base run is COLUMNAR (storage/key_runs.py: one
  key blob + cumulative bounds, ~key_len+8 bytes/key instead of
  ~50-100 of PyObject overhead), which is what lets the window's index
  track millions of keys; the chains dict itself stays per-key and is
  the next wall when the MVCC window holds a huge hot set (ROADMAP
  item 5 follow-up (b)).

Reads at version V binary-search each chain for the newest entry <= V.
Clears append tombstones to every covered live key — O(keys cleared),
same cost class as upstream's range insert into the PTree fringe.
Compaction (``forget_before``) folds chain prefixes below the new oldest
readable version; fully-dead keys leave the index in ONE batched pass.

``apply_batch`` is the storage role's hot path: a whole TLog pull
reply's ops in one call — fresh keys are collected, sorted once, and
merged into the index in a single O(n+m) pass.

This trades upstream's O(log n) snapshot-copy for chain append, which is
faster in CPython and keeps GC pressure flat; correctness properties
(exact-version reads, half-open ranges, tombstone semantics) are identical
and tested against a brute-force model.
"""

from __future__ import annotations

import bisect
from collections import deque
from typing import Iterator

from ..core.data import Version
# apply_batch op codes ARE the engine's WAL op codes — one definition,
# so the storage server can feed either surface from the same tuples
from .key_index import PackedKeyIndex
from .kv_store import OP_CLEAR, OP_SET

__all__ = ["VersionedMap", "OP_SET", "OP_CLEAR"]


class VersionedMap:
    def __init__(self) -> None:
        self._chains: dict[bytes, list[tuple[Version, bytes | None]]] = {}
        self._index = PackedKeyIndex()
        self.oldest_version: Version = 0   # reads below this raise at the role layer
        self.latest_version: Version = 0   # newest version any entry carries
        # every write/tombstone pushes (version, key) here in version
        # order; compaction (forget_before / drop_before) pops entries at
        # or below its target and touches ONLY those keys, and
        # rollback_after pops the strict suffix above its target — a
        # full-map walk per GC tick measured ~1s of event-loop stall per
        # million keys on a 1-cpu host (the r5 YCSB-at-1M-rows collapse).
        # A server uses one consumer (engine-less -> forget,
        # engine-backed -> drop).
        self._touched: deque[tuple[Version, bytes]] = deque()

    def __len__(self) -> int:
        return len(self._index)

    def keys(self) -> list[bytes]:
        """Full sorted key list (test/debug surface; O(n))."""
        return self._index.to_list()

    def index_stats(self) -> dict:
        return self._index.stats()

    # --- writes (storage role applies mutations in version order) ---

    def set(self, version: Version, key: bytes, value: bytes) -> None:
        assert version >= self.latest_version, \
            f"mutations must arrive in version order " \
            f"(v={version} < latest={self.latest_version})"
        self.latest_version = version
        self._touched.append((version, key))
        chain = self._chains.get(key)
        if chain is None:
            self._chains[key] = [(version, value)]
            self._index.add(key)
        elif chain[-1][0] == version:
            chain[-1] = (version, value)
        else:
            chain.append((version, value))

    def clear_range(self, version: Version, begin: bytes, end: bytes) -> None:
        assert version >= self.latest_version
        self.latest_version = version
        for key in self._index.keys_in_range(begin, end):
            chain = self._chains[key]
            if chain[-1][1] is not None:          # live at tip: tombstone it
                self._touched.append((version, key))
                if chain[-1][0] == version:
                    chain[-1] = (version, None)
                else:
                    chain.append((version, None))

    def apply_batch(self, ops: list[tuple[Version, int, bytes, bytes]]) -> int:
        """Apply a version-ordered run of (version, OP_SET|OP_CLEAR,
        p1, p2) ops — a whole TLog pull reply in one call.

        Sets are chain-appends with the index insert DEFERRED: fresh keys
        are collected and merged into the index in one sorted pass at the
        end (or just before a clear, whose range scan must see them).
        State after the call is identical to the equivalent sequence of
        ``set``/``clear_range`` calls (tests/test_versioned_map.py proves
        this against the brute-force model); only the cost differs —
        O(batch + merge) instead of O(batch × index).
        """
        chains = self._chains
        touched = self._touched
        index = self._index
        fresh: list[bytes] = []
        latest = self.latest_version
        n = len(ops)
        i = 0
        while i < n:
            version, op, p1, p2 = ops[i]
            assert version >= latest, \
                f"mutations must arrive in version order " \
                f"(v={version} < latest={latest})"
            latest = version
            if op == OP_SET:
                touched.append((version, p1))
                chain = chains.get(p1)
                if chain is None:
                    chains[p1] = [(version, p2)]
                    fresh.append(p1)
                elif chain[-1][0] == version:
                    chain[-1] = (version, p2)
                else:
                    chain.append((version, p2))
                i += 1
                continue
            # a run of consecutive clears: the range scans must see fresh
            # keys from this batch, and with no intervening inserts all
            # the runs' bounds can resolve in one vectorized pass
            if fresh:
                index.add_many(fresh)
                fresh = []
            j = i
            while j < n and ops[j][1] == OP_CLEAR:
                j += 1
            run = ops[i:j]
            for (version, _op, begin, end), keys in zip(
                    run, index.ranges_keys([(o[2], o[3]) for o in run])):
                assert version >= latest, \
                    f"mutations must arrive in version order " \
                    f"(v={version} < latest={latest})"
                latest = version
                for key in keys:
                    chain = chains[key]
                    if chain[-1][1] is not None:
                        touched.append((version, key))
                        if chain[-1][0] == version:
                            chain[-1] = (version, None)
                        else:
                            chain.append((version, None))
            i = j
        if fresh:
            index.add_many(fresh)
        self.latest_version = latest
        return n

    def apply_packed(self, version: Version, batch) -> int:
        """Apply one version's simple-only packed ``MutationBatch`` (type
        codes are OP_SET/OP_CLEAR by construction — MutationType values 0
        and 1) straight off its columnar arrays: param bytes are sliced
        from the blob exactly once, and no per-op tuple or ``Mutation``
        object is ever built.  State after the call is identical to
        ``apply_batch`` over the equivalent (version, op, p1, p2) run
        (tests/test_mutation_batch.py proves equivalence on randomized
        workloads)."""
        assert version >= self.latest_version, \
            f"mutations must arrive in version order " \
            f"(v={version} < latest={self.latest_version})"
        chains = self._chains
        touched = self._touched
        index = self._index
        types = batch.types
        offs = batch.offsets()
        blob = batch.blob
        fresh: list[bytes] = []
        clears: list[tuple[bytes, bytes]] = []

        def flush_clears() -> None:
            for keys in index.ranges_keys(clears):
                for key in keys:
                    chain = chains[key]
                    if chain[-1][1] is not None:
                        touched.append((version, key))
                        if chain[-1][0] == version:
                            chain[-1] = (version, None)
                        else:
                            chain.append((version, None))
            clears.clear()

        prev = 0
        for i in range(len(types)):
            e1, e2 = offs[2 * i], offs[2 * i + 1]
            p1 = blob[prev:e1]
            if types[i] == OP_SET:
                if clears:
                    flush_clears()
                p2 = blob[e1:e2]
                touched.append((version, p1))
                chain = chains.get(p1)
                if chain is None:
                    chains[p1] = [(version, p2)]
                    fresh.append(p1)
                elif chain[-1][0] == version:
                    chain[-1] = (version, p2)
                else:
                    chain.append((version, p2))
            else:
                # clears must see fresh keys from this batch in the
                # index; consecutive clears resolve vectorized
                if fresh:
                    index.add_many(fresh)
                    fresh = []
                clears.append((p1, blob[e1:e2]))
            prev = e2
        if clears:
            flush_clears()
        if fresh:
            index.add_many(fresh)
        self.latest_version = version
        return len(types)

    # --- reads ---

    def get(self, key: bytes, version: Version) -> bytes | None:
        found, value = self.get2(key, version)
        return value if found else None

    def get2(self, key: bytes, version: Version) -> tuple[bool, bytes | None]:
        """(found, value): found=False means this map has no entry at or
        below ``version`` — the caller falls through to the persistent
        engine (the PTree→IKeyValueStore read path of getValueQ,
        REF:fdbserver/storageserver.actor.cpp)."""
        chain = self._chains.get(key)
        if chain is None:
            return False, None
        i = bisect.bisect_right(chain, version, key=lambda e: e[0]) - 1
        if i < 0:
            return False, None
        return True, chain[i][1]

    def get2_batch(self, keys: list[bytes],
                   version: Version) -> list[tuple[bool, bytes | None]]:
        """Batched ``get2`` — one pass over the whole probe list (the
        multiget read path's window probe, ISSUE 5).  Result i is
        exactly ``get2(keys[i], version)``; callers separate the
        found=False entries in the same pass and resolve them through
        the engine's ``get_batch``.

        Cheaper than a ``get2`` loop by construction, not cleverness:
        one bound method per batch instead of per key, and the common
        cases — no chain at all, or the chain tip already at-or-below
        ``version`` (every key outside the current commit wave) —
        resolve without the keyed bisect."""
        chains = self._chains
        out: list[tuple[bool, bytes | None]] = []
        append = out.append
        br = bisect.bisect_right
        for key in keys:
            chain = chains.get(key)
            if chain is None:
                append((False, None))
                continue
            v0, val = chain[-1]
            if v0 <= version:
                append((True, val))
            elif chain[0][0] > version:
                append((False, None))
            else:
                i = br(chain, version, key=lambda e: e[0]) - 1
                append((True, chain[i][1]))
        return out

    def get_latest(self, key: bytes) -> bytes | None:
        chain = self._chains.get(key)
        return chain[-1][1] if chain else None

    def range_iter(self, begin: bytes, end: bytes, version: Version,
                   reverse: bool = False) -> Iterator[tuple[bytes, bytes]]:
        keys = self._index.keys_in_range(begin, end)
        if reverse:
            keys = list(reversed(keys))
        for key in keys:
            v = self.get(key, version)
            if v is not None:
                yield key, v

    def range_read(self, begin: bytes, end: bytes, version: Version,
                   limit: int = 0, reverse: bool = False,
                   byte_limit: int = 0) -> tuple[list[tuple[bytes, bytes]], bool]:
        """Returns (kv pairs, more) where more=True means limits truncated."""
        out: list[tuple[bytes, bytes]] = []
        nbytes = 0
        it = self.range_iter(begin, end, version, reverse)
        for kv in it:
            out.append(kv)
            nbytes += len(kv[0]) + len(kv[1])
            if (limit and len(out) >= limit) or (byte_limit and nbytes >= byte_limit):
                # one probe to learn if anything remains
                more = next(it, None) is not None
                return out, more
        return out, False

    def overlay_keys(self, begin: bytes, end: bytes) -> list[bytes]:
        """Sorted keys with a chain in [begin, end) — the overlay the
        run-wise packed range merge bisects into the engine's runs
        (ISSUE 9).  Entries resolve lazily via ``get2`` so a
        limit-bounded merge never probes past its cut."""
        return self._index.keys_in_range(begin, end)

    def range_rows(self, begin: bytes, end: bytes, version: Version,
                   limit: int = 0, byte_limit: int = 0
                   ) -> tuple[list[tuple[bytes, bytes]], bool]:
        """Forward bulk range read — result identical to
        ``range_read(begin, end, version, limit, False, byte_limit)``
        (tested), built in ONE tight loop over the interval's key slice
        instead of the per-row generator chain: the engine-less packed
        range path (ISSUE 9).  ``more`` is exact, like ``range_read``'s:
        True iff a live row remains past the cut."""
        keys = self._index.keys_in_range(begin, end)
        chains = self._chains
        br = bisect.bisect_right

        def _ver(e):
            return e[0]

        out: list[tuple[bytes, bytes]] = []
        nbytes = 0
        i, n = 0, len(keys)
        while i < n:
            key = keys[i]
            i += 1
            chain = chains[key]
            v0, val = chain[-1]
            if v0 > version:
                if chain[0][0] > version:
                    continue
                val = chain[br(chain, version, key=_ver) - 1][1]
            if val is None:
                continue
            out.append((key, val))
            nbytes += len(key) + len(val)
            if (limit and len(out) >= limit) \
                    or (byte_limit and nbytes >= byte_limit):
                # probe ahead for the exact `more`: the next LIVE row,
                # skipping tombstones/not-yet-visible chains (what
                # range_read's one-probe continuation does)
                while i < n:
                    k2 = keys[i]
                    i += 1
                    c2 = chains[k2]
                    v0, val = c2[-1]
                    if v0 > version:
                        if c2[0][0] > version:
                            continue
                        val = c2[br(c2, version, key=_ver) - 1][1]
                    if val is not None:
                        return out, True
                return out, False
        return out, False

    def overlay_iter(self, begin: bytes, end: bytes, version: Version,
                     reverse: bool = False):
        """Yield (key, found, value) for every key with a chain in range —
        including not-found and tombstone markers — for merging over an
        engine's range iterator."""
        keys = self._index.keys_in_range(begin, end)
        if reverse:
            keys = list(reversed(keys))
        for key in keys:
            found, v = self.get2(key, version)
            yield key, found, v

    # --- compaction (setOldestVersion analog) ---

    def _pop_touched(self, version: Version) -> set[bytes]:
        """Keys with at least one entry at or below ``version`` — every
        such entry has a queued (version, key) record by construction."""
        keys: set[bytes] = set()
        q = self._touched
        while q and q[0][0] <= version:
            keys.add(q.popleft()[1])
        return keys

    def _remove_dead(self, dead: list[bytes]) -> None:
        """Drop fully-compacted keys from chains and index in one batched
        pass (the seed's per-key bisect+del was the quadratic shape on
        the compaction side)."""
        if not dead:
            return
        for key in dead:
            del self._chains[key]
        self._index.discard_many(dead)

    def forget_before(self, version: Version) -> None:
        """Drop history below ``version``; reads at >= version unaffected.
        Touches only keys written at or below ``version`` (incremental)."""
        if version <= self.oldest_version:
            return
        self.oldest_version = version
        dead: list[bytes] = []
        for key in self._pop_touched(version):
            chain = self._chains.get(key)
            if chain is None:
                continue
            # newest entry <= version becomes the base; older ones go
            i = len(chain) - 1
            while i > 0 and chain[i][0] > version:
                i -= 1
            if i > 0:
                del chain[:i]
            if len(chain) == 1 and chain[0][1] is None and chain[0][0] <= version:
                dead.append(key)
        self._remove_dead(dead)

    def rollback_after(self, version: Version) -> None:
        """Discard every entry newer than ``version`` — the storage-server
        rollback at recovery (REF:fdbserver/storageserver.actor.cpp
        rollback): mutations the server applied from a log generation's
        unacked suffix were clamped out of the recovered history and must
        be un-applied before pulling from the new generation.

        Incremental: the touched queue is version-sorted (writes arrive
        in version order), and every chain entry above ``version`` has a
        queued record — so popping the queue's suffix names exactly the
        affected chains, no full-map walk.  Popping the suffix also IS
        the stale-record purge: a higher-version record left at the
        front would park ``_pop_touched`` (it pops while monotonically
        <= target) and stall compaction for every key queued behind it."""
        if version >= self.latest_version:
            return
        self.latest_version = version
        q = self._touched
        if version >= self.oldest_version:
            keys: set[bytes] = set()
            while q and q[-1][0] > version:
                keys.add(q.pop()[1])
            items = [(k, c) for k in keys
                     if (c := self._chains.get(k)) is not None]
        else:
            # rolling below the readable floor (never legal from the role
            # layer, but keep the seed's full-walk semantics as a net)
            items = list(self._chains.items())
            self._touched = deque(e for e in q if e[0] <= version)
        dead: list[bytes] = []
        for key, chain in items:
            i = len(chain)
            while i > 0 and chain[i - 1][0] > version:
                i -= 1
            if i < len(chain):
                del chain[i:]
            if not chain:
                dead.append(key)
        self._remove_dead(dead)

    def drop_before(self, version: Version) -> None:
        """Remove entries at or below ``version`` entirely (they are now
        durable in the engine); reads at those versions must fall through.
        Mirrors the PTree erase after makeVersionDurable.  Touches only
        keys written at or below ``version`` (incremental)."""
        if version <= self.oldest_version:
            return
        self.oldest_version = version
        dead: list[bytes] = []
        for key in self._pop_touched(version):
            chain = self._chains.get(key)
            if chain is None:
                continue
            i = 0
            while i < len(chain) and chain[i][0] <= version:
                i += 1
            if i > 0:
                del chain[:i]
            if not chain:
                dead.append(key)
        self._remove_dead(dead)
