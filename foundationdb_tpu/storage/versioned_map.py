"""MVCC versioned key-value map — the storage server's in-memory window.

Reference: REF:fdbserver/VersionedMap.h — upstream keeps a persistent
red-black tree (PTree) per version so the last ~5 seconds of versions are
all readable at once while TLog data ahead of the durable version is
replayed.  Two implementations live here behind one constructor
(ISSUE 13, the ``columnar=False`` pattern of PackedKeyIndex):

LEGACY (``columnar=False``) — per-key version chains over one sorted key
index: ``_chains[key]`` is an append-only list of (version,
value-or-None) in increasing version order (None = tombstone), plus a
``_touched`` deque driving incremental compaction.  Fine while the MVCC
window stays small; on a huge hot set the dict-of-PyObject-lists is the
dominant RSS and GC load of the whole server (ROADMAP item 5 follow-up
(b)), every ``forget_before``/``drop_before`` tick does per-key list
surgery, and ``get2_batch`` bisects one Python chain at a time.

COLUMNAR (default) — a generational window:

- a small mutable **tip**: the per-key chain dict, scoped to versions
  above the last seal, bounded by the seal budget (ops / bytes /
  version span), with its own PackedKeyIndex for range scans;
- immutable **sealed segments**, newest first: a distinct-key sorted
  ``KeyRun`` + a cumulative per-key entry-count column + parallel int64
  version / value-offset columns over ONE value blob (offset -1 = the
  tombstone bit).  ``apply_packed`` seals a whole all-SET TLog batch
  into a segment near-zero-copy — the segment's value blob IS the
  ``MutationBatch`` blob, only the keys are re-sorted;
- reads probe tip-then-segments-newest-first.  ``get2_batch`` narrows
  each segment with ONE vectorized prefix-searchsorted band per batch
  (the PR 5/PR 10 probe discipline) instead of a per-key dict+bisect;
- ``drop_before`` retires whole segments at-or-below the floor in
  O(segments); ``forget_before`` advances the floor and lazily FOLDS
  wholly-below segments into a base segment with geometric
  amortization; ``rollback_after`` truncates the tip and the suffix
  segments.

Entries an eager compactor would delete may linger inside retained
segments; they are INVISIBLE by the floor rules below, so the two modes
are observably equivalent (tests/test_mvcc_window.py proves it against
the legacy twin and the brute-force model on randomized interleavings):

- drop floor: a resolved entry at or below ``_drop_floor`` reads as
  found=False (the engine is authoritative — what ``drop_before``
  physically deleted in legacy mode);
- forget base: the newest entry at or below ``oldest_version`` stays
  readable (legacy kept it as the folded chain base);
- dead keys: a key whose newest entry anywhere is a tombstone at or
  below ``oldest_version`` reads as found=False (legacy removed the
  single-tombstone chain).

Known semantic gap REPRODUCED deliberately (pre-existing, both modes,
now documented in ROADMAP item 5): ``clear_range`` materializes
tombstones only for keys currently IN the window — a key cold for
longer than one MVCC window (its chain dropped to the engine) then
cleared serves its stale engine row until the clear itself becomes
durable.  Fixing it needs range tombstones in the window (upstream
keeps clears as range nodes in the PTree); the columnar rewrite keeps
the legacy behavior bit-for-bit so the A/B twin stays meaningful.
"""

from __future__ import annotations

import bisect
import time
from array import array as _array
from collections import deque
from typing import Iterator

from ..core.data import Version
# apply_batch op codes ARE the engine's WAL op codes — one definition,
# so the storage server can feed either surface from the same tuples
from .key_index import PackedKeyIndex
from .key_runs import KeyRun
from .kv_store import OP_CLEAR, OP_SET

__all__ = ["VersionedMap", "LegacyVersionedMap", "ColumnarVersionedMap",
           "OP_SET", "OP_CLEAR"]

# --- columnar seal / compaction defaults (constructor-overridable; the
#     storage server passes the STORAGE_MVCC_* knobs through, and the
#     knob defaults ARE the one definition — re-exported here so direct
#     constructions and knob-driven ones can never drift) ---
from ..runtime.knobs import Knobs as _Knobs

SEAL_OPS = _Knobs.STORAGE_MVCC_SEAL_OPS          # tip entries before a seal
SEAL_BYTES = _Knobs.STORAGE_MVCC_SEAL_BYTES      # tip key+value bytes
SEAL_VERSIONS = _Knobs.STORAGE_MVCC_SEAL_VERSIONS  # tip version span (just
#                            under the 5M-version MVCC window: a low-rate
#                            trickle stays tip-resident for its whole life)
_DIRECT_SEAL_MIN = 256     # all-SET packed batches this big seal directly
_SEG_CAP = 12              # live segments before adjacent pairs merge
_FOLD_MIN_SEGS = 2         # wholly-below-floor segments before a fold
_BATCH_MIN = 16            # below this, batched probes fall back to bisect
_RANGE_WINDOW = 4096       # candidate keys per layer per range-walk step
_SMALL_PROBE_BATCH = 64    # point-probe batches at or under this ride the
#                            per-key recent-hit cache (ISSUE 14 satellite,
#                            ROADMAP 5 (e)) instead of the per-segment
#                            vectorized probe — the transient-KeyRun setup
#                            cost only amortizes at larger batches
_PROBE_CACHE_CAP = 1 << 17  # recent-hit cache entries before a reset


def VersionedMap(columnar: bool = True, seal_ops: int = SEAL_OPS,
                 seal_bytes: int = SEAL_BYTES,
                 seal_versions: int = SEAL_VERSIONS):
    """Construct the MVCC window — columnar by default, the legacy
    dict-of-chains twin behind ``columnar=False`` (the equivalence / RSS
    A/B baseline, exactly PackedKeyIndex's pattern)."""
    if columnar:
        return ColumnarVersionedMap(seal_ops=seal_ops,
                                    seal_bytes=seal_bytes,
                                    seal_versions=seal_versions)
    return LegacyVersionedMap()


class LegacyVersionedMap:
    """The dict-of-per-key-chains window (the pre-ISSUE-13 layout)."""

    columnar = False

    def __init__(self) -> None:
        self._chains: dict[bytes, list[tuple[Version, bytes | None]]] = {}
        self._index = PackedKeyIndex()
        self.oldest_version: Version = 0   # reads below this raise at the role layer
        self.latest_version: Version = 0   # newest version any entry carries
        # every write/tombstone pushes (version, key) here in version
        # order; compaction (forget_before / drop_before) pops entries at
        # or below its target and touches ONLY those keys, and
        # rollback_after pops the strict suffix above its target — a
        # full-map walk per GC tick measured ~1s of event-loop stall per
        # million keys on a 1-cpu host (the r5 YCSB-at-1M-rows collapse).
        # A server uses one consumer (engine-less -> forget,
        # engine-backed -> drop).
        self._touched: deque[tuple[Version, bytes]] = deque()

    def __len__(self) -> int:
        return len(self._index)

    def keys(self) -> list[bytes]:
        """Full sorted key list (test/debug surface; O(n))."""
        return self._index.to_list()

    def index_stats(self) -> dict:
        return self._index.stats()

    # --- writes (storage role applies mutations in version order) ---

    def set(self, version: Version, key: bytes, value: bytes) -> None:
        assert version >= self.latest_version, \
            f"mutations must arrive in version order " \
            f"(v={version} < latest={self.latest_version})"
        self.latest_version = version
        self._touched.append((version, key))
        chain = self._chains.get(key)
        if chain is None:
            self._chains[key] = [(version, value)]
            self._index.add(key)
        elif chain[-1][0] == version:
            chain[-1] = (version, value)
        else:
            chain.append((version, value))

    def clear_range(self, version: Version, begin: bytes, end: bytes) -> None:
        assert version >= self.latest_version
        self.latest_version = version
        for key in self._index.keys_in_range(begin, end):
            chain = self._chains[key]
            if chain[-1][1] is not None:          # live at tip: tombstone it
                self._touched.append((version, key))
                if chain[-1][0] == version:
                    chain[-1] = (version, None)
                else:
                    chain.append((version, None))

    def apply_batch(self, ops: list[tuple[Version, int, bytes, bytes]]) -> int:
        """Apply a version-ordered run of (version, OP_SET|OP_CLEAR,
        p1, p2) ops — a whole TLog pull reply in one call.

        Sets are chain-appends with the index insert DEFERRED: fresh keys
        are collected and merged into the index in one sorted pass at the
        end (or just before a clear, whose range scan must see them).
        State after the call is identical to the equivalent sequence of
        ``set``/``clear_range`` calls (tests/test_versioned_map.py proves
        this against the brute-force model); only the cost differs —
        O(batch + merge) instead of O(batch × index).
        """
        chains = self._chains
        touched = self._touched
        index = self._index
        fresh: list[bytes] = []
        latest = self.latest_version
        n = len(ops)
        i = 0
        while i < n:
            version, op, p1, p2 = ops[i]
            assert version >= latest, \
                f"mutations must arrive in version order " \
                f"(v={version} < latest={latest})"
            latest = version
            if op == OP_SET:
                touched.append((version, p1))
                chain = chains.get(p1)
                if chain is None:
                    chains[p1] = [(version, p2)]
                    fresh.append(p1)
                elif chain[-1][0] == version:
                    chain[-1] = (version, p2)
                else:
                    chain.append((version, p2))
                i += 1
                continue
            # a run of consecutive clears: the range scans must see fresh
            # keys from this batch, and with no intervening inserts all
            # the runs' bounds can resolve in one vectorized pass
            if fresh:
                index.add_many(fresh)
                fresh = []
            j = i
            while j < n and ops[j][1] == OP_CLEAR:
                j += 1
            run = ops[i:j]
            for (version, _op, begin, end), keys in zip(
                    run, index.ranges_keys([(o[2], o[3]) for o in run])):
                assert version >= latest, \
                    f"mutations must arrive in version order " \
                    f"(v={version} < latest={latest})"
                latest = version
                for key in keys:
                    chain = chains[key]
                    if chain[-1][1] is not None:
                        touched.append((version, key))
                        if chain[-1][0] == version:
                            chain[-1] = (version, None)
                        else:
                            chain.append((version, None))
            i = j
        if fresh:
            index.add_many(fresh)
        self.latest_version = latest
        return n

    def apply_packed(self, version: Version, batch) -> int:
        """Apply one version's simple-only packed ``MutationBatch`` (type
        codes are OP_SET/OP_CLEAR by construction — MutationType values 0
        and 1) straight off its columnar arrays: param bytes are sliced
        from the blob exactly once, and no per-op tuple or ``Mutation``
        object is ever built.  State after the call is identical to
        ``apply_batch`` over the equivalent (version, op, p1, p2) run
        (tests/test_mutation_batch.py proves equivalence on randomized
        workloads)."""
        assert version >= self.latest_version, \
            f"mutations must arrive in version order " \
            f"(v={version} < latest={self.latest_version})"
        chains = self._chains
        touched = self._touched
        index = self._index
        types = batch.types
        offs = batch.offsets()
        blob = batch.blob
        fresh: list[bytes] = []
        clears: list[tuple[bytes, bytes]] = []

        def flush_clears() -> None:
            for keys in index.ranges_keys(clears):
                for key in keys:
                    chain = chains[key]
                    if chain[-1][1] is not None:
                        touched.append((version, key))
                        if chain[-1][0] == version:
                            chain[-1] = (version, None)
                        else:
                            chain.append((version, None))
            clears.clear()

        prev = 0
        for i in range(len(types)):
            e1, e2 = offs[2 * i], offs[2 * i + 1]
            p1 = blob[prev:e1]
            if types[i] == OP_SET:
                if clears:
                    flush_clears()
                p2 = blob[e1:e2]
                touched.append((version, p1))
                chain = chains.get(p1)
                if chain is None:
                    chains[p1] = [(version, p2)]
                    fresh.append(p1)
                elif chain[-1][0] == version:
                    chain[-1] = (version, p2)
                else:
                    chain.append((version, p2))
            else:
                # clears must see fresh keys from this batch in the
                # index; consecutive clears resolve vectorized
                if fresh:
                    index.add_many(fresh)
                    fresh = []
                clears.append((p1, blob[e1:e2]))
            prev = e2
        if clears:
            flush_clears()
        if fresh:
            index.add_many(fresh)
        self.latest_version = version
        return len(types)

    # --- reads ---

    def get(self, key: bytes, version: Version) -> bytes | None:
        found, value = self.get2(key, version)
        return value if found else None

    def get2(self, key: bytes, version: Version) -> tuple[bool, bytes | None]:
        """(found, value): found=False means this map has no entry at or
        below ``version`` — the caller falls through to the persistent
        engine (the PTree→IKeyValueStore read path of getValueQ,
        REF:fdbserver/storageserver.actor.cpp)."""
        chain = self._chains.get(key)
        if chain is None:
            return False, None
        i = bisect.bisect_right(chain, version, key=lambda e: e[0]) - 1
        if i < 0:
            return False, None
        return True, chain[i][1]

    def get2_batch(self, keys: list[bytes],
                   version: Version) -> list[tuple[bool, bytes | None]]:
        """Batched ``get2`` — one pass over the whole probe list (the
        multiget read path's window probe, ISSUE 5).  Result i is
        exactly ``get2(keys[i], version)``; callers separate the
        found=False entries in the same pass and resolve them through
        the engine's ``get_batch``.

        Cheaper than a ``get2`` loop by construction, not cleverness:
        one bound method per batch instead of per key, and the common
        cases — no chain at all, or the chain tip already at-or-below
        ``version`` (every key outside the current commit wave) —
        resolve without the keyed bisect."""
        chains = self._chains
        out: list[tuple[bool, bytes | None]] = []
        append = out.append
        br = bisect.bisect_right
        for key in keys:
            chain = chains.get(key)
            if chain is None:
                append((False, None))
                continue
            v0, val = chain[-1]
            if v0 <= version:
                append((True, val))
            elif chain[0][0] > version:
                append((False, None))
            else:
                i = br(chain, version, key=lambda e: e[0]) - 1
                append((True, chain[i][1]))
        return out

    def get_latest(self, key: bytes) -> bytes | None:
        chain = self._chains.get(key)
        return chain[-1][1] if chain else None

    def range_iter(self, begin: bytes, end: bytes, version: Version,
                   reverse: bool = False) -> Iterator[tuple[bytes, bytes]]:
        keys = self._index.keys_in_range(begin, end)
        if reverse:
            keys = list(reversed(keys))
        for key in keys:
            v = self.get(key, version)
            if v is not None:
                yield key, v

    def range_read(self, begin: bytes, end: bytes, version: Version,
                   limit: int = 0, reverse: bool = False,
                   byte_limit: int = 0) -> tuple[list[tuple[bytes, bytes]], bool]:
        """Returns (kv pairs, more) where more=True means limits truncated."""
        out: list[tuple[bytes, bytes]] = []
        nbytes = 0
        it = self.range_iter(begin, end, version, reverse)
        for kv in it:
            out.append(kv)
            nbytes += len(kv[0]) + len(kv[1])
            if (limit and len(out) >= limit) or (byte_limit and nbytes >= byte_limit):
                # one probe to learn if anything remains
                more = next(it, None) is not None
                return out, more
        return out, False

    def overlay_keys(self, begin: bytes, end: bytes) -> list[bytes]:
        """Sorted keys with a chain in [begin, end) — the overlay the
        run-wise packed range merge bisects into the engine's runs
        (ISSUE 9).  Entries resolve lazily via ``get2`` so a
        limit-bounded merge never probes past its cut."""
        return self._index.keys_in_range(begin, end)

    def range_rows(self, begin: bytes, end: bytes, version: Version,
                   limit: int = 0, byte_limit: int = 0
                   ) -> tuple[list[tuple[bytes, bytes]], bool]:
        """Forward bulk range read — result identical to
        ``range_read(begin, end, version, limit, False, byte_limit)``
        (tested), built in ONE tight loop over the interval's key slice
        instead of the per-row generator chain: the engine-less packed
        range path (ISSUE 9).  ``more`` is exact, like ``range_read``'s:
        True iff a live row remains past the cut."""
        keys = self._index.keys_in_range(begin, end)
        chains = self._chains
        br = bisect.bisect_right

        def _ver(e):
            return e[0]

        out: list[tuple[bytes, bytes]] = []
        nbytes = 0
        i, n = 0, len(keys)
        while i < n:
            key = keys[i]
            i += 1
            chain = chains[key]
            v0, val = chain[-1]
            if v0 > version:
                if chain[0][0] > version:
                    continue
                val = chain[br(chain, version, key=_ver) - 1][1]
            if val is None:
                continue
            out.append((key, val))
            nbytes += len(key) + len(val)
            if (limit and len(out) >= limit) \
                    or (byte_limit and nbytes >= byte_limit):
                # probe ahead for the exact `more`: the next LIVE row,
                # skipping tombstones/not-yet-visible chains (what
                # range_read's one-probe continuation does)
                while i < n:
                    k2 = keys[i]
                    i += 1
                    c2 = chains[k2]
                    v0, val = c2[-1]
                    if v0 > version:
                        if c2[0][0] > version:
                            continue
                        val = c2[br(c2, version, key=_ver) - 1][1]
                    if val is not None:
                        return out, True
                return out, False
        return out, False

    def overlay_iter(self, begin: bytes, end: bytes, version: Version,
                     reverse: bool = False):
        """Yield (key, found, value) for every key with a chain in range —
        including not-found and tombstone markers — for merging over an
        engine's range iterator."""
        keys = self._index.keys_in_range(begin, end)
        if reverse:
            keys = list(reversed(keys))
        for key in keys:
            found, v = self.get2(key, version)
            yield key, found, v

    # --- compaction (setOldestVersion analog) ---

    def _pop_touched(self, version: Version) -> set[bytes]:
        """Keys with at least one entry at or below ``version`` — every
        such entry has a queued (version, key) record by construction."""
        keys: set[bytes] = set()
        q = self._touched
        while q and q[0][0] <= version:
            keys.add(q.popleft()[1])
        return keys

    def _remove_dead(self, dead: list[bytes]) -> None:
        """Drop fully-compacted keys from chains and index in one batched
        pass (the seed's per-key bisect+del was the quadratic shape on
        the compaction side)."""
        if not dead:
            return
        for key in dead:
            del self._chains[key]
        self._index.discard_many(dead)

    def forget_before(self, version: Version) -> None:
        """Drop history below ``version``; reads at >= version unaffected.
        Touches only keys written at or below ``version`` (incremental)."""
        if version <= self.oldest_version:
            return
        self.oldest_version = version
        dead: list[bytes] = []
        for key in self._pop_touched(version):
            chain = self._chains.get(key)
            if chain is None:
                continue
            # newest entry <= version becomes the base; older ones go
            i = len(chain) - 1
            while i > 0 and chain[i][0] > version:
                i -= 1
            if i > 0:
                del chain[:i]
            if len(chain) == 1 and chain[0][1] is None and chain[0][0] <= version:
                dead.append(key)
        self._remove_dead(dead)

    def rollback_after(self, version: Version) -> None:
        """Discard every entry newer than ``version`` — the storage-server
        rollback at recovery (REF:fdbserver/storageserver.actor.cpp
        rollback): mutations the server applied from a log generation's
        unacked suffix were clamped out of the recovered history and must
        be un-applied before pulling from the new generation.

        Incremental: the touched queue is version-sorted (writes arrive
        in version order), and every chain entry above ``version`` has a
        queued record — so popping the queue's suffix names exactly the
        affected chains, no full-map walk.  Popping the suffix also IS
        the stale-record purge: a higher-version record left at the
        front would park ``_pop_touched`` (it pops while monotonically
        <= target) and stall compaction for every key queued behind it."""
        if version >= self.latest_version:
            return
        self.latest_version = version
        q = self._touched
        if version >= self.oldest_version:
            keys: set[bytes] = set()
            while q and q[-1][0] > version:
                keys.add(q.pop()[1])
            items = [(k, c) for k in keys
                     if (c := self._chains.get(k)) is not None]
        else:
            # rolling below the readable floor (never legal from the role
            # layer, but keep the seed's full-walk semantics as a net)
            items = list(self._chains.items())
            self._touched = deque(e for e in q if e[0] <= version)
            # the stale floor would otherwise park drop/forget_before
            # (their <= oldest_version early-return) until the new
            # generation climbed past it — void it like the columnar
            # twin does, so the nets stay observably equivalent
            self.oldest_version = version
        dead: list[bytes] = []
        for key, chain in items:
            i = len(chain)
            while i > 0 and chain[i - 1][0] > version:
                i -= 1
            if i < len(chain):
                del chain[i:]
            if not chain:
                dead.append(key)
        self._remove_dead(dead)

    def drop_before(self, version: Version) -> None:
        """Remove entries at or below ``version`` entirely (they are now
        durable in the engine); reads at those versions must fall through.
        Mirrors the PTree erase after makeVersionDurable.  Touches only
        keys written at or below ``version`` (incremental)."""
        if version <= self.oldest_version:
            return
        self.oldest_version = version
        dead: list[bytes] = []
        for key in self._pop_touched(version):
            chain = self._chains.get(key)
            if chain is None:
                continue
            i = 0
            while i < len(chain) and chain[i][0] <= version:
                i += 1
            if i > 0:
                del chain[:i]
            if not chain:
                dead.append(key)
        self._remove_dead(dead)


# ---------------------------------------------------------------------------
# Columnar window (ISSUE 13)
# ---------------------------------------------------------------------------


import numpy as np


def _np_q(arr: _array) -> np.ndarray:
    """Zero-copy int64 view of an array('q') column (vector ops only;
    scalar access stays on the stdlib array — the KeyRun discipline)."""
    return np.frombuffer(arr, dtype=np.int64)


def _q_from(npa: np.ndarray) -> _array:
    """array('q') column from an int64 ndarray (one C-speed copy)."""
    a = _array("q")
    a.frombytes(np.ascontiguousarray(npa, dtype=np.int64).tobytes())
    return a


class _Segment:
    """One immutable sealed run of MVCC entries.

    ``keys`` holds the DISTINCT sorted keys; ``counts`` is the cumulative
    entry count per key (so key j's entries live at
    [counts[j-1], counts[j]) — counts[-1] == total entries).  Per entry,
    ``versions`` ascends within each key (ties across layers are broken
    by segment order, never inside one segment), and ``vstarts[i] == -1``
    is the tombstone bit; live values are ``vblob[vstarts[i]:vends[i]]``.
    ``vblob`` may BE a ``MutationBatch`` blob (the near-zero-copy direct
    seal) — offsets are absolute into whatever blob the segment carries.
    """

    __slots__ = ("keys", "counts", "versions", "vstarts", "vends", "vblob",
                 "min_version", "max_version", "fanout1", "_npcols")

    def __init__(self, keys: KeyRun, counts: _array, versions: _array,
                 vstarts: _array, vends: _array, vblob: bytes,
                 min_version: Version, max_version: Version) -> None:
        self.keys = keys
        self.counts = counts
        self.versions = versions
        self.vstarts = vstarts
        self.vends = vends
        self.vblob = vblob
        self.min_version = min_version
        self.max_version = max_version
        # one entry per key — the direct-seal shape; lets range
        # extraction and the batched probe skip the per-key version
        # bisect entirely
        self.fanout1 = len(versions) == len(keys)
        self._npcols = None

    def np_cols(self):
        """(versions, vstarts, vends) as cached zero-copy int64 views —
        the vectorized probe/extraction operands."""
        if self._npcols is None:
            self._npcols = (np.frombuffer(self.versions, dtype=np.int64),
                            np.frombuffer(self.vstarts, dtype=np.int64),
                            np.frombuffer(self.vends, dtype=np.int64))
        return self._npcols

    def __len__(self) -> int:
        return len(self.versions)

    @property
    def nbytes(self) -> int:
        """Resident bytes of the columns (the memory-wall accounting)."""
        return (self.keys.nbytes + len(self.vblob)
                + 8 * (len(self.counts) + 3 * len(self.versions)))

    def find(self, key: bytes) -> int:
        """Distinct-key index of ``key`` or -1."""
        j = self.keys.bisect_left(key)
        if j < len(self.keys) and self.keys.key(j) == key:
            return j
        return -1

    def band(self, j: int) -> tuple[int, int]:
        c = self.counts
        return (c[j - 1] if j else 0), c[j]

    def value(self, i: int) -> bytes | None:
        s = self.vstarts[i]
        return None if s < 0 else self.vblob[s:self.vends[i]]

    def resolve(self, j: int, version: Version
                ) -> tuple[Version, bytes | None] | None:
        """Newest entry of key j at or below ``version`` as (entry
        version, value-or-tombstone-None); None when every entry of the
        key is above ``version``."""
        lo, hi = self.band(j)
        vs = self.versions
        i = bisect.bisect_right(vs, version, lo, hi) - 1
        if i < lo:
            return None
        return vs[i], self.value(i)

    def newest(self, j: int) -> tuple[Version, bytes | None]:
        lo, hi = self.band(j)
        return self.versions[hi - 1], self.value(hi - 1)

    def key_span(self, begin: bytes, end: bytes) -> tuple[int, int]:
        return self.keys.bisect_left(begin), self.keys.bisect_left(end)

    def entries_of(self, j: int) -> list[tuple[Version, bytes | None]]:
        lo, hi = self.band(j)
        vs = self.versions
        return [(vs[i], self.value(i)) for i in range(lo, hi)]

    def truncated(self, version: Version) -> "_Segment | None":
        """Entries at or below ``version`` only (rollback); None when
        nothing survives."""
        if self.max_version <= version:
            return self
        b = _SegmentBuilder()
        keys = self.keys
        for j in range(len(keys)):
            kept = [e for e in self.entries_of(j) if e[0] <= version]
            if kept:
                b.add_key(keys.key(j), kept)
        return b.finish()


class _SegmentBuilder:
    """Accumulates (key, entries) in sorted key order into one segment."""

    __slots__ = ("_keys", "_counts", "_versions", "_vstarts", "_vends",
                 "_chunks", "_pos", "_n", "_vmin", "_vmax")

    def __init__(self) -> None:
        self._keys: list[bytes] = []
        self._counts = _array("q")
        self._versions = _array("q")
        self._vstarts = _array("q")
        self._vends = _array("q")
        self._chunks: list[bytes] = []
        self._pos = 0
        self._n = 0
        self._vmin: Version | None = None
        self._vmax: Version | None = None

    def add_key(self, key: bytes,
                entries: list[tuple[Version, bytes | None]]) -> None:
        self._keys.append(key)
        for ver, val in entries:
            self._versions.append(ver)
            if val is None:
                self._vstarts.append(-1)
                self._vends.append(-1)
            else:
                self._vstarts.append(self._pos)
                self._pos += len(val)
                self._vends.append(self._pos)
                self._chunks.append(val)
            if self._vmin is None or ver < self._vmin:
                self._vmin = ver
            if self._vmax is None or ver > self._vmax:
                self._vmax = ver
        self._n += len(entries)
        self._counts.append(self._n)

    def finish(self) -> _Segment | None:
        if not self._keys:
            return None
        return _Segment(KeyRun.from_keys(self._keys), self._counts,
                        self._versions, self._vstarts, self._vends,
                        b"".join(self._chunks), self._vmin, self._vmax)


class ColumnarVersionedMap:
    """Generational columnar MVCC window — see the module docstring."""

    columnar = True

    def __init__(self, seal_ops: int = SEAL_OPS,
                 seal_bytes: int = SEAL_BYTES,
                 seal_versions: int = SEAL_VERSIONS) -> None:
        self.seal_ops = max(1, seal_ops)
        self.seal_bytes = max(1, seal_bytes)
        self.seal_versions = max(1, seal_versions)
        self.oldest_version: Version = 0
        self.latest_version: Version = 0
        # entries at or below this are dropped-invisible (the engine is
        # authoritative); forget mode never advances it
        self._drop_floor: Version = 0
        # tombstone registry + dead markers: legacy's dead-key removal
        # is TEMPORAL — a lone tombstone judged dead when the floor
        # crossed it stays dead even if the key is re-set later, which
        # retained entries alone cannot reconstruct.  Every tombstone
        # write queues (version, key) here (version-ordered, the
        # _touched discipline restricted to clears); ``forget_before``
        # pops the at-or-below prefix and marks keys whose newest entry
        # is that tombstone in ``_dead`` (key -> tombstone version).  A
        # marker is a PER-KEY drop floor: every entry of the key at or
        # below it reads found=False (legacy removed the whole chain),
        # merges prune those entries physically, and a marker retires
        # only once no remaining layer reaches that far back — pruning
        # just the tombstone would resurrect older shadowed values
        # still sitting in layers outside the merge.
        self._clears: deque[tuple[Version, bytes]] = deque()
        self._dead: dict[bytes, Version] = {}
        # mutable tip: per-key chains for versions above the last seal
        self._tip: dict[bytes, list[tuple[Version, bytes | None]]] = {}
        self._tip_index = PackedKeyIndex()
        self._tip_entries = 0
        self._tip_bytes = 0
        self._tip_min: Version | None = None
        # immutable sealed segments, NEWEST FIRST (resolution order is
        # layer order; version ranges are non-increasing down the list,
        # ties at layer boundaries resolved by layer)
        self._segments: list[_Segment] = []
        self._sealed_through: Version = 0
        # recent-hit probe cache (ISSUE 14 satellite, ROADMAP 5 (e)):
        # key -> (version, value, found) — the key's NEWEST sealed
        # entry (or found=False for a key certified to live in no
        # segment) — so a repeat point probe against a multi-segment
        # window resolves at the legacy dict-hit shape: tip miss,
        # cache hit, done.  Entries are recorded only from walks that
        # skipped NO newer segment (a version-filtered walk cannot
        # certify the newest entry), answer only bounds at-or-above
        # the cached version, and the whole cache clears on ANY
        # segment-list change (seal/merge/fold/drop/rollback); newer
        # TIP writes need no invalidation — the tip probe runs first
        # and shadows the cache exactly when it should.
        self._probe_cache: dict[bytes, tuple[Version, bytes | None,
                                             bool]] = {}
        # observability
        self.seals = 0
        self.compactions = 0
        self.folds = 0
        self.seal_s = 0.0

    # --- accounting / observability ---

    def __len__(self) -> int:
        # distinct-key UPPER BOUND (duplicates across layers counted
        # once per layer) — the O(1) metrics surface; ``keys()`` is the
        # exact-but-O(n) test surface
        return len(self._tip) + sum(len(s.keys) for s in self._segments)

    @property
    def nbytes(self) -> int:
        return self._tip_bytes + sum(s.nbytes for s in self._segments)

    def index_stats(self) -> dict:
        return {
            "keys": len(self),
            "pending": self._tip_entries,
            "merges": self.seals + self.compactions + self.folds,
            "merge_ms": round(self.seal_s * 1e3, 3),
            "base_bytes": sum(s.keys.nbytes for s in self._segments),
            "columnar": True,
            "segments": len(self._segments),
            "entries": self._tip_entries + sum(len(s) for s in
                                               self._segments),
            "resident_bytes": self.nbytes,
            "seals": self.seals,
            "folds": self.folds,
        }

    def keys(self) -> list[bytes]:
        """Sorted keys a legacy map would still hold a chain for
        (test/debug surface; O(n))."""
        out: list[bytes] = []
        dead = self._dead
        for key, group in self._groups(b"", None):
            ver, _val = self._newest_in_group(group)
            if ver <= self._drop_floor:
                continue        # every entry dropped to the engine
            d = dead.get(key)
            if d is not None and ver <= d:
                continue        # dead: judged at a past forget tick
            out.append(key)
        return out

    # --- internal: layer resolution ---

    def _resolve_tip(self, key: bytes, version: Version
                     ) -> tuple[Version, bytes | None] | None:
        """Tip probe: None = no chain OR chain entirely above
        ``version`` (older layers may still answer)."""
        chain = self._tip.get(key)
        if chain is None:
            return None
        v0, val = chain[-1]
        if v0 <= version:
            return v0, val
        if chain[0][0] > version:
            return None
        i = bisect.bisect_right(chain, version, key=lambda e: e[0]) - 1
        return chain[i]

    def _finish(self, key: bytes, ver: Version,
                val: bytes | None) -> tuple[bool, bytes | None]:
        """Apply the visibility rules to a resolved entry."""
        if ver <= self._drop_floor:
            # everything at or below the resolved version is older still:
            # all dropped to the engine — fall through
            return False, None
        if self._dead:
            d = self._dead.get(key)
            if d is not None and ver <= d:
                # dead key: legacy forget removed the whole chain when
                # the floor crossed its lone tombstone — the marker is
                # a per-key drop floor over everything it shadowed
                return False, None
        return True, val

    # --- reads ---

    def get(self, key: bytes, version: Version) -> bytes | None:
        found, value = self.get2(key, version)
        return value if found else None

    def get2(self, key: bytes, version: Version) -> tuple[bool, bytes | None]:
        r = self._resolve_tip(key, version)
        if r is not None:
            return self._finish(key, r[0], r[1])
        return self._get2_sealed(key, version)

    def _get2_sealed(self, key: bytes, version: Version
                     ) -> tuple[bool, bytes | None]:
        """``get2`` below the tip (probe cache, then the segment walk)
        — the entry point for callers that already know the tip missed
        (the small-batch fast path), so the tip dict probe is not paid
        twice per key."""
        hint = self._probe_cache.get(key)
        if hint is not None:
            ver, val, found = hint
            if not found:
                # clean-walk-certified: the key lives in NO segment
                return False, None
            if version >= ver:
                # the cached entry is the key's newest sealed entry and
                # the bound clears it: the answer, at dict-hit cost
                # (_finish re-applies the CURRENT floor/dead rules —
                # they move without touching the segment list)
                return self._finish(key, ver, val)
            # bound below the newest sealed entry: rare — full walk
        clean = True        # no newer segment skipped or unresolved yet
        for seg in self._segments:
            if seg.min_version > version:
                clean = False   # a version-filtered walk cannot certify
                #                 the newest sealed entry for any key
                continue
            j = seg.find(key)
            if j < 0:
                continue
            r = seg.resolve(j, version)
            if r is not None:
                if clean:
                    nv, nval = seg.newest(j)
                    if r[0] == nv:
                        # resolved the newest entry of the key's newest
                        # holding segment == its newest sealed entry
                        if len(self._probe_cache) >= _PROBE_CACHE_CAP:
                            self._probe_cache.clear()
                        self._probe_cache[key] = (nv, nval, True)
                return self._finish(key, r[0], r[1])
            clean = False   # the band sits wholly above the bound: an
            #                 older layer may answer, but not with the
            #                 key's newest sealed entry
        if clean:
            # a clean full walk proves the key is in NO segment: cache
            # the negative so repeat misses skip the walk outright
            if len(self._probe_cache) >= _PROBE_CACHE_CAP:
                self._probe_cache.clear()
            self._probe_cache[key] = (0, None, False)
        return False, None

    def get2_batch(self, keys: list[bytes],
                   version: Version) -> list[tuple[bool, bytes | None]]:
        """Batched ``get2`` — the tip resolves as dict probes; each
        segment then answers every still-unresolved key with ONE
        vectorized prefix-searchsorted band per segment (the PR 5/PR 10
        probe discipline) refined by a bisect inside the band."""
        n = len(keys)
        out: list[tuple[bool, bytes | None] | None] = [None] * n
        pending: list[int] = []
        tip = self._tip
        br = bisect.bisect_right
        finish = self._finish
        for i, key in enumerate(keys):
            chain = tip.get(key)
            if chain is None:
                pending.append(i)
                continue
            v0, val = chain[-1]
            if v0 <= version:
                out[i] = finish(key, v0, val)
            elif chain[0][0] > version:
                pending.append(i)
            else:
                k = br(chain, version, key=lambda e: e[0]) - 1
                out[i] = finish(key, chain[k][0], chain[k][1])
        if not pending or not self._segments:
            for i in pending:
                out[i] = (False, None)
            return out  # type: ignore[return-value]
        if n <= _SMALL_PROBE_BATCH:
            # small point-probe batches (ISSUE 14 satellite, ROADMAP
            # 5 (e)): the per-segment vectorized probe's transient-
            # KeyRun setup swamps ≤64-key batches — ride the per-key
            # recent-hit cache instead.  The hit path is inlined (one
            # cache dict get + the floor rules), so a warm repeat
            # probe resolves at the legacy dict-hit shape; only cache
            # misses and below-newest version bounds pay a walk.
            cache = self._probe_cache
            drop = self._drop_floor
            dead = self._dead
            for i in pending:
                key = keys[i]
                hint = cache.get(key)
                if hint is None:
                    # pending ⇒ the tip already missed: walk below it
                    out[i] = self._get2_sealed(key, version)
                    continue
                ver, val, found = hint
                if not found or ver <= drop:
                    out[i] = (False, None)
                elif version < ver:
                    out[i] = self._get2_sealed(key, version)
                elif dead and (d := dead.get(key)) is not None \
                        and ver <= d:
                    out[i] = (False, None)
                else:
                    out[i] = (True, val)
            return out  # type: ignore[return-value]
        # a sorted probe list (the wire contract of the multiget path)
        # unlocks the fully-vectorized run-vs-run probe: the probe keys
        # become ONE transient KeyRun whose prefixes encode once, each
        # segment answers the WHOLE batch with one two-level
        # searchsorted (run_positions), and newest-layer-wins resolves
        # as vectorized masks — no per-key dict/bisect work at all.
        srt = n > 1 and all(keys[x] <= keys[x + 1] for x in range(n - 1))
        if srt:
            prun = KeyRun.from_keys(keys)
            if n < 512:
                # list-based encode (2 numpy calls) beats the columnar
                # _pfx_from (~10) at small probe batches; above the
                # crossover the vectorized column encode wins
                from ..ops.keycode import encode_prefix_u64
                prun.adopt_prefixes(
                    encode_prefix_u64(keys),
                    encode_prefix_u64([k[8:16] for k in keys]),
                    np.fromiter(map(len, keys), dtype=np.int64, count=n))
            done = np.zeros(n, dtype=bool)
            done[[i for i in range(n) if out[i] is not None]] = True
            res_ver = np.zeros(n, dtype=np.int64)
            res_s = np.zeros(n, dtype=np.int64)
            res_e = np.zeros(n, dtype=np.int64)
            res_seg = np.full(n, -1, dtype=np.int64)
            for si, seg in enumerate(self._segments):
                if done.all():
                    break
                if seg.min_version > version:
                    continue
                pos, dupm = seg.keys.run_positions(prun)
                if seg.fanout1:
                    npv, nps, npe = seg.np_cols()
                    safe = np.where(dupm, pos, 0)
                    vers = npv[safe]
                    hit = dupm & (vers <= version) & ~done
                    if hit.any():
                        done |= hit
                        res_ver[hit] = vers[hit]
                        res_s[hit] = nps[safe][hit]
                        res_e[hit] = npe[safe][hit]
                        res_seg[hit] = si
                    continue
                # multi-entry segment: per-key band bisect for the
                # still-unresolved matches only
                cand = np.nonzero(dupm & ~done)[0]
                for i in cand.tolist():
                    r = seg.resolve(int(pos[i]), version)
                    if r is None:
                        continue
                    done[i] = True
                    if r[1] is None:
                        # tombstone: settle through the reconciliation
                        # pass (the drop-floor / dead-marker rules)
                        res_ver[i] = r[0]
                        res_seg[i] = si
                        res_s[i] = -1
                    else:
                        # finish applies the same visibility rules
                        # inline; out[i] set skips the reconciliation
                        out[i] = finish(keys[i], r[0], r[1])
            drop = self._drop_floor
            dead = self._dead
            segs = self._segments
            rsl = res_s.tolist()
            rel = res_e.tolist()
            rvl = res_ver.tolist()
            rgl = res_seg.tolist()
            for i in range(n):
                if out[i] is not None:
                    continue
                g = rgl[i]
                if g < 0:
                    out[i] = (False, None)
                    continue
                ver = rvl[i]
                if ver <= drop:
                    out[i] = (False, None)
                    continue
                if dead:
                    d = dead.get(keys[i])
                    if d is not None and ver <= d:
                        out[i] = (False, None)
                        continue
                s = rsl[i]
                out[i] = (True, None) if s == -1 \
                    else (True, segs[g].vblob[s:rel[i]])
            return out  # type: ignore[return-value]
        for seg in self._segments:
            if not pending:
                break
            if seg.min_version > version:
                continue
            nxt: list[int] = []
            probe = [keys[i] for i in pending]
            fnd = seg.keys.batch_find(probe)
            for p, i in enumerate(pending):
                j = fnd[p]
                if j < 0:
                    nxt.append(i)
                    continue
                r = seg.resolve(j, version)
                if r is None:
                    nxt.append(i)
                    continue
                out[i] = finish(probe[p], r[0], r[1])
            pending = nxt
        for i in pending:
            out[i] = (False, None)
        return out  # type: ignore[return-value]

    def _newest_entry(self, key: bytes) -> tuple[Version, bytes | None] | None:
        """The key's newest entry across all layers, or None."""
        chain = self._tip.get(key)
        if chain is not None:
            return chain[-1]
        for seg in self._segments:
            j = seg.find(key)
            if j >= 0:
                return seg.newest(j)
        return None

    def get_latest(self, key: bytes) -> bytes | None:
        e = self._newest_entry(key)
        if e is None or e[0] <= self._drop_floor:
            return None     # absent or dropped: the engine is authoritative
        d = self._dead.get(key) if self._dead else None
        if d is not None and e[0] <= d:
            return None     # dead: the legacy chain was removed
        return e[1]

    # --- range reads (merged candidate walk) ---

    def _candidates(self, begin: bytes, end: bytes | None
                    ) -> list[tuple[bytes, int, int]]:
        """(key, layer, position) for every layer occurrence in
        [begin, end) — ONE C-speed sort puts same-key occurrences
        adjacent with the newest layer first (layer 0 = tip)."""
        out: list[tuple[bytes, int, int]] = []
        if end is None:
            tipkeys = self._tip_index.to_list()
        else:
            tipkeys = self._tip_index.keys_in_range(begin, end)
        out.extend((k, 0, 0) for k in tipkeys)
        for layer, seg in enumerate(self._segments, start=1):
            lo = seg.keys.bisect_left(begin) if begin else 0
            hi = (seg.keys.bisect_left(end) if end is not None
                  else len(seg.keys))
            if lo >= hi:
                continue
            ks = seg.keys.slice_keys(lo, hi)
            out.extend(zip(ks, (layer,) * len(ks), range(lo, hi)))
        out.sort()
        return out

    def _groups(self, begin: bytes, end: bytes | None):
        """Yield (key, [(layer, pos), ...]) per distinct key in range,
        occurrences newest layer first — WINDOWED: candidates
        materialize at most ``_RANGE_WINDOW`` keys per layer per step,
        so a limit-bounded consumer over a huge range (the chunked
        packed-scan continuation) pays O(consumed × layers), never the
        whole remaining range per chunk."""
        cur = begin
        while True:
            if end is not None and cur >= end:
                return
            # pivot: the window-th key of whichever layer reaches it
            # first (strictly > cur since keys are distinct and sorted,
            # so every step progresses)
            pivot = end
            for seg in self._segments:
                lo = seg.keys.bisect_left(cur)
                kth = lo + _RANGE_WINDOW
                if kth < len(seg.keys):
                    k = seg.keys.key(kth)
                    if pivot is None or k < pivot:
                        pivot = k
            if pivot is None:
                allk = self._tip_index.to_list()
                tipkeys = allk[bisect.bisect_left(allk, cur):]
            else:
                tipkeys = self._tip_index.keys_in_range(cur, pivot)
            if len(tipkeys) > _RANGE_WINDOW:
                pivot = tipkeys[_RANGE_WINDOW]
                tipkeys = tipkeys[:_RANGE_WINDOW]
            cands: list[tuple[bytes, int, int]] = []
            cands.extend((k, 0, 0) for k in tipkeys)
            for layer, seg in enumerate(self._segments, start=1):
                lo = seg.keys.bisect_left(cur)
                hi = (seg.keys.bisect_left(pivot) if pivot is not None
                      else len(seg.keys))
                if lo < hi:
                    ks = seg.keys.slice_keys(lo, hi)
                    cands.extend(zip(ks, (layer,) * len(ks),
                                     range(lo, hi)))
            cands.sort()
            i, n = 0, len(cands)
            while i < n:
                key = cands[i][0]
                j = i + 1
                while j < n and cands[j][0] == key:
                    j += 1
                yield key, cands[i:j]
                i = j
            if pivot is None:
                return
            cur = pivot

    def _newest_in_group(self, group) -> tuple[Version, bytes | None]:
        _k, layer, pos = group[0]
        if layer == 0:
            return self._tip[_k][-1]
        return self._segments[layer - 1].newest(pos)

    def _resolve_group(self, key: bytes, group,
                       version: Version) -> tuple[bool, bytes | None]:
        for _k, layer, pos in group:
            if layer == 0:
                r = self._resolve_tip(key, version)
            else:
                seg = self._segments[layer - 1]
                if seg.min_version > version:
                    continue
                r = seg.resolve(pos, version)
            if r is not None:
                return self._finish(key, r[0], r[1])
        return False, None

    def overlay_keys(self, begin: bytes, end: bytes) -> list[bytes]:
        """Sorted distinct keys with any entry in [begin, end) — the
        overlay the run-wise packed range merge bisects into the
        engine's runs (ISSUE 9).  May include keys that resolve
        found=False (retained-but-invisible entries); the consumer's
        lazy ``get2`` makes those indistinguishable from absent chains."""
        out: list[bytes] = []
        last = None
        for cand in self._candidates(begin, end):
            if cand[0] != last:
                last = cand[0]
                out.append(last)
        return out

    def overlay_iter(self, begin: bytes, end: bytes, version: Version,
                     reverse: bool = False):
        """Yield (key, found, value) for every key with an entry in
        range — the row-wise merge feed (engine-backed legacy + reverse
        paths).  Forward iteration stays LAZY (the windowed group walk
        — a limit-bounded consumer never pays for the range's tail);
        reverse — the selector-resolution shape, small by contract —
        materializes and flips."""
        if reverse:
            groups = list(self._groups(begin, end))
            groups.reverse()
            for key, group in groups:
                found, val = self._resolve_group(key, group, version)
                yield key, found, val
            return
        for key, group in self._groups(begin, end):
            found, val = self._resolve_group(key, group, version)
            yield key, found, val

    def range_iter(self, begin: bytes, end: bytes, version: Version,
                   reverse: bool = False) -> Iterator[tuple[bytes, bytes]]:
        for key, found, val in self.overlay_iter(begin, end, version,
                                                 reverse):
            if found and val is not None:
                yield key, val

    def range_read(self, begin: bytes, end: bytes, version: Version,
                   limit: int = 0, reverse: bool = False,
                   byte_limit: int = 0
                   ) -> tuple[list[tuple[bytes, bytes]], bool]:
        """Returns (kv pairs, more); more=True means limits truncated."""
        out: list[tuple[bytes, bytes]] = []
        nbytes = 0
        it = self.range_iter(begin, end, version, reverse)
        for kv in it:
            out.append(kv)
            nbytes += len(kv[0]) + len(kv[1])
            if (limit and len(out) >= limit) \
                    or (byte_limit and nbytes >= byte_limit):
                more = next(it, None) is not None
                return out, more
        return out, False

    def range_rows(self, begin: bytes, end: bytes, version: Version,
                   limit: int = 0, byte_limit: int = 0
                   ) -> tuple[list[tuple[bytes, bytes]], bool]:
        """Forward bulk range read, result identical to ``range_read``
        without reverse (tested) — the engine-less packed range path.
        The hot shape — one sealed fanout-1 segment covering the range
        at-or-below ``version``, no tip or sibling overlap — extracts
        rows as C-speed column slices with no per-key resolution at
        all; mixed layers fall back to the merged candidate walk."""
        fast = self._range_rows_fast(begin, end, version, limit, byte_limit)
        if fast is not None:
            return fast
        return self.range_read(begin, end, version, limit, False,
                               byte_limit)

    def _range_rows_fast(self, begin: bytes, end: bytes, version: Version,
                         limit: int, byte_limit: int):
        """The single-segment bulk extraction, or None to fall back."""
        if (self._drop_floor or self._dead) and self._segments:
            return None     # dropped/dead-invisible entries need the walk
        owner = None
        for layer, seg in enumerate(self._segments, start=1):
            lo, hi = seg.key_span(begin, end)
            if lo >= hi:
                continue
            if owner is not None:
                return None
            owner = (seg, lo, hi)
        if owner is None:
            return None     # tip-only (or empty): the walk handles it
        if self._tip_index.keys_in_range(begin, end):
            return None
        seg, lo, hi = owner
        if not seg.fanout1 or seg.max_version > version \
                or seg.min_version <= self._drop_floor:
            return None
        out: list[tuple[bytes, bytes]] = []
        nbytes = 0
        blob = seg.vblob
        step = 4096
        for base in range(lo, hi, step):
            top = min(base + step, hi)
            ks = seg.keys.slice_keys(base, top)
            starts = seg.vstarts[base:top].tolist()
            ends = seg.vends[base:top].tolist()
            for off, (k, s, e) in enumerate(zip(ks, starts, ends)):
                if s < 0:
                    continue            # tombstone
                v = blob[s:e]
                out.append((k, v))
                nbytes += len(k) + len(v)
                if (limit and len(out) >= limit) \
                        or (byte_limit and nbytes >= byte_limit):
                    # exact `more`: probe ahead for the next live row
                    pos = base + off + 1
                    vs2 = seg.vstarts
                    while pos < hi:
                        if vs2[pos] >= 0:
                            return out, True
                        pos += 1
                    return out, False
        return out, False

    # --- writes ---

    def _tip_append(self, version: Version, key: bytes,
                    value: bytes | None, fresh: list[bytes] | None) -> None:
        """One entry into the tip chain (index insert via ``fresh`` when
        deferred, direct otherwise)."""
        chain = self._tip.get(key)
        if chain is None:
            self._tip[key] = [(version, value)]
            if fresh is None:
                self._tip_index.add(key)
            else:
                fresh.append(key)
            self._tip_entries += 1
            self._tip_bytes += len(key) + (len(value) if value else 0)
        elif chain[-1][0] == version:
            old = chain[-1][1]
            chain[-1] = (version, value)
            self._tip_bytes += ((len(value) if value else 0)
                                - (len(old) if old else 0))
        else:
            chain.append((version, value))
            self._tip_entries += 1
            self._tip_bytes += len(key) + (len(value) if value else 0)
        if value is None:
            # tombstone registry: drives the eager dead-key judgment in
            # forget_before (see the constructor comment)
            self._clears.append((version, key))
        if self._tip_min is None:
            self._tip_min = version

    def _live_newest(self, key: bytes) -> bool:
        """True when a legacy chain for ``key`` would exist with a LIVE
        tip — the clear_range predicate (the newest entry anywhere is a
        value above the drop floor and the dead marker)."""
        e = self._newest_entry(key)
        if e is None or e[1] is None or e[0] <= self._drop_floor:
            return False
        d = self._dead.get(key) if self._dead else None
        return d is None or e[0] > d

    def _clear_keys(self, ranges: list[tuple[bytes, bytes]]
                    ) -> list[list[bytes]]:
        """Per range: sorted distinct keys with any entry in it (the
        clear_range candidate sets; tip + segments merged)."""
        tip_parts = self._tip_index.ranges_keys(ranges)
        out: list[list[bytes]] = []
        for (b, e), tipkeys in zip(ranges, tip_parts):
            parts = [tipkeys] if tipkeys else []
            for seg in self._segments:
                lo, hi = seg.key_span(b, e)
                if lo < hi:
                    parts.append(seg.keys.slice_keys(lo, hi))
            if not parts:
                out.append([])
            elif len(parts) == 1:
                out.append(parts[0])
            else:
                allk = set()
                for p in parts:
                    allk.update(p)
                out.append(sorted(allk))
        return out

    def set(self, version: Version, key: bytes, value: bytes) -> None:
        assert version >= self.latest_version, \
            f"mutations must arrive in version order " \
            f"(v={version} < latest={self.latest_version})"
        self.latest_version = version
        self._tip_append(version, key, value, None)
        self._maybe_seal()

    def clear_range(self, version: Version, begin: bytes,
                    end: bytes) -> None:
        assert version >= self.latest_version
        self.latest_version = version
        for key in self._clear_keys([(begin, end)])[0]:
            if self._live_newest(key):
                self._tip_append(version, key, None, None)
        self._maybe_seal()

    def apply_batch(self, ops: list[tuple[Version, int, bytes, bytes]]
                    ) -> int:
        """Version-ordered (version, OP_SET|OP_CLEAR, p1, p2) run —
        state-equivalent to the set/clear_range loop (tested against the
        legacy twin and the brute-force model)."""
        fresh: list[bytes] = []
        latest = self.latest_version
        n = len(ops)
        i = 0
        while i < n:
            version, op, p1, p2 = ops[i]
            assert version >= latest, \
                f"mutations must arrive in version order " \
                f"(v={version} < latest={latest})"
            latest = version
            if op == OP_SET:
                self._tip_append(version, p1, p2, fresh)
                i += 1
                continue
            # a run of consecutive clears: candidate sets must see fresh
            # keys from this batch, and the tip bounds resolve in one
            # vectorized pass
            if fresh:
                self._tip_index.add_many(fresh)
                fresh = []
            j = i
            while j < n and ops[j][1] == OP_CLEAR:
                j += 1
            run = ops[i:j]
            for (version, _op, _b, _e), keys in zip(
                    run, self._clear_keys([(o[2], o[3]) for o in run])):
                latest = version
                for key in keys:
                    if self._live_newest(key):
                        self._tip_append(version, key, None, None)
            i = j
        if fresh:
            self._tip_index.add_many(fresh)
        self.latest_version = latest
        self._maybe_seal()
        return n

    def apply_packed(self, version: Version, batch) -> int:
        """One version's simple-only packed ``MutationBatch`` straight
        off its columnar arrays.  An all-SET batch of at least
        ``_DIRECT_SEAL_MIN`` ops SEALS DIRECTLY into a segment: the
        value column IS the batch blob (zero value copies), only the
        keys are sorted into a fresh ``KeyRun``.  Smaller or
        clear-bearing batches ride the tip like ``apply_batch``."""
        assert version >= self.latest_version, \
            f"mutations must arrive in version order " \
            f"(v={version} < latest={self.latest_version})"
        types = batch.types
        n = len(types)
        if (n >= _DIRECT_SEAL_MIN and batch.simple_only
                and b"\x01" not in types):
            self._seal_batch(version, batch)
            return n
        offs = batch.offsets()
        blob = batch.blob
        fresh: list[bytes] = []
        clears: list[tuple[bytes, bytes]] = []

        def flush_clears() -> None:
            for keys in self._clear_keys(clears):
                for key in keys:
                    if self._live_newest(key):
                        self._tip_append(version, key, None, None)
            clears.clear()

        prev = 0
        for i in range(n):
            e1, e2 = offs[2 * i], offs[2 * i + 1]
            p1 = blob[prev:e1]
            if types[i] == OP_SET:
                if clears:
                    flush_clears()
                self._tip_append(version, p1, blob[e1:e2], fresh)
            else:
                if fresh:
                    self._tip_index.add_many(fresh)
                    fresh = []
                clears.append((p1, blob[e1:e2]))
            prev = e2
        if clears:
            flush_clears()
        if fresh:
            self._tip_index.add_many(fresh)
        self.latest_version = version
        self._maybe_seal()
        return n

    def _seal_batch(self, version: Version, batch) -> None:
        """Direct seal of one all-SET packed batch (near-zero-copy: the
        value offsets point into the batch's own blob)."""
        t0 = time.perf_counter()
        if self._tip:
            self._seal_tip()    # older layer must seal first
        from itertools import starmap
        blob = batch.blob
        n = len(batch.types)
        bounds = np.frombuffer(batch.bounds, dtype="<u4").astype(np.int64)
        e1 = bounds[0::2]
        e2 = bounds[1::2]
        kstarts = np.empty(n, dtype=np.int64)
        kstarts[0] = 0
        kstarts[1:] = e2[:-1]
        # one C-speed map-of-slices; already-sorted batches (bulk loads,
        # fetchKeys pages) skip the pair sort entirely
        keys = list(map(blob.__getitem__,
                        starmap(slice, zip(kstarts.tolist(), e1.tolist()))))
        dup = len({*keys}) != n
        if not dup and n > 1 \
                and all(keys[x] < keys[x + 1] for x in range(n - 1)):
            dkeys = keys
            vstarts = _q_from(e1)
            vends = _q_from(e2)
            versions = _q_from(np.full(n, version, dtype=np.int64))
            counts = _q_from(np.arange(1, n + 1, dtype=np.int64))
        elif not dup:
            pairs = sorted(zip(keys, range(n)))
            order = np.array([i for _k, i in pairs], dtype=np.int64)
            dkeys = [k for k, _i in pairs]
            vstarts = _q_from(e1[order])
            vends = _q_from(e2[order])
            versions = _q_from(np.full(n, version, dtype=np.int64))
            counts = _q_from(np.arange(1, n + 1, dtype=np.int64))
        else:
            pairs = sorted(zip(keys, range(n)))
            # duplicates within one version: the LAST occurrence wins
            # (the legacy same-version chain-tip replace); the sort is
            # stable, so equal keys keep batch order
            dkeys = []
            vstarts = _array("q")
            vends = _array("q")
            versions = _array("q")
            counts = _array("q")
            last = None
            for k, i in pairs:
                if k == last:
                    vstarts[-1] = e1[i]
                    vends[-1] = e2[i]
                    continue
                last = k
                dkeys.append(k)
                vstarts.append(e1[i])
                vends.append(e2[i])
                versions.append(version)
                counts.append(len(dkeys))
        seg = _Segment(KeyRun.from_keys(dkeys), counts, versions,
                       vstarts, vends, blob, version, version)
        self._segments.insert(0, seg)
        self._probe_cache.clear()
        self._sealed_through = version
        self.latest_version = version
        self.seals += 1
        self.seal_s += time.perf_counter() - t0
        self._compact()

    def _maybe_seal(self) -> None:
        if not self._tip:
            return
        if (self._tip_entries >= self.seal_ops
                or self._tip_bytes >= self.seal_bytes
                or (self._tip_min is not None
                    and self.latest_version - self._tip_min
                    >= self.seal_versions)):
            self._seal_tip()
            self._compact()

    def _seal_tip(self) -> None:
        """Freeze the tip into one sealed segment (key-sorted via the
        tip's own index — no re-sort of the chains dict)."""
        if not self._tip:
            return
        t0 = time.perf_counter()
        b = _SegmentBuilder()
        tip = self._tip
        for key in self._tip_index.to_list():
            b.add_key(key, tip[key])
        seg = b.finish()
        if seg is not None:
            self._segments.insert(0, seg)
            self._probe_cache.clear()
            self._sealed_through = max(self._sealed_through,
                                       seg.max_version)
        self._tip = {}
        self._tip_index = PackedKeyIndex()
        self._tip_entries = 0
        self._tip_bytes = 0
        self._tip_min = None
        self.seals += 1
        self.seal_s += time.perf_counter() - t0

    # --- compaction / fold ---

    def _merge_pair(self, old: _Segment, new: _Segment) -> _Segment | None:
        """Merge two ADJACENT layers into one segment, fully
        vectorized: the newer (smaller) side's keys locate in the older
        run with one two-level batched bisect, the int64 entry columns
        combine as single ``np.insert`` calls, and the value blobs
        CONCATENATE — offsets are absolute, so no value byte is ever
        copied until a vacuum.  Entries the floor rules make permanently
        invisible are pruned on the way out (``_prune_build``)."""
        A, B = old, new
        posb_np, dup = A.keys.run_positions(B.keys)
        ca = np.diff(_np_q(A.counts), prepend=0)
        cb = np.diff(_np_q(B.counts), prepend=0)
        prev_cum = np.concatenate([np.zeros(1, dtype=np.int64),
                                   _np_q(A.counts)])
        # entry-space insertion points: a duplicate key's B entries land
        # AFTER its A band (B is the newer layer — bisect_right tie
        # order preserved); a fresh key's land at its band gap
        ins_entry = prev_cum[posb_np + dup]
        ins_rep = np.repeat(ins_entry, cb)
        versions = np.insert(_np_q(A.versions), ins_rep, _np_q(B.versions))
        shift = len(A.vblob)
        vsb = _np_q(B.vstarts)
        veb = _np_q(B.vends)
        vstarts = np.insert(_np_q(A.vstarts), ins_rep,
                            np.where(vsb < 0, vsb, vsb + shift))
        vends = np.insert(_np_q(A.vends), ins_rep,
                          np.where(veb < 0, veb, veb + shift))
        vblob = A.vblob + B.vblob
        ca2 = ca.copy()
        np.add.at(ca2, posb_np[dup], cb[dup])
        fresh = ~dup
        fresh_pos = posb_np[fresh]
        counts_per = np.insert(ca2, fresh_pos, cb[fresh])
        # one gather-based columnar stitch; the prefix/length caches
        # ride along via np.insert (prefixes are position-independent)
        keys = A.keys.insert_run_at(fresh_pos, B.keys, fresh)
        return self._prune_build(keys, counts_per, versions, vstarts,
                                 vends, vblob)

    def _prune_build(self, keys: KeyRun, counts_per: np.ndarray,
                     versions: np.ndarray, vstarts: np.ndarray,
                     vends: np.ndarray, vblob: bytes) -> _Segment | None:
        """Drop permanently-invisible entries and build the segment:
        everything at or below the drop floor goes; per key, entries
        below the newest at-or-below the forget floor go (the legacy
        folded chain prefix); tombstones carrying a ``_dead`` marker go
        (the legacy dead-key removal, judged eagerly in forget_before)
        and their markers retire.  All vectorized (reduceat over entry
        bands); the value blob keeps dead bytes until a vacuum pass
        rewrites it at >50% waste."""
        ne = len(versions)
        if ne == 0:
            return None
        drop = self._drop_floor
        forget = self.oldest_version
        starts = np.concatenate([np.zeros(1, dtype=np.int64),
                                 np.cumsum(counts_per)[:-1]])
        keep = versions > drop
        le = versions <= forget
        base = None
        if le.any():
            band_id = np.repeat(np.arange(len(counts_per)), counts_per)
            idx = np.where(le, np.arange(ne), -1)
            base = np.maximum.reduceat(idx, starts)
            keep &= (~le) | (np.arange(ne) == base[band_id])
        if self._dead:
            dkeys = sorted(self._dead)
            dpos = keys.batch_find(dkeys, assume_sorted=True)
            cum = np.cumsum(counts_per)
            for k, p in zip(dkeys, dpos):
                if p < 0:
                    continue
                # the marker is a per-key drop floor: every entry it
                # shadows goes.  The marker itself stays — other layers
                # outside this merge may still hold entries that old
                # (forget_before retires it once none can)
                ver = self._dead[k]
                lo, hi = int(starts[p]), int(cum[p])
                for e in range(lo, hi):
                    if versions[e] <= ver:
                        keep[e] = False
        if not keep.all():
            versions = versions[keep]
            vstarts = vstarts[keep]
            vends = vends[keep]
            new_per = np.add.reduceat(keep.astype(np.int64), starts)
            gone = np.nonzero(new_per == 0)[0]
            if len(gone):
                keys = keys.delete_at(gone.tolist())
                new_per = new_per[new_per > 0]
            counts_per = new_per
            if len(versions) == 0:
                return None
        live = int(np.where(vstarts >= 0, vends - vstarts, 0).sum())
        if len(vblob) > 2 * live + 4096:
            # vacuum: >50% of the blob is dead value bytes — rewrite it
            sl = vstarts.tolist()
            el = vends.tolist()
            parts = [vblob[s:e] for s, e in zip(sl, el) if s >= 0]
            lens = np.where(vstarts < 0, 0, vends - vstarts)
            ends2 = np.cumsum(lens)
            vends = np.where(vstarts < 0, -1, ends2)
            vstarts = np.where(vstarts < 0, -1, ends2 - lens)
            vblob = b"".join(parts)
        return _Segment(keys, _q_from(np.cumsum(counts_per)),
                        _q_from(versions), _q_from(vstarts),
                        _q_from(vends), vblob,
                        int(versions.min()), int(versions.max()))

    def _compact(self) -> None:
        """Bound the live segment count with binary-counter tiering:
        the fresh seal at the head merges into its older neighbor while
        it has grown to a comparable size, cascading — every entry is
        merged O(log n) times total and the live layer count stays
        O(log(entries / seal budget)).  A hard cap backstops degenerate
        seal patterns by merging the smallest adjacent pair."""
        segs = self._segments
        t0 = time.perf_counter()
        did = 0
        while len(segs) >= 2 and 2 * len(segs[0]) >= len(segs[1]):
            merged = self._merge_pair(segs[1], segs[0])
            segs[1:2] = []
            segs[0:1] = [merged] if merged is not None else []
            did += 1
        while len(segs) > _SEG_CAP:
            best, bi = None, 0
            for i in range(len(segs) - 1):
                n = len(segs[i]) + len(segs[i + 1])
                if best is None or n < best:
                    best, bi = n, i
            merged = self._merge_pair(segs[bi + 1], segs[bi])
            segs[bi:bi + 2] = [merged] if merged is not None else []
            did += 1
        if did:
            self._probe_cache.clear()
            self.compactions += did
            self.seal_s += time.perf_counter() - t0

    # --- compaction floors (setOldestVersion analogs) ---

    def forget_before(self, version: Version) -> None:
        """Advance the readable floor; entries below each key's newest
        at-or-below ``version`` become permanently invisible and are
        reclaimed by the lazy fold (geometrically amortized so a hot
        2M-key base is not re-merged every durability tick).  Dead keys
        are judged EAGERLY off the tombstone registry — the temporal
        half of legacy semantics that retained entries cannot encode."""
        if version <= self.oldest_version:
            return
        self.oldest_version = version
        q = self._clears
        while q and q[0][0] <= version:
            _v, key = q.popleft()
            e = self._newest_entry(key)
            if (e is not None and e[1] is None
                    and self._drop_floor < e[0] <= version):
                # newest entry is a tombstone the floor just crossed:
                # the legacy fold would remove this chain outright
                self._dead[key] = e[0]
        if self._tip and self._tip_min is not None \
                and version >= self._tip_min:
            self._seal_tip()
        below = [s for s in self._segments if s.max_version <= version]
        if len(below) >= _FOLD_MIN_SEGS:
            base = below[-1]
            newer_mass = sum(len(s) for s in below[:-1])
            if newer_mass > len(base) or len(base) < 4096:
                # fold only once the newer wholly-below mass EXCEEDS
                # the base (geometric amortization: each fold at least
                # doubles it, so a key folds O(log n) times total — an
                # every-tick fold would re-merge a 2M-entry base per
                # durability tick, the r5 shape again).  Between folds
                # the tiered compaction's per-merge prune keeps
                # reclaiming superseded entries.
                t0 = time.perf_counter()
                keep = [s for s in self._segments
                        if s.max_version > version]
                # pairwise oldest-up fold: each step one vectorized
                # pair merge, every merge pruning on the way out
                acc: _Segment | None = below[-1]
                for s in reversed(below[:-1]):
                    acc = s if acc is None else self._merge_pair(acc, s)
                if acc is not None:
                    keep.append(acc)
                self._segments = keep
                self._probe_cache.clear()
                self.folds += 1
                self.seal_s += time.perf_counter() - t0
        self._retire_markers()

    def _retire_markers(self) -> None:
        """Drop dead markers no remaining layer can reach: once every
        layer's oldest entry is newer than a marker, nothing it shadows
        exists anywhere and the dict entry is moot."""
        if not self._dead:
            return
        gmin: Version | None = None
        for s in self._segments:
            gmin = s.min_version if gmin is None else min(gmin,
                                                         s.min_version)
        if self._tip and self._tip_min is not None:
            gmin = self._tip_min if gmin is None else min(gmin,
                                                          self._tip_min)
        if gmin is None:
            self._dead.clear()
        else:
            self._dead = {k: v for k, v in self._dead.items() if v >= gmin}

    def drop_before(self, version: Version) -> None:
        """Entries at or below ``version`` are now durable in the
        engine: whole segments at-or-below the floor retire in
        O(segments); a straddling segment's sub-floor entries turn
        invisible via the drop-floor read rule and fall out at its next
        merge."""
        if version <= self.oldest_version:
            return
        self.oldest_version = version
        self._drop_floor = version
        q = self._clears
        while q and q[0][0] <= version:
            q.popleft()     # dropped-invisible: no dead judgment needed
        if self._dead:
            self._dead = {k: v for k, v in self._dead.items()
                          if v > version}
        if self._tip and self._tip_min is not None \
                and version >= self._tip_min:
            self._seal_tip()
        keep = [s for s in self._segments if s.max_version > version]
        if len(keep) != len(self._segments):
            self._segments = keep
            self._probe_cache.clear()

    def rollback_after(self, version: Version) -> None:
        """Discard every entry newer than ``version`` (storage rejoin):
        suffix segments drop whole, a straddling segment truncates, and
        the tip trims per chain (bounded by the seal budget)."""
        if version >= self.latest_version:
            return
        self.latest_version = version
        q = self._clears
        while q and q[-1][0] > version:
            q.pop()         # the rolled-back suffix's registry records
        if version < self.oldest_version:
            # rolling below the readable floor (the legacy full-walk
            # net): markers could otherwise outlive a version the new
            # generation re-uses
            self._clears = deque(e for e in self._clears
                                 if e[0] <= version)
            if self._dead:
                self._dead = {k: v for k, v in self._dead.items()
                              if v <= version}
            # ...and so could the FLOORS: a stale drop floor above the
            # rollback target would read every new-generation write at
            # or below it as engine-durable-and-dropped (found=False)
            # while the legacy twin serves it — the judgments both
            # floors encode are void for versions the new generation
            # re-uses.  (Entries physically retained at or below the
            # target stay at-or-below the lowered drop floor, so
            # nothing previously dropped resurrects.)
            self.oldest_version = version
            if self._drop_floor > version:
                self._drop_floor = version
        if self._tip:
            dead: list[bytes] = []
            entries = 0
            nbytes = 0
            vmin: Version | None = None
            for key, chain in self._tip.items():
                i = len(chain)
                while i > 0 and chain[i - 1][0] > version:
                    i -= 1
                if i < len(chain):
                    del chain[i:]
                if not chain:
                    dead.append(key)
                    continue
                entries += len(chain)
                for ver, val in chain:
                    nbytes += len(key) + (len(val) if val else 0)
                    if vmin is None or ver < vmin:
                        vmin = ver
            for key in dead:
                del self._tip[key]
            self._tip_index.discard_many(dead)
            self._tip_entries = entries
            self._tip_bytes = nbytes
            self._tip_min = vmin
        segs: list[_Segment] = []
        for s in self._segments:
            if s.min_version > version:
                continue                    # whole layer rolled back
            if s.max_version > version:
                t = s.truncated(version)
                if t is not None:
                    segs.append(t)
            else:
                segs.append(s)
        self._segments = segs
        self._probe_cache.clear()
        if self._sealed_through > version:
            self._sealed_through = version
