"""Copy-on-write B+tree key-value engine (the Redwood-class analog).

Reference: REF:fdbserver/VersionedBTree.actor.cpp — FDB's production
"ssd" engine Redwood is a copy-on-write paged B+tree: updates rewrite
the modified leaf-to-root path into fresh pages, and a small commit
header atomically switches the durable root, so a crash at any point
recovers to the last committed tree with no WAL replay.  This engine
keeps that shape with an append-friendly layout:

- nodes (leaves + internals) are encoded blobs appended to the current
  tree file; a commit bulk-applies the op batch functionally — every
  modified node is rewritten at the file tail, unmodified subtrees are
  shared by reference (off, len);
- the commit point is a tiny header written to one of TWO alternating
  header files (gen parity picks the slot): {gen, file, root, end,
  count, meta}.  The data file is fsynced BEFORE the header, the header
  after, so a torn commit always leaves one older decodable header and
  the tree it names is fully durable — recovery is "read both headers,
  take the newest that decodes" (Redwood's dual pager-commit-header);
- dead versions of rewritten nodes accumulate in the file; when it grows
  past a multiple of the live size the whole tree is compacted into a
  fresh file (bulk rebuild) and the old file removed — the role
  Redwood's free list + lazy page reuse plays, traded for sequential-only
  writes (the right trade on this fs abstraction: no block reuse means
  no torn-page hazard and no free-list recovery logic);
- reads traverse from the in-memory root through a shared LRU node cache
  (the pager cache), synchronous block reads like the LSM engine.

Unlike the LSM engine there is no WAL and no memtable: the op batch IS
the durability tick, and reads have no merge across runs — point reads
are one root-to-leaf descent, ranges are an in-order walk.

The IKeyValueStore surface (open/get/range/commit/meta/close) matches
kv_store.MemoryKVStore (REF:fdbserver/IKeyValueStore.h).
"""

from __future__ import annotations

import bisect
from typing import Iterator

from ..rpc.wire import decode, encode, frame, unframe
from .kv_store import OP_CLEAR, OP_SET
from .lsm import _BlockCache

_LEAF_BYTES = 1 << 13       # split leaves past ~8KB encoded payload
_FANOUT = 64                # max children per internal node
_CACHE_NODES = 512          # shared LRU node cache entries
_COMPACT_MIN = 1 << 20      # never compact files under 1MB
_COMPACT_FACTOR = 5         # compact when file > factor * post-compact size


class BTreeKVStore:
    """IKeyValueStore-compatible copy-on-write B+tree engine."""

    def __init__(self, fs, prefix: str) -> None:
        self.fs = fs
        self.prefix = prefix
        self.meta: dict = {}
        self._gen = 0
        self._fileno = 0
        self._f = None
        self._root: tuple[int, int] | None = None   # (off, len) in _f
        self._end = 0                               # durable append offset
        self._count = 0
        self._cache = _BlockCache(_CACHE_NODES)
        self._live_size = 0     # file end right after the last compaction
        self._heads = [None, None]      # the two alternating header files

    # --- lifecycle ---

    def _file_path(self, fileno: int) -> str:
        return f"{self.prefix}.bt.{fileno:08d}"

    def _head_path(self, slot: int) -> str:
        return f"{self.prefix}.head{slot}"

    @classmethod
    async def open(cls, fs, prefix: str, knobs=None) -> "BTreeKVStore":
        # ``knobs`` accepted for engine-factory uniformity (the lsm
        # engine keys its compaction mode on it); unused here
        kv = cls(fs, prefix)
        best = None
        for slot in (0, 1):
            hf = fs.open(kv._head_path(slot))
            kv._heads[slot] = hf
            blob = await hf.read(0, hf.size())
            if not blob:
                continue
            try:
                # crc-framed since ISSUE 12 so a torn header write FAILS
                # the checksum instead of possibly decoding into garbage
                # (pre-frame headers decode raw for compatibility)
                try:
                    payload = unframe(blob)
                except ValueError:
                    payload = blob
                head = decode(payload)
                gen = int(head["gen"])
            except Exception:   # torn header: the other slot has the commit
                continue
            if best is None or gen > best["gen"]:
                best = head
        if best is not None:
            kv._gen = int(best["gen"])
            kv._fileno = int(best["file"])
            kv._root = (tuple(best["root"]) if best["root"] is not None
                        else None)
            kv._end = int(best["end"])
            kv._count = int(best["count"])
            kv._live_size = int(best.get("live", kv._end))
            kv.meta = best["meta"]
        kv._f = fs.open(kv._file_path(kv._fileno))
        # garbage from a torn commit may sit past the durable end or in
        # orphaned files from an interrupted compaction — both harmless
        # (never referenced), but orphan files are removed for hygiene
        for path in fs.listdir(prefix + ".bt."):
            if path != kv._file_path(kv._fileno):
                fs.remove(path)
        return kv

    async def close(self) -> None:
        if self._f is not None:
            await self._f.close()
            self._f = None
        for hf in self._heads:
            if hf is not None:
                await hf.close()
        self._heads = [None, None]

    def __len__(self) -> int:
        return self._count

    # --- node io ---

    def _read_node(self, ref: tuple[int, int]) -> list:
        key = (self._fileno, ref[0])
        node = self._cache.get(key)
        if node is None:
            node = decode(self._f.read_sync(ref[0], ref[1]))
            self._cache.put(key, node)
        return node

    # --- reads ---

    def get(self, key: bytes) -> bytes | None:
        ref = self._root
        if ref is None:
            return None
        node = self._read_node(ref)
        while node[0] == 0:
            kids = node[1]              # [[first_key, off, len], ...]
            i = bisect.bisect_right([bytes(c[0]) for c in kids], key) - 1
            if i < 0:
                i = 0
            ref = (kids[i][1], kids[i][2])
            node = self._read_node(ref)
        entries = node[1]
        keys = [bytes(e[0]) for e in entries]
        j = bisect.bisect_left(keys, key)
        if j < len(keys) and keys[j] == key:
            return bytes(entries[j][1])
        return None

    def get_batch(self, keys: list[bytes]) -> list[bytes | None]:
        """Batched point reads over SORTED keys: ONE root-to-leaf
        descent per leaf RUN — every consecutive probe key routing to
        the same leaf resolves off the single decoded node, so a batch
        of n keys over l distinct leaves costs l descents instead of n
        (the multiget engine fall-through, ISSUE 5)."""
        out: list[bytes | None] = [None] * len(keys)
        if self._root is None or not keys:
            return out
        i, n = 0, len(keys)
        while i < n:
            ref = self._root
            node = self._read_node(ref)
            upper: bytes | None = None  # tightest right bound on the path
            while node[0] == 0:
                kids = node[1]
                firsts = [bytes(c[0]) for c in kids]
                j = bisect.bisect_right(firsts, keys[i]) - 1
                if j < 0:
                    j = 0
                if j + 1 < len(kids):
                    nb = firsts[j + 1]
                    if upper is None or nb < upper:
                        upper = nb
                ref = (kids[j][1], kids[j][2])
                node = self._read_node(ref)
            entries = node[1]
            lkeys = [bytes(e[0]) for e in entries]
            # every probe key below the path's right bound lives (if
            # anywhere) in THIS leaf
            hi = n if upper is None else bisect.bisect_left(keys, upper, i)
            for t in range(i, max(hi, i + 1)):
                j2 = bisect.bisect_left(lkeys, keys[t])
                if j2 < len(lkeys) and lkeys[j2] == keys[t]:
                    out[t] = bytes(entries[j2][1])
            i = max(hi, i + 1)
        return out

    def range(self, begin: bytes, end: bytes,
              reverse: bool = False) -> Iterator[tuple[bytes, bytes]]:
        if self._root is None:
            return
        yield from self._walk(self._root, begin, end, reverse)

    def range_runs(self, begin: bytes,
                   end: bytes) -> Iterator[list[tuple[bytes, bytes]]]:
        """Forward scan of [begin, end) as whole LEAF runs — one run per
        decoded leaf, the in-range slice extracted wholesale by two
        bisects instead of a per-row yield (the columnar range-read
        extraction, ISSUE 9).  Flattened output is byte-identical to
        ``range``; a limit-bounded caller that stops consuming leaves
        the remaining subtrees untouched."""
        if self._root is None:
            return
        yield from self._walk_runs(self._root, begin, end)

    def _walk_runs(self, ref, begin, end):
        node = self._read_node(ref)
        if node[0] == 0:
            kids = node[1]
            firsts = [bytes(c[0]) for c in kids]
            lo = max(0, bisect.bisect_right(firsts, begin) - 1)
            hi = min(bisect.bisect_left(firsts, end) + 1, len(kids))
            for i in range(lo, hi):
                yield from self._walk_runs((kids[i][1], kids[i][2]),
                                           begin, end)
        else:
            entries = node[1]
            keys = [bytes(e[0]) for e in entries]
            lo = bisect.bisect_left(keys, begin)
            hi = bisect.bisect_left(keys, end)
            if lo < hi:
                yield [(keys[i], bytes(entries[i][1]))
                       for i in range(lo, hi)]

    def _walk(self, ref, begin, end, reverse):
        """In-order walk of [begin, end); ``end=None`` means unbounded —
        the whole-tree walk compaction relies on (a key range would
        silently drop any key sorting above the chosen sentinel)."""
        node = self._read_node(ref)
        if node[0] == 0:
            kids = node[1]
            firsts = [bytes(c[0]) for c in kids]
            # children whose key range can intersect [begin, end)
            lo = max(0, bisect.bisect_right(firsts, begin) - 1)
            hi = len(kids) if end is None else \
                min(bisect.bisect_left(firsts, end) + 1, len(kids))
            idxs = range(lo, hi)
            if reverse:
                idxs = reversed(idxs)
            for i in idxs:
                yield from self._walk((kids[i][1], kids[i][2]),
                                      begin, end, reverse)
        else:
            entries = node[1]
            keys = [bytes(e[0]) for e in entries]
            lo = bisect.bisect_left(keys, begin)
            hi = len(keys) if end is None else bisect.bisect_left(keys, end)
            idxs = range(lo, hi)
            if reverse:
                idxs = reversed(idxs)
            for i in idxs:
                yield keys[i], bytes(entries[i][1])

    # --- writes ---

    async def commit(self, ops, meta: dict) -> None:
        """Durably apply one ordered op batch (a tuple list or a
        ``PackedOps`` slice — only iterated): CoW-update the tree at the
        file tail, fsync data, then flip the commit header."""
        eff: dict[bytes, bytes | None] = {}
        for op, p1, p2 in ops:
            if op == OP_SET:
                eff[p1] = p2
            else:
                assert op == OP_CLEAR
                for k, _ in self.range(p1, p2):
                    eff[k] = None
                for k in [k for k in eff if p1 <= k < p2]:
                    eff[k] = None
        self.meta = meta
        # meta-only commits still flip the header (durable_version bumps)
        await self._apply(sorted(eff.items()))

    async def _apply(self, items: list[tuple[bytes, bytes | None]]) -> None:
        self._pending: list[bytes] = []     # node blobs to append
        self._pend_off = self._end
        new_refs, delta = (self._update(self._root, items)
                           if items else
                           ([(None, *self._root)] if self._root else [], 0))
        # collapse to a single root (possibly adding internal levels)
        while len(new_refs) > 1:
            new_refs = [self._write_internal(chunk)
                        for chunk in _chunks(new_refs, _FANOUT)]
        if self._pending:
            await self._f.write(self._pend_off, b"".join(self._pending))
            await self._f.sync()
        self._end = self._pend_off + sum(len(b) for b in self._pending)
        self._root = ((new_refs[0][1], new_refs[0][2])
                      if new_refs else None)
        self._count += delta
        self._pending = []
        if self._end > _COMPACT_MIN and \
                self._end > _COMPACT_FACTOR * max(self._live_size, 1):
            await self._compact()
        else:
            await self._write_header()

    def _append_node(self, node: list) -> tuple[bytes | None, int, int]:
        """Stage a node blob for the tail write; returns its ref entry
        (first_key, off, len)."""
        blob = encode(node)
        off = self._pend_off + sum(len(b) for b in self._pending)
        self._pending.append(blob)
        first = (bytes(node[1][0][0]) if node[1] else None)
        self._cache.put((self._fileno, off), node)
        return (first, off, len(blob))

    def _write_internal(self, child_refs) -> tuple[bytes, int, int]:
        node = [0, [[fk, off, ln] for fk, off, ln in child_refs]]
        return self._append_node(node)

    def _update(self, ref, items):
        """Functionally apply sorted (key, value|None) items under ``ref``.
        Returns ([(first_key, off, len), ...] replacement refs — empty if
        the subtree vanished, possibly several if it split), count delta.
        Unmodified subtrees are returned by reference, never rewritten."""
        if ref is None:
            live = [(k, v) for k, v in items if v is not None]
            return self._build_leaves(live), len(live)
        node = self._read_node(ref)
        if node[0] == 1:
            entries = [(bytes(e[0]), bytes(e[1])) for e in node[1]]
            merged: list[tuple[bytes, bytes]] = []
            delta = 0
            i = j = 0
            while i < len(entries) or j < len(items):
                if j >= len(items) or \
                        (i < len(entries) and entries[i][0] < items[j][0]):
                    merged.append(entries[i])
                    i += 1
                    continue
                k, v = items[j]
                existed = i < len(entries) and entries[i][0] == k
                if existed:
                    i += 1
                if v is None:
                    delta -= 1 if existed else 0
                else:
                    delta += 0 if existed else 1
                    merged.append((k, v))
                j += 1
            return self._build_leaves(merged), delta
        # internal: partition items among children by routing ranges
        kids = node[1]
        firsts = [bytes(c[0]) for c in kids]
        out_refs: list = []
        delta = 0
        changed = False
        pos = 0
        for ci in range(len(kids)):
            hi_key = firsts[ci + 1] if ci + 1 < len(kids) else None
            hi = len(items)
            if hi_key is not None:
                hi = bisect.bisect_left(items, (hi_key,), pos)
            sub = items[pos:hi]
            pos = hi
            if not sub:
                out_refs.append((firsts[ci], kids[ci][1], kids[ci][2]))
                continue
            refs, d = self._update((kids[ci][1], kids[ci][2]), sub)
            delta += d
            changed = True
            out_refs.extend(refs)
        if not changed:
            return [(firsts[0], ref[0], ref[1])], 0
        if not out_refs:
            return [], delta
        return [self._write_internal(chunk)
                for chunk in _chunks(out_refs, _FANOUT)], delta

    def _build_leaves(self, entries):
        """Pack sorted live entries into appended leaves by byte budget."""
        refs = []
        block: list = []
        bbytes = 0
        for k, v in entries:
            block.append([k, v])
            bbytes += len(k) + len(v) + 8
            if bbytes >= _LEAF_BYTES:
                refs.append(self._append_node([1, block]))
                block, bbytes = [], 0
        if block:
            refs.append(self._append_node([1, block]))
        return refs

    async def _write_header(self) -> None:
        self._gen += 1
        head = {"gen": self._gen, "file": self._fileno,
                "root": (list(self._root) if self._root else None),
                "end": self._end, "count": self._count,
                "live": self._live_size, "meta": self.meta}
        hf = self._heads[self._gen % 2]
        blob = frame(encode(head))
        await hf.write(0, blob)
        await hf.truncate(len(blob))
        await hf.sync()

    # --- compaction ---

    async def _compact(self) -> None:
        """Rewrite the live tree into a fresh file (sequential bulk
        build), flip the header to it, remove the old file.  A crash
        before the header flip leaves an orphan file that open() GCs."""
        old_f, old_path = self._f, self._file_path(self._fileno)
        items = list(self._walk(self._root, b"", None, False)) \
            if self._root else []
        self._fileno += 1
        self._f = self.fs.open(self._file_path(self._fileno))
        await self._f.truncate(0)
        self._pending = []
        self._pend_off = 0
        refs = self._build_leaves(items)
        while len(refs) > 1:
            refs = [self._write_internal(chunk)
                    for chunk in _chunks(refs, _FANOUT)]
        if self._pending:
            await self._f.write(0, b"".join(self._pending))
            await self._f.sync()
        self._end = sum(len(b) for b in self._pending)
        self._live_size = self._end
        self._root = (refs[0][1], refs[0][2]) if refs else None
        self._pending = []
        await self._write_header()
        await old_f.close()
        self.fs.remove(old_path)
        # evict the dead file's nodes so they stop crowding the LRU
        for k in [k for k in self._cache._d if k[0] != self._fileno]:
            del self._cache._d[k]


def _chunks(seq, n):
    return [seq[i:i + n] for i in range(0, len(seq), n)]
