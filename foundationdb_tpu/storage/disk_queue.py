"""DiskQueue — the append-only durable record queue under the TLog.

Reference: REF:fdbserver/DiskQueue.actor.cpp — FDB's TLog writes redo
records into a page-aligned two-file queue; push appends, commit fsyncs,
pop logically truncates the front.  Records surviving a crash are exactly
those up to the last completed sync (proved in sim by AsyncFileNonDurable).

Format: a 4KB header page (magic, physical front offset) followed by
frames [u32 len][u32 crc32][payload].  Recovery scans frames from the
header's front until EOF/bad-crc (a torn tail after a crash is discarded).

Offsets handed to callers are *logical* and monotonic: physical
compaction (copying the live region down over a large popped prefix)
shifts the mapping internally, so offsets recorded across a compaction
stay valid.  Compaction only runs when the live region fits inside the
popped prefix, so a crash mid-copy can never damage bytes the current
header still references.
"""

from __future__ import annotations

import struct
import zlib

_FRAME = struct.Struct("<II")
# magic, physical front offset, caller meta (the TLog stores its durable
# tip version here: popped frames vanish, so the tip of the surviving
# frames UNDERSTATES how far the log durably acked — recovery computed
# from that would precede storage durability and wedge every rejoin)
_HEADER = struct.Struct("<QQQ")
_MAGIC = 0xFDB7D15C  # arbitrary magic for our queue files
_HEADER_SIZE = 4096
_COMPACT_SLACK = 1 << 22            # compact when popped prefix > 4MB


class DiskQueue:
    def __init__(self, file) -> None:
        self.file = file
        self._front = _HEADER_SIZE   # logical offset of first live frame
        self._end = _HEADER_SIZE     # logical append position
        self._shift = 0              # logical - physical
        self.meta = 0                # caller-owned u64, durable w/ commits

    def _phys(self, logical: int) -> int:
        return logical - self._shift

    @classmethod
    async def open(cls, file) -> tuple["DiskQueue", list[tuple[bytes, int]]]:
        """Open + recover: returns (queue, [(payload, end_offset), ...]) —
        the end offset is what pop_to() takes to discard through a frame."""
        q = cls(file)
        size = file.size()
        if size >= _HEADER_SIZE:
            hdr = await file.read(0, _HEADER.size)
            magic, front, meta = _HEADER.unpack(hdr)
            if magic == _MAGIC and _HEADER_SIZE <= front:
                q._front = front     # logical == physical on a fresh open
                q.meta = meta
        payloads: list[tuple[bytes, int]] = []
        pos = q._front
        while pos + _FRAME.size <= size:
            ln, crc = _FRAME.unpack(await file.read(pos, _FRAME.size))
            data = await file.read(pos + _FRAME.size, ln)
            if len(data) < ln or zlib.crc32(data) != crc:
                break               # torn tail: discard from here
            pos += _FRAME.size + ln
            payloads.append((data, pos))
        q._end = pos
        await file.truncate(pos)    # drop any torn tail bytes
        if size < _HEADER_SIZE:
            await q._write_header()
        return q, payloads

    async def _write_header(self) -> None:
        await self.file.write(0, _HEADER.pack(_MAGIC, self._phys(self._front),
                                              self.meta))

    async def push(self, payload: bytes) -> int:
        """Append one frame; returns its logical end offset (record this
        to pop_to() later)."""
        frame = _FRAME.pack(len(payload), zlib.crc32(payload)) + payload
        await self.file.write(self._phys(self._end), frame)
        self._end += len(frame)
        return self._end

    async def commit(self, meta: int | None = None) -> None:
        """Make all pushed frames durable (the TLog's fsync point).
        ``meta`` rides the header under the same sync."""
        if meta is not None and meta != self.meta:
            self.meta = meta
            await self._write_header()
        await self.file.sync()

    async def pop_to(self, offset: int) -> None:
        """Discard everything before logical ``offset``; physically
        compact when worthwhile and safe."""
        if offset <= self._front:
            return
        self._front = min(offset, self._end)
        await self._write_header()
        popped_phys = self._phys(self._front) - _HEADER_SIZE
        live = self._end - self._front
        if popped_phys > _COMPACT_SLACK and live <= popped_phys:
            data = await self.file.read(self._phys(self._front), live)
            await self.file.write(_HEADER_SIZE, data)
            await self.file.sync()          # live bytes safe at new home
            self._shift += popped_phys
            await self._write_header()      # recovery now reads the copy
            await self.file.truncate(_HEADER_SIZE + live)
            await self.file.sync()

    async def read_frames(self, from_logical: int,
                          to_logical: int | None = None) -> list[tuple[bytes, int]]:
        """Re-read live frames in [from_logical, to_logical) — the TLog's
        spilled-by-reference peek path (REF:fdbserver/TLogServer.actor.cpp
        spilled data stays in the DiskQueue and is read back on demand)."""
        pos = max(from_logical, self._front)
        stop = self._end if to_logical is None else min(to_logical, self._end)
        out: list[tuple[bytes, int]] = []
        while pos + _FRAME.size <= stop:
            ln, crc = _FRAME.unpack(await self.file.read(self._phys(pos),
                                                         _FRAME.size))
            data = await self.file.read(self._phys(pos) + _FRAME.size, ln)
            if len(data) < ln or zlib.crc32(data) != crc:
                break
            pos += _FRAME.size + ln
            out.append((data, pos))
        return out

    @property
    def end_offset(self) -> int:
        return self._end

    @property
    def front_offset(self) -> int:
        """First live logical offset (recovery re-indexes frames from
        here — the change-feed side queue's restore path)."""
        return self._front

    @property
    def bytes_used(self) -> int:
        return self._end - self._front
