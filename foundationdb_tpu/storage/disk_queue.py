"""DiskQueue — the append-only durable record queue under the TLog.

Reference: REF:fdbserver/DiskQueue.actor.cpp — FDB's TLog writes redo
records into a page-aligned two-file queue; push appends, commit fsyncs,
pop logically truncates the front.  Records surviving a crash are exactly
those up to the last completed sync (proved in sim by AsyncFileNonDurable).

Format: a 4KB header page holding TWO alternating crc32-stamped header
slots (magic, generation, physical front, caller meta, durable frontier),
followed by frames [u32 len][u32 crc32][payload].  Each header write goes
to the slot its generation selects, so a torn or corrupted header write
from a kill always leaves the previous slot's older header intact —
the dual-commit-header discipline the btree engine already uses.

Recovery scans frames from the header's front.  The header's *durable
frontier* (the append position as of a previously COMPLETED sync — it
deliberately lags one commit, so a torn in-flight commit can never
over-claim) splits the scan into two regimes (ISSUE 12):

- a bad crc AT OR PAST the frontier is a torn tail from a crash —
  discarded, today's behavior;
- a bad crc BEFORE the frontier is corruption of COMMITTED data — the
  recovery raises ``DiskCorrupt`` loudly instead of silently truncating
  acked frames (the silent-truncation bug this split fixes).

``meta`` is caller-owned and rides the header under the same sync (the
TLog stores its durable tip version here: popped frames vanish, so the
tip of the surviving frames UNDERSTATES how far the log durably acked —
recovery computed from that would precede storage durability and wedge
every rejoin).

Offsets handed to callers are *logical* and monotonic: physical
compaction (copying the live region down over a large popped prefix)
shifts the mapping internally, so offsets recorded across a compaction
stay valid.  Compaction only runs when the live region fits inside the
popped prefix, so a crash mid-copy can never damage bytes the current
header still references.
"""

from __future__ import annotations

import struct
import zlib

from ..runtime.errors import DiskCorrupt

_FRAME = struct.Struct("<II")
# magic, generation, physical front offset, caller meta, durable
# frontier (physical), crc32 of the five preceding fields
_HEADER = struct.Struct("<QQQQQI")
_LEGACY_HEADER = struct.Struct("<QQQ")   # pre-ISSUE-12: magic, front, meta
_MAGIC = 0xFDB7D15C  # arbitrary magic for our queue files
_HEADER_SIZE = 4096
_SLOT = 512                         # header slot stride (one sim sector)
_COMPACT_SLACK = 1 << 22            # compact when popped prefix > 4MB


class DiskQueue:
    def __init__(self, file) -> None:
        self.file = file
        self._front = _HEADER_SIZE   # logical offset of first live frame
        self._end = _HEADER_SIZE     # logical append position
        self._shift = 0              # logical - physical
        self._gen = 0                # header generation (slot parity)
        self._synced_end = _HEADER_SIZE  # logical end at the last sync
        self._hdr_synced = -1        # durable frontier the header carries
        self.meta = 0                # caller-owned u64, durable w/ commits

    def _phys(self, logical: int) -> int:
        return logical - self._shift

    @staticmethod
    def _read_best_header(raw: bytes) -> tuple | None:
        """Newest valid header slot: (gen, front, meta, synced) — or the
        legacy single-slot format, or None (fresh/never-synced file)."""
        best = None
        for slot in (0, 1):
            chunk = raw[slot * _SLOT: slot * _SLOT + _HEADER.size]
            if len(chunk) < _HEADER.size:
                continue
            magic, gen, front, meta, synced, crc = _HEADER.unpack(chunk)
            if magic != _MAGIC or crc != zlib.crc32(chunk[:-4]):
                continue
            if best is None or gen > best[0]:
                best = (gen, front, meta, synced)
        if best is not None:
            return best
        if len(raw) >= _LEGACY_HEADER.size:
            magic, front, meta = _LEGACY_HEADER.unpack_from(raw)
            if magic == _MAGIC:
                # pre-dual-slot file: no recorded frontier — the whole
                # scan runs in torn-tail mode (the old behavior)
                return (0, front, meta, _HEADER_SIZE)
        return None

    @classmethod
    async def open(cls, file) -> tuple["DiskQueue", list[tuple[bytes, int]]]:
        """Open + recover: returns (queue, [(payload, end_offset), ...]) —
        the end offset is what pop_to() takes to discard through a frame.

        Raises ``DiskCorrupt`` when a frame BEFORE the recorded durable
        frontier fails its crc (committed data damaged — never silently
        truncated); a bad frame at or past it is a torn tail, discarded."""
        q = cls(file)
        size = file.size()
        durable = _HEADER_SIZE
        if size > 0:
            # the header slots are read whenever ANY bytes exist — not
            # only past the full header page.  A file shorter than the
            # header page whose surviving slot records a durable
            # frontier is a LENGTH regression of the header page itself
            # (truncation of committed state, which a torn kill can
            # never produce: synced bytes are untouchable) — the frame
            # scan below then finds the frontier unreachable and raises
            # DiskCorrupt instead of silently re-initializing the queue
            # (ROADMAP 6 (d))
            best = cls._read_best_header(await file.read(0, 2 * _SLOT))
            if best is not None:
                gen, front, meta, synced = best
                if _HEADER_SIZE <= front:
                    q._gen = gen
                    q._front = front     # logical == physical on a fresh open
                    q.meta = meta
                    durable = max(synced, _HEADER_SIZE)
        payloads: list[tuple[bytes, int]] = []
        pos = q._front
        while pos + _FRAME.size <= size:
            ln, crc = _FRAME.unpack(await file.read(pos, _FRAME.size))
            data = await file.read(pos + _FRAME.size, ln)
            if len(data) < ln or zlib.crc32(data) != crc:
                if pos < durable:
                    raise DiskCorrupt(
                        f"disk queue frame at {pos} is inside the "
                        f"committed region (durable frontier {durable}) "
                        f"and failed its crc — refusing to silently "
                        f"truncate acked data")
                break               # torn tail: discard from here
            pos += _FRAME.size + ln
            payloads.append((data, pos))
        if pos < durable:
            # the file ends before the durable frontier: committed
            # frames are missing outright (a truncated/overwritten file,
            # not a crash — a torn kill can never shorten synced bytes)
            raise DiskCorrupt(
                f"disk queue ends at {pos} before the durable frontier "
                f"{durable} — committed frames are missing")
        q._end = pos
        q._synced_end = pos         # everything surviving sits on disk
        await file.truncate(pos)    # drop any torn tail bytes
        if size < _HEADER_SIZE:
            await q._write_header()
        return q, payloads

    async def _write_header(self) -> None:
        """One crc-stamped header into the generation's slot; the other
        slot keeps the previous header, so a torn header write can never
        orphan the queue.  The generation advances only AFTER the write
        call returns: a transient IoError raised from the write must
        leave the parity untouched, or the retry would land on the
        OPPOSITE slot — the one holding the freshest synced header."""
        gen = self._gen + 1
        body = _HEADER.pack(_MAGIC, gen, self._phys(self._front),
                            self.meta, self._phys(self._synced_end), 0)[:-4]
        await self.file.write((gen % 2) * _SLOT,
                              body + zlib.crc32(body).to_bytes(4, "little"))
        self._gen = gen
        self._hdr_synced = self._synced_end

    async def push(self, payload: bytes) -> int:
        """Append one frame; returns its logical end offset (record this
        to pop_to() later)."""
        frame = _FRAME.pack(len(payload), zlib.crc32(payload)) + payload
        await self.file.write(self._phys(self._end), frame)
        self._end += len(frame)
        return self._end

    async def commit(self, meta: int | None = None) -> None:
        """Make all pushed frames durable (the TLog's fsync point).
        ``meta`` rides the header under the same sync, as does the
        durable frontier of the PREVIOUS completed sync — lagging one
        commit on purpose: a header claiming this commit's frames while
        the same kill tears them would turn every crash into a false
        corruption alarm."""
        if (meta is not None and meta != self.meta) \
                or self._synced_end > self._hdr_synced:
            if meta is not None:
                self.meta = meta
            await self._write_header()
        await self.file.sync()
        self._synced_end = self._end

    async def pop_to(self, offset: int) -> None:
        """Discard everything before logical ``offset``; physically
        compact when worthwhile and safe."""
        if offset <= self._front:
            return
        self._front = min(offset, self._end)
        await self._write_header()
        popped_phys = self._phys(self._front) - _HEADER_SIZE
        live = self._end - self._front
        if popped_phys > _COMPACT_SLACK and live <= popped_phys:
            data = await self.file.read(self._phys(self._front), live)
            await self.file.write(_HEADER_SIZE, data)
            await self.file.sync()          # live bytes safe at new home
            self._synced_end = self._end
            self._shift += popped_phys
            await self._write_header()      # recovery now reads the copy
            # the remapped header must be DURABLE before the truncate is
            # even issued: a torn kill keeping the truncate but dropping
            # the header write would otherwise leave the old header
            # pointing past the shortened file — recovery would then
            # raise a false 'committed frames missing' DiskCorrupt for a
            # legitimate crash and brick the boot (ISSUE 12 review find)
            await self.file.sync()
            await self.file.truncate(_HEADER_SIZE + live)
            await self.file.sync()

    async def read_frames(self, from_logical: int,
                          to_logical: int | None = None) -> list[tuple[bytes, int]]:
        """Re-read live frames in [from_logical, to_logical) — the TLog's
        spilled-by-reference peek path (REF:fdbserver/TLogServer.actor.cpp
        spilled data stays in the DiskQueue and is read back on demand).

        Every frame in the live region was pushed whole by this process,
        so a crc mismatch here is CORRUPTION, raised as ``DiskCorrupt``
        — a silent short read would hand the caller a hole it can't
        distinguish from a released prefix (ISSUE 12).  Frames already
        released by pop_to simply fall outside [front, end) and return
        an empty/short list, never an error."""
        pos = max(from_logical, self._front)
        stop = self._end if to_logical is None else min(to_logical, self._end)
        out: list[tuple[bytes, int]] = []
        while pos + _FRAME.size <= stop:
            ln, crc = _FRAME.unpack(await self.file.read(self._phys(pos),
                                                         _FRAME.size))
            data = await self.file.read(self._phys(pos) + _FRAME.size, ln)
            if len(data) < ln or zlib.crc32(data) != crc:
                raise DiskCorrupt(
                    f"disk queue frame at {pos} failed its crc on "
                    f"read-back (live region [{self._front}, {stop}))")
            pos += _FRAME.size + ln
            out.append((data, pos))
        return out

    @property
    def end_offset(self) -> int:
        return self._end

    @property
    def front_offset(self) -> int:
        """First live logical offset (recovery re-indexes frames from
        here — the change-feed side queue's restore path)."""
        return self._front

    @property
    def bytes_used(self) -> int:
        return self._end - self._front
