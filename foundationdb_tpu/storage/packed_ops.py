"""Packed op slices — the storage durability buffer and its engine feed.

Reference: REF:fdbserver/storageserver.actor.cpp updateStorage — the
reference drains the MVCC window's aged-out versions into the engine in
version order.  The seed kept that pending set as a Python list of
(version, (op, p1, p2)) tuples rebuilt by TWO full list comprehensions
per durability tick (ROADMAP PR 1 follow-up (c)): O(total buffered) per
tick regardless of how little aged out.

``DurabilityRing`` replaces it with an append-only ring of packed
segments (each a simple-only ``MutationBatch`` — op codes ARE the engine
WAL op codes) plus a bisect version cursor: each tick commits the slice
of whole segments at or below the durable floor and advances the cursor,
O(slice) instead of O(buffer).  A TLog pull batch that took the storage
fast path lands here as ONE zero-copy segment (the same types/bounds/
blob objects, no per-op materialization); stragglers (resolved atomics,
fetchKeys rows) accumulate into small builder segments.

DISK SPILL (ISSUE 11, ROADMAP item 5 / PR 3 follow-up (c)): the ring
retains every version between the engine's durable floor and the
applied tip — a THROTTLED engine commit (slow disk, a ratekeeper-wedged
durability tick) therefore grew RSS without bound.  When retained
memory exceeds ``spill_bytes``, ``maybe_spill`` moves the OLDEST sealed
segments into a per-server DiskQueue side file (one crc-framed record
per segment: version + the raw (types, bounds, blob) columns), fsync
BEFORE the memory copy drops — ``ChangeFeedStore.maybe_spill``'s
discipline.  The per-tick commit slice reads spilled frames back
transparently (``peek_through``), bit-identical to the memory copy.

The side file carries NO recovery obligation: everything in the ring is
above the durable floor, so the TLog — popped only after the engine
commit — still holds every replay copy, and a rebooted replica rebuilds
the ring from the TLog (the side file is truncated at attach).  That is
also why ``rollback_after`` (storage rejoin) only trims bookkeeping:
frames of a rolled-back suffix become dead bytes the next ``pop_to``
releases, never decoded again.  A failed spill push/fsync mutates no
bookkeeping — the retry re-pushes fresh frames and the orphan bytes are
overwritten or released; a read-back crc failure raises (the durability
loop traces + retries) rather than silently committing a short slice.

``PackedOps`` is the slice handed to ``engine.commit``: iterable of
(op, p1, p2) for engines that replay ops, with ``wire_parts()`` exposing
the raw (types, bounds, blob) triples so the memory engine's WAL frame
encodes three contiguous byte strings per segment instead of thousands
of tuple elements.
"""

from __future__ import annotations

import bisect

from ..core.data import MutationBatch, MutationBatchBuilder, Version

__all__ = ["PackedOps", "DurabilityRing"]


class PackedOps:
    """An ordered, zero-copy run of packed op segments."""

    __slots__ = ("segments",)

    def __init__(self, segments: list[MutationBatch]) -> None:
        self.segments = segments

    def __len__(self) -> int:
        return sum(len(s) for s in self.segments)

    def __bool__(self) -> bool:
        return any(self.segments)

    @property
    def nbytes(self) -> int:
        return sum(s.nbytes for s in self.segments)

    def __iter__(self):
        for seg in self.segments:
            yield from seg.iter_ops()

    def wire_parts(self) -> list[tuple[bytes, bytes, bytes]]:
        return [(s.types, s.bounds, s.blob) for s in self.segments]


class DurabilityRing:
    """Version-ordered packed op buffer with a bisect commit cursor.

    Segments are (version, MutationBatch) pairs appended in apply order;
    versions are non-decreasing, and a version is never split across the
    commit floor (the floor compares whole versions).  ``peek_through``
    returns the committable slice WITHOUT consuming it — the caller pops
    only after the engine commit succeeded, so a failed tick retries the
    same slice (the disk-trouble contract of the seed's loop).

    With a side ``queue`` attached (durable deployments), the oldest
    sealed segments may live on disk instead of in the lists below —
    ``_spilled`` tracks them as (version, frame start, frame end,
    nbytes, ops) in version order, always a PREFIX of the ring (spill
    takes from the front, appends land in memory).  ``peek_through`` /
    ``pop_through`` become awaitable to read/release them; the sync
    surfaces (append/extend/rollback/len) never touch the disk.
    """

    __slots__ = ("_versions", "_segs", "_start", "_pend", "_pend_version",
                 "queue", "spill_bytes", "mem_bytes", "spilled_bytes",
                 "_spilled", "spills", "spill_frames", "_io_lock")

    def __init__(self, queue=None, spill_bytes: int = 0) -> None:
        self._versions: list[Version] = []
        self._segs: list[MutationBatch] = []
        self._start = 0                     # segments below are committed
        self._pend: MutationBatchBuilder | None = None
        self._pend_version: Version = -1
        # --- disk spill (ISSUE 11) ---
        self.queue = queue                  # DiskQueue side file when durable
        self.spill_bytes = spill_bytes      # memory budget; 0 = never spill
        self.mem_bytes = 0                  # payload bytes in [_start:]
        self.spilled_bytes = 0              # payload bytes living on disk
        self._spilled: list[tuple[Version, int, int, int, int]] = []
        self.spills = 0                     # observability: spill passes
        self.spill_frames = 0               # ...and frames written
        self._io_lock = None                # lazily built asyncio.Lock

    def _lock(self):
        import asyncio
        if self._io_lock is None:   # lazily: rings are built outside loops
            self._io_lock = asyncio.Lock()
        return self._io_lock

    def append(self, version: Version, op: int, p1: bytes, p2: bytes) -> None:
        """Buffer one op (atomics resolved at apply time, fetchKeys rows)."""
        if self._pend is not None and self._pend_version != version:
            self._seal()
        if self._pend is None:
            self._pend = MutationBatchBuilder()
            self._pend_version = version
        self._pend.add(op, p1, p2)

    def extend_packed(self, version: Version, batch: MutationBatch) -> None:
        """Buffer a whole simple-only batch as one zero-copy segment."""
        self._seal()
        self._versions.append(version)
        self._segs.append(batch)
        self.mem_bytes += batch.nbytes

    def _seal(self) -> None:
        if self._pend is not None and len(self._pend):
            seg = self._pend.finish()
            self._versions.append(self._pend_version)
            self._segs.append(seg)
            self.mem_bytes += seg.nbytes
        self._pend = None

    def __len__(self) -> int:
        n = sum(len(s) for s in self._segs[self._start:])
        n += sum(t[4] for t in self._spilled)
        if self._pend is not None:
            n += len(self._pend)
        return n

    @property
    def retained_bytes(self) -> int:
        """Resident payload bytes (memory segments only — the quantity
        the spill budget bounds)."""
        return self.mem_bytes

    @property
    def needs_spill(self) -> bool:
        return (self.queue is not None and self.spill_bytes > 0
                and self.mem_bytes > self.spill_bytes)

    # --- the commit slice ---

    def peek_memory_through(self, floor: Version) -> PackedOps:
        """The committable MEMORY slice: every buffered op at version <=
        floor.  Spill-free deployments (no queue) use this directly."""
        self._seal()
        i = bisect.bisect_right(self._versions, floor, lo=self._start)
        return PackedOps(self._segs[self._start:i])

    async def peek_through(self, floor: Version) -> PackedOps:
        """The committable slice — spilled frames at or below ``floor``
        read back transparently (oldest first, exactly the order they
        left memory), then the memory slice.  Raises IOError when a
        spilled frame fails its crc — a silently short slice would
        commit a hole the TLog pop then makes permanent."""
        if not self._spilled or self._spilled[0][0] > floor:
            return self.peek_memory_through(floor)
        async with self._lock():
            segs: list[MutationBatch] = []
            # iterate a SNAPSHOT: a rejoin rollback between frame reads
            # may trim the bookkeeping list under us
            for v, st, en, _nb, _ops in list(self._spilled):
                if v > floor:
                    break
                frames = await self.queue.read_frames(st, en)
                if not frames:
                    raise IOError(
                        f"spilled durability frame [{st},{en}) at version "
                        f"{v} unreadable (crc/short read)")
                from ..rpc.wire import decode
                rec = decode(frames[0][0])
                segs.append(MutationBatch(*(bytes(p) for p in rec["pk"])))
        mem = self.peek_memory_through(floor)
        return PackedOps(segs + mem.segments)

    def pop_memory_through(self, floor: Version) -> None:
        """Advance the cursor past the committed slice (amortized trim)."""
        i = bisect.bisect_right(self._versions, floor, lo=self._start)
        self.mem_bytes -= sum(s.nbytes for s in self._segs[self._start:i])
        self._start = i
        if self._start > 64 and self._start * 2 > len(self._segs):
            del self._versions[:self._start]
            del self._segs[:self._start]
            self._start = 0

    async def pop_through(self, floor: Version) -> None:
        """Pop the committed slice: the spilled frames' dead disk prefix
        releases FIRST (pop_to does real file I/O — header write,
        possibly a compaction; a failure leaves every piece of
        bookkeeping untouched so the caller's next tick retries), then
        the bookkeeping and memory cursor advance synchronously.  Fully
        serialized behind the io lock — the memory trim can compact
        list indices, and a spill pass awaiting its pushes must never
        observe that mid-flight."""
        async with self._lock():
            if self._spilled and self._spilled[0][0] <= floor:
                i = 0
                while i < len(self._spilled) and self._spilled[i][0] <= floor:
                    i += 1
                # frames are appended in offset order and this drops a
                # prefix, so the release offset is the last dead frame's
                # end (rolled-back dead bytes below it go with it)
                await self.queue.pop_to(self._spilled[i - 1][2])
                dead = self._spilled[:i]
                del self._spilled[:i]
                self.spilled_bytes -= sum(t[3] for t in dead)
            self.pop_memory_through(floor)

    # --- spill (the memory-wall valve; durability/pull-loop hook) ---

    async def maybe_spill(self) -> int:
        """Move the oldest sealed memory segments to the side queue
        until resident bytes drop to half the budget (hysteresis: a
        ring hovering at the budget must not pay a spill pass per
        append).  Frames are pushed AND fsync'd before any bookkeeping
        or memory trim (the ChangeFeedStore.maybe_spill discipline), so
        a failed push/sync leaves the ring exactly as it was — the
        orphan bytes are overwritten by the retry or released by a
        later pop.  Returns bytes spilled."""
        if not self.needs_spill:
            return 0
        async with self._lock():
            if not self.needs_spill:        # raced with another pass
                return 0
            from ..rpc.wire import encode
            self._seal()
            target = self.spill_bytes // 2
            budget = self.mem_bytes - target
            pushed: list[tuple[MutationBatch,
                               tuple[Version, int, int, int, int]]] = []
            # snapshot the front slice as OBJECTS, never indices: a
            # rejoin rollback is sync and may trim/compact the lists
            # between the pushes' awaits (pop_through serializes behind
            # the lock, rollback cannot)
            for v, seg in zip(self._versions[self._start:],
                              self._segs[self._start:]):
                if budget <= 0:
                    break
                st = self.queue.end_offset
                en = await self.queue.push(encode(
                    {"v": v, "pk": (seg.types, seg.bounds, seg.blob)}))
                pushed.append((seg, (v, st, en, seg.nbytes, len(seg))))
                budget -= seg.nbytes
            if not pushed:
                return 0
            await self.queue.commit()       # fsync BEFORE the memory drop
            # re-locate each pushed segment by IDENTITY: one rolled back
            # mid-spill already left the window — its frames are dead
            # bytes a later pop releases, never bookkept
            alive = {id(s): j for j, s in enumerate(self._segs)}
            spilled = 0
            used: set[int] = set()
            drops: list[tuple[int, tuple]] = []
            for seg, rec in pushed:
                j = alive.get(id(seg))
                if j is None or j < self._start or j in used:
                    continue
                used.add(j)
                drops.append((j, rec))
            for j, rec in sorted(drops, reverse=True):
                del self._versions[j]
                del self._segs[j]
                self._spilled.append(rec)
                self.mem_bytes -= rec[3]
                spilled += rec[3]
            self._spilled.sort(key=lambda t: (t[0], t[1]))
            self.spilled_bytes += spilled
            if spilled:
                self.spills += 1
                self.spill_frames += len(drops)
            return spilled

    # --- rollback (storage rejoin) ---

    def rollback_after(self, version: Version) -> None:
        """Discard buffered ops newer than ``version`` (storage rejoin:
        the unacked suffix of a dead log generation rolls back before
        it could ever become durable).  Spilled frames of the suffix
        drop from the bookkeeping only — their bytes are dead on disk
        until a later pop releases them (a disk queue cannot un-append;
        nothing ever reads an untracked frame)."""
        if self._pend is not None and self._pend_version > version:
            self._pend = None
        self._seal()
        i = bisect.bisect_right(self._versions, version, lo=self._start)
        self.mem_bytes -= sum(s.nbytes for s in self._segs[i:])
        del self._versions[i:]
        del self._segs[i:]
        if self._spilled and self._spilled[-1][0] > version:
            keep = [t for t in self._spilled if t[0] <= version]
            self.spilled_bytes -= sum(t[3] for t in self._spilled[len(keep):])
            self._spilled = keep

    # --- observability ---

    def stats(self) -> dict:
        return {
            "dbuf_mem_bytes": self.mem_bytes,
            "dbuf_spilled_bytes": self.spilled_bytes,
            "dbuf_spilled_frames": len(self._spilled),
            "dbuf_spills": self.spills,
        }
