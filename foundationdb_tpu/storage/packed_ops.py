"""Packed op slices — the storage durability buffer and its engine feed.

Reference: REF:fdbserver/storageserver.actor.cpp updateStorage — the
reference drains the MVCC window's aged-out versions into the engine in
version order.  The seed kept that pending set as a Python list of
(version, (op, p1, p2)) tuples rebuilt by TWO full list comprehensions
per durability tick (ROADMAP PR 1 follow-up (c)): O(total buffered) per
tick regardless of how little aged out.

``DurabilityRing`` replaces it with an append-only ring of packed
segments (each a simple-only ``MutationBatch`` — op codes ARE the engine
WAL op codes) plus a bisect version cursor: each tick commits the slice
of whole segments at or below the durable floor and advances the cursor,
O(slice) instead of O(buffer).  A TLog pull batch that took the storage
fast path lands here as ONE zero-copy segment (the same types/bounds/
blob objects, no per-op materialization); stragglers (resolved atomics,
fetchKeys rows) accumulate into small builder segments.

``PackedOps`` is the slice handed to ``engine.commit``: iterable of
(op, p1, p2) for engines that replay ops, with ``wire_parts()`` exposing
the raw (types, bounds, blob) triples so the memory engine's WAL frame
encodes three contiguous byte strings per segment instead of thousands
of tuple elements.
"""

from __future__ import annotations

import bisect

from ..core.data import MutationBatch, MutationBatchBuilder, Version

__all__ = ["PackedOps", "DurabilityRing"]


class PackedOps:
    """An ordered, zero-copy run of packed op segments."""

    __slots__ = ("segments",)

    def __init__(self, segments: list[MutationBatch]) -> None:
        self.segments = segments

    def __len__(self) -> int:
        return sum(len(s) for s in self.segments)

    def __bool__(self) -> bool:
        return any(self.segments)

    def __iter__(self):
        for seg in self.segments:
            yield from seg.iter_ops()

    @property
    def nbytes(self) -> int:
        return sum(s.nbytes for s in self.segments)

    def wire_parts(self) -> list[tuple[bytes, bytes, bytes]]:
        return [(s.types, s.bounds, s.blob) for s in self.segments]


class DurabilityRing:
    """Version-ordered packed op buffer with a bisect commit cursor.

    Segments are (version, MutationBatch) pairs appended in apply order;
    versions are non-decreasing, and a version is never split across the
    commit floor (the floor compares whole versions).  ``peek_through``
    returns the committable slice WITHOUT consuming it — the caller pops
    only after the engine commit succeeded, so a failed tick retries the
    same slice (the disk-trouble contract of the seed's loop).
    """

    __slots__ = ("_versions", "_segs", "_start", "_pend", "_pend_version")

    def __init__(self) -> None:
        self._versions: list[Version] = []
        self._segs: list[MutationBatch] = []
        self._start = 0                     # segments below are committed
        self._pend: MutationBatchBuilder | None = None
        self._pend_version: Version = -1

    def append(self, version: Version, op: int, p1: bytes, p2: bytes) -> None:
        """Buffer one op (atomics resolved at apply time, fetchKeys rows)."""
        if self._pend is not None and self._pend_version != version:
            self._seal()
        if self._pend is None:
            self._pend = MutationBatchBuilder()
            self._pend_version = version
        self._pend.add(op, p1, p2)

    def extend_packed(self, version: Version, batch: MutationBatch) -> None:
        """Buffer a whole simple-only batch as one zero-copy segment."""
        self._seal()
        self._versions.append(version)
        self._segs.append(batch)

    def _seal(self) -> None:
        if self._pend is not None and len(self._pend):
            self._versions.append(self._pend_version)
            self._segs.append(self._pend.finish())
        self._pend = None

    def __len__(self) -> int:
        n = sum(len(s) for s in self._segs[self._start:])
        if self._pend is not None:
            n += len(self._pend)
        return n

    def peek_through(self, floor: Version) -> PackedOps:
        """The committable slice: every buffered op at version <= floor."""
        self._seal()
        i = bisect.bisect_right(self._versions, floor, lo=self._start)
        return PackedOps(self._segs[self._start:i])

    def pop_through(self, floor: Version) -> None:
        """Advance the cursor past the committed slice (amortized trim)."""
        i = bisect.bisect_right(self._versions, floor, lo=self._start)
        self._start = i
        if self._start > 64 and self._start * 2 > len(self._segs):
            del self._versions[:self._start]
            del self._segs[:self._start]
            self._start = 0

    def rollback_after(self, version: Version) -> None:
        """Discard buffered ops newer than ``version`` (storage rejoin:
        the unacked suffix of a dead log generation rolls back before
        it could ever become durable)."""
        if self._pend is not None and self._pend_version > version:
            self._pend = None
        self._seal()
        i = bisect.bisect_right(self._versions, version, lo=self._start)
        del self._versions[i:]
        del self._segs[i:]
