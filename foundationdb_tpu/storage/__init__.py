"""Storage engines and versioned structures.

Reference: REF:fdbserver/VersionedMap.h (MVCC in-memory window) and
REF:fdbserver/IKeyValueStore.h (pluggable persistent engines).
"""

from .versioned_map import VersionedMap

# engine name registry (REF:fdbserver/IKeyValueStore.h openKVStore by
# KeyValueStoreType); names are what `configure storage_engine=...` takes
ENGINE_NAMES = ("memory", "lsm", "btree")


def engine_class(name: str):
    from .btree import BTreeKVStore
    from .kv_store import MemoryKVStore
    from .lsm import LSMKVStore
    try:
        return {"memory": MemoryKVStore, "lsm": LSMKVStore,
                "btree": BTreeKVStore}[name]
    except KeyError:
        raise ValueError(f"unknown storage engine {name!r}") from None
