"""Storage engines and versioned structures.

Reference: REF:fdbserver/VersionedMap.h (MVCC in-memory window) and
REF:fdbserver/IKeyValueStore.h (pluggable persistent engines).
"""

from .versioned_map import VersionedMap
