"""Columnar sorted key runs — the shared home of the big-run layout.

Reference: the memory walls of ROADMAP item 5.  Two structures in this
repo keep a large sorted run of keys: ``PackedKeyIndex._base`` (the
MVCC/engine key index, storage/key_index.py) and the lsm engine's
per-run sparse index (the block first-keys, storage/lsm.py).  Both were
plain Python ``list[bytes]`` — ~50-100 bytes of PyObject overhead per
key, so 10M keys burned ~1GB before storing a single value, and both
had independently grown the same keycode-u64-prefix ``searchsorted``
fast path.

``KeyRun`` is that run gone columnar (the ``PackedRows`` discipline
applied to keys): ONE contiguous blob of concatenated keys plus a
cumulative int64 end-offset column, with the keycode-packed uint64
prefixes cached alongside.  Per-key memory drops to ~key_len + 8 (+8
once the prefixes are built); merges become one vectorized
``np.insert`` over the length column + an O(overlay)-segment blob
stitch; probes bisect straight over blob slices.

Two probe disciplines compose:

- the u64-prefix ``searchsorted`` narrows a batch to equal-prefix bands
  in one vectorized call (the PackedKeyIndex/lsm idiom, now one home);
- the exact bisect runs over LOCAL blob/bounds variables (the bounds
  column is a stdlib ``array('q')`` precisely so scalar indexing stays
  a ~50ns Python int, not a numpy scalar box), and batched bisects over
  SORTED probes carry a monotone lower bound — key i's insertion point
  floors key i+1's search — which matters exactly when a keyspace
  shares its first 8 bytes and the prefix bands collapse to the whole
  run.

The run is IMMUTABLE: mutation surfaces (``merge_sorted``,
``delete_keys``) return a new run sharing no state with the old one, so
readers holding a reference (a device mirror mid-upload, a spilled
segment) can never observe a half-built state.  The sequence protocol
(``__len__``/``__getitem__``/``__iter__``) makes a run a drop-in for
the sorted ``list[bytes]`` it replaces wherever callers only index,
slice, and bisect.
"""

from __future__ import annotations

from array import array as _array

import numpy as np

__all__ = ["KeyRun"]

_ITER_CHUNK = 4096      # keys materialized per __iter__ slab

# batched probes below this fall back to a per-key bisect: one scalar
# np.searchsorted costs ~5µs of call overhead where bisect is ~1µs (the
# PackedKeyIndex threshold reasoning, kept at the shared home)
_BATCH_MIN = 16


class KeyRun:
    """One immutable columnar sorted run of byte keys."""

    __slots__ = ("blob", "bounds", "_pfx", "_pfx2", "_lens")

    def __init__(self, blob: bytes = b"",
                 bounds: _array | None = None) -> None:
        self.blob = blob
        self.bounds = bounds if bounds is not None else _array("q")
        self._pfx: np.ndarray | None = None
        self._pfx2: np.ndarray | None = None
        self._lens: np.ndarray | None = None

    # --- construction ---

    @classmethod
    def from_keys(cls, keys: list[bytes]) -> "KeyRun":
        """Pack an already-sorted key list (duplicates permitted for
        directory uses; the index contract keeps them distinct)."""
        if not keys:
            return cls()
        from itertools import accumulate
        return cls(b"".join(keys), _array("q", accumulate(map(len, keys))))

    def _np_bounds(self) -> np.ndarray:
        """Zero-copy numpy view of the bounds column (vector ops only —
        scalar access stays on the stdlib array)."""
        return np.frombuffer(self.bounds, dtype=np.int64)

    # --- sequence protocol (drop-in for the sorted list it replaces) ---

    def __len__(self) -> int:
        return len(self.bounds)

    def __bool__(self) -> bool:
        return len(self.bounds) > 0

    def key(self, i: int) -> bytes:
        b = self.bounds
        return self.blob[(b[i - 1] if i else 0):b[i]]

    def __getitem__(self, i):
        if isinstance(i, slice):
            lo, hi, step = i.indices(len(self.bounds))
            keys = self.slice_keys(lo, hi)
            return keys if step == 1 else keys[::step]
        n = len(self.bounds)
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        return self.key(i)

    def __iter__(self):
        n = len(self.bounds)
        for lo in range(0, n, _ITER_CHUNK):
            yield from self.slice_keys(lo, min(lo + _ITER_CHUNK, n))

    def __eq__(self, other) -> bool:
        if isinstance(other, KeyRun):
            return self.blob == other.blob and self.bounds == other.bounds
        if isinstance(other, (list, tuple)):
            return len(self) == len(other) and self.to_list() == list(other)
        return NotImplemented

    __hash__ = None  # type: ignore[assignment] — mutable-adjacent semantics

    def slice_keys(self, lo: int, hi: int) -> list[bytes]:
        """Rows [lo, hi) materialized as ``list[bytes]`` — the bounds
        unpack is C-speed map-of-slices (the PackedRows.rows idiom),
        never a per-key Python loop."""
        n = len(self.bounds)
        lo, hi = max(0, lo), min(hi, n)
        if lo >= hi:
            return []
        from itertools import starmap
        ends = self.bounds[lo:hi].tolist()
        starts = [self.bounds[lo - 1] if lo else 0] + ends[:-1]
        return list(map(self.blob.__getitem__,
                        starmap(slice, zip(starts, ends))))

    def to_list(self) -> list[bytes]:
        return self.slice_keys(0, len(self.bounds))

    @property
    def nbytes(self) -> int:
        """Resident bytes of the columnar storage (blob + bounds +
        prefixes when built) — what the memory-wall accounting reports."""
        n = len(self.blob) + len(self.bounds) * self.bounds.itemsize
        if self._pfx is not None:
            n += self._pfx.nbytes
        return n

    # --- prefixes (the vectorized-searchsorted operand) ---

    def _pfx_from(self, skip: int) -> np.ndarray:
        """u64 of key bytes [skip, skip+8) per key, zero-padded —
        computed straight off the columns."""
        n = len(self.bounds)
        if n == 0:
            return np.zeros(0, dtype=np.uint64)
        flat = np.frombuffer(self.blob, dtype=np.uint8)
        ends = self._np_bounds()
        starts = np.empty(n, dtype=np.int64)
        starts[0] = 0
        starts[1:] = ends[:-1]
        starts = starts + skip
        plens = np.minimum(np.maximum(ends - starts, 0), 8)
        buf = np.zeros((n, 8), dtype=np.uint8)
        cols = np.arange(8)[None, :]
        mask = cols < plens[:, None]
        src = np.minimum(starts[:, None] + cols, max(len(flat) - 1, 0))
        buf[mask] = flat[src[mask]]
        return buf.view(">u8").ravel().astype(np.uint64)

    def prefixes(self) -> np.ndarray:
        """keycode-u64 prefixes of every key (cached) — computed straight
        off the columns, byte-identical to
        ``keycode.encode_prefix_u64(self.to_list())`` without the join."""
        if self._pfx is None:
            self._pfx = self._pfx_from(0)
        return self._pfx

    def lens(self) -> np.ndarray:
        """Per-key byte lengths (cached) — run_positions' tie-breaker."""
        if self._lens is None:
            self._lens = np.diff(self._np_bounds(), prepend=0)
        return self._lens

    def prefixes2(self) -> np.ndarray:
        """SECOND-word prefixes (key bytes [8, 16), cached): the rescue
        level for keyspaces sharing their first 8 bytes, where the
        primary bands collapse to the whole run (the ISSUE 11 band-
        collapse shape).  Within an equal-``prefixes()`` band, keys sort
        by this word, so a second searchsorted restricted to the band
        is exact up to 16 bytes."""
        if self._pfx2 is None:
            self._pfx2 = self._pfx_from(8)
        return self._pfx2

    # --- point probes ---
    #
    # Hand-rolled bisects over LOCAL blob/bounds: the inner loop is a
    # python-int index, one blob slice and one compare per step —
    # bisect.bisect_left(self, ...) would pay __getitem__ dispatch,
    # bounds checks and len() per step, measured ~6x slower at 2M keys.

    def bisect_left(self, key: bytes, lo: int = 0, hi: int | None = None
                    ) -> int:
        bounds = self.bounds
        blob = self.blob
        if hi is None:
            hi = len(bounds)
        while lo < hi:
            mid = (lo + hi) >> 1
            if blob[(bounds[mid - 1] if mid else 0):bounds[mid]] < key:
                lo = mid + 1
            else:
                hi = mid
        return lo

    def bisect_right(self, key: bytes, lo: int = 0, hi: int | None = None
                     ) -> int:
        bounds = self.bounds
        blob = self.blob
        if hi is None:
            hi = len(bounds)
        while lo < hi:
            mid = (lo + hi) >> 1
            if key < blob[(bounds[mid - 1] if mid else 0):bounds[mid]]:
                hi = mid
            else:
                lo = mid + 1
        return lo

    def __contains__(self, key: bytes) -> bool:
        i = self.bisect_left(key)
        return i < len(self.bounds) and self.key(i) == key

    # --- batched probes (ONE vectorized searchsorted for the batch) ---

    def batch_bisect(self, keys: list[bytes], side: str = "left",
                     sorted_keys: bool = False) -> list[int]:
        """Exact insertion points for many keys — prefix searchsorted +
        per-key bisect refinement, with a plain-bisect fallback below
        the amortization threshold.  ``sorted_keys=True`` (the merge /
        delete path) additionally floors each refinement at the
        previous result, and COLLAPSED bands (a keyspace sharing its
        first 8 bytes maps every probe to the whole run — the ISSUE 11
        band-collapse shape) re-narrow through one second-word
        searchsorted per distinct band (``prefixes2``), so the
        refinement never degenerates to m full-run bisects."""
        point = self.bisect_left if side == "left" else self.bisect_right
        m = len(keys)
        if m < _BATCH_MIN or len(self.bounds) < _BATCH_MIN:
            if not sorted_keys:
                return [point(k) for k in keys]
            out: list[int] = []
            prev = 0
            for k in keys:
                prev = point(k, prev)
                out.append(prev)
            return out
        from ..ops.keycode import encode_prefix_u64
        pfx = self.prefixes()
        probes = encode_prefix_u64(keys)
        los = np.searchsorted(pfx, probes, side="left").tolist()
        his = np.searchsorted(pfx, probes, side="right").tolist()
        out = [0] * m
        prev = 0
        i = 0
        while i < m:
            lo, hi = los[i], his[i]
            j = i + 1
            while j < m and los[j] == lo and his[j] == hi:
                j += 1
            if hi - lo > 32 and (hi - lo) > 2 * (j - i):
                # collapsed band shared by probes [i, j): one restricted
                # second-word searchsorted re-narrows them all
                pfx2 = self.prefixes2()
                p2 = encode_prefix_u64([k[8:16] for k in keys[i:j]])
                l2 = (lo + np.searchsorted(pfx2[lo:hi], p2,
                                           side="left")).tolist()
                h2 = (lo + np.searchsorted(pfx2[lo:hi], p2,
                                           side="right")).tolist()
                for p in range(i, j):
                    blo, bhi = l2[p - i], h2[p - i]
                    if sorted_keys and prev > blo:
                        blo = prev
                    if bhi < blo:
                        bhi = blo
                    prev = point(keys[p], blo, bhi)
                    out[p] = prev
            else:
                for p in range(i, j):
                    blo, bhi = lo, hi
                    if sorted_keys and prev > blo:
                        blo = prev
                    if bhi < blo:
                        bhi = blo
                    prev = point(keys[p], blo, bhi)
                    out[p] = prev
            i = j
        return out

    def adopt_prefixes(self, pfx: np.ndarray | None,
                       pfx2: np.ndarray | None,
                       lens: np.ndarray | None = None) -> "KeyRun":
        """Install precomputed prefix (and optionally length) caches
        (the segment-merge path: prefixes are position-independent, so
        a merge can np.insert the parents' cached arrays instead of
        re-encoding the whole run)."""
        self._pfx = pfx
        self._pfx2 = pfx2
        if lens is not None:
            self._lens = lens
        return self

    def run_positions(self, other: "KeyRun"
                      ) -> tuple[np.ndarray, np.ndarray]:
        """(left insertion positions, exact-match mask) of another
        SORTED run's keys in this run — the columnar MVCC merge/probe
        primitive (ISSUE 13), fully vectorized: one searchsorted pair
        over the first-word prefixes, one per collapsed band over the
        second word, and a LENGTH compare settles order and equality
        for prefix-tied keys of <= 16 bytes (a shorter key is a strict
        prefix of the longer, so it sorts first; equal length means
        equal key).  Only ties past 16 bytes fall back to byte-level
        bisects."""
        m = len(other)
        nA = len(self.bounds)
        if m == 0:
            return (np.zeros(0, dtype=np.int64),
                    np.zeros(0, dtype=bool))
        if nA == 0:
            return (np.zeros(m, dtype=np.int64),
                    np.zeros(m, dtype=bool))
        pa = self.prefixes()
        pb = other.prefixes()
        lo = np.searchsorted(pa, pb, side="left").astype(np.int64)
        hi = np.searchsorted(pa, pb, side="right")
        pos = lo.copy()
        dup = np.zeros(m, dtype=bool)
        amb = hi > lo
        if not amb.any():
            return pos, dup
        pa2 = self.prefixes2()
        pb2 = other.prefixes2()
        lenA = self.lens()
        lenB = other.lens()
        ai = np.nonzero(amb)[0]
        # ``other`` is sorted, so equal-prefix probes (hence equal
        # bands) are contiguous in ai; group by the band's lo value
        band_lo = lo[ai]
        cuts = np.nonzero(np.diff(band_lo))[0] + 1
        group_starts = np.concatenate([[0], cuts, [len(ai)]])
        hard: list[int] = []
        for g in range(len(group_starts) - 1):
            gi = ai[group_starts[g]:group_starts[g + 1]]
            blo = int(lo[gi[0]])
            bhi = int(hi[gi[0]])
            sub2 = pa2[blo:bhi]
            p2 = pb2[gi]
            l2 = blo + np.searchsorted(sub2, p2, side="left")
            h2 = blo + np.searchsorted(sub2, p2, side="right")
            pos[gi] = l2
            sz = h2 - l2
            one = sz == 1
            if one.any():
                ii = gi[one]
                p1 = l2[one]
                la = lenA[p1]
                lb = lenB[ii]
                easy = (la <= 16) & (lb <= 16)
                dup[ii[easy & (la == lb)]] = True
                pos[ii[easy & (la < lb)]] += 1
                hard.extend(ii[~easy].tolist())
            multi = sz > 1
            if multi.any():
                hard.extend(gi[multi].tolist())
        if hard:
            okey = other.key
            n = nA
            for i in hard:
                k = okey(i)
                p = self.bisect_left(k, int(lo[i]), int(hi[i]))
                pos[i] = p
                dup[i] = p < n and self.key(p) == k
        return pos, dup

    def merge_newest_wins(self, newer: "KeyRun"
                          ) -> tuple["KeyRun", np.ndarray]:
        """Distinct-key union of self (the OLDER layer) and ``newer``,
        duplicate keys taking the newer side — the lsm leveled
        compactor's 2-source merge primitive (ISSUE 14).  Returns
        (merged run, per-merged-row source index: [0, len(self)) names
        self's rows, [len(self), len(self)+len(newer)) names newer's),
        so a parallel value column resolves with one fancy-index pass.
        Fully vectorized: one ``run_positions`` call locates every
        newer key, duplicates overwrite in the source-index column, and
        the merged key blob stitches through ``insert_run_at``'s
        byte-gather."""
        nA = len(self.bounds)
        nB = len(newer.bounds)
        if nA == 0:
            return newer, np.arange(nB, dtype=np.int64)
        if nB == 0:
            return self, np.arange(nA, dtype=np.int64)
        pos, dup = self.run_positions(newer)
        src = np.arange(nA, dtype=np.int64)
        di = np.nonzero(dup)[0]
        if len(di):
            src[pos[di]] = nA + di
        fresh = ~dup
        fi = np.nonzero(fresh)[0]
        merged = np.insert(src, pos[fresh], nA + fi)
        keys = self.insert_run_at(pos[fresh], newer, fresh)
        return keys, merged

    def batch_find(self, keys: list[bytes],
                   assume_sorted: bool = False) -> list[int]:
        """Exact positions of ``keys`` (or -1 where absent) — the
        columnar MVCC window's per-segment probe (ISSUE 13): the
        two-level ``batch_bisect`` banding plus one membership slice
        compare per probe."""
        n = len(self.bounds)
        if not keys or n == 0:
            return [-1] * len(keys)
        pos = self.batch_bisect(keys, "left", sorted_keys=assume_sorted)
        key_at = self.key
        return [p if p < n and key_at(p) == k else -1
                for p, k in zip(pos, keys)]

    # --- mutation (immutable: each returns a NEW run) ---

    def merge_sorted(self, new_keys: list[bytes]) -> "KeyRun":
        """Merge a sorted list of distinct keys NOT already present:
        insertion points resolve in one monotone batched pass, the new
        bounds build as one ``np.insert`` + cumsum, and the blob
        stitches from O(m) segment slices — never a per-key pass over
        the base."""
        if not new_keys:
            return self
        if not len(self.bounds):
            return KeyRun.from_keys(new_keys)
        pos = self.batch_bisect(new_keys, "left", sorted_keys=True)
        return self.insert_at(pos, new_keys)

    def insert_run_at(self, pos: np.ndarray, other: "KeyRun",
                      mask: np.ndarray) -> "KeyRun":
        """Stitch ``other``'s rows selected by ``mask`` in at ascending
        insertion points ``pos`` (one per selected row) — the columnar
        MVCC segment merge's key build (ISSUE 13).  Fully vectorized:
        the merged blob assembles through ONE byte-level gather over the
        two source blobs, and the prefix/length caches merge by
        ``np.insert`` instead of re-encoding (prefixes are
        position-independent)."""
        m = int(mask.sum())
        if m == 0:
            return self
        if not len(self.bounds):
            if m == len(other.bounds):
                return other
            # partial adoption of another run: fall back to the list path
            from itertools import compress
            return KeyRun.from_keys(
                list(compress(other.to_list(), mask.tolist())))
        lenA = self.lens()
        lenBall = other.lens()
        lenB = lenBall[mask]
        endsB = other._np_bounds()
        startsB = (endsB - lenBall)[mask] + len(self.blob)
        endsA = self._np_bounds()
        startsA = endsA - lenA
        flat = np.frombuffer(self.blob + other.blob, dtype=np.uint8)
        mstarts = np.insert(startsA, pos, startsB)
        mlens = np.insert(lenA, pos, lenB)
        tot = int(mlens.sum())
        row_off = np.concatenate([np.zeros(1, dtype=np.int64),
                                  np.cumsum(mlens)[:-1]])
        gidx = np.repeat(mstarts - row_off, mlens) \
            + np.arange(tot, dtype=np.int64)
        bounds = _array("q")
        bounds.frombytes(np.cumsum(mlens).tobytes())
        out = KeyRun(flat[gidx].tobytes(), bounds)
        if self._pfx is not None and other._pfx is not None:
            out._pfx = np.insert(self._pfx, pos, other._pfx[mask])
        if self._pfx2 is not None and other._pfx2 is not None:
            out._pfx2 = np.insert(self._pfx2, pos, other._pfx2[mask])
        out._lens = mlens
        return out

    def insert_at(self, pos: list[int], new_keys: list[bytes]) -> "KeyRun":
        """Stitch ``new_keys`` in at precomputed ascending insertion
        points (the merge_sorted build with the bisect pass already
        paid — the columnar MVCC segment merge's shape, ISSUE 13)."""
        if not new_keys:
            return self
        if not len(self.bounds):
            return KeyRun.from_keys(new_keys)
        ends = self.bounds
        np_ends = self._np_bounds()
        base_lens = np.diff(np_ends, prepend=0)
        new_lens = np.fromiter(map(len, new_keys), dtype=np.int64,
                               count=len(new_keys))
        merged = np.insert(base_lens, pos, new_lens)
        bounds = _array("q")
        bounds.frombytes(np.cumsum(merged).tobytes())
        parts: list[bytes] = []
        blob = self.blob
        prev = 0
        for p, k in zip(pos, new_keys):
            boff = ends[p - 1] if p else 0
            if boff > prev:
                parts.append(blob[prev:boff])
                prev = boff
            parts.append(k)
        if prev < len(blob):
            parts.append(blob[prev:])
        return KeyRun(b"".join(parts), bounds)

    def delete_keys(self, dead: list[bytes]) -> tuple["KeyRun", int]:
        """Remove every present key of ``dead``; returns (new run,
        number removed).  Locations resolve in one monotone batched
        pass; the survivor columns build from O(d) segment slices."""
        if not dead or not len(self.bounds):
            return self, 0
        dead_sorted = sorted(set(dead))
        pos = self.batch_bisect(dead_sorted, "left", sorted_keys=True)
        n = len(self.bounds)
        hit = [p for p, k in zip(pos, dead_sorted)
               if p < n and self.key(p) == k]
        if not hit:
            return self, 0
        return self.delete_at(hit), len(hit)

    def delete_at(self, hit: list[int]) -> "KeyRun":
        """Remove the keys at the given ascending positions (the
        located half of ``delete_keys``; the columnar MVCC segment
        prune's shape, ISSUE 13)."""
        ends = self.bounds
        lens = np.diff(self._np_bounds(), prepend=0)
        bounds = _array("q")
        bounds.frombytes(np.cumsum(np.delete(lens, hit)).tobytes())
        parts: list[bytes] = []
        blob = self.blob
        prev = 0
        for p in hit:
            start = ends[p - 1] if p else 0
            if start > prev:
                parts.append(blob[prev:start])
            prev = ends[p]
        if prev < len(blob):
            parts.append(blob[prev:])
        return KeyRun(b"".join(parts), bounds)
