"""Packed sorted key index — the VersionedMap's range-scan structure.

Reference: REF:fdbserver/VersionedMap.h keeps keys in a persistent
red-black tree, paying O(log n) per insert.  The seed's Python port used
one flat sorted list with ``bisect.insort`` per fresh key — an O(n) list
memmove per insert, O(n²) across a bulk load, which is exactly the r5
YCSB-at-1M-rows collapse (BENCH_r05.json: ~900ms SlowTask stalls all in
``bisect.insort``).

The replacement is two sorted runs merged lazily:

- ``_base``   — the big immutable sorted run.  COLUMNAR by default
  (ISSUE 11): a ``storage/key_runs.py`` ``KeyRun`` — one contiguous key
  blob + cumulative int64 bounds with the keycode-u64 prefixes cached
  alongside — so per-key memory is ~key_len + 8 instead of the ~50-100
  bytes of PyObject overhead a ``list[bytes]`` pays (10M keys: tens of
  MB instead of ~1GB).  ``columnar=False`` keeps the plain-list base —
  the genuinely-old layout, retained as the equivalence/RSS A/B
  baseline (tools/perf_smoke.py --stage bigkeys measures both).
- ``_pending``— a small sorted ``list[bytes]`` overlay absorbing
  inserts (always tiny relative to the base; object overhead is noise).

Inserts go to the overlay (cheap memmove while it is small); when the
overlay outgrows ``max(_PENDING_MIN, len(base) >> _MERGE_SHIFT)`` the two
runs are merged in ONE pass — columnar: a vectorized ``np.insert`` over
the bounds + an O(m)-segment blob stitch; list: concat + Timsort's
galloping two-run merge.  Because the merge threshold scales with the
base, a key insert costs amortized O(log n) work overall in either mode
— the same cost class as the PTree.

Batch inserts (``add_many``) skip the per-key overlay memmove entirely;
batch removals (``discard_many``) are one located pass.

Bound queries (range scans, clear_range) binary-search both runs.  For
BATCHES of ranges (``ranges_keys``, fed by a run of consecutive clears
in ``VersionedMap.apply_batch``) a numpy ``searchsorted`` over the
cached keycode-u64 prefixes resolves every bound in one vectorized
call, with a bisect refining inside the equal-prefix band — the same
pack-keys-into-lane-arrays idiom the TPU resolver uses, applied to the
storage role.  The prefix cache now lives on the ``KeyRun`` itself, the
ONE home the lsm sparse index and the device read mirror share.
"""

from __future__ import annotations

import bisect
import time

import numpy as np

from .key_runs import KeyRun

_PENDING_MIN = 1024     # overlay always allowed to reach this size
_MERGE_SHIFT = 3        # ...or base/8, whichever is larger
_ADD_PENDING_CAP = 8192  # single-key adds merge earlier: insort's memmove
#                          over a base/8-sized overlay would itself go
#                          quadratic across a long run of lone set() calls
_NP_MIN = 1 << 14       # numpy prefix fast path needs a base this large...
_NP_BOUNDS_MIN = 16     # ...and this many bounds to amortize call overhead
_SMALL_DISCARD = 32     # list mode: below this, per-key del beats a filter
_CHANGE_LOG_CAP = 64    # retained per-gen change spans; older mutations
#                         degrade sharded mirrors to a full re-split


class PackedKeyIndex:
    __slots__ = ("_base", "_pending", "_list_pfx", "merges", "merge_s",
                 "gen", "columnar", "changes")

    def __init__(self, columnar: bool = True) -> None:
        self.columnar = columnar
        self._base: KeyRun | list[bytes] = KeyRun() if columnar else []
        self._list_pfx: np.ndarray | None = None   # list-mode prefix cache
        self._pending: list[bytes] = []     # sorted overlay
        self.merges = 0                      # observability: merge count
        self.merge_s = 0.0                   # ...and total merge seconds
        # base-run generation: bumped whenever _base mutates (merge,
        # discard).  Device mirrors (device/read_serve.py) stamp their
        # uploaded copy with this and refresh on mismatch; the pending
        # overlay is probed host-side, so inserts alone never stale them
        self.gen = 0
        # per-gen change spans (ISSUE 18): each base mutation records
        # (gen, lo_key, hi_key) — the key span it touched (None span =
        # a gen bump that changed no keys).  The sharded device mirror
        # reads changed_since() to re-upload ONLY the shards whose key
        # range a merge/discard intersected.
        self.changes: list[tuple[int, bytes | None, bytes | None]] = []

    def __len__(self) -> int:
        return len(self._base) + len(self._pending)

    def __iter__(self):
        yield from self._merged(self._base, self._pending)

    def _base_bisect(self, key: bytes, lo: int = 0,
                     hi: int | None = None) -> int:
        base = self._base
        if self.columnar:
            return base.bisect_left(key, lo, hi)
        return bisect.bisect_left(base, key, lo,
                                  len(base) if hi is None else hi)

    def __contains__(self, key: bytes) -> bool:
        i = bisect.bisect_left(self._pending, key)
        if i < len(self._pending) and self._pending[i] == key:
            return True
        base = self._base
        i = self._base_bisect(key)
        return i < len(base) and base[i] == key

    def to_list(self) -> list[bytes]:
        return list(self)

    # --- inserts ---

    def add(self, key: bytes) -> None:
        """Insert one key NOT already present (amortized O(log n))."""
        pending = self._pending
        if pending and key > pending[-1]:
            pending.append(key)         # sequential keys: no memmove
        else:
            bisect.insort(pending, key)
        if len(pending) >= min(max(_PENDING_MIN,
                                   len(self._base) >> _MERGE_SHIFT),
                               _ADD_PENDING_CAP):
            self._merge()

    def add_many(self, keys: list[bytes]) -> None:
        """Bulk-insert distinct keys not already present: one sort over
        the overlay, one merge when it overflows — never a per-key pass
        over the base."""
        if not keys:
            return
        self._pending.extend(keys)
        self._pending.sort()
        self._maybe_merge()

    def _maybe_merge(self) -> None:
        if len(self._pending) >= max(_PENDING_MIN,
                                     len(self._base) >> _MERGE_SHIFT):
            self._merge()

    def _merge(self) -> None:
        t0 = time.perf_counter()
        pend = self._pending
        span = (pend[0], pend[-1]) if pend else (None, None)
        if self.columnar:
            # one vectorized bounds insert + O(overlay) blob stitch
            self._base = self._base.merge_sorted(self._pending)
        else:
            # two sorted runs back to back: Timsort's run detection makes
            # this a single galloping merge, O(n+m)
            self._base = self._base + self._pending
            self._base.sort()
        self._pending = []
        self._list_pfx = None
        self.merges += 1
        self.gen += 1
        self._note_change(*span)
        self.merge_s += time.perf_counter() - t0

    # --- removals ---

    def discard_many(self, keys: list[bytes]) -> None:
        """Remove keys (each assumed present in at most one run) in one
        located pass per run — never a per-key bisect+del over the base."""
        if not keys:
            return
        dead = set(keys)
        if self._pending:
            kept = [k for k in self._pending if k not in dead]
            removed = len(self._pending) - len(kept)
            if removed:
                self._pending = kept
                if removed == len(dead):
                    return
        if self.columnar:
            self._base, removed = self._base.delete_keys(list(dead))
            if removed:
                self.gen += 1
                self._note_change(min(dead), max(dead))
            return
        base = self._base
        if len(dead) <= _SMALL_DISCARD:
            hit = False
            for k in sorted(dead):
                i = bisect.bisect_left(base, k)
                if i < len(base) and base[i] == k:
                    del base[i]
                    hit = True
            if hit:
                self._list_pfx = None
                self.gen += 1
                self._note_change(min(dead), max(dead))
        else:
            nb = len(base)
            self._base = [k for k in base if k not in dead]
            if len(self._base) != nb:
                self._list_pfx = None
                self.gen += 1
                self._note_change(min(dead), max(dead))

    # --- bound queries ---
    #
    # A LONE bound query stays on bisect: measured at 1M keys, plain
    # bisect_left is ~0.8µs (list) / a few µs of per-step key slicing
    # (columnar) while a scalar np.searchsorted costs ~5µs of numpy call
    # overhead per probe.  The numpy prefix path only wins BATCHED,
    # where one vectorized searchsorted over all 2N bounds amortizes the
    # call overhead — see ranges_keys.

    def keys_in_range(self, begin: bytes, end: bytes) -> list[bytes]:
        """Sorted keys in [begin, end) across both runs."""
        return self._slice(self._base_bisect(begin),
                           self._base_bisect(end),
                           begin, end)

    def _base_slice(self, lo: int, hi: int):
        base = self._base
        return base.slice_keys(lo, hi) if self.columnar else base[lo:hi]

    def _slice(self, blo: int, bhi: int,
               begin: bytes, end: bytes) -> list[bytes]:
        plo = bisect.bisect_left(self._pending, begin)
        phi = bisect.bisect_left(self._pending, end)
        if plo == phi:
            return self._base_slice(blo, bhi)
        if blo == bhi:
            return self._pending[plo:phi]
        return list(self._merged(self._base_slice(blo, bhi),
                                 self._pending[plo:phi]))

    def _prefixes(self) -> np.ndarray:
        if self.columnar:
            return self._base.prefixes()
        if self._list_pfx is None:
            from ..ops.keycode import encode_prefix_u64
            self._list_pfx = encode_prefix_u64(self._base)
        return self._list_pfx

    def ranges_keys(self,
                    ranges: list[tuple[bytes, bytes]]) -> list[list[bytes]]:
        """Keys for many [begin, end) ranges — the clear_range bounds
        fast path.  All 2N bounds resolve in ONE vectorized searchsorted
        over the keycode-packed uint64 prefixes of the base run; a
        per-bound bisect then refines within the (usually tiny)
        equal-prefix band.  The index must not mutate between the ranges
        (apply_batch guarantees this: a run of consecutive clears has no
        intervening inserts)."""
        if len(self._base) < _NP_MIN or 2 * len(ranges) < _NP_BOUNDS_MIN:
            return [self.keys_in_range(b, e) for b, e in ranges]
        from ..ops.keycode import encode_prefix_u64
        flat = [k for r in ranges for k in r]
        pfx = self._prefixes()
        probes = encode_prefix_u64(flat)
        los = np.searchsorted(pfx, probes, side="left")
        his = np.searchsorted(pfx, probes, side="right")
        out = []
        for i, (begin, end) in enumerate(ranges):
            blo = self._base_bisect(begin,
                                    int(los[2 * i]), int(his[2 * i]))
            bhi = self._base_bisect(end,
                                    int(los[2 * i + 1]), int(his[2 * i + 1]))
            out.append(self._slice(blo, bhi, begin, end))
        return out

    @staticmethod
    def _merged(a, b):
        """Two-run sorted merge (both runs hold distinct keys; either
        may be a list or a KeyRun — only indexing/iteration is used)."""
        if not b:
            yield from a
            return
        if not a:
            yield from b
            return
        i = j = 0
        na, nb = len(a), len(b)
        while i < na and j < nb:
            if a[i] <= b[j]:
                yield a[i]
                i += 1
            else:
                yield b[j]
                j += 1
        if i < na:
            yield from (a.slice_keys(i, na) if isinstance(a, KeyRun)
                        else a[i:])
        else:
            yield from (b.slice_keys(j, nb) if isinstance(b, KeyRun)
                        else b[j:])

    # --- device-mirror accessors (device/read_serve.py) ---

    def base_run(self):
        """The sorted base run itself (NOT a copy — read-only callers).
        A ``KeyRun`` in columnar mode, a plain list otherwise; both
        support len/index/bisect."""
        return self._base

    def pending_run(self) -> list[bytes]:
        """The sorted pending overlay (NOT a copy — read-only callers)."""
        return self._pending

    def base_prefixes(self) -> np.ndarray:
        """The base run's keycode-u64 prefixes (the cached array the
        numpy bound path uses — one home for the encoding)."""
        return self._prefixes()

    def _note_change(self, lo: bytes | None, hi: bytes | None) -> None:
        self.changes.append((self.gen, lo, hi))
        if len(self.changes) > _CHANGE_LOG_CAP:
            del self.changes[:len(self.changes) // 2]

    def changed_since(self, gen: int
                      ) -> list[tuple[bytes, bytes]] | None:
        """Key spans the base mutations after ``gen`` touched, or None
        when the log cannot account for EVERY bump since then (trimmed
        entries, a caller older than the cap) — the sharded mirror then
        falls back to a full re-split.  Empty-span bumps (a merge with
        nothing pending) count toward completeness but add no span."""
        if gen == self.gen:
            return []
        recent = [e for e in self.changes if e[0] > gen]
        if len(recent) != self.gen - gen:
            return None
        return [(lo, hi) for _g, lo, hi in recent if lo is not None]

    # --- observability ---

    def stats(self) -> dict:
        return {
            "keys": len(self),
            "pending": len(self._pending),
            "merges": self.merges,
            "merge_ms": round(self.merge_s * 1e3, 3),
            "base_bytes": (self._base.nbytes if self.columnar else None),
            "columnar": self.columnar,
        }
