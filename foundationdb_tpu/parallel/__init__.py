"""Multi-resolver parallelism over a jax device Mesh (REF:fdbserver/Resolver.actor.cpp's
key-range partitioning, mapped onto TPU cores per SURVEY.md §2.6)."""

from .sharded import ShardedConflictState, make_partition_boundaries, make_sharded_resolve_step, init_sharded_state

__all__ = ["ShardedConflictState", "make_partition_boundaries",
           "make_sharded_resolve_step", "init_sharded_state"]
