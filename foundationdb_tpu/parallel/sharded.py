"""Key-range-partitioned conflict detection sharded over a device mesh.

The reference scales conflict detection with multiple Resolver roles, each
owning a key-range partition; every CommitProxy broadcasts its batch to all
resolvers and ANDs the verdicts (REF:fdbserver/Resolver.actor.cpp,
REF:fdbserver/CommitProxyServer.actor.cpp).  TPU-native, the partitions
live on the devices of a ``jax.sharding.Mesh`` axis named ``resolvers``:

- each device holds its partition's history ring (state sharded on the
  leading axis);
- the encoded batch is replicated to all devices (it is ~100KB — the
  broadcast rides ICI, the analog of the proxy's fan-out over TCP);
- each device masks *write* ranges to its partition (reads need no mask:
  a ring only ever holds writes inside its own partition, so foreign
  reads simply match nothing), runs the same resolve core as the
  single-chip kernel, and the per-device verdicts combine with a pmax —
  TOO_OLD(2) > CONFLICT(1) > COMMITTED(0) gives the reference's verdict
  precedence for free.

Fidelity note: like the reference's multi-resolver mode, each partition
decides commits from its *local* view, so a transaction aborted by one
partition may still have its writes recorded by another ("phantom"
conflict ranges).  That is conservative (false conflicts only) and is
exactly the documented behavior of FDB multi-resolver clusters.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import keycode
from ..ops.conflict_jax import ConflictState, _possibly_lt, resolve_core
from ..ops.keycode import DEFAULT_WIDTH


def _resolve_shard_map():
    """(shard_map callable, replication-check kwargs) for this jax build,
    or (None, {}) when the build has neither spelling.  Newer jax exposes
    ``jax.shard_map`` (``check_vma``); older builds only have
    ``jax.experimental.shard_map.shard_map`` (``check_rep``)."""
    try:
        from jax import shard_map as sm
        return sm, {"check_vma": False}
    except ImportError:
        pass
    try:
        from jax.experimental.shard_map import shard_map as sm
        return sm, {"check_rep": False}
    except ImportError:
        return None, {}


def have_shard_map() -> bool:
    """Capability probe: can this jax build run the sharded resolver?
    Tests and benches gate on this instead of failing on import."""
    return _resolve_shard_map()[0] is not None


class ShardedConflictState(NamedTuple):
    """ConflictState arrays with a leading resolver-shard axis, plus the
    partition boundary table (replicated).  Per-shard layout matches the
    single-chip kernel: lane-major canonical ring (ops/conflict_jax.py)."""
    hb: jax.Array     # [S, L, C]
    he: jax.Array     # [S, L, C]
    hver: jax.Array   # [S, C]
    floor: jax.Array  # [S]
    part_lo: jax.Array  # [S, L] partition begin keys (encoded)
    part_hi: jax.Array  # [S, L] partition end keys


def make_partition_boundaries(n_shards: int, width: int = DEFAULT_WIDTH,
                              split_keys: list[bytes] | None = None) -> np.ndarray:
    """[S+1, L] boundary table: shard i owns [b[i], b[i+1]).

    Default split: even slices of the first-byte space — data distribution
    will supply real split keys once shard statistics exist (the analog of
    ResolverMoveKeys in the reference).
    """
    L = keycode.nlanes(width)
    out = np.zeros((n_shards + 1, L), dtype=np.uint32)
    if split_keys is not None:
        assert len(split_keys) == n_shards - 1
        for i, k in enumerate(split_keys):
            out[i + 1] = keycode.encode_key(k, width)
    else:
        for i in range(1, n_shards):
            first = (i * 256) // n_shards
            out[i] = keycode.encode_key(bytes([first]), width)
    out[0] = 0                      # "" — below every key
    out[n_shards] = 0xFFFFFFFF      # sentinel — above every key
    return out


def init_sharded_state(mesh: Mesh, capacity_per_shard: int,
                       width: int = DEFAULT_WIDTH, oldest_version: int = 0,
                       split_keys: list[bytes] | None = None) -> ShardedConflictState:
    S = mesh.shape["resolvers"]
    L = keycode.nlanes(width)
    C = capacity_per_shard
    bounds = make_partition_boundaries(S, width, split_keys)
    state = ShardedConflictState(
        hb=jnp.full((S, L, C), 0xFFFFFFFF, jnp.uint32),
        he=jnp.full((S, L, C), 0xFFFFFFFF, jnp.uint32),
        hver=jnp.full((S, C), -1, jnp.int64),
        floor=jnp.full(S, oldest_version, jnp.int64),
        part_lo=jnp.asarray(bounds[:-1]),
        part_hi=jnp.asarray(bounds[1:]),
    )
    shard = NamedSharding(mesh, P("resolvers"))
    return ShardedConflictState(*[jax.device_put(x, shard) for x in state])


def _mask_writes_to_partition(wb, we, lo, hi, width):
    """Replace write ranges not overlapping [lo, hi) with sentinels."""
    overlap = (_possibly_lt(wb, hi[None, None, :], width) &
               _possibly_lt(lo[None, None, :], we, width))   # [B,R]
    S = jnp.uint32(0xFFFFFFFF)
    wb2 = jnp.where(overlap[..., None], wb, S)
    we2 = jnp.where(overlap[..., None], we, S)
    return wb2, we2


def make_sharded_resolve_step(mesh: Mesh, width: int = DEFAULT_WIDTH,
                              window: int = 0):
    """Build the jitted multi-resolver step for ``mesh`` (axis 'resolvers').

    step(state, rb, re, wb, we, snap, commit_version) -> (state', verdicts[B])
    with state sharded over resolvers and the batch replicated.  ``window``
    enables each shard's exact fast-path scan (CONFLICT_WINDOW_SLOTS knob),
    same semantics as the single-chip kernel.
    """
    shard_map, rep_kwargs = _resolve_shard_map()
    if shard_map is None:
        raise ImportError(
            "this jax build exposes neither jax.shard_map nor "
            "jax.experimental.shard_map (probe with "
            "parallel.sharded.have_shard_map)")

    def local_step(hb, he, hver, floor, lo, hi, rb, re, wb, we, snap, cv):
        # drop the leading length-1 shard axis inside the mapped body
        st = ConflictState(hb[0], he[0], hver[0], floor[0])
        wbm, wem = _mask_writes_to_partition(wb, we, lo[0], hi[0], width)
        st2, verdicts = resolve_core(st, rb, re, wbm, wem, snap, cv,
                                     width=width, window=window)
        verdicts = jax.lax.pmax(verdicts, "resolvers")   # combine across partitions
        return (st2.hb[None], st2.he[None], st2.hver[None],
                st2.floor[None], verdicts)

    sharded = P("resolvers")
    repl = P()
    # replication checking off (check_vma / legacy check_rep): resolve_core
    # is shared with the single-chip jit, so its internals (scan carry) are
    # not annotated with varying manual axes; the pmax guarantees the
    # replicated verdict output is truly replicated.
    fn = shard_map(
        local_step, mesh=mesh,
        in_specs=(sharded, sharded, sharded, sharded, sharded, sharded,
                  repl, repl, repl, repl, repl, repl),
        out_specs=(sharded, sharded, sharded, sharded, repl),
        **rep_kwargs,
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state: ShardedConflictState, rb, re, wb, we, snap, commit_version):
        hb, he, hver, floor, verdicts = fn(
            state.hb, state.he, state.hver, state.floor,
            state.part_lo, state.part_hi, rb, re, wb, we, snap, commit_version)
        return ShardedConflictState(hb, he, hver, floor,
                                    state.part_lo, state.part_hi), verdicts

    return step

