"""foundationdb_tpu — a TPU-native distributed transactional key-value store.

A ground-up rebuild of FoundationDB's capabilities (reference:
wesleypeck/foundationdb, i.e. the apple/foundationdb architecture:
REF:flow/, REF:fdbrpc/, REF:fdbclient/, REF:fdbserver/) designed TPU-first:

- Python/asyncio structured concurrency replaces the Flow actor runtime
  (REF:flow/flow.h ACTOR/Future/Promise), with a deterministic virtual-time
  event loop replacing the Sim2 simulator (REF:fdbrpc/sim2.actor.cpp).
- The OCC conflict-detection data plane (REF:fdbserver/SkipList.cpp,
  REF:fdbserver/Resolver.actor.cpp) is a vectorized JAX interval-overlap
  kernel with persistent on-device state, sharded across TPU cores via
  shard_map for multi-resolver clusters.
- A C++ sorted-structure conflict set (skiplist-analog) provides the CPU
  baseline and a NumPy twin keeps simulation deterministic off-TPU.

Package layout:
  runtime/   L0: event loop, sim, knobs, trace, errors, RNG   (REF:flow/)
  ops/       conflict-detection kernels + key encoding        (REF:fdbserver/SkipList.cpp)
  parallel/  mesh/shard_map multi-resolver partitioning       (REF:fdbserver/Resolver.actor.cpp)
  models/    flagship pipeline models (resolver step)         —
  core/      txn system roles: sequencer/proxy/resolver/storage (REF:fdbserver/)
  rpc/       typed endpoint RPC over asyncio / sim transports (REF:fdbrpc/)
  utils/     tuple & directory layers, misc                   (REF:bindings/python/)
  native/    C++ components (conflict-set baseline, IO)       —
"""

__version__ = "0.1.0"
