"""Deterministic in-memory network — the Sim2 network model.

Reference: REF:fdbrpc/sim2.actor.cpp — simulated message delivery with
seeded random latency, plus fault injection: clogged links (delayed
delivery), partitions (dropped packets → request timeouts), and process
death.  All scheduling flows through the virtual-time loop, so a seed
reproduces every delivery order exactly.
"""

from __future__ import annotations

import asyncio
from typing import Any

from ..runtime.errors import ConnectionFailed, RequestMaybeDelivered, TimedOut
from ..runtime.knobs import Knobs
from ..runtime.rng import deterministic_random
from .transport import Endpoint, NetworkAddress, Transport


class SimNetwork:
    """The shared medium: address → transport, plus link-level faults.
    One per simulation (pass to every SimTransport)."""

    def __init__(self, knobs: Knobs | None = None) -> None:
        self.knobs = knobs or Knobs()
        self.listeners: dict[NetworkAddress, "SimTransport"] = {}
        self._clogged: dict[tuple[NetworkAddress, NetworkAddress], float] = {}
        self._partitioned: set[tuple[NetworkAddress, NetworkAddress]] = set()
        self._dead: set[NetworkAddress] = set()
        self._dead_ips: set[str] = set()
        self._death_event: asyncio.Event | None = None

    def death_event(self) -> asyncio.Event:
        """Set (and replaced) on every kill — lets an in-flight request
        notice its peer's machine died mid-dispatch, the way a real TCP
        connection would reset."""
        if self._death_event is None:
            self._death_event = asyncio.Event()
        return self._death_event

    def _signal_death(self) -> None:
        if self._death_event is not None:
            self._death_event.set()
            self._death_event = None

    # --- fault injection (RandomClogging / partition workloads use these) ---

    def clog_pair(self, a: NetworkAddress, b: NetworkAddress,
                  seconds: float) -> None:
        until = asyncio.get_running_loop().time() + seconds
        for pair in ((a, b), (b, a)):
            self._clogged[pair] = max(self._clogged.get(pair, 0.0), until)

    def partition(self, a: NetworkAddress, b: NetworkAddress) -> None:
        self._partitioned.add((a, b))
        self._partitioned.add((b, a))

    def heal(self, a: NetworkAddress, b: NetworkAddress) -> None:
        self._partitioned.discard((a, b))
        self._partitioned.discard((b, a))

    def kill(self, addr: NetworkAddress) -> None:
        self._dead.add(addr)
        self._signal_death()

    def reboot(self, addr: NetworkAddress) -> None:
        self._dead.discard(addr)

    def kill_ip(self, ip: str) -> None:
        """Machine kill: every endpoint on this IP goes dark — a process's
        server transport AND its outbound client transports (the machine
        model of REF:fdbrpc/sim2.actor.cpp killProcess)."""
        self._dead_ips.add(ip)
        self._signal_death()

    def reboot_ip(self, ip: str) -> None:
        self._dead_ips.discard(ip)

    def is_dead(self, addr: NetworkAddress) -> bool:
        return addr in self._dead or addr.ip in self._dead_ips

    # --- delivery ---

    def _delay(self, src: NetworkAddress, dst: NetworkAddress) -> float | None:
        """Seconds until delivery, or None if the packet is dropped."""
        if ((src, dst) in self._partitioned or self.is_dead(dst)
                or self.is_dead(src)):
            return None
        rng = deterministic_random()
        d = (self.knobs.SIM_NETWORK_MIN_DELAY +
             rng.random() * (self.knobs.SIM_NETWORK_MAX_DELAY
                             - self.knobs.SIM_NETWORK_MIN_DELAY))
        clog_until = self._clogged.get((src, dst), 0.0)
        now = asyncio.get_running_loop().time()
        if clog_until > now:
            d += clog_until - now
        return d


class SimTransport(Transport):
    def __init__(self, network: SimNetwork, address: NetworkAddress) -> None:
        super().__init__(address)
        self.network = network
        network.listeners[address] = self
        self._tasks: set[asyncio.Task] = set()

    async def request(self, endpoint: Endpoint, payload: Any,
                      timeout: float | None = None) -> Any:
        payload = self.attach_span(payload)   # sampled ctx rides the wire
        loop = asyncio.get_running_loop()
        d1 = self.network._delay(self.address, endpoint.address)
        if d1 is None:
            # like a TCP connect failure: the request was definitely not
            # delivered, so callers may retry freely
            await asyncio.sleep(self.network.knobs.CONNECT_TIMEOUT)
            raise ConnectionFailed()
        await asyncio.sleep(d1)
        peer = self.network.listeners.get(endpoint.address)
        if peer is None or self.network.is_dead(endpoint.address):
            raise ConnectionFailed()
        # dispatch, but notice if either machine dies mid-call: the real
        # network would reset the connection; without this, a handler
        # whose process was killed leaves the caller awaiting forever
        dispatch = asyncio.ensure_future(
            peer.dispatcher.dispatch(endpoint.token, payload))
        while True:
            death = self.network.death_event()
            waiter = asyncio.ensure_future(death.wait())
            done, _ = await asyncio.wait(
                {dispatch, waiter}, return_when=asyncio.FIRST_COMPLETED)
            waiter.cancel()
            if dispatch in done:
                break
            if (self.network.is_dead(endpoint.address)
                    or self.network.is_dead(self.address)):
                dispatch.cancel()
                await asyncio.gather(dispatch, return_exceptions=True)
                await asyncio.sleep(self.network.knobs.CONNECT_TIMEOUT)
                raise RequestMaybeDelivered()
        ok, reply = dispatch.result()
        d2 = self.network._delay(endpoint.address, self.address)
        if d2 is None:
            # executed remotely but the reply was lost: ambiguous outcome
            await asyncio.sleep(self.network.knobs.CONNECT_TIMEOUT)
            raise RequestMaybeDelivered()
        await asyncio.sleep(d2)
        if not ok:
            Transport.raise_remote_error(reply)
        return reply

    def one_way(self, endpoint: Endpoint, payload: Any) -> None:
        payload = self.attach_span(payload)

        async def deliver():
            d = self.network._delay(self.address, endpoint.address)
            if d is None:
                return
            await asyncio.sleep(d)
            peer = self.network.listeners.get(endpoint.address)
            if peer is not None and not self.network.is_dead(endpoint.address):
                await peer.dispatcher.dispatch(endpoint.token, payload)
        t = asyncio.get_running_loop().create_task(deliver(), name="sim-oneway")
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)

    async def close(self) -> None:
        self.network.listeners.pop(self.address, None)
        for t in list(self._tasks):
            t.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
