"""Self-describing binary wire codec.

Reference: REF:flow/serialize.h + ObjectSerializer/flat_buffers — FDB
serializes RPC structs into a tagged binary format so old/new versions can
interoperate.  This codec is deliberately simple and deterministic:
tag byte + payload, varints for ints, length-prefixed bytes, and a
registry for dataclass "structs" (encoded as tag + registry id + field
list).  numpy arrays are supported for the resolver batch path (dtype
string + shape + raw bytes, C-order).

Not pickle: no code execution on decode, stable across processes, and
implementable from C++ for the native bridge.
"""

from __future__ import annotations

import dataclasses
import enum
import struct as _struct
from typing import Any, Type

import numpy as np

# tags
_NONE, _FALSE, _TRUE, _INT, _NEGINT, _BYTES, _STR, _LIST, _TUPLE, _DICT, \
    _STRUCT, _FLOAT, _NDARRAY, _ENUM = range(14)

_STRUCTS: dict[int, Type] = {}
_STRUCT_IDS: dict[Type, int] = {}
_ENUMS: dict[int, Type] = {}
_ENUM_IDS: dict[Type, int] = {}


def register_struct(cls: Type, *, sid: int | None = None) -> Type:
    """Register a dataclass for wire encoding.  Ids are assigned in
    registration order; both sides must register the same structs in the
    same order (they share the module that defines them)."""
    i = sid if sid is not None else len(_STRUCTS)
    assert i not in _STRUCTS, f"struct id {i} taken"
    _STRUCTS[i] = cls
    _STRUCT_IDS[cls] = i
    return cls


def register_enum(cls: Type, *, eid: int | None = None) -> Type:
    i = eid if eid is not None else len(_ENUMS)
    _ENUMS[i] = cls
    _ENUM_IDS[cls] = i
    return cls


def _put_varint(out: bytearray, n: int) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _get_varint(buf: memoryview, pos: int) -> tuple[int, int]:
    n = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not (b & 0x80):
            return n, pos
        shift += 7


def _enc(out: bytearray, obj: Any) -> None:
    if obj is None:
        out.append(_NONE)
    elif obj is False:
        out.append(_FALSE)
    elif obj is True:
        out.append(_TRUE)
    elif isinstance(obj, enum.Enum):
        out.append(_ENUM)
        _put_varint(out, _ENUM_IDS[type(obj)])
        _put_varint(out, obj.value)
    elif isinstance(obj, int):
        if obj >= 0:
            out.append(_INT)
            _put_varint(out, obj)
        else:
            out.append(_NEGINT)
            _put_varint(out, -obj)
    elif isinstance(obj, float):
        out.append(_FLOAT)
        out += _struct.pack("<d", obj)
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        out.append(_BYTES)
        b = bytes(obj)
        _put_varint(out, len(b))
        out += b
    elif isinstance(obj, str):
        out.append(_STR)
        b = obj.encode("utf-8")
        _put_varint(out, len(b))
        out += b
    elif isinstance(obj, list):
        out.append(_LIST)
        _put_varint(out, len(obj))
        for x in obj:
            _enc(out, x)
    elif isinstance(obj, tuple):
        out.append(_TUPLE)
        _put_varint(out, len(obj))
        for x in obj:
            _enc(out, x)
    elif isinstance(obj, dict):
        out.append(_DICT)
        _put_varint(out, len(obj))
        for k, v in obj.items():
            _enc(out, k)
            _enc(out, v)
    elif isinstance(obj, np.ndarray):
        out.append(_NDARRAY)
        dt = obj.dtype.str.encode()
        _put_varint(out, len(dt))
        out += dt
        _put_varint(out, obj.ndim)
        for d in obj.shape:
            _put_varint(out, d)
        b = np.ascontiguousarray(obj).tobytes()
        _put_varint(out, len(b))
        out += b
    elif dataclasses.is_dataclass(obj) and type(obj) in _STRUCT_IDS:
        out.append(_STRUCT)
        _put_varint(out, _STRUCT_IDS[type(obj)])
        fields = dataclasses.fields(obj)
        _put_varint(out, len(fields))
        for f in fields:
            _enc(out, getattr(obj, f.name))
    else:
        raise TypeError(f"cannot encode {type(obj)}")


def encode(obj: Any) -> bytes:
    out = bytearray()
    _enc(out, obj)
    return bytes(out)


def _dec(buf: memoryview, pos: int) -> tuple[Any, int]:
    tag = buf[pos]
    pos += 1
    if tag == _NONE:
        return None, pos
    if tag == _FALSE:
        return False, pos
    if tag == _TRUE:
        return True, pos
    if tag == _INT:
        return _get_varint(buf, pos)
    if tag == _NEGINT:
        n, pos = _get_varint(buf, pos)
        return -n, pos
    if tag == _FLOAT:
        return _struct.unpack_from("<d", buf, pos)[0], pos + 8
    if tag == _BYTES:
        n, pos = _get_varint(buf, pos)
        return bytes(buf[pos:pos + n]), pos + n
    if tag == _STR:
        n, pos = _get_varint(buf, pos)
        return bytes(buf[pos:pos + n]).decode("utf-8"), pos + n
    if tag in (_LIST, _TUPLE):
        n, pos = _get_varint(buf, pos)
        items = []
        for _ in range(n):
            x, pos = _dec(buf, pos)
            items.append(x)
        return (items if tag == _LIST else tuple(items)), pos
    if tag == _DICT:
        n, pos = _get_varint(buf, pos)
        d = {}
        for _ in range(n):
            k, pos = _dec(buf, pos)
            v, pos = _dec(buf, pos)
            d[k] = v
        return d, pos
    if tag == _NDARRAY:
        n, pos = _get_varint(buf, pos)
        dt = np.dtype(bytes(buf[pos:pos + n]).decode())
        pos += n
        ndim, pos = _get_varint(buf, pos)
        shape = []
        for _ in range(ndim):
            d, pos = _get_varint(buf, pos)
            shape.append(d)
        n, pos = _get_varint(buf, pos)
        arr = np.frombuffer(bytes(buf[pos:pos + n]), dtype=dt).reshape(shape)
        return arr, pos + n
    if tag == _ENUM:
        eid, pos = _get_varint(buf, pos)
        val, pos = _get_varint(buf, pos)
        return _ENUMS[eid](val), pos
    if tag == _STRUCT:
        sid, pos = _get_varint(buf, pos)
        cls = _STRUCTS[sid]
        n, pos = _get_varint(buf, pos)
        vals = []
        for _ in range(n):
            v, pos = _dec(buf, pos)
            vals.append(v)
        return cls(*vals), pos
    raise ValueError(f"bad tag {tag} at {pos - 1}")


_FRAME_HDR = _struct.Struct("<II")      # payload length, crc32(payload)


def frame(payload: bytes) -> bytes:
    """crc32-stamp one payload: [u32 len][u32 crc][payload] — the shared
    torn-write detector for single-blob durable files (engine snapshots,
    manifests, commit headers; ISSUE 12).  A torn or corrupted write
    fails ``unframe`` instead of decoding into garbage."""
    import zlib
    return _FRAME_HDR.pack(len(payload), zlib.crc32(payload)) + payload


def unframe(raw: bytes) -> bytes:
    """Inverse of ``frame``; raises ValueError on a short or corrupt
    frame (callers map that to their torn-vs-corrupt policy)."""
    import zlib
    if len(raw) < _FRAME_HDR.size:
        raise ValueError("short frame")
    length, crc = _FRAME_HDR.unpack_from(raw)
    payload = raw[_FRAME_HDR.size:_FRAME_HDR.size + length]
    if len(payload) != length or zlib.crc32(payload) != crc:
        raise ValueError("frame crc mismatch")
    return payload


def decode(data: bytes) -> Any:
    obj, pos = _dec(memoryview(data), 0)
    if pos != len(data):
        raise ValueError(f"trailing {len(data) - pos} bytes")
    return obj


def _register_core_structs() -> None:
    """Register the shared RPC structs in one canonical order."""
    from ..core import change_feed as cf
    from ..core import data as d
    from ..core import resolver as r
    from ..core import tlog as t
    from ..ops import batch as b
    from ..runtime import span as sp
    register_enum(d.MutationType, eid=0)
    for i, cls in enumerate([
        d.Mutation, d.KeyRange, d.KeySelector, d.CommitTransactionRequest,
        d.CommitResult, b.TxnRequest, r.ResolveBatchRequest,
        r.ResolveBatchReply, t.TLogPushRequest, t.TLogPeekReply,
        sp.SpanEnvelope, d.MutationBatch,
        cf.ChangeFeedStreamRequest, cf.ChangeFeedStreamReply,
        d.GetValuesRequest, d.GetValuesReply,
        d.GetRangeRequest, d.GetRangeReply,
        d.GetKeyRequest, d.GetKeyReply,
    ]):
        register_struct(cls, sid=i)


_register_core_structs()
