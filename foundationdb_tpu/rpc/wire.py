"""Self-describing binary wire codec.

Reference: REF:flow/serialize.h + ObjectSerializer/flat_buffers — FDB
serializes RPC structs into a tagged binary format so old/new versions can
interoperate.  This codec is deliberately simple and deterministic:
tag byte + payload, varints for ints, length-prefixed bytes, and a
registry for dataclass "structs" (encoded as tag + registry id + field
list).  numpy arrays are supported for the resolver batch path (dtype
string + shape + raw bytes, C-order).

Not pickle: no code execution on decode, stable across processes, and
implementable from C++ for the native bridge.
"""

from __future__ import annotations

import dataclasses
import enum
import struct as _struct
from typing import Any, Type

import numpy as np

# tags
_NONE, _FALSE, _TRUE, _INT, _NEGINT, _BYTES, _STR, _LIST, _TUPLE, _DICT, \
    _STRUCT, _FLOAT, _NDARRAY, _ENUM = range(14)

_STRUCTS: dict[int, Type] = {}
_STRUCT_IDS: dict[Type, int] = {}
_ENUMS: dict[int, Type] = {}
_ENUM_IDS: dict[Type, int] = {}


def register_struct(cls: Type, *, sid: int | None = None) -> Type:
    """Register a dataclass for wire encoding.  Ids are assigned in
    registration order; both sides must register the same structs in the
    same order (they share the module that defines them)."""
    i = sid if sid is not None else len(_STRUCTS)
    assert i not in _STRUCTS, f"struct id {i} taken"
    _STRUCTS[i] = cls
    _STRUCT_IDS[cls] = i
    return cls


def register_enum(cls: Type, *, eid: int | None = None) -> Type:
    i = eid if eid is not None else len(_ENUMS)
    _ENUMS[i] = cls
    _ENUM_IDS[cls] = i
    return cls


def _put_varint(out: bytearray, n: int) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _get_varint(buf: memoryview, pos: int) -> tuple[int, int]:
    n = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not (b & 0x80):
            return n, pos
        shift += 7


def _enc(out: bytearray, obj: Any) -> None:
    if obj is None:
        out.append(_NONE)
    elif obj is False:
        out.append(_FALSE)
    elif obj is True:
        out.append(_TRUE)
    elif isinstance(obj, enum.Enum):
        out.append(_ENUM)
        _put_varint(out, _ENUM_IDS[type(obj)])
        _put_varint(out, obj.value)
    elif isinstance(obj, int):
        if obj >= 0:
            out.append(_INT)
            _put_varint(out, obj)
        else:
            out.append(_NEGINT)
            _put_varint(out, -obj)
    elif isinstance(obj, float):
        out.append(_FLOAT)
        out += _struct.pack("<d", obj)
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        out.append(_BYTES)
        b = bytes(obj)
        _put_varint(out, len(b))
        out += b
    elif isinstance(obj, str):
        out.append(_STR)
        b = obj.encode("utf-8")
        _put_varint(out, len(b))
        out += b
    elif isinstance(obj, list):
        out.append(_LIST)
        _put_varint(out, len(obj))
        for x in obj:
            _enc(out, x)
    elif isinstance(obj, tuple):
        out.append(_TUPLE)
        _put_varint(out, len(obj))
        for x in obj:
            _enc(out, x)
    elif isinstance(obj, dict):
        out.append(_DICT)
        _put_varint(out, len(obj))
        for k, v in obj.items():
            _enc(out, k)
            _enc(out, v)
    elif isinstance(obj, np.ndarray):
        out.append(_NDARRAY)
        dt = obj.dtype.str.encode()
        _put_varint(out, len(dt))
        out += dt
        _put_varint(out, obj.ndim)
        for d in obj.shape:
            _put_varint(out, d)
        b = np.ascontiguousarray(obj).tobytes()
        _put_varint(out, len(b))
        out += b
    elif dataclasses.is_dataclass(obj) and type(obj) in _STRUCT_IDS:
        out.append(_STRUCT)
        _put_varint(out, _STRUCT_IDS[type(obj)])
        fields = dataclasses.fields(obj)
        _put_varint(out, len(fields))
        for f in fields:
            _enc(out, getattr(obj, f.name))
    else:
        raise TypeError(f"cannot encode {type(obj)}")


def encode(obj: Any) -> bytes:
    out = bytearray()
    _enc(out, obj)
    return bytes(out)


def _dec(buf: memoryview, pos: int) -> tuple[Any, int]:
    tag = buf[pos]
    pos += 1
    if tag == _NONE:
        return None, pos
    if tag == _FALSE:
        return False, pos
    if tag == _TRUE:
        return True, pos
    if tag == _INT:
        return _get_varint(buf, pos)
    if tag == _NEGINT:
        n, pos = _get_varint(buf, pos)
        return -n, pos
    if tag == _FLOAT:
        return _struct.unpack_from("<d", buf, pos)[0], pos + 8
    if tag == _BYTES:
        n, pos = _get_varint(buf, pos)
        return bytes(buf[pos:pos + n]), pos + n
    if tag == _STR:
        n, pos = _get_varint(buf, pos)
        return bytes(buf[pos:pos + n]).decode("utf-8"), pos + n
    if tag in (_LIST, _TUPLE):
        n, pos = _get_varint(buf, pos)
        items = []
        for _ in range(n):
            x, pos = _dec(buf, pos)
            items.append(x)
        return (items if tag == _LIST else tuple(items)), pos
    if tag == _DICT:
        n, pos = _get_varint(buf, pos)
        d = {}
        for _ in range(n):
            k, pos = _dec(buf, pos)
            v, pos = _dec(buf, pos)
            d[k] = v
        return d, pos
    if tag == _NDARRAY:
        n, pos = _get_varint(buf, pos)
        dt = np.dtype(bytes(buf[pos:pos + n]).decode())
        pos += n
        ndim, pos = _get_varint(buf, pos)
        shape = []
        for _ in range(ndim):
            d, pos = _get_varint(buf, pos)
            shape.append(d)
        n, pos = _get_varint(buf, pos)
        arr = np.frombuffer(bytes(buf[pos:pos + n]), dtype=dt).reshape(shape)
        return arr, pos + n
    if tag == _ENUM:
        eid, pos = _get_varint(buf, pos)
        val, pos = _get_varint(buf, pos)
        return _ENUMS[eid](val), pos
    if tag == _STRUCT:
        sid, pos = _get_varint(buf, pos)
        cls = _STRUCTS[sid]
        n, pos = _get_varint(buf, pos)
        vals = []
        for _ in range(n):
            v, pos = _dec(buf, pos)
            vals.append(v)
        return cls(*vals), pos
    raise ValueError(f"bad tag {tag} at {pos - 1}")


_FRAME_HDR = _struct.Struct("<II")      # payload length, crc32(payload)


def frame(payload: bytes) -> bytes:
    """crc32-stamp one payload: [u32 len][u32 crc][payload] — the shared
    torn-write detector for single-blob durable files (engine snapshots,
    manifests, commit headers; ISSUE 12).  A torn or corrupted write
    fails ``unframe`` instead of decoding into garbage."""
    import zlib
    return _FRAME_HDR.pack(len(payload), zlib.crc32(payload)) + payload


def unframe(raw: bytes) -> bytes:
    """Inverse of ``frame``; raises ValueError on a short or corrupt
    frame (callers map that to their torn-vs-corrupt policy)."""
    import zlib
    if len(raw) < _FRAME_HDR.size:
        raise ValueError("short frame")
    length, crc = _FRAME_HDR.unpack_from(raw)
    payload = raw[_FRAME_HDR.size:_FRAME_HDR.size + length]
    if len(payload) != length or zlib.crc32(payload) != crc:
        raise ValueError("frame crc mismatch")
    return payload


def decode(data: bytes) -> Any:
    obj, pos = _dec(memoryview(data), 0)
    if pos != len(data):
        raise ValueError(f"trailing {len(data) - pos} bytes")
    return obj


class SlottedBlob:
    """Dual-slot crc-framed single-blob persistence — THE one audited
    corruption policy for small durable state files (ISSUE 13, ROADMAP
    6 (f)): the lsm MANIFEST, the coordinator state, and the backup
    ``logs.manifest`` each hand-rolled the same discipline three times.

    Invariants (the DiskQueue ``_write_header`` discipline):

    - writes ALTERNATE between two slot files, so the slot not being
      written always holds the previous synced payload — a kill tearing
      one write can never destroy the committed state;
    - the sequence number advances only AFTER the write+sync, so a
      failed (retried) save re-targets the SAME slot, never the one
      holding the freshest synced state;
    - ``load`` returns the highest-seq slot that passes its crc frame; a
      torn slot silently loses to the intact one.  What to do when
      slots exist but NONE decodes is the CALLER's policy (each site
      raises its own corruption class with its own evidence rule) —
      ``slots_seen`` carries the evidence.

    The payload travels as ``frame(MAGIC + seq_u64_le + payload)``, so
    the seq lives inside the crc envelope and sites no longer embed
    their own copy.  The magic makes the envelope self-identifying: a
    pre-helper slot (``frame(encode(dict))`` — encode output always
    leads with a type tag < 14, never an ASCII 'S') also passes
    ``unframe``, and without the magic its first 8 content bytes would
    parse as a garbage seq and the mis-sliced remainder would be
    returned as a "valid" payload — crashing every caller's decode AND
    making their legacy-format fallbacks unreachable.  Callers own
    serialization of concurrent saves (two in-flight saves could
    otherwise dirty BOTH slots at once)."""

    MAGIC = b"SBv1"

    def __init__(self, fs, base: str,
                 suffixes: tuple[str, str] = (".a", ".b")) -> None:
        self.fs = fs
        self.base = base
        self.suffixes = suffixes
        self._seq: int | None = None    # lazily learned from load

    def _slot(self, seq: int) -> str:
        return self.base + self.suffixes[0 if seq % 2 else 1]

    def seed(self, seq: int) -> None:
        """Arm the save sequence from a LEGACY-format slot's embedded
        seq (the envelope-migration path): keeps the alternation parity
        continuous so the next save never targets the only valid
        old-format slot."""
        self._seq = seq

    async def load(self) -> tuple[bytes | None, int]:
        """(newest valid payload or None, slot files seen).  Also arms
        the save sequence, so load-before-first-save is the expected
        lifecycle (a never-loaded save starts at seq 1)."""
        best: bytes | None = None
        best_seq = 0
        seen = 0
        for suffix in self.suffixes:
            f = self.fs.open(self.base + suffix)
            try:
                raw = await f.read(0, f.size())
            finally:
                await f.close()
            if not raw:
                continue
            seen += 1
            try:
                payload = unframe(raw)
                if not payload.startswith(self.MAGIC):
                    # a pre-helper-format slot (or foreign frame): not
                    # ours to parse — the caller's legacy fallback owns
                    # it, and it still counts as evidence in ``seen``
                    continue
                m = len(self.MAGIC)
                seq = int.from_bytes(payload[m:m + 8], "little")
                body = payload[m + 8:]
            except Exception:   # noqa: BLE001 — torn slot: other one wins
                continue
            if best is None or seq > best_seq:
                best, best_seq = body, seq
        if self._seq is None or best_seq > self._seq:
            self._seq = best_seq
        return best, seen

    async def save(self, payload: bytes) -> int:
        """Write ``payload`` into the next slot; returns the new seq."""
        if self._seq is None:
            await self.load()
        seq = (self._seq or 0) + 1
        f = self.fs.open(self._slot(seq))
        blob = frame(self.MAGIC + seq.to_bytes(8, "little") + payload)
        try:
            # a faulted disk op must not leak the handle — persist
            # retries on a sick disk (PR 11's erroring-disk chaos)
            # would otherwise exhaust fds one per attempt
            await f.write(0, blob)
            await f.truncate(len(blob))
            await f.sync()
        finally:
            await f.close()
        self._seq = seq
        return seq


def _register_core_structs() -> None:
    """Register the shared RPC structs in one canonical order."""
    from ..core import change_feed as cf
    from ..core import data as d
    from ..core import resolver as r
    from ..core import tlog as t
    from ..ops import batch as b
    from ..runtime import span as sp
    register_enum(d.MutationType, eid=0)
    for i, cls in enumerate([
        d.Mutation, d.KeyRange, d.KeySelector, d.CommitTransactionRequest,
        d.CommitResult, b.TxnRequest, r.ResolveBatchRequest,
        r.ResolveBatchReply, t.TLogPushRequest, t.TLogPeekReply,
        sp.SpanEnvelope, d.MutationBatch,
        cf.ChangeFeedStreamRequest, cf.ChangeFeedStreamReply,
        d.GetValuesRequest, d.GetValuesReply,
        d.GetRangeRequest, d.GetRangeReply,
        d.GetKeyRequest, d.GetKeyReply,
        d.ScrubPageRequest, d.ScrubPageReply,
    ]):
        register_struct(cls, sid=i)


_register_core_structs()
