"""Role RPC stubs: serve a role object over a transport + client proxies.

Reference: REF:fdbrpc/fdbrpc.h — a role interface struct is a bundle of
RequestStreams at consecutive tokens; a client holding the struct calls
typed endpoints.  Here each role instance owns a token block on its
transport; the client proxy mirrors the in-process role's async surface,
so pipeline code (commit proxy, Transaction) cannot tell a stub from a
local object — the property that let the reference run identical role
code in sim and production.
"""

from __future__ import annotations

import asyncio
from typing import Any

from ..core.data import KeyRange
from ..runtime.span import SpanSink, current_span
from .transport import Endpoint, NetworkAddress, Transport

# method table per role: (name, oneway?)
ROLE_METHODS: dict[str, list[tuple[str, bool]]] = {
    # metrics appended LAST (ISSUE 15): token layout is base+index, so
    # new methods must never reorder existing slots
    "sequencer": [("get_commit_version", False),
                  ("get_live_committed_version", False),
                  ("report_committed", True), ("lock", False),
                  ("report_lock", True), ("metrics", False)],
    "resolver": [("resolve", False), ("metrics", False)],
    "tlog": [("push", False), ("peek", False), ("pop", True),
             ("lock", False), ("metrics", False)],
    # change-feed methods appended at 713, get_values at 714,
    # shard_metrics with the shard-heat subsystem, get_key_values_packed
    # at 715, get_key at 716, scrub_page at 718 — always LAST: token
    # layout is base+index, so new methods must never reorder existing
    # slots
    "storage": [("get_value", False), ("get_key_values", False),
                ("watch_value", False), ("metrics", False),
                ("get_latest_range", False), ("sample_split_key", False),
                ("change_feed_stream", False), ("fetch_feed_state", False),
                ("get_values", False), ("shard_metrics", False),
                ("get_key_values_packed", False), ("get_key", False),
                ("scrub_page", False)],
    # metrics appended LAST: token layout is base+index, so new methods
    # must never reorder existing slots
    "commit_proxy": [("commit", False), ("metrics", False)],
    "grv_proxy": [("get_read_version", False), ("metrics", False)],
    # metrics appended LAST (ISSUE 15)
    "ratekeeper": [("admit", False), ("get_rate", False),
                   ("get_throttle", False), ("set_tag_throttle", False),
                   ("metrics", False)],
    "coordinator": [("read", False), ("write", False),
                    ("nominate", False), ("confirm", False),
                    ("withdraw", False), ("leader_heartbeat", False),
                    ("open_database", False), ("read_leader", False),
                    ("move", False), ("get_forward", False)],
    # disk_health appended LAST (ISSUE 12): token layout is base+index,
    # so new methods must never reorder existing slots
    "worker": [("recruit", False), ("stop_role", False),
               ("rejoin_storage", False), ("list_roles", False),
               ("disk_health", False)],
    "cluster_controller": [("register_worker", False),
                           ("get_cluster_state", False)],
    "log_router": [("peek", False), ("pop", True), ("metrics", False)],
}

TOKEN_BLOCK = 16  # tokens reserved per role instance

# wire-level receive events for sampled requests: one per dispatched RPC,
# timestamping the server-side arrival of each hop so the trace analyzer
# can split client-observed latency into network/queue vs service time
_RPC_SPANS = SpanSink("rpc")


def serve_role(transport: Transport, role: str, obj: Any,
               base_token: int) -> None:
    """Register obj's role methods at base_token + method index, plus a
    role-liveness ping at the block's LAST token (base + TOKEN_BLOCK-1).
    The ping answers only while THIS role instance is registered — a
    process that crashed and was respawned by its supervisor answers
    address-level pings fine while its recruited role endpoints are
    gone; the cluster controller probes this slot to tell the two
    apart (the reference's waitFailureClient on role interfaces)."""
    for i, (name, _oneway) in enumerate(ROLE_METHODS[role]):
        method = getattr(obj, name)

        async def handler(args, method=method, loc=f"{role}.{name}"):
            ctx = current_span()
            if ctx is not None and ctx.sampled:
                _RPC_SPANS.event("RpcDebug", ctx, loc)
            result = method(*args)
            if asyncio.iscoroutine(result):
                result = await result
            return result
        transport.dispatcher.register(handler, token=base_token + i)

    async def role_ping(_args, role=role, obj=obj):
        # a fail-stopped role instance (resolver poison, proxy
        # unrepairable batch) must probe DEAD even though its process —
        # and this handler — are alive: the CC's role-liveness probe is
        # what converts the fail-stop into an epoch recovery
        if getattr(obj, "_failed", None) is not None \
                or getattr(obj, "_poisoned", None) is not None:
            from ..runtime.errors import EndpointNotFound
            raise EndpointNotFound()
        return role
    ping_token = base_token + TOKEN_BLOCK - 1
    # static layouts (worker block + CC surface sharing one block) may
    # overlap; the probe only targets RECRUITED role blocks, which are
    # always distinct
    if ping_token not in transport.dispatcher._handlers:
        transport.dispatcher.register(role_ping, token=ping_token)


class RoleClient:
    """Generic client proxy; subclasses pin the role name and add the
    static attributes pipeline code reads (shard, tag, key_range)."""

    role: str = ""

    def __init__(self, transport: Transport, address: NetworkAddress,
                 base_token: int) -> None:
        self._transport = transport
        self._address = address
        self._base = base_token
        for i, (name, oneway) in enumerate(ROLE_METHODS[self.role]):
            ep = Endpoint(address, base_token + i)
            if oneway:
                setattr(self, name, self._make_oneway(ep))
            else:
                setattr(self, name, self._make_call(ep))

    def _make_call(self, ep: Endpoint):
        async def call(*args):
            return await self._transport.request(ep, list(args))
        return call

    def _make_oneway(self, ep: Endpoint):
        def send(*args):
            self._transport.one_way(ep, list(args))
        return send


class SequencerClient(RoleClient):
    role = "sequencer"


class ResolverClient(RoleClient):
    role = "resolver"

    def __init__(self, transport, address, base_token, key_range: KeyRange):
        super().__init__(transport, address, base_token)
        self.key_range = key_range


class TLogClient(RoleClient):
    role = "tlog"


class StorageClient(RoleClient):
    role = "storage"

    def __init__(self, transport, address, base_token, tag: int,
                 shard: KeyRange):
        super().__init__(transport, address, base_token)
        self.tag = tag
        self.shard = shard


class CommitProxyClient(RoleClient):
    role = "commit_proxy"


class RatekeeperClient(RoleClient):
    role = "ratekeeper"


class ClusterControllerClient(RoleClient):
    role = "cluster_controller"


class GrvProxyClient(RoleClient):
    role = "grv_proxy"


class CoordinatorClient(RoleClient):
    role = "coordinator"


def make_coordinator_stubs(addrs, transport=None, transport_factory=None,
                           token=None):
    """Build CoordinatorClients from wire-shaped ([ip, port]) or
    NetworkAddress addresses — the ONE home of the address normalization
    every quorum-change site needs.  Pass either a shared ``transport``
    or a per-stub ``transport_factory``."""
    from .transport import WLTOKEN_COORDINATOR, NetworkAddress
    token = WLTOKEN_COORDINATOR if token is None else token
    out = []
    for a in addrs:
        na = NetworkAddress(a[0], a[1]) if isinstance(a, (list, tuple)) else a
        t = transport if transport is not None else transport_factory()
        out.append(CoordinatorClient(t, na, token))
    return out


class LogRouterClient(RoleClient):
    role = "log_router"


class WorkerClient(RoleClient):
    role = "worker"
