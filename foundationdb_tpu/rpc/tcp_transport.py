"""Real asyncio TCP transport with packet framing (optionally over TLS).

Reference: REF:fdbrpc/FlowTransport.actor.cpp + REF:fdbrpc/TLSConnection —
persistent connections per peer, length-prefixed packets with a checksum,
automatic reconnect.  With a ``TlsConfig`` every listener requires client
certificates and every outbound connection verifies the peer against the
shared CA (mutual TLS, the reference's fdb_tls_* model).
Frame: [u32 len][u32 crc32][u64 token][u64 reply_id][u8 kind][payload].
kind: 0=request, 1=reply-ok, 2=reply-error (payload = varint error code),
3=one-way.
"""

from __future__ import annotations

import asyncio
import dataclasses
import itertools
import ssl as ssl_mod
import struct
import zlib
from typing import Any

from ..runtime.errors import ConnectionFailed, RequestMaybeDelivered
from .transport import Endpoint, NetworkAddress, Transport
from .wire import decode, encode

_HDR = struct.Struct("<IIQQB")


@dataclasses.dataclass
class TlsConfig:
    """Mutual-TLS material (fdb_tls_certificate_file/_key_file/_ca_file)."""
    cert_file: str
    key_file: str
    ca_file: str
    verify_hostname: bool = False    # clusters dial IPs; identity = the CA

    def server_context(self) -> ssl_mod.SSLContext:
        ctx = ssl_mod.SSLContext(ssl_mod.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.cert_file, self.key_file)
        ctx.load_verify_locations(self.ca_file)
        ctx.verify_mode = ssl_mod.CERT_REQUIRED
        return ctx

    def client_context(self) -> ssl_mod.SSLContext:
        ctx = ssl_mod.SSLContext(ssl_mod.PROTOCOL_TLS_CLIENT)
        ctx.load_cert_chain(self.cert_file, self.key_file)
        ctx.load_verify_locations(self.ca_file)
        ctx.check_hostname = self.verify_hostname
        return ctx


class _Peer:
    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter) -> None:
        self.reader = reader
        self.writer = writer
        # in-flight requests *to this peer*; a peer failure must only fail
        # its own requests, never those pending on other connections
        self.pending: dict[int, asyncio.Future] = {}


class TcpTransport(Transport):
    def __init__(self, address: NetworkAddress,
                 tls: TlsConfig | None = None) -> None:
        super().__init__(address)
        self.tls = tls
        self._server: asyncio.AbstractServer | None = None
        self._peers: dict[NetworkAddress, _Peer] = {}
        self._reply_ids = itertools.count(1)
        self._tasks: set[asyncio.Task] = set()

    async def listen(self) -> None:
        self._server = await asyncio.start_server(
            self._on_connection, self.address.ip, self.address.port,
            ssl=self.tls.server_context() if self.tls else None)

    async def _on_connection(self, reader, writer) -> None:
        await self._read_loop(_Peer(reader, writer), None)

    def _spawn(self, coro, name: str) -> None:
        t = asyncio.get_running_loop().create_task(coro, name=name)
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)

    async def _get_peer(self, addr: NetworkAddress) -> _Peer:
        peer = self._peers.get(addr)
        if peer is not None and not peer.writer.is_closing():
            return peer
        try:
            reader, writer = await asyncio.open_connection(
                addr.ip, addr.port,
                ssl=self.tls.client_context() if self.tls else None,
                server_hostname=addr.ip if self.tls
                and self.tls.verify_hostname else None)
        except (OSError, ssl_mod.SSLError) as e:
            raise ConnectionFailed(str(e)) from None
        peer = _Peer(reader, writer)
        self._peers[addr] = peer
        self._spawn(self._read_loop(peer, addr), f"tcp-read-{addr}")
        return peer

    @staticmethod
    def _frame(token: int, reply_id: int, kind: int, payload: bytes) -> bytes:
        crc = zlib.crc32(payload)
        return _HDR.pack(len(payload), crc, token, reply_id, kind) + payload

    async def _read_loop(self, peer: _Peer, addr: NetworkAddress | None) -> None:
        try:
            while True:
                hdr = await peer.reader.readexactly(_HDR.size)
                ln, crc, token, reply_id, kind = _HDR.unpack(hdr)
                payload = await peer.reader.readexactly(ln)
                if zlib.crc32(payload) != crc:
                    raise ConnectionError("checksum mismatch")
                if kind == 0:        # request
                    self._spawn(self._serve(peer, token, reply_id, payload),
                                "tcp-serve")
                elif kind == 3:      # one-way
                    self._spawn(self._serve(peer, token, 0, payload),
                                "tcp-oneway-serve")
                else:                # reply
                    fut = peer.pending.pop(reply_id, None)
                    if fut is not None and not fut.done():
                        if kind == 1:
                            fut.set_result(decode(payload))
                        else:
                            code = decode(payload)
                            fut.set_exception(ConnectionFailed()
                                              if not isinstance(code, int)
                                              else _remote_error(code))
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            if addr is not None and self._peers.get(addr) is peer:
                self._peers.pop(addr, None)
            peer.writer.close()
            # fail this peer's requests: they will never be answered and
            # we cannot know whether the peer executed them
            for fut in peer.pending.values():
                if not fut.done():
                    fut.set_exception(RequestMaybeDelivered())
            peer.pending.clear()

    async def _serve(self, peer: _Peer, token: int, reply_id: int,
                     payload: bytes) -> None:
        # any failure (bad payload, handler bug) must still produce an
        # error reply or the caller's future hangs forever
        try:
            ok, reply = await self.dispatcher.dispatch(token, decode(payload))
        except Exception:
            ok, reply = False, 1000  # operation_failed
        if reply_id == 0:
            return
        kind = 1 if ok else 2
        try:
            peer.writer.write(self._frame(token, reply_id, kind, encode(reply)))
            await peer.writer.drain()
        except (ConnectionError, OSError):
            pass

    async def request(self, endpoint: Endpoint, payload: Any,
                      timeout: float | None = None) -> Any:
        payload = self.attach_span(payload)   # sampled ctx rides the frame
        peer = await self._get_peer(endpoint.address)
        reply_id = next(self._reply_ids)
        fut = asyncio.get_running_loop().create_future()
        peer.pending[reply_id] = fut
        try:
            peer.writer.write(self._frame(endpoint.token, reply_id, 0,
                                          encode(payload)))
            await peer.writer.drain()
        except (ConnectionError, OSError):
            peer.pending.pop(reply_id, None)
            raise ConnectionFailed() from None
        if timeout is not None:
            return await asyncio.wait_for(fut, timeout)
        return await fut

    def one_way(self, endpoint: Endpoint, payload: Any) -> None:
        payload = self.attach_span(payload)

        async def go():
            try:
                peer = await self._get_peer(endpoint.address)
                peer.writer.write(self._frame(endpoint.token, 0, 3,
                                              encode(payload)))
                await peer.writer.drain()
            except (ConnectionFailed, ConnectionError, OSError):
                pass
        self._spawn(go(), "tcp-oneway")

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for peer in self._peers.values():
            peer.writer.close()
        self._peers.clear()
        for t in list(self._tasks):
            t.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)


def _remote_error(code: int):
    from ..runtime.errors import error_from_code
    return error_from_code(code)
