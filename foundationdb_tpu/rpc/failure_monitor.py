"""FailureMonitor — liveness tracking for remote endpoints.

Reference: REF:fdbrpc/FailureMonitor.actor.cpp (SimpleFailureMonitor /
FailureStatus) — every process tracks, per peer address, whether the peer
is currently believed reachable; actors block on state transitions
(``onStateChanged``, ``onFailedFor``) instead of inventing their own retry
timers.  The cluster controller uses it to decide a role is dead and
trigger recovery; load balancing skips failed replicas.

Detection here is active pinging over the swappable Transport (the
well-known PING token every process answers), which works identically on
the deterministic simulator and on TCP:

- a ping round-trip marks the address available;
- ``FAILURE_TIMEOUT`` seconds without a successful round-trip marks it
  failed (pings are sent every ``PING_INTERVAL``).
"""

from __future__ import annotations

import asyncio
import dataclasses

from ..runtime.knobs import Knobs
from ..runtime.trace import TraceEvent
from .transport import Endpoint, NetworkAddress, Transport, WLTOKEN_PING


@dataclasses.dataclass
class FailureStatus:
    failed: bool
    since: float       # loop time of the last transition


class FailureMonitor:
    """One per process; monitors any address it is asked about.

    Besides the binary alive/dead state, the monitor tracks a DEGRADED
    state (ISSUE 12; the gray-failure signal of Huang et al. HotOS'17 —
    FDB 7.x's degraded-peer detection): a machine whose disk is
    slow-but-alive answers every ping, so the binary state never flips,
    yet recruiting on it or moving data to it drags cluster p99.
    Degradation is REPORTED into the monitor (the CC polls worker disk
    health) rather than detected by pinging — the signal lives where
    the latency is measured, the policy (recruitment/move
    deprioritization) lives with the consumers."""

    def __init__(self, transport: Transport, knobs: Knobs) -> None:
        self.transport = transport
        self.knobs = knobs
        self._status: dict[NetworkAddress, FailureStatus] = {}
        self._tasks: dict[NetworkAddress, asyncio.Task] = {}
        self._change_waiters: dict[NetworkAddress, list[asyncio.Future]] = {}
        self._degraded: dict[NetworkAddress, float] = {}  # addr -> since
        self._closed = False

    # --- queries (IFailureMonitor surface) ---

    def get_state(self, addr: NetworkAddress) -> FailureStatus:
        self._ensure_monitored(addr)
        return self._status[addr]

    def is_available(self, addr: NetworkAddress) -> bool:
        return not self.get_state(addr).failed

    async def wait_for_failure(self, addr: NetworkAddress) -> None:
        """Resolves when addr is considered failed (onFailedFor analog)."""
        while not self.get_state(addr).failed:
            await self._on_change(addr)

    async def wait_for_recovery(self, addr: NetworkAddress) -> None:
        while self.get_state(addr).failed:
            await self._on_change(addr)

    # --- degraded (gray failure) state ---

    def set_degraded(self, addr: NetworkAddress, degraded: bool,
                     latency_ms: float = 0.0) -> None:
        """Record a disk-health report for ``addr``.  Transitions emit
        a ``DiskDegraded`` trace event either way, so a chaos run's
        degradation timeline reconstructs from the trace alone."""
        was = addr in self._degraded
        if degraded == was:
            return
        if degraded:
            try:
                now = asyncio.get_running_loop().time()
            except RuntimeError:
                now = 0.0
            self._degraded[addr] = now
        else:
            self._degraded.pop(addr, None)
        TraceEvent("DiskDegraded").detail("Address", str(addr)) \
            .detail("Degraded", degraded) \
            .detail("LatencyMs", round(latency_ms, 3)).log()

    def is_degraded(self, addr: NetworkAddress) -> bool:
        return addr in self._degraded

    def degraded_addresses(self) -> list[NetworkAddress]:
        return sorted(self._degraded)

    # --- lifecycle ---

    def stop_monitoring(self, addr: NetworkAddress) -> None:
        t = self._tasks.pop(addr, None)
        if t is not None:
            t.cancel()
        self._status.pop(addr, None)
        # waiters are cancelled, not resolved: "monitoring stopped" is not
        # an answer to "did this address fail", and resolving them would
        # send wait_for_failure loops back through _ensure_monitored,
        # resurrecting the ping task after shutdown
        for fut in self._change_waiters.pop(addr, ()):
            fut.cancel()

    async def close(self) -> None:
        self._closed = True
        tasks = list(self._tasks.values())
        for addr in list(self._status):
            self.stop_monitoring(addr)
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)

    # --- internals ---

    def _ensure_monitored(self, addr: NetworkAddress) -> None:
        if self._closed:
            raise RuntimeError("FailureMonitor is closed")
        if addr in self._tasks:
            return
        loop = asyncio.get_running_loop()
        # optimistically available until the first timeout elapses — the
        # reference treats unknown endpoints the same way
        self._status[addr] = FailureStatus(False, loop.time())
        self._tasks[addr] = loop.create_task(
            self._ping_loop(addr), name=f"failmon-{addr}")

    async def _on_change(self, addr: NetworkAddress) -> None:
        fut = asyncio.get_running_loop().create_future()
        self._change_waiters.setdefault(addr, []).append(fut)
        await fut

    def _set_failed(self, addr: NetworkAddress, failed: bool) -> None:
        st = self._status.get(addr)
        if st is None or st.failed == failed:
            return
        loop = asyncio.get_running_loop()
        self._status[addr] = FailureStatus(failed, loop.time())
        TraceEvent("FailureDetectionStatus").detail("Address", str(addr)) \
            .detail("Failed", failed).log()
        for fut in self._change_waiters.pop(addr, ()):
            if not fut.done():
                fut.set_result(None)

    async def _ping_loop(self, addr: NetworkAddress) -> None:
        """Ping until cancelled; flip state on timeout/recovery."""
        loop = asyncio.get_running_loop()
        ep = Endpoint(addr, WLTOKEN_PING)
        last_ok = loop.time()
        while True:
            try:
                await asyncio.wait_for(
                    self.transport.request(ep, b"ping"),
                    timeout=self.knobs.FAILURE_TIMEOUT)
                last_ok = loop.time()
                self._set_failed(addr, False)
            except asyncio.CancelledError:
                raise
            except Exception:
                if loop.time() - last_ok >= self.knobs.FAILURE_TIMEOUT:
                    self._set_failed(addr, True)
            await asyncio.sleep(self.knobs.PING_INTERVAL)
