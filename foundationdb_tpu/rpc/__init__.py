"""Typed RPC over swappable transports.

Reference: REF:fdbrpc/ — FlowTransport (framed packets, endpoint tokens,
connection management) carrying RequestStream<T>/ReplyPromise<T> typed
endpoints, with the simulator (Sim2) substituting an in-memory network
behind the same interface.  Here:

- wire.py        — self-describing binary codec (ObjectSerializer analog)
- transport.py   — Endpoint/NetworkAddress + the Transport interface
- sim_transport.py — deterministic in-memory network w/ latency, clogs,
                     partitions (Sim2's SimClogging analog)
- tcp_transport.py — real asyncio TCP framing
- stubs.py       — RequestStream server loops + client proxies for roles
"""

from .transport import Endpoint, NetworkAddress, Transport
from .wire import decode, encode, register_struct
