"""Endpoint addressing + the Transport interface.

Reference: REF:fdbrpc/FlowTransport.actor.h — an Endpoint is
(NetworkAddress, token); a token names a receiver within a process.
Messages are request/reply: each request carries a reply token the
receiving side answers to (ReplyPromise over the wire).  Well-known
tokens (WLTOKEN_*) bootstrap discovery before any endpoint exchange.
"""

from __future__ import annotations

import asyncio
import dataclasses
import itertools
from typing import Any, Awaitable, Callable

from ..runtime import span as _span
from ..runtime.errors import FdbError, error_from_code

# well-known tokens (REF: WLTOKEN_* in FlowTransport.actor.cpp)
WLTOKEN_PING = 1
WLTOKEN_ENDPOINT_NOT_FOUND = 2
WLTOKEN_COORDINATOR = 40     # coordinator role block on shared-process transports
WLTOKEN_FIRST_AVAILABLE = 100


@dataclasses.dataclass(frozen=True, order=True)
class NetworkAddress:
    ip: str
    port: int

    def __str__(self) -> str:
        return f"{self.ip}:{self.port}"

    @staticmethod
    def parse(s: str) -> "NetworkAddress":
        host, port = s.rsplit(":", 1)
        return NetworkAddress(host, int(port))


@dataclasses.dataclass(frozen=True, order=True)
class Endpoint:
    address: NetworkAddress
    token: int


class RequestDispatcher:
    """Token → handler table one process exposes (the receiver side of
    FlowTransport).  Handlers are ``async (payload) -> reply payload``;
    FdbErrors raised by handlers travel back as error replies."""

    def __init__(self) -> None:
        self._handlers: dict[int, Callable[[Any], Awaitable[Any]]] = {}
        self._next_token = itertools.count(WLTOKEN_FIRST_AVAILABLE)

    def register(self, handler: Callable[[Any], Awaitable[Any]],
                 token: int | None = None) -> int:
        t = token if token is not None else next(self._next_token)
        assert t not in self._handlers, f"token {t} in use"
        self._handlers[t] = handler
        return t

    def unregister(self, token: int) -> None:
        self._handlers.pop(token, None)

    async def dispatch(self, token: int, payload: Any) -> tuple[bool, Any]:
        """Returns (ok, reply_or_error_code).  A payload wrapped in a
        SpanEnvelope (a sampled request) re-activates the sender's span
        context around the handler, so role code reads it back with
        ``current_span()`` — the receive half of wire propagation."""
        payload, ctx = _span.detach(payload)
        h = self._handlers.get(token)
        if h is None:
            # endpoint_not_found: the role at this token is gone (stopped,
            # or its process rebooted).  Retryable — clients refresh their
            # cluster view and re-dial the new generation.
            return False, 1012
        tok = _span.activate(ctx) if ctx is not None else None
        try:
            return True, await h(payload)
        except FdbError as e:
            return False, e.code
        finally:
            if tok is not None:
                _span.deactivate(tok)

    @property
    def tokens(self) -> list[int]:
        return sorted(self._handlers)


class Transport:
    """Base transport: request/reply to endpoints.  Implementations:
    SimTransport (deterministic in-memory) and TcpTransport (asyncio)."""

    def __init__(self, address: NetworkAddress) -> None:
        self.address = address
        self.dispatcher = RequestDispatcher()

        # Every process answers pings at the well-known token — the probe
        # surface FailureMonitor uses (REF: FlowTransport's ping endpoint).
        async def _ping(payload: Any) -> Any:
            return payload
        self.dispatcher.register(_ping, token=WLTOKEN_PING)

    async def request(self, endpoint: Endpoint, payload: Any,
                      timeout: float | None = None) -> Any:
        raise NotImplementedError

    def one_way(self, endpoint: Endpoint, payload: Any) -> None:
        """Fire-and-forget send (PacketWriter without reply token)."""
        raise NotImplementedError

    @staticmethod
    def attach_span(payload: Any) -> Any:
        """Envelope hook every transport calls at send time: wraps the
        payload with the active sampled span context (no-op otherwise),
        so cross-role attribution survives the wire."""
        return _span.attach(payload)

    async def close(self) -> None:
        pass

    # helpers
    def endpoint(self, token: int) -> Endpoint:
        return Endpoint(self.address, token)

    @staticmethod
    def raise_remote_error(code: int) -> None:
        raise error_from_code(code)
