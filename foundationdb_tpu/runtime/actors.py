"""Flow-style concurrency helpers over asyncio.

Reference: REF:flow/genericactors.actor.h — waitForAll, choose/when,
timeoutError, ActorCollection.  asyncio's primitives cover most of it;
these wrappers give the FDB-shaped API the roles are written against and
keep cancellation semantics consistent (dropping a Future cancels the
actor, like Flow).
"""

from __future__ import annotations

import asyncio
from typing import Any, Awaitable, Coroutine, Iterable, TypeVar

from .errors import TimedOut, BrokenPromise

T = TypeVar("T")


async def wait_for_all(futs: Iterable[Awaitable[T]]) -> list[T]:
    return list(await asyncio.gather(*futs))


async def timeout_error(aw: Awaitable[T], seconds: float) -> T:
    """Raise TimedOut (FDB error 1004) if aw does not finish in time."""
    try:
        return await asyncio.wait_for(asyncio.ensure_future(aw), seconds)
    except asyncio.TimeoutError:
        raise TimedOut() from None


async def delay(seconds: float) -> None:
    await asyncio.sleep(seconds)


def now() -> float:
    return asyncio.get_running_loop().time()


class Promise:
    """Single-assignment variable; the consumer side is ``.future``.

    Mirrors Flow's Promise/Future pair (REF:flow/flow.h SAV<T>), except
    drop-detection: Flow sends broken_promise when the last Promise copy is
    destroyed; here the owner must call ``break_promise()`` explicitly (we
    do not rely on GC finalizers).  An abandoned waiter surfaces as
    SimQuiescenceError in simulation rather than hanging silently.

    The underlying asyncio.Future is created lazily on first ``.future``
    access so a Promise may be constructed before the (sim) loop exists
    and sent from plain code; it binds to the loop of its first awaiter.
    """

    _UNSET = object()

    def __init__(self) -> None:
        self._fut: asyncio.Future | None = None
        self._value: Any = self._UNSET
        self._error: BaseException | None = None

    def send(self, value: Any = None) -> None:
        if self._fut is not None:
            if not self._fut.done():
                self._fut.set_result(value)
        elif self._value is self._UNSET and self._error is None:
            self._value = value

    def send_error(self, err: BaseException) -> None:
        if self._fut is not None:
            if not self._fut.done():
                self._fut.set_exception(err)
        elif self._value is self._UNSET and self._error is None:
            self._error = err

    def break_promise(self) -> None:
        self.send_error(BrokenPromise())

    @property
    def future(self) -> asyncio.Future:
        if self._fut is None:
            self._fut = asyncio.get_running_loop().create_future()
            if self._error is not None:
                self._fut.set_exception(self._error)
            elif self._value is not self._UNSET:
                self._fut.set_result(self._value)
        return self._fut

    def is_set(self) -> bool:
        if self._fut is not None:
            return self._fut.done()
        return self._value is not self._UNSET or self._error is not None


class PromiseStream:
    """Unbounded typed stream (REF:flow/flow.h PromiseStream<T>)."""

    def __init__(self) -> None:
        self._q: asyncio.Queue = asyncio.Queue()
        self._closed_err: BaseException | None = None

    def send(self, value: Any) -> None:
        if self._closed_err is None:
            self._q.put_nowait(value)

    def send_error(self, err: BaseException) -> None:
        self._closed_err = err
        self._q.put_nowait(_StreamError(err))

    def close(self) -> None:
        """Cleanly end the stream; async-for consumers exit their loop."""
        self.send_error(EndOfStream())

    async def recv(self) -> Any:
        v = await self._q.get()
        if isinstance(v, _StreamError):
            self._q.put_nowait(v)  # keep rethrowing for other readers
            raise v.err
        return v

    def __aiter__(self):
        return self

    async def __anext__(self):
        try:
            return await self.recv()
        except EndOfStream:
            raise StopAsyncIteration from None
        # Real stream errors (send_error) propagate to the async-for body.


class EndOfStream(Exception):
    """Clean close marker for PromiseStream (maps to StopAsyncIteration)."""


class _StreamError:
    def __init__(self, err: BaseException):
        self.err = err


class ActorCollection:
    """Owns a set of background tasks; cancelling the collection cancels all.

    Mirrors REF:flow/genericactors.actor.h ActorCollection: errors in any
    child surface on ``wait_for_error()``.
    """

    def __init__(self) -> None:
        self._tasks: set[asyncio.Task] = set()
        self._error = Promise()

    def add(self, coro: Coroutine) -> asyncio.Task:
        t = asyncio.get_running_loop().create_task(coro)
        self._tasks.add(t)
        t.add_done_callback(self._done)
        return t

    def _done(self, t: asyncio.Task) -> None:
        self._tasks.discard(t)
        if t.cancelled():
            return
        e = t.exception()
        if e is not None:
            self._error.send_error(e)

    async def wait_for_error(self) -> None:
        await self._error.future

    def cancel_all(self) -> None:
        for t in list(self._tasks):
            t.cancel()

    async def aclose(self) -> None:
        self.cancel_all()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
