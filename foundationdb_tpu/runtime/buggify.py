"""BUGGIFY — sim-only random rare-path fault injection.

Reference: REF:flow/Buggify.h — ``BUGGIFY`` blocks are compiled in always
but fire only in simulation, each site independently enabled with 25%
probability per run and then firing with a per-site probability.  This is
how FDB forces rare paths (early buffer flushes, pathological knob values,
injected delays) to be exercised constantly in simulation.
"""

from __future__ import annotations

from .rng import deterministic_random

_enabled = False
_site_enabled: dict[str, bool] = {}
SITE_ACTIVATION_P = 0.25
FIRE_P = 0.05


def enable_buggify(on: bool = True) -> None:
    global _enabled
    _enabled = on
    _site_enabled.clear()


def reset_buggify_sites() -> None:
    """Clear per-run site activations (called by run_simulation so the same
    seed replays identically within one process)."""
    _site_enabled.clear()


def buggify_enabled() -> bool:
    return _enabled


def buggify(site: str, fire_p: float = FIRE_P) -> bool:
    """``if buggify("tlog_slow_commit"): await sleep(r.random())``"""
    if not _enabled:
        return False
    rng = deterministic_random()
    en = _site_enabled.get(site)
    if en is None:
        en = _site_enabled[site] = rng.coinflip(SITE_ACTIVATION_P)
    return en and rng.coinflip(fire_p)
