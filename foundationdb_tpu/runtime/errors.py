"""Numbered error system, mirroring FDB's error model.

Reference: REF:flow/Error.h, REF:flow/error_definitions.h — FDB errors are
small numbered values thrown through futures; clients switch on the code in
``Transaction::onError`` to decide retry behavior.  We keep the same codes for
the errors we implement so FDB users find familiar numbers.
"""

from __future__ import annotations


class FdbError(Exception):
    """An error with an FDB-compatible numeric code."""

    code: int = 0
    name: str = "unknown_error"

    def __init__(self, *args):
        super().__init__(*args or (self.name,))

    # --- retry classification (mirrors fdb_error_predicate in REF:bindings/c) ---
    @property
    def retryable(self) -> bool:
        return self.code in _RETRYABLE

    @property
    def maybe_committed(self) -> bool:
        return self.code in _MAYBE_COMMITTED


_REGISTRY: dict[int, type[FdbError]] = {}


def _err(code: int, name: str, doc: str) -> type[FdbError]:
    cls = type(name, (FdbError,), {"code": code, "name": name, "__doc__": doc})
    _REGISTRY[code] = cls
    return cls


def error_from_code(code: int) -> FdbError:
    cls = _REGISTRY.get(code)
    if cls is None:
        e = FdbError(f"error code {code}")
        e.code = code
        return e
    return cls()


# Codes match upstream flow/error_definitions.h where an equivalent exists.
OperationFailed = _err(1000, "operation_failed", "Operation failed")
TimedOut = _err(1004, "timed_out", "Operation timed out")
TransactionTooOld = _err(1007, "transaction_too_old", "Read version is too old to be satisfied")
FutureVersion = _err(1009, "future_version", "Request for a future version")
NotCommitted = _err(1020, "not_committed", "Transaction not committed due to a conflict")
CommitUnknownResult = _err(1021, "commit_unknown_result", "Commit result unknown")
TransactionCancelled = _err(1025, "transaction_cancelled", "Transaction was cancelled")
ConnectionFailed = _err(1026, "connection_failed", "Network connection failed")
TransactionTimedOut = _err(1031, "transaction_timed_out", "Transaction timed out")
TLogStopped = _err(1011, "tlog_stopped", "TLog stopped (generation locked by recovery)")
EndpointNotFound = _err(1012, "endpoint_not_found", "Endpoint not found (role gone or fail-stopped)")
ProcessBehind = _err(1037, "process_behind", "Storage process does not have recent mutations")
DatabaseLocked = _err(1038, "database_locked", "Database is locked")
ClusterVersionChanged = _err(1039, "cluster_version_changed", "Cluster has been upgraded to a new protocol version")
BrokenPromise = _err(1100, "broken_promise", "The promise was never set or was dropped")
OperationCancelled = _err(1101, "operation_cancelled", "Asynchronous operation cancelled")
IoError = _err(1510, "io_error", "Disk i/o operation failed")
DiskCorrupt = _err(1512, "disk_corrupt",
                   "Committed on-disk data failed its checksum — NOT a "
                   "torn tail: recovery must fail loudly, never silently "
                   "truncate acked data (upstream's file_corrupt; its "
                   "exact code was unverifiable this session, 1512 "
                   "reserved here)")
PlatformError = _err(1500, "platform_error", "Platform error")
ClientInvalidOperation = _err(2000, "client_invalid_operation", "Invalid API call")
KeyOutsideLegalRange = _err(2003, "key_outside_legal_range", "Key outside legal range")
InvertedRange = _err(2005, "inverted_range", "Range begin key exceeds end key")
InvalidOption = _err(2007, "invalid_option", "Option not valid in this context")
VersionInvalid = _err(2011, "version_invalid", "Version not valid")
TransactionReadOnly = _err(2023, "transaction_read_only", "Transaction is read-only and cannot be committed")
UsedDuringCommit = _err(2017, "used_during_commit", "Operation issued while a commit was outstanding")
KeyTooLarge = _err(2102, "key_too_large", "Key length exceeds limit")
ValueTooLarge = _err(2103, "value_too_large", "Value length exceeds limit")
TransactionTooLarge = _err(2101, "transaction_too_large", "Transaction exceeds byte limit")

WrongShardServer = _err(1001, "wrong_shard_server",
                        "Shard is no longer served by this storage server "
                        "(client must refresh its location map and retry); "
                        "upstream's exact code was unverifiable this session "
                        "— 1001 is reserved here for it")
RequestMaybeDelivered = _err(1213, "request_maybe_delivered",
                             "Request may or may not have been delivered")

CoordinatorsChanged = _err(1101 + 100, "coordinators_changed",
                           "The coordinator set has changed; refetch the "
                           "connection string and retry (upstream's "
                           "coordinators_changed — its exact code was "
                           "unverifiable this session, 1201 reserved here)")

# resolver-internal (ours; no upstream equivalent needed on the wire)
ResolverCapacityExceeded = _err(2900, "resolver_capacity_exceeded",
                                "Conflict-set history ring overflowed; txn forced too-old")
ResolverFailed = _err(2901, "resolver_failed",
                      "Resolver backend failed after history mutation; "
                      "role is fail-stopped pending recovery")
LogDataLoss = _err(2902, "log_data_loss",
                   "Every replica of a log tag is gone; recovery impossible")

# change feeds (upstream's exact codes were unverifiable this session;
# the 2903/2904 block is reserved here for them)
ChangeFeedNotRegistered = _err(2903, "change_feed_not_registered",
                               "No such change feed on this storage server "
                               "(never registered, destroyed, or the range "
                               "moved — consumers refresh and retry briefly)")
ChangeFeedPopped = _err(2904, "change_feed_popped",
                        "Requested change-feed data was released by a pop "
                        "(cursor is below the durable low-water mark)")
ChangeFeedDestroyed = _err(2905, "feed_destroyed",
                           "The change feed's registration row is gone: it "
                           "was destroyed while a cursor was draining it.  "
                           "Unlike change_feed_not_registered (a transient "
                           "handoff race the cursor retries through), this "
                           "is a definite terminal outcome — the retained "
                           "segments were released at the destroy version "
                           "and no retry can recover them.  NOT retryable "
                           "(upstream's change_feed_cancelled analog; its "
                           "exact code was unverifiable this session, 2905 "
                           "reserved here)")

# 1213 is retryable for idempotent operations (reads, GRV); the commit
# path converts it to commit_unknown_result (1021) before the client's
# retry loop can see it, because re-running a maybe-delivered commit is
# not idempotent.
# 1510 (io_error) is retryable HERE unlike upstream (where it kills the
# process): with the sim injecting transient per-op disk errors
# (ISSUE 12), every consumer's existing retry loop absorbs them instead
# of fail-stopping a role per glitch.  1512 (disk_corrupt) is NOT —
# corruption of committed data must surface loudly, never be retried
# into silence.
_RETRYABLE = {1001, 1004, 1007, 1009, 1012, 1020, 1021, 1026, 1031, 1037,
              1039, 1191, 1201, 1213, 1510, 2900}
# 1031 is maybe-committed like upstream: a commit cut off by the
# transaction deadline (ISSUE 12's bounded-failure trio) may already
# have been delivered — callers consulting e.maybe_committed must not
# treat the write as definitely absent.
_MAYBE_COMMITTED = {1021, 1031}
