"""The per-process metrics plane — the cluster flight recorder's source side.

Reference: REF:fdbrpc/Stats.h — every role owns CounterCollections whose
``traceCounters`` actor emits one ``*Metrics`` TraceEvent per interval,
and REF:fdbserver/Status.actor.cpp aggregates the latest emission into
``status json``.  Before this module the port wired that loop into only
two roles (commit proxy, storage) as private ``asyncio.sleep`` loops;
everything else was visible only at the instant someone pulled
``cluster_status``, and the version frontiers the ratekeeper reads every
interval were never recorded anywhere.

Here every role registers ONE :class:`MetricsSource` — its existing
``CounterCollection``/``Histogram``/``RateMeter`` instruments plus cheap
gauge callbacks (version frontiers, queue depths, MVCC window occupancy,
lsm compaction debt, device-pipeline depth, SlowTask stalls) — into the
hosting process's :class:`MetricsRegistry`, and ONE emitter actor per
worker drains the whole registry every ``METRICS_INTERVAL``.  The
emitter sleeps on the event loop's clock, so under ``SimEventLoop`` the
cadence is virtual time and same-seed traces stay bit-identical; the
emission order is registration order (recruitment order — itself
deterministic under the sim), never set/dict iteration over ids.

The trace file becomes a flight recorder: ``tools/metrics_tool.py``
reconstructs any role's gauge as a time-series from the rolled JSONL
alone (``lag`` rebuilds the per-tag durability-lag series, ``recovery``
the version-cut audit), so an incident can be replayed after the fact
instead of reproduced under a live status poll.
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable, Optional

from .trace import CounterCollection, Histogram, TraceEvent, TraceLog, get_trace_log


class MetricsSource:
    """One role's registered instruments, emitted as one ``<Name>Metrics``
    event per interval (counters with rates + meter rates + gauge values
    as details) plus each histogram's own ``Histogram*`` event.

    Gauges are zero-argument callables sampled AT EMIT TIME — they must
    be cheap (attribute reads) and may return any JSON-serializable
    scalar.  A gauge that raises is skipped for that emission (a dying
    subsystem must not take the whole metrics plane down with it)."""

    __slots__ = ("name", "id", "counters", "histograms", "meters", "_gauges")

    def __init__(self, name: str, id_: str = "",
                 counters: CounterCollection | None = None) -> None:
        self.name = name
        # adopt the role's existing collection (its counters keep being
        # bumped by the hot path) or create an empty one for gauge-only
        # sources; an adopted collection's id (e.g. the storage tag) is
        # authoritative for the source too, so registry snapshot keys
        # and trace-event IDs always agree
        self.counters = counters if counters is not None \
            else CounterCollection(name, str(id_))
        self.id = str(id_) or self.counters.id
        self.histograms: list[Histogram] = []
        self.meters: list = []                 # RateMeter ducks
        self._gauges: dict[str, Callable[[], Any]] = {}

    def gauge(self, name: str, fn: Callable[[], Any]) -> "MetricsSource":
        self._gauges[name] = fn
        return self

    def histogram(self, h: Histogram) -> "MetricsSource":
        self.histograms.append(h)
        return self

    def meter(self, m) -> "MetricsSource":
        self.meters.append(m)
        return self

    def gauge_values(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for name, fn in self._gauges.items():
            try:
                out[name] = fn()
            except Exception:  # noqa: BLE001 — skip, never take the plane down
                continue
        return out

    def _meter_fields(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for m in self.meters:
            s = m.snapshot()
            base = _camel(m.name)
            out[f"{base}Count"] = s["count"]
            out[f"{base}PerSec"] = s["per_sec"]
            out[f"{base}MeanBatch"] = s["mean_batch"]
        return out

    def snapshot(self) -> dict[str, Any]:
        """Point-in-time view (status/lag rollups, tests): counter values
        + gauges + meter rates, with no trace emission (meters may
        rotate their trailing-window marks — the multi-poller-safe
        behavior they already have)."""
        out = {n: c.value for n, c in self.counters.counters.items()}
        out.update(self._meter_fields())
        out.update(self.gauge_values())
        return out

    def emit(self, log: TraceLog | None = None) -> None:
        lg = log or get_trace_log()
        extra = self._meter_fields()
        extra.update(self.gauge_values())
        self.counters.log_metrics(lg, extra=extra)
        for h in self.histograms:
            # the source's id rides each histogram event too, so a
            # multi-instance role's latency series stay distinct
            h.log_metrics(lg, id_=self.id)


def _camel(name: str) -> str:
    return "".join(p.title() for p in name.split("_"))


class MetricsRegistry:
    """Per-process (per-worker) registry of MetricsSources + the ONE
    emitter actor that drains them.

    Registration order is emission order — recruitment order, which a
    seeded sim replays exactly — so same-seed trace streams stay
    bit-identical with the plane on."""

    def __init__(self) -> None:
        self._sources: list[MetricsSource] = []
        self._task: asyncio.Task | None = None
        self.emissions = 0          # emitter passes completed

    # --- registration ---

    def register(self, source: MetricsSource,
                 default_id: str | None = None) -> MetricsSource:
        if default_id is not None and not source.id:
            source.id = str(default_id)
            if not source.counters.id:
                source.counters.id = str(default_id)
        if source not in self._sources:
            self._sources.append(source)
        return source

    def unregister(self, source: MetricsSource | None) -> None:
        if source is not None and source in self._sources:
            self._sources.remove(source)

    def add_role(self, obj: Any, default_id: str | None = None
                 ) -> MetricsSource | None:
        """Register a role object's source, duck-typed on
        ``metrics_source()`` (roles without one are silently skipped —
        the worker hosts whatever it is asked to)."""
        fn = getattr(obj, "metrics_source", None)
        if fn is None:
            return None
        return self.register(fn(), default_id=default_id)

    def sources(self) -> list[MetricsSource]:
        return list(self._sources)

    def snapshot(self) -> dict[str, dict]:
        """{``Name/id``: values} across every registered source."""
        out: dict[str, dict] = {}
        for s in self._sources:
            out[f"{s.name}/{s.id}"] = s.snapshot()
        return out

    # --- emission ---

    def emit_all(self, log: TraceLog | None = None) -> None:
        for s in list(self._sources):
            s.emit(log)
        self.emissions += 1

    def start_emitter(self, interval: float) -> None:
        """Start the one per-process emitter actor (idempotent).  Must be
        called with a running event loop; the sleep rides the loop clock,
        so sim runs emit on the virtual-time cadence."""
        if self._task is not None and not self._task.done():
            return
        self._task = asyncio.get_running_loop().create_task(
            self._emit_loop(interval), name="metrics-emitter")

    async def _emit_loop(self, interval: float) -> None:
        while True:
            await asyncio.sleep(interval)
            try:
                self.emit_all()
            except Exception as e:  # noqa: BLE001 — a broken source must
                # not kill the plane for every other role on this worker
                TraceEvent("MetricsEmitError", severity=30) \
                    .detail("Error", repr(e)[:200]).log()

    async def stop_emitter(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
