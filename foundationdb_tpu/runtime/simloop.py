"""Deterministic virtual-time asyncio event loop — the Sim2 replacement.

Reference: REF:fdbrpc/sim2.actor.cpp + REF:flow/Net2.actor.cpp — FDB swaps
the real network (Net2) for a simulator (Sim2) behind the INetwork
interface; simulated time advances instantly to the next timer, so an
entire multi-machine cluster run takes wall-milliseconds and is exactly
reproducible from a seed.

Here the swap point is the asyncio event loop itself: ``SimEventLoop``
subclasses ``asyncio.SelectorEventLoop`` with a selector that never touches
the OS — ``select(timeout)`` *advances the virtual clock* instead of
sleeping, and ``loop.time()`` returns virtual time.  All simulated network
and disk I/O is in-memory (see rpc/sim_transport.py), so no real file
descriptors are ever waited on.  asyncio's ready-queue and timer-heap
scheduling are FIFO/stable, so runs are deterministic given a seeded RNG.
"""

from __future__ import annotations

import asyncio
import selectors
from typing import Any, Coroutine

from .rng import DeterministicRandom, set_deterministic_random


class SimQuiescenceError(RuntimeError):
    """The simulation has no runnable or scheduled work but the main task is unfinished."""


class _VirtualSelector(selectors.BaseSelector):
    """A selector that advances virtual time rather than blocking."""

    def __init__(self) -> None:
        self.loop: "SimEventLoop | None" = None
        self._map: dict[int, selectors.SelectorKey] = {}

    def register(self, fileobj, events, data=None):
        key = selectors.SelectorKey(fileobj, self._fd(fileobj), events, data)
        self._map[key.fd] = key
        return key

    def unregister(self, fileobj):
        return self._map.pop(self._fd(fileobj), None)

    def _fd(self, fileobj) -> int:
        return fileobj if isinstance(fileobj, int) else fileobj.fileno()

    def select(self, timeout=None):
        assert self.loop is not None
        if timeout is None:
            # No timers and nothing ready: the sim is quiesced.
            raise SimQuiescenceError(
                "simulation deadlock: no runnable tasks and no pending timers")
        if timeout > 0:
            self.loop._vtime += timeout
        return []

    def get_map(self):
        return self._map

    def close(self):
        self._map.clear()


class SimEventLoop(asyncio.SelectorEventLoop):
    def __init__(self) -> None:
        sel = _VirtualSelector()
        super().__init__(selector=sel)
        sel.loop = self
        self._vtime = 0.0
        # asyncio clamps selector timeouts to 24h (MAXIMUM_SELECT_TIMEOUT);
        # that is fine — long delays just take several _run_once passes.

    def time(self) -> float:
        return self._vtime

    # Real-world side effects are forbidden under simulation.
    def run_in_executor(self, executor, func, *args):  # pragma: no cover
        raise RuntimeError("run_in_executor is not allowed in simulation")


def run_simulation(main: Coroutine[Any, Any, Any], seed: int = 0,
                   install_global_rng: bool = True) -> Any:
    """Run ``main`` to completion on a fresh virtual-time loop.

    The analog of ``fdbserver -r simulation -s <seed>``: a seed fully
    determines scheduling, latencies, and faults.
    """
    if install_global_rng:
        set_deterministic_random(DeterministicRandom(seed))
        from .buggify import reset_buggify_sites
        reset_buggify_sites()
    loop = SimEventLoop()
    try:
        return loop.run_until_complete(main)
    finally:
        # Cancel leftovers so closing the loop is clean and deterministic.
        # all_tasks() is a set (address-ordered); sort by task name so the
        # cancellation order is reproducible across runs.
        def _task_key(t: asyncio.Task):
            name = t.get_name()
            if name.startswith("Task-"):
                try:
                    return (0, int(name[5:]), name)
                except ValueError:
                    pass
            return (1, 0, name)

        pending = sorted(asyncio.all_tasks(loop), key=_task_key)
        for t in pending:
            t.cancel()
        if pending:
            try:
                loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True))
            except SimQuiescenceError:
                pass
        loop.close()
