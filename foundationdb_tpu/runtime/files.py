"""Async file abstraction with a kill-lossy, fault-injecting sim twin.

Reference: REF:fdbrpc/IAsyncFile.h — all durable state flows through
IAsyncFile; in simulation AsyncFileNonDurable
(REF:fdbrpc/AsyncFileNonDurable.actor.h) doesn't just *lose* writes that
were not sync()ed when the process is killed — it tears them at sector
granularity (a random subset of the dirty sectors persists), corrupts
bytes inside the torn region, and injects IO errors and latency into
live operations.  That hostile-disk model is how FDB's simulation proves
recovery against real crash semantics ("we have not lost committed data
in simulation in years", SIGMOD'21).  SimFile buffers unsynced writes
separately; a machine kill routes them through the machine's
``DiskFaultProfile`` (default: the all-or-nothing drop).

Two always-on observability pieces ride the same layer:

- ``DiskHealth`` — decayed per-op disk latency (the DecayingRate
  discipline of core/shard_load.py) per filesystem, the signal the
  gray-failure detection (a slow-but-alive disk, Huang et al. HotOS'17)
  publishes through role metrics and the FailureMonitor's ``degraded``
  state;
- ``DiskFaultInjected`` trace events for every injected fault, so a
  chaos run's fault activity is auditable from the trace file alone.

Determinism: the profile draws from its OWN seeded rng (never the
global sim stream) and a disarmed profile draws nothing at all, so
same-seed sims with every fault knob at its default stay bit-identical.

RealFile uses blocking os I/O directly: individual operations are small
and the event loop stall is bounded; an io-thread pool (the reference's
eio) can slot in behind the same interface later without changing callers.
"""

from __future__ import annotations

import asyncio
import os
import time
from typing import Protocol


class IAsyncFile(Protocol):
    async def read(self, offset: int, length: int) -> bytes: ...
    async def write(self, offset: int, data: bytes) -> None: ...
    async def sync(self) -> None: ...
    async def truncate(self, size: int) -> None: ...
    def size(self) -> int: ...
    async def close(self) -> None: ...


def _now() -> float:
    """Loop time inside a running loop (VIRTUAL under simulation), wall
    monotonic outside — the shard_load._monotonic_now discipline."""
    try:
        return asyncio.get_running_loop().time()
    except RuntimeError:
        return time.monotonic()


class DiskHealth:
    """Exponentially-decayed mean per-op disk latency + a degraded flag.

    Two decayed counters (ops, busy-seconds) share one timestamp; their
    ratio is the decayed mean seconds-per-op, so one historic slow op
    fades while a *sustained* stall (the gray-failure signature) holds
    the mean above ``degraded_ms``.  ``min_ops`` keeps an idle disk's
    single outlier from flagging a machine that does no disk work.
    Pure arithmetic — no RNG, no tasks — so observing health perturbs
    no same-seed trace."""

    __slots__ = ("halflife_s", "degraded_ms", "min_ops", "_ops", "_busy",
                 "_ts")

    def __init__(self, halflife_s: float = 5.0,
                 degraded_ms: float = 25.0, min_ops: float = 4.0) -> None:
        self.configure(halflife_s, degraded_ms)
        self.min_ops = min_ops
        self._ops = 0.0
        self._busy = 0.0
        self._ts: float | None = None

    def configure(self, halflife_s: float, degraded_ms: float) -> None:
        self.halflife_s = max(halflife_s, 1e-6)
        self.degraded_ms = degraded_ms

    def _decayed(self, now: float) -> tuple[float, float]:
        if self._ts is None:
            return 0.0, 0.0
        f = 0.5 ** (max(0.0, now - self._ts) / self.halflife_s)
        return self._ops * f, self._busy * f

    def observe(self, seconds: float) -> None:
        now = _now()
        self._ops, self._busy = self._decayed(now)
        self._ts = now
        self._ops += 1.0
        self._busy += max(0.0, seconds)

    def latency_ms(self) -> float:
        ops, busy = self._decayed(_now())
        return (busy / ops) * 1e3 if ops > 0 else 0.0

    @property
    def degraded(self) -> bool:
        ops, busy = self._decayed(_now())
        return ops >= self.min_ops and \
            (busy / ops) * 1e3 >= self.degraded_ms

    def snapshot(self) -> dict:
        """The metrics payload every disk-bearing role publishes."""
        return {"disk_latency_ms": round(self.latency_ms(), 3),
                "disk_degraded": self.degraded}


class DiskFaultProfile:
    """Deterministic hostile-disk model for one simulated machine.

    Armed per-machine (seeded from the sim rng when knob
    ``SIM_DISK_FAULTS`` is on, or by DiskFaultWorkload mid-run) and
    consulted by every SimFile operation plus the kill path:

    - live ops: IO errors (``io_error_p`` per op, raised as IoError so
      each role's retry loop absorbs them) and latency stalls
      (``stall_p``/``stall_max_s`` random, ``stall_floor_s`` a fixed
      per-op stall — THE slow-disk gray failure);
    - kill time: with probability ``torn_p`` the unsynced writes TEAR at
      sector granularity — each dirty sector independently persists or
      drops, and a persisted sector is garbage with ``corrupt_p`` — the
      AsyncFileNonDurable crash model (default: all-or-nothing drop).

    Synced bytes are never touched: committed data survives every
    injected fault, which is what makes "zero acked-write loss under
    chaos" a provable acceptance instead of a hope.  A disarmed profile
    draws no randomness and awaits nothing.
    """

    __slots__ = ("rng", "armed", "io_error_p", "stall_p", "stall_max_s",
                 "stall_floor_s", "torn_p", "corrupt_p", "sector",
                 "io_errors", "stalls", "torn_kills", "dropped_sectors",
                 "corrupt_sectors")

    def __init__(self) -> None:
        self.rng = None
        self.armed = False
        self.io_error_p = 0.0
        self.stall_p = 0.0
        self.stall_max_s = 0.0
        self.stall_floor_s = 0.0
        self.torn_p = 0.0
        self.corrupt_p = 0.0
        self.sector = 512
        self.io_errors = 0
        self.stalls = 0
        self.torn_kills = 0
        self.dropped_sectors = 0
        self.corrupt_sectors = 0

    def arm(self, rng, io_error_p: float = 0.0, stall_p: float = 0.0,
            stall_max_s: float = 0.0, stall_floor_s: float = 0.0,
            torn_p: float = 0.0, corrupt_p: float = 0.0,
            sector: int = 512) -> None:
        self.rng = rng
        self.io_error_p = io_error_p
        self.stall_p = stall_p
        self.stall_max_s = stall_max_s
        self.stall_floor_s = stall_floor_s
        self.torn_p = torn_p
        self.corrupt_p = corrupt_p
        self.sector = max(1, sector)
        self.armed = True

    def arm_from_knobs(self, knobs, rng) -> None:
        self.arm(rng, io_error_p=knobs.SIM_DISK_IO_ERROR_P,
                 stall_p=knobs.SIM_DISK_STALL_P,
                 stall_max_s=knobs.SIM_DISK_STALL_MAX_S,
                 torn_p=knobs.SIM_DISK_TORN_P,
                 corrupt_p=knobs.SIM_DISK_CORRUPT_P,
                 sector=knobs.SIM_DISK_SECTOR)

    def quiesce(self) -> None:
        """Stop injecting into LIVE ops (workload wind-down so the final
        consistency checks run on a quiet disk); kill-time torn/corrupt
        semantics stay armed — they model the crash itself."""
        self.io_error_p = 0.0
        self.stall_p = 0.0
        self.stall_floor_s = 0.0

    def disarm(self) -> None:
        self.armed = False

    async def before_op(self, op: str, path: str) -> None:
        """Live-op injection hook: stall, then maybe fail."""
        from .trace import TraceEvent
        stall = self.stall_floor_s
        if self.stall_p and self.rng.coinflip(self.stall_p):
            stall += self.rng.random() * self.stall_max_s
        if stall > 0.0:
            self.stalls += 1
            TraceEvent("DiskFaultInjected").detail("Kind", "stall") \
                .detail("Op", op).detail("Path", path) \
                .detail("StallMs", round(stall * 1e3, 3)).log()
            await asyncio.sleep(stall)
        if self.io_error_p and self.rng.coinflip(self.io_error_p):
            self.io_errors += 1
            from .errors import IoError
            TraceEvent("DiskFaultInjected").detail("Kind", "io_error") \
                .detail("Op", op).detail("Path", path).log()
            raise IoError(f"injected {op} error on {path}")

    def tear(self, synced: bytearray, pending: list, path: str) -> None:
        """Kill-time torn write: apply a random sector-granular subset
        of the unsynced ops to the synced image, corrupting some of the
        surviving sectors.  Mutates ``synced`` in place.  Only sectors
        the pending ops actually dirtied can change — synced-clean
        sectors always survive byte-identical."""
        from .trace import TraceEvent
        old = bytes(synced)
        new = bytearray(old)
        SimFile._replay(new, pending)
        if bytes(new) == old:
            return
        sec = self.sector
        length = max(len(old), len(new))
        oldp = old.ljust(length, b"\x00")
        newp = bytes(new).ljust(length, b"\x00")
        out = bytearray(oldp)
        rng = self.rng
        dropped = corrupted = kept = 0
        for s in range(0, length, sec):
            oc, nc = oldp[s:s + sec], newp[s:s + sec]
            if oc == nc:
                continue
            if rng.coinflip(0.5):       # this dirty sector made it to disk
                kept += 1
                if self.corrupt_p and rng.coinflip(self.corrupt_p):
                    out[s:s + sec] = rng.random_bytes(len(nc))
                    corrupted += 1
                else:
                    out[s:s + sec] = nc
            else:
                dropped += 1
        # file length is metadata with its own torn fate: either the
        # pending ops' final length or the synced one (never below both,
        # so no synced byte is ever silently shortened)
        end = len(new) if rng.coinflip(0.5) else len(old)
        synced[:] = out[:end]
        self.torn_kills += 1
        self.dropped_sectors += dropped
        self.corrupt_sectors += corrupted
        TraceEvent("DiskFaultInjected").detail("Kind", "torn_write") \
            .detail("Path", path).detail("KeptSectors", kept) \
            .detail("DroppedSectors", dropped) \
            .detail("CorruptSectors", corrupted).log()

    def stats(self) -> dict:
        return {"io_errors": self.io_errors, "stalls": self.stalls,
                "torn_kills": self.torn_kills,
                "dropped_sectors": self.dropped_sectors,
                "corrupt_sectors": self.corrupt_sectors}


class RealFile:
    def __init__(self, path: str, health: DiskHealth | None = None) -> None:
        self.path = path
        self.health = health
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)

    def _observe(self, t0: float) -> None:
        if self.health is not None:
            self.health.observe(time.monotonic() - t0)

    async def read(self, offset: int, length: int) -> bytes:
        t0 = time.monotonic()
        out = os.pread(self._fd, length, offset)
        self._observe(t0)
        return out

    def read_sync(self, offset: int, length: int) -> bytes:
        """Synchronous block read — the LSM engine's page-cache path
        (bounded block-sized stalls, same caveat as the class docstring)."""
        return os.pread(self._fd, length, offset)

    async def write(self, offset: int, data: bytes) -> None:
        t0 = time.monotonic()
        os.pwrite(self._fd, data, offset)
        self._observe(t0)

    async def sync(self) -> None:
        t0 = time.monotonic()
        os.fsync(self._fd)
        self._observe(t0)

    async def truncate(self, size: int) -> None:
        os.ftruncate(self._fd, size)

    def size(self) -> int:
        return os.fstat(self._fd).st_size

    async def close(self) -> None:
        os.close(self._fd)


class SimFile:
    """In-memory file whose unsynced writes vanish (or TEAR) on kill."""

    def __init__(self, fs: "SimFileSystem", path: str) -> None:
        self.fs = fs
        self.path = path
        # synced: survives kill.  _pending: ordered op log since last sync
        # — ("w", offset, data) and ("t", size, b"") must interleave in
        # program order or a truncate could chop later appends.
        if path not in fs.disks:
            fs.disks[path] = bytearray()
        self._pending: list[tuple[str, int, bytes]] = []

    @property
    def health(self) -> DiskHealth:
        return self.fs.health

    @staticmethod
    def _replay(buf: bytearray, ops) -> None:
        for kind, arg, data in ops:
            if kind == "w":
                if len(buf) < arg + len(data):
                    buf.extend(b"\x00" * (arg + len(data) - len(buf)))
                buf[arg:arg + len(data)] = data
            else:
                del buf[arg:]

    def _view(self) -> bytes:
        """Content as a reader would see it (synced + pending)."""
        buf = bytearray(self.fs.disks[self.path])
        self._replay(buf, self._pending)
        return bytes(buf)

    def read_sync(self, offset: int, length: int) -> bytes:
        # the page-cache path: no fault injection (it cannot await a
        # stall) — the async surfaces carry the whole fault model
        v = self._view()
        return bytes(v[offset:offset + length])

    async def read(self, offset: int, length: int) -> bytes:
        await self.fs._disk_op("read", self.path)
        v = self._view()
        return v[offset:offset + length]

    async def write(self, offset: int, data: bytes) -> None:
        await self.fs._disk_op("write", self.path)
        self._pending.append(("w", offset, bytes(data)))

    async def sync(self) -> None:
        await self.fs._disk_op("sync", self.path)
        self._replay(self.fs.disks[self.path], self._pending)
        self._pending.clear()

    async def truncate(self, size: int) -> None:
        await self.fs._disk_op("truncate", self.path)
        self._pending.append(("t", size, b""))

    def size(self) -> int:
        return len(self._view())

    async def close(self) -> None:
        pass  # unsynced writes remain pending-lost, like a closed-then-killed fd


class SimFileSystem:
    """Shared simulated disk: path → synced bytes.  kill_unsynced()
    models machine loss (AsyncFileNonDurable semantics, optionally torn
    and corrupted through the attached DiskFaultProfile)."""

    def __init__(self, profile: DiskFaultProfile | None = None) -> None:
        self.disks: dict[str, bytearray] = {}
        self._open: list[SimFile] = []
        self.profile = profile
        self.health = DiskHealth()

    async def _disk_op(self, op: str, path: str) -> None:
        """Per-op hook: fault injection + latency accounting.  With no
        armed profile this awaits nothing and draws nothing — the
        default-off path is schedule-identical to the pre-fault layer."""
        prof = self.profile
        if prof is None or not prof.armed:
            self.health.observe(0.0)
            return
        t0 = _now()
        try:
            await prof.before_op(op, path)
        finally:
            self.health.observe(_now() - t0)

    def open(self, path: str) -> SimFile:
        f = SimFile(self, path)
        self._open.append(f)
        return f

    def kill_unsynced(self) -> None:
        """The machine died: every open file's unsynced writes are gone —
        or, with a fault profile armed, torn at sector granularity with
        possible bit corruption of the dirty region (never of synced
        bytes)."""
        prof = self.profile
        for f in self._open:
            if f._pending and prof is not None and prof.armed \
                    and prof.rng is not None and prof.torn_p > 0 \
                    and prof.rng.coinflip(prof.torn_p):
                prof.tear(self.disks[f.path], f._pending, f.path)
            f._pending.clear()

    def listdir(self, prefix: str) -> list[str]:
        return sorted(p for p in self.disks if p.startswith(prefix))

    def remove(self, path: str) -> None:
        self.disks.pop(path, None)
        self._open = [f for f in self._open if f.path != path]


class RealFileSystem:
    """Real-disk twin of SimFileSystem (RealFile-backed, rooted)."""

    def __init__(self, root: str = ".") -> None:
        self.root = root
        self.health = DiskHealth()

    def open(self, path: str) -> RealFile:
        return RealFile(os.path.join(self.root, path), health=self.health)

    def listdir(self, prefix: str) -> list[str]:
        base = os.path.join(self.root, prefix)
        d = base if os.path.isdir(base) else os.path.dirname(base)
        if not os.path.isdir(d):
            return []
        rel = os.path.relpath(d, self.root)
        out = []
        for name in os.listdir(d):
            p = name if rel == "." else os.path.join(rel, name)
            if p.startswith(prefix):
                out.append(p)
        return sorted(out)

    def remove(self, path: str) -> None:
        try:
            os.remove(os.path.join(self.root, path))
        except FileNotFoundError:
            pass
