"""Async file abstraction with a kill-lossy simulated implementation.

Reference: REF:fdbrpc/IAsyncFile.h — all durable state flows through
IAsyncFile; in simulation AsyncFileNonDurable *loses writes that were not
sync()ed* when the process is killed, which is how FDB proves its
recovery logic against real crash semantics.  That property is the whole
point of this module: SimFile buffers unsynced writes separately and a
machine kill drops them.

RealFile uses blocking os I/O directly: individual operations are small
and the event loop stall is bounded; an io-thread pool (the reference's
eio) can slot in behind the same interface later without changing callers.
"""

from __future__ import annotations

import os
from typing import Protocol


class IAsyncFile(Protocol):
    async def read(self, offset: int, length: int) -> bytes: ...
    async def write(self, offset: int, data: bytes) -> None: ...
    async def sync(self) -> None: ...
    async def truncate(self, size: int) -> None: ...
    def size(self) -> int: ...
    async def close(self) -> None: ...


class RealFile:
    def __init__(self, path: str) -> None:
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)

    async def read(self, offset: int, length: int) -> bytes:
        return os.pread(self._fd, length, offset)

    def read_sync(self, offset: int, length: int) -> bytes:
        """Synchronous block read — the LSM engine's page-cache path
        (bounded block-sized stalls, same caveat as the class docstring)."""
        return os.pread(self._fd, length, offset)

    async def write(self, offset: int, data: bytes) -> None:
        os.pwrite(self._fd, data, offset)

    async def sync(self) -> None:
        os.fsync(self._fd)

    async def truncate(self, size: int) -> None:
        os.ftruncate(self._fd, size)

    def size(self) -> int:
        return os.fstat(self._fd).st_size

    async def close(self) -> None:
        os.close(self._fd)


class SimFile:
    """In-memory file whose unsynced writes vanish on kill."""

    def __init__(self, fs: "SimFileSystem", path: str) -> None:
        self.fs = fs
        self.path = path
        # synced: survives kill.  _pending: ordered op log since last sync
        # — ("w", offset, data) and ("t", size, b"") must interleave in
        # program order or a truncate could chop later appends.
        if path not in fs.disks:
            fs.disks[path] = bytearray()
        self._pending: list[tuple[str, int, bytes]] = []

    @staticmethod
    def _replay(buf: bytearray, ops) -> None:
        for kind, arg, data in ops:
            if kind == "w":
                if len(buf) < arg + len(data):
                    buf.extend(b"\x00" * (arg + len(data) - len(buf)))
                buf[arg:arg + len(data)] = data
            else:
                del buf[arg:]

    def _view(self) -> bytes:
        """Content as a reader would see it (synced + pending)."""
        buf = bytearray(self.fs.disks[self.path])
        self._replay(buf, self._pending)
        return bytes(buf)

    def read_sync(self, offset: int, length: int) -> bytes:
        v = self._view()
        return bytes(v[offset:offset + length])

    async def read(self, offset: int, length: int) -> bytes:
        v = self._view()
        return v[offset:offset + length]

    async def write(self, offset: int, data: bytes) -> None:
        self._pending.append(("w", offset, bytes(data)))

    async def sync(self) -> None:
        self._replay(self.fs.disks[self.path], self._pending)
        self._pending.clear()

    async def truncate(self, size: int) -> None:
        self._pending.append(("t", size, b""))

    def size(self) -> int:
        return len(self._view())

    async def close(self) -> None:
        pass  # unsynced writes remain pending-lost, like a closed-then-killed fd


class SimFileSystem:
    """Shared simulated disk: path → synced bytes.  kill_unsynced()
    models machine loss (AsyncFileNonDurable semantics)."""

    def __init__(self) -> None:
        self.disks: dict[str, bytearray] = {}
        self._open: list[SimFile] = []

    def open(self, path: str) -> SimFile:
        f = SimFile(self, path)
        self._open.append(f)
        return f

    def kill_unsynced(self) -> None:
        """The machine died: every open file's unsynced writes are gone."""
        for f in self._open:
            f._pending.clear()

    def listdir(self, prefix: str) -> list[str]:
        return sorted(p for p in self.disks if p.startswith(prefix))

    def remove(self, path: str) -> None:
        self.disks.pop(path, None)
        self._open = [f for f in self._open if f.path != path]


class RealFileSystem:
    """Real-disk twin of SimFileSystem (RealFile-backed, rooted)."""

    def __init__(self, root: str = ".") -> None:
        self.root = root

    def open(self, path: str) -> RealFile:
        return RealFile(os.path.join(self.root, path))

    def listdir(self, prefix: str) -> list[str]:
        base = os.path.join(self.root, prefix)
        d = base if os.path.isdir(base) else os.path.dirname(base)
        if not os.path.isdir(d):
            return []
        rel = os.path.relpath(d, self.root)
        out = []
        for name in os.listdir(d):
            p = name if rel == "." else os.path.join(rel, name)
            if p.startswith(prefix):
                out.append(p)
        return sorted(out)

    def remove(self, path: str) -> None:
        try:
            os.remove(os.path.join(self.root, path))
        except FileNotFoundError:
            pass
