"""Deterministic seeded RNG so simulation replays bit-for-bit.

Reference: REF:flow/IRandom.h, REF:flow/DeterministicRandom.h/.cpp —
every source of randomness in simulation flows through one seeded
generator; a seed reproduces a whole cluster run exactly.

We implement xoshiro256** ourselves (rather than wrapping random.Random)
so the C++ side (native/) can share the identical stream if it ever needs
randomness, keeping cross-language determinism on the table.
"""

from __future__ import annotations

_MASK = (1 << 64) - 1


def _rotl(x: int, k: int) -> int:
    return ((x << k) | (x >> (64 - k))) & _MASK


def _splitmix64(seed: int):
    state = seed & _MASK
    while True:
        state = (state + 0x9E3779B97F4A7C15) & _MASK
        z = state
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK
        yield z ^ (z >> 31)


class DeterministicRandom:
    def __init__(self, seed: int):
        sm = _splitmix64(seed)
        self._s = [next(sm) for _ in range(4)]
        self.seed = seed

    def next_u64(self) -> int:
        s = self._s
        result = (_rotl((s[1] * 5) & _MASK, 7) * 9) & _MASK
        t = (s[1] << 17) & _MASK
        s[2] ^= s[0]
        s[3] ^= s[1]
        s[1] ^= s[2]
        s[0] ^= s[3]
        s[2] ^= t
        s[3] = _rotl(s[3], 45)
        return result

    def random(self) -> float:
        """Uniform in [0, 1)."""
        return (self.next_u64() >> 11) * (1.0 / (1 << 53))

    def random_int(self, lo: int, hi: int) -> int:
        """Uniform integer in [lo, hi) — matches deterministicRandom()->randomInt."""
        if hi <= lo:
            raise ValueError("empty range")
        span = hi - lo
        return lo + self.next_u64() % span

    def random_unique_id(self) -> str:
        return f"{self.next_u64():016x}{self.next_u64():016x}"

    def coinflip(self, p: float = 0.5) -> bool:
        return self.random() < p

    def choice(self, seq):
        return seq[self.random_int(0, len(seq))]

    def shuffle(self, lst: list) -> None:
        for i in range(len(lst) - 1, 0, -1):
            j = self.random_int(0, i + 1)
            lst[i], lst[j] = lst[j], lst[i]

    def random_bytes(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            out += self.next_u64().to_bytes(8, "little")
        return bytes(out[:n])

    def split(self) -> "DeterministicRandom":
        """Derive an independent child stream deterministically."""
        return DeterministicRandom(self.next_u64())

    def random_exp(self, mean: float) -> float:
        """Exponential with given mean (for sim latencies)."""
        import math
        u = self.random()
        if u <= 0.0:
            u = 2.0 ** -53
        return -mean * math.log(u)


_global_rng: DeterministicRandom | None = None


def set_deterministic_random(rng: DeterministicRandom) -> None:
    global _global_rng
    _global_rng = rng


def deterministic_random() -> DeterministicRandom:
    global _global_rng
    if _global_rng is None:
        import os
        _global_rng = DeterministicRandom(int.from_bytes(os.urandom(8), "little"))
    return _global_rng
