"""Wire-propagated distributed span contexts — cross-role transaction tracing.

Reference: REF:fdbclient/NativeAPI.actor.cpp ``debugTransaction`` — the
reference attributes a sampled transaction's latency across roles by
propagating one debug ID with every request and emitting
``TransactionDebug`` / ``CommitDebug`` events keyed by that ID at each
role boundary (GRV queue/reply, commit batch, resolution, TLog push,
storage read).  That is the Dapper span-propagation model: a trace id
plus a parent span id travel in the RPC envelope; every hop logs point
events the offline analyzer (tools/trace_tool.py, modeled on the
reference's transaction_profiling_analyzer) stitches into one
cross-role timeline.

Design constraints honored here:

- **Determinism**: sampling decisions come from the client's existing
  counter-based TraceBatch sampler (runtime/latency_probe.py) — no RNG
  draws, so seeded simulation streams are unperturbed.  Span ids come
  from a process-local counter; they never feed scheduling.
- **Zero cost unsampled**: an unsampled request carries nothing — the
  transports only build a ``SpanEnvelope`` when a sampled context is
  active, and every role-side emit site is a ``ctx is None`` check.
- **One substrate**: span events are ordinary TraceEvents (JSONL), so
  sim trace output stays deterministic and the analyzer needs only the
  rolled trace files.

Propagation is a contextvar: the client activates its root context
around an RPC; transports wrap the payload in a ``SpanEnvelope``;
``RequestDispatcher.dispatch`` unwraps it and re-activates the context
around the handler, so role code just calls ``current_span()``.
"""

from __future__ import annotations

import contextvars
import dataclasses
import itertools
from typing import Any, Optional

from .trace import Severity, TraceEvent

_CURRENT: contextvars.ContextVar[Optional["SpanContext"]] = \
    contextvars.ContextVar("fdbtpu_span", default=None)

# process-local span id source: ids label events, never drive
# scheduling, so this stays outside the deterministic RNG on purpose
_ids = itertools.count(1)

# process-wide rollup (reset per test/sim run via reset_totals)
TOTALS = {"sampled_txns": 0, "spans_emitted": 0, "dropped_spans": 0}


@dataclasses.dataclass(frozen=True)
class SpanContext:
    """What travels with a request: which trace, which parent span."""
    trace_id: int
    span_id: int
    parent_id: int = 0
    sampled: bool = True


@dataclasses.dataclass
class SpanEnvelope:
    """RPC payload wrapper carrying the sender's span context over the
    wire (registered as a wire struct in rpc/wire.py).  Transports build
    one only for sampled contexts; the dispatcher unwraps it before the
    handler sees the payload."""
    trace_id: int
    span_id: int
    parent_id: int
    payload: Any


_SALT: int | None = None


def _trace_salt() -> int:
    """High bits mixed into root trace ids so two client PROCESSES of
    one real cluster cannot collide (each starts its probe counter at
    0).  Under the virtual-time simulator the salt is always 0: every
    sim client shares one process, and a pid/wall-time salt would break
    same-seed bit-identical trace output."""
    import asyncio
    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        loop = None
    from .simloop import SimEventLoop
    if loop is not None and isinstance(loop, SimEventLoop):
        return 0
    global _SALT
    if _SALT is None:
        import os
        import time
        _SALT = ((os.getpid() & 0xFFFF) << 32) \
            | ((int(time.time()) & 0xFFFF) << 48)
    return _SALT


def new_root(trace_id: int) -> SpanContext:
    """Client-side root span for a sampled transaction (the moment the
    TraceBatch sampler fires)."""
    TOTALS["sampled_txns"] += 1
    return SpanContext(_trace_salt() | trace_id, next(_ids), 0, True)


def new_server_root(seq: int, namespace: int = 1) -> SpanContext:
    """Server-side root for a request that arrived WITHOUT a sampled
    client context (GRV-only / read-only-heavy clients, feed-stream
    consumers — ROADMAP PR 2 follow-up (a)).  ``namespace`` keeps the
    serving role's trace ids disjoint from client probe counters (and
    from other roles') in one process: client roots use the low bits
    raw, so any namespace >= 1 shifted past them cannot collide."""
    TOTALS["sampled_txns"] += 1
    return SpanContext(_trace_salt() | ((namespace & 0xFF) << 24) | seq,
                       next(_ids), 0, True)


class ServerSampler:
    """Deterministic counter-based 1-in-N server-side root sampling —
    the one home of the period arithmetic every serving role shares
    (GRV proxy, feed streams).  ``root()`` returns a fresh root context
    on sampled requests, None otherwise; never draws from the seeded
    RNG, so sim streams are unperturbed."""

    __slots__ = ("namespace", "count")

    def __init__(self, namespace: int) -> None:
        self.namespace = namespace
        self.count = 0

    def root(self, sample_rate: float) -> SpanContext | None:
        if sample_rate <= 0:
            return None
        self.count += 1
        period = max(1, round(1 / sample_rate))
        if self.count % period:
            return None
        return new_server_root(self.count, self.namespace)


def child_of(ctx: SpanContext) -> SpanContext:
    """A new span under ``ctx`` — created at explicit role-boundary
    forwarding sites (client→GRV, proxy→resolver, proxy→TLog, ...)."""
    return SpanContext(ctx.trace_id, next(_ids), ctx.span_id, ctx.sampled)


def current_span() -> SpanContext | None:
    return _CURRENT.get()


def activate(ctx: SpanContext | None) -> contextvars.Token:
    return _CURRENT.set(ctx)


def deactivate(token: contextvars.Token) -> None:
    _CURRENT.reset(token)


class child_scope:
    """Activate a child span of ``ctx`` for the scope (no-op when ctx is
    None) — the one home of the activate/child_of/deactivate dance every
    role-boundary hop needs; a hand-rolled copy that forgets the reset
    leaks the contextvar across batches."""

    def __init__(self, ctx: SpanContext | None) -> None:
        self._ctx = ctx
        self._tok = None

    def __enter__(self) -> SpanContext | None:
        if self._ctx is None:
            return None
        child = child_of(self._ctx)
        self._tok = _CURRENT.set(child)
        return child

    def __exit__(self, *exc):
        if self._tok is not None:
            _CURRENT.reset(self._tok)
        return False


class no_span:
    """Context manager masking the active span — REQUIRED around
    ``create_task`` for any long-lived worker spawned lazily from a
    request path: the task copies the caller's context at creation, so
    without the mask a batching loop would attribute every later
    request's downstream RPCs to the first sampled transaction that
    happened to spawn it."""

    def __enter__(self):
        self._tok = _CURRENT.set(None)
        return self

    def __exit__(self, *exc):
        _CURRENT.reset(self._tok)
        return False


def attach(payload: Any) -> Any:
    """Wrap an outbound RPC payload with the active sampled context (the
    transports' envelope hook); unsampled requests pass through as-is."""
    ctx = _CURRENT.get()
    if ctx is None or not ctx.sampled:
        return payload
    return SpanEnvelope(ctx.trace_id, ctx.span_id, ctx.parent_id, payload)


def detach(payload: Any) -> tuple[Any, SpanContext | None]:
    """Dispatcher-side unwrap: (inner payload, context or None)."""
    if isinstance(payload, SpanEnvelope):
        return payload.payload, SpanContext(payload.trace_id,
                                            payload.span_id,
                                            payload.parent_id, True)
    return payload, None


def fmt_trace(trace_id: int) -> str:
    return f"{trace_id:016x}"


def reset_totals() -> None:
    """Reset the rollup AND the span-id counter — a harness re-running
    a seeded sim in one process needs ids to restart or the second
    run's trace JSONL differs from the first despite the same seed."""
    global _ids
    for k in TOTALS:
        TOTALS[k] = 0
    _ids = itertools.count(1)
    from .latency_probe import EVICTIONS_TOTAL
    EVICTIONS_TOTAL["probe_evictions"] = 0


def process_counters() -> dict:
    """The process-wide trace-plane loss/volume counters under stable
    metric names (ISSUE 17 satellite): span TOTALS plus the TraceBatch
    probe-eviction rollup.  Splatted into every role's ``metrics()`` —
    status dedupes per process by address, the slow-task discipline —
    so silent probe/span loss under load finally shows up in the
    tracing rollup.  Key names deliberately avoid the per-role
    ``spans_emitted``/``spans_dropped`` of ``SpanSink.counters()``."""
    from .latency_probe import EVICTIONS_TOTAL
    return {"span_sampled_txns": TOTALS["sampled_txns"],
            "span_totals_emitted": TOTALS["spans_emitted"],
            "span_totals_dropped": TOTALS["dropped_spans"],
            "probe_evictions": EVICTIONS_TOTAL["probe_evictions"]}


class SpanSink:
    """Per-role span emitter: a role holds one and calls ``event`` at
    its boundaries; it counts what it emitted (surfaced via the role's
    ``metrics()`` and the cluster_status tracing rollup)."""

    __slots__ = ("role", "emitted", "dropped")

    def __init__(self, role: str) -> None:
        self.role = role
        self.emitted = 0
        # spans this role had to drop (e.g. a second sampled txn in a
        # commit batch whose downstream hops are keyed to the first)
        self.dropped = 0

    def event(self, type_: str, ctx: SpanContext | None, location: str,
              severity: int = Severity.INFO, **details: Any) -> None:
        """Emit one span point event iff ``ctx`` is a sampled context.

        Schema: Type (TransactionDebug/CommitDebug), TraceID (hex),
        SpanID, ParentID, Role, Location, plus free-form details —
        exactly what tools/trace_tool.py reconstructs timelines from."""
        if ctx is None or not ctx.sampled:
            return
        from .trace import get_trace_log
        if severity < get_trace_log().min_severity:
            # the log would drop it — don't count a span that never
            # reached the file, or the status rollup overstates
            return
        ev = TraceEvent(type_, severity=severity) \
            .detail("TraceID", fmt_trace(ctx.trace_id)) \
            .detail("SpanID", ctx.span_id) \
            .detail("ParentID", ctx.parent_id) \
            .detail("Role", self.role) \
            .detail("Location", location)
        for k, v in details.items():
            ev.detail(k, v)
        ev.log()
        self.emitted += 1
        TOTALS["spans_emitted"] += 1

    def drop(self, n: int = 1) -> None:
        self.dropped += n
        TOTALS["dropped_spans"] += n

    def counters(self) -> dict:
        return {"spans_emitted": self.emitted, "spans_dropped": self.dropped}
