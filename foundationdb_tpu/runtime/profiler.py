"""Slow-task profiler — catches event-loop stalls and attributes them.

Reference: REF:flow/Profiler.actor.cpp — the reference samples the
program counter when the Flow event loop runs one task for longer than a
threshold, emitting a trace with the offending stack.  Same instrument
here, asyncio-shaped: a watchdog THREAD watches a heartbeat the loop
refreshes every tick; when the heartbeat goes stale past
``SLOW_TASK_THRESHOLD`` the watchdog captures the loop thread's current
Python stack via ``sys._current_frames`` and emits one
``SlowTask`` TraceEvent with the duration and the innermost frames.

The reference's single-threaded-event-loop discipline makes this the
race-free observability primitive: a stall IS a bug (a coroutine doing
blocking work on the loop), and the stack names it.  Under the
virtual-time simulator the profiler is a no-op — virtual time never
stalls and extra threads would break determinism.
"""

from __future__ import annotations

import asyncio
import sys
import threading
import time
import traceback

from .knobs import Knobs
from .trace import TraceEvent


def _meter_clock() -> float:
    """The running event loop's clock when one exists, else monotonic.

    The ``_default_clock`` pattern from trace.py: on a real asyncio loop
    ``loop.time()`` IS the monotonic clock, so behavior is unchanged —
    but under ``SimEventLoop`` it is the virtual clock, so a RateMeter's
    ``per_sec`` measures virtual-time work against virtual time instead
    of clocking wall seconds against instantly-advancing sim work
    (which made every sim-run rate gauge nonsense)."""
    try:
        return asyncio.get_running_loop().time()
    except RuntimeError:
        return time.monotonic()


class RateMeter:
    """Hot-path throughput counter: total count, batch count, and
    clock rate — no locks, no per-event timestamps, safe to bump
    from the apply path at millions of events/sec.  The storage role
    uses one for ``mutations_applied`` so an apply-throughput regression
    (the r5 O(n²) index collapse) shows up as a falling rate in status
    instead of a bench timeout."""

    _WINDOW_S = 5.0

    __slots__ = ("name", "count", "batches", "_t0", "_m0", "_m1", "_clock")

    def __init__(self, name: str, clock=None) -> None:
        self.name = name
        self.count = 0
        self.batches = 0
        self._clock = clock or _meter_clock
        self._t0 = self._clock()
        # rolling window marks (time, count): per_sec is measured against
        # a 5-10s trailing mark, NOT a per-reader delta — multiple pollers
        # (ratekeeper, status) would otherwise shrink each other's window
        # to nothing, and a lifetime average would dilute a stall on a
        # long-lived server to noise
        self._m0 = (self._t0, 0)
        self._m1 = (self._t0, 0)

    def add(self, n: int) -> None:
        self.count += n
        self.batches += 1

    def snapshot(self) -> dict:
        now = self._clock()
        if now < self._t0:
            # clock base changed under us: constructed before a virtual-
            # time loop existed (monotonic anchor), sampled inside it
            # (virtual now).  Re-anchor instead of dividing the whole
            # count by the 1e-9 clamp — rates read 0 for one interval,
            # then measure virtual time like everything else.
            self._t0 = now
            self._m0 = (now, self.count)
            self._m1 = (now, self.count)
        if now - self._m1[0] >= self._WINDOW_S:
            self._m0 = self._m1
            self._m1 = (now, self.count)
        t0, c0 = self._m0
        dt_recent = now - t0
        dt_life = now - self._t0
        recent = (self.count - c0) / dt_recent if dt_recent > 1e-9 else 0.0
        return {
            "count": self.count,
            "batches": self.batches,
            "per_sec": round(recent, 1),
            "per_sec_lifetime":
                round(self.count / dt_life, 1) if dt_life > 1e-9 else 0.0,
            "mean_batch": round(self.count / self.batches, 1)
            if self.batches else 0.0,
        }


# the process's live profiler (set by start(), cleared by stop()): roles
# splat stall_metrics() into their metrics() replies so the r5-class
# event-loop-occupancy incident reaches the status rollup at one glance
# instead of living only in the SlowTask trace events
_ACTIVE: "SlowTaskProfiler | None" = None


def active_profiler() -> "SlowTaskProfiler | None":
    return _ACTIVE


def stall_metrics() -> dict:
    """The process's slow-task counters for role metrics() surfaces:
    empty when no profiler is armed (sim runs — virtual time never
    stalls), so knob-default sim metrics stay byte-identical."""
    p = _ACTIVE
    if p is None or p._watchdog is None:
        return {}
    return {
        "slow_task_stalls": p.stalls,
        "slow_task_last_stall_ms":
            round((p.last_stall_s or 0.0) * 1e3, 1),
    }


class SlowTaskProfiler:
    """Watchdog for one asyncio event loop (the production loop)."""

    def __init__(self, knobs: Knobs | None = None,
                 threshold: float | None = None) -> None:
        k = knobs or Knobs()
        self.threshold = threshold if threshold is not None \
            else k.SLOW_TASK_THRESHOLD
        self.interval = max(self.threshold / 4, 0.005)
        self._beat = time.monotonic()
        self._loop_thread_id: int | None = None
        self._stop = threading.Event()
        self._heartbeat_task: asyncio.Task | None = None
        self._watchdog: threading.Thread | None = None
        self.stalls = 0                 # total stalls caught
        self.last_stall_s: float | None = None

    # --- loop side ---

    async def _heartbeat(self) -> None:
        while not self._stop.is_set():
            self._beat = time.monotonic()
            await asyncio.sleep(self.interval)

    def start(self) -> "SlowTaskProfiler":
        global _ACTIVE
        from .simloop import SimEventLoop
        loop = asyncio.get_running_loop()
        if isinstance(loop, SimEventLoop):
            return self             # no-op under the simulator (see module doc)
        self._loop_thread_id = threading.get_ident()
        self._beat = time.monotonic()
        self._heartbeat_task = loop.create_task(
            self._heartbeat(), name="slow-task-heartbeat")
        self._watchdog = threading.Thread(
            target=self._watch, daemon=True, name="slow-task-watchdog")
        self._watchdog.start()
        _ACTIVE = self
        return self

    def stop(self) -> None:
        global _ACTIVE
        self._stop.set()
        if self._heartbeat_task is not None:
            self._heartbeat_task.cancel()
            self._heartbeat_task = None
        if _ACTIVE is self:
            _ACTIVE = None

    # --- watchdog thread ---

    def _watch(self) -> None:
        # On detection the watchdog captures the loop thread's stack (the
        # culprit is mid-stall, so the frame names it); the event is
        # emitted when the heartbeat RESUMES, carrying the whole stall's
        # duration rather than the duration at detection time.
        stall_stack: str | None = None
        stall_beat = 0.0
        while not self._stop.is_set():
            time.sleep(self.interval)
            stale = time.monotonic() - self._beat
            if stale >= self.threshold:
                if stall_stack is None or self._beat > stall_beat:
                    stall_beat = self._beat
                    frame = sys._current_frames().get(self._loop_thread_id)
                    stall_stack = "".join(
                        traceback.format_stack(frame, limit=8)) \
                        if frame is not None else "<no frame>"
                continue
            if stall_stack is not None:
                # the stall just ended: heartbeat resumed
                duration = self._beat - stall_beat
                self.stalls += 1
                self.last_stall_s = duration
                # Begin/End ride the MONOTONIC clock — the same base a
                # real asyncio loop's time() (and hence every span
                # event's Time) uses.  The event's own Time field comes
                # from the watchdog THREAD where no loop runs, so it
                # falls back to wall time; trace_tool's SlowTask↔span
                # overlap join must use these fields, not Time.
                TraceEvent("SlowTask", severity=30) \
                    .detail("DurationMs", round(duration * 1e3, 1)) \
                    .detail("BeginMonotonic", round(stall_beat, 6)) \
                    .detail("EndMonotonic", round(self._beat, 6)) \
                    .detail("Stack", stall_stack[-2000:]).log()
                stall_stack = None
