"""Typed runtime constants ("knobs"), overridable per-process.

Reference: REF:flow/Knobs.h/.cpp plus ServerKnobs/ClientKnobs
(REF:fdbclient/ServerKnobs.cpp) — hundreds of typed constants set via
``--knob_name=value``; BUGGIFY randomizes some of them in simulation.

The north star adds ``RESOLVER_CONFLICT_BACKEND in {cpp, numpy, tpu}``:
the resolver role selects the conflict-set implementation at role start,
exactly as Resolver.actor.cpp would consult a server knob.
"""

from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass
class Knobs:
    # --- resolver / conflict detection (north star) ---
    RESOLVER_CONFLICT_BACKEND: str = "numpy"  # cpp | numpy | tpu (jax)
    CONFLICT_RING_CAPACITY: int = 1 << 16     # history entries on device
    CONFLICT_WINDOW_SLOTS: int = 4096         # exact fast-path scan window (0 = always full ring)
    CONFLICT_DICT_SLOTS: int = 1 << 21        # device endpoint-lane dictionary (0 = ship lanes)
    KEY_ENCODE_BYTES: int = 32                # fixed-width key prefix lanes (multiple of 8)
    RESOLVER_BATCH_TXNS: int = 64             # txns per resolve launch (static shape)
    RESOLVER_RANGES_PER_TXN: int = 8          # padded read/write ranges per txn
    MAX_WRITE_TRANSACTION_LIFE_VERSIONS: int = 5_000_000  # ~5s at 1M versions/s (REF:fdbclient/ServerKnobs)
    VERSIONS_PER_SECOND: int = 1_000_000
    # adaptive group fusion (r5): batches arriving while device dispatches
    # are in flight fuse into grouped dispatches — amortizes the device
    # round-trip across live concurrency without adding batching latency
    RESOLVER_GROUP_FUSION: bool = True        # encoded backends only
    RESOLVER_GROUP_MAX: int = 64              # max batches fused per dispatch
    RESOLVER_MAX_INFLIGHT_GROUPS: int = 4     # device pipeline depth
    # pin fused dispatches to ONE compiled K bucket (0 = native bucket
    # quantization).  Production resolvers see varying group sizes; each
    # new bucket is a fresh XLA compile (~10s over the tunnel) landing
    # mid-traffic — padding every group to a fixed bucket trades a few KB
    # of sentinel rows for a single warmup-time compile
    RESOLVER_GROUP_BUCKET: int = 0
    # device commit pipeline (ISSUE 6): the resolver's encoded backends
    # dispatch through device/pipeline.py's DevicePipeline — persistent
    # on-device ConflictState in donated buffers, batches enqueued
    # host-side and fused into pipelined dispatches so batch N+1's
    # encode+transfer overlaps batch N's kernel and N-1's verdict
    # readback.  Off = the legacy per-role dispatch loop (bit-identical
    # verdicts either way; the knob exists for fallback and A/B)
    RESOLVER_DEVICE_PIPELINE: bool = True
    # in-flight dispatch depth for the device pipeline (two-deep default:
    # one group on the device, one group's verdicts reading back)
    RESOLVER_PIPELINE_DEPTH: int = 2
    # routed resolver mesh (ISSUE 16): the proxy sends each resolver ONLY
    # the txns whose clipped conflict ranges are non-empty on its
    # partition (a sparse sub-batch; the proxy keeps the index map and
    # scatters the verdicts back into the AND-join), and when EVERY txn
    # clips empty it sends a header-only version-advance request that the
    # resolver answers without touching the conflict backend or the
    # device pipeline.  Version-advance invariant: every resolver still
    # sees every (prev_version, version) pair — skipping a resolver
    # entirely would wedge its version chain and freeze its too-old
    # window/frontier.  Off = the broadcast twin, kept verbatim for A/B
    # (same wire shapes either way, so no protocol gate is needed).
    RESOLVER_MESH_ROUTING: bool = True
    # on-device verdict reduction (ISSUE 18): the encoded backends pack
    # each fused group's verdicts INTO BITMASKS on device — a per-group
    # any-conflict summary word vector synced first, and per-batch
    # conflict/too-old bit planes synced only when the summary says some
    # batch aborted — so a clean group's readback is ceil(K/32) u32
    # words instead of K x B x i32 verdict vectors.  The resolver also
    # piggybacks the packed abort words on ResolveBatchReply so the
    # proxy's AND-join scatters set bits instead of iterating every
    # verdict.  Off = the raw-vector twin, kept verbatim for A/B
    # (bit-identical verdicts either way, asserted in situ by
    # perf_smoke --stage devplane).
    RESOLVER_VERDICT_BITMASK: bool = True
    # Pallas in-place ring write probe (ISSUE 18, ROADMAP 1 (b)): the
    # conflict ring's append writes the shifted window + new slab into
    # the donated output buffer via a pallas_call with input/output
    # aliasing instead of the concat+where / concat+dynamic_slice XLA
    # rebuild.  Interpret-mode on CPU (tier-1 + determinism children
    # pin it both ways); bit-identical ring contents by construction.
    # Default OFF: a probe for the real-TPU gate re-measure (1 (a)) —
    # flip it when a TPU profile shows the append on the critical path.
    RESOLVER_RING_INPLACE: bool = False

    # --- commit pipeline ---
    COMMIT_BATCH_INTERVAL: float = 0.002      # proxy batching window seconds (REF: COMMIT_TRANSACTION_BATCH_INTERVAL_MIN)
    COMMIT_BATCH_BYTE_LIMIT: int = 1 << 20
    COMMIT_BATCH_COUNT_LIMIT: int = 1024
    GRV_BATCH_INTERVAL: float = 0.001
    # empty batches keep versions flowing while clients are active so
    # storage durability floors and resolver windows advance; after
    # IDLE_COMMIT_LIMIT without a real commit the proxy goes quiet so the
    # simulator's deadlock detection still works
    COMMIT_EMPTY_BATCH_INTERVAL: float = 0.25
    IDLE_COMMIT_LIMIT: float = 5.0

    # --- observability ---
    SLOW_TASK_THRESHOLD: float = 0.2    # event-loop stall before a SlowTask
    #                                     trace fires (REF:flow/Profiler)
    CLIENT_LATENCY_PROBE_SAMPLE: float = 0.01   # TraceBatch sampling rate

    # --- storage ---
    STORAGE_ENGINE: str = "memory"            # memory | lsm | btree
    # wire/protocol version this "binary" speaks (the reference's
    # currentProtocolVersion): published in the cluster state; a client
    # pinned to a different version gets cluster_version_changed and the
    # multi-version client re-resolves (REF:fdbclient/MultiVersionTransaction)
    # 711: SpanEnvelope (wire struct id 10) may wrap any sampled request —
    # a 710 peer cannot decode it, so the version gate must fence them
    # 712: packed columnar MutationBatch (wire struct id 11) replaces
    # list[Mutation] in TLogPushRequest/TLogPeekReply payloads — a 711
    # peer cannot decode the struct id, so the gate fences it
    # 713: change feeds — ChangeFeedStreamRequest/Reply (wire struct ids
    # 12/13), PRIVATE_FEED_* mutation opcodes in tag streams, and the
    # packed-MutationBatch state-transaction piggyback; a 712 peer can
    # decode none of these, so the gate fences it
    # 714: batched multiget reads — GetValuesRequest/Reply (wire struct
    # ids 14/15) on the storage read surface; a 713 peer cannot decode
    # the struct ids, so the gate fences it
    # 715: columnar range reads — GetRangeRequest/Reply (wire struct ids
    # 16/17) on the storage read surface, rows as packed key/value blobs
    # + cumulative u32 bounds with a per-chunk status byte; a 714 peer
    # cannot decode the struct ids, so the gate fences it
    # 716: packed selector resolution — GetKeyRequest/Reply (wire struct
    # ids 18/19): key selectors resolve to ONE key per shard reply
    # instead of row-probing ``offset`` rows through the range path; a
    # 715 peer cannot decode the struct ids, so the gate fences it
    # 717: error codes 2903/2904 renumbered (ISSUE 12) — they were
    # DOUBLE-registered (coordination's not_latest_generation/
    # coordinators_unreachable vs the change-feed errors), so which
    # class a wire error decoded to depended on import order; the
    # coordination pair moved to 2910/2911.  Error codes cross the wire
    # numerically, so a 716 peer would mistype them — the gate fences it
    # 718: online consistency scrub — ScrubPageRequest/Reply (wire
    # struct ids 20/21) on the storage surface: per-page digests over a
    # key range at a pinned read version, pages as packed end-key
    # columns + u32 row counts + 8-byte blake2b digests; a 717 peer
    # cannot decode the struct ids, so the gate fences it
    # 719: resolver verdict bitmasks (ISSUE 18) — ResolveBatchReply
    # grew a trailing abort_words field (packed per-batch conflict +
    # too-old bit planes the proxy AND-join consumes directly).  The
    # codec writes a per-struct field count, but a 718 peer constructs
    # the reply dataclass positionally and would crash (or silently
    # drop the words), so the gate fences it
    PROTOCOL_VERSION: int = 719
    # --- change feeds ---
    # (sealed feed segments at or below the durable floor ALWAYS spill
    # to the DiskQueue side file on durable servers — a durability
    # obligation, not a memory knob: the TLog pop drops their replay
    # copies in the same tick)
    # default reply byte cap for one change_feed_stream long-poll
    CHANGE_FEED_STREAM_BYTES: int = 1 << 20
    # how long a feed stream long-polls for new versions before
    # returning an empty heartbeat reply
    CHANGE_FEED_POLL_WAIT: float = 0.5
    # server-side span sampling for requests arriving WITHOUT a sampled
    # client context (GRV/read-only-heavy workloads and feed streams):
    # a deterministic counter-based 1-in-N root per serving role (0
    # disables).  Matches the client probe default.
    SERVER_SPAN_SAMPLE: float = 0.01
    STORAGE_VERSION_WINDOW: int = 5_000_000   # in-memory MVCC window, versions
    STORAGE_DURABILITY_LAG: float = 0.25      # seconds between making versions durable
    STORAGE_FUTURE_VERSION_WAIT: float = 1.0  # read wait before future_version
    FETCH_KEYS_BYTES_PER_BATCH: int = 1 << 20
    # durability-ring disk spill (ISSUE 11, the second memory wall): a
    # storage server whose ENGINE commits lag its ingest retains the
    # whole pending-durable window in the DurabilityRing — RSS grew
    # without bound under a throttled disk.  When retained bytes exceed
    # this budget, sealed segments spill (oldest first, fsync before
    # the memory drop) to a per-server DiskQueue side file
    # (storage-<tag>.dbuf.dq) and the per-tick commit slice reads them
    # back transparently.  The side file carries no recovery
    # obligation — the TLog is popped only after the engine commit, so
    # a reboot replays the ring from the TLog and the side file is
    # truncated at attach.  0 disables.  Memory-only servers (no
    # engine) never buffer durably and are unaffected.
    STORAGE_DBUF_SPILL_BYTES: int = 128 << 20
    # max mutations one synchronous _apply_batch slice may hold: a bulk
    # load's pull reply can carry 100k+ mutations, and applying them in
    # one event-loop turn is a ~100-500ms stall (SlowTask); the pull
    # loop yields between slices, never splitting a version
    STORAGE_APPLY_CHUNK_MUTATIONS: int = 32768
    # --- columnar MVCC window (ISSUE 13, ROADMAP item 5 (b)) ---
    # the storage server's in-memory version window as a generational
    # columnar store: a small mutable tip (per-key chains above the last
    # seal) plus immutable sealed segments (distinct-key KeyRun + int64
    # version column + value blob/bounds + tombstone bits).  All-SET
    # packed TLog batches seal directly off the MutationBatch columns;
    # drop_before retires whole segments in O(segments).  Off = the
    # legacy dict-of-per-key-chains window, retained as the
    # equivalence / RSS A/B twin (tools/perf_smoke.py --stage mvcc
    # measures both; bit-identical serving asserted in situ).
    STORAGE_MVCC_COLUMNAR: bool = True
    # seal budgets: the tip freezes into a segment when it holds this
    # many entries / this many key+value bytes / a version span this
    # wide (whichever trips first).  Smaller budgets mean more, smaller
    # segments (more probe layers before compaction); larger budgets
    # mean more per-key dict state in the tip.  The version span sits
    # just under the MVCC window so a low-rate trickle (sim traffic, a
    # quiet shard) lives its whole windowed life in the tip — point
    # reads stay one dict probe — while sustained batch traffic seals
    # on the ops/bytes budgets and bulk all-SET batches seal DIRECTLY
    # regardless.
    STORAGE_MVCC_SEAL_OPS: int = 8192
    STORAGE_MVCC_SEAL_BYTES: int = 4 << 20
    STORAGE_MVCC_SEAL_VERSIONS: int = 4_000_000

    # --- leveled lsm compaction (ISSUE 14, ROADMAP item 5 (d)) ---
    # the lsm engine's compaction as a leveled, partitioned, budget-
    # sliced BACKGROUND subsystem: L0 holds overlapping flush runs; L1+
    # hold key-range-disjoint partitioned runs, so one compaction
    # rewrites only the selected runs plus the OVERLAPPING next-level
    # partitions — write amplification drops from O(keyspace) per cycle
    # to O(overlap), and commit() never awaits a merge (it only nudges
    # the background compactor).  Off = the pre-ISSUE-14 monolithic
    # merge-every-run-into-one, awaited inline from commit(), kept
    # verbatim as the equivalence / write-amp A/B twin (the
    # STORAGE_MVCC_COLUMNAR pattern).  Both modes serve byte-identical
    # data (tests/test_lsm_leveled.py proves it on randomized op
    # streams) and either mode opens the other's MANIFEST.
    LSM_LEVELED_COMPACTION: bool = True
    # input bytes one compaction slice processes before yielding the
    # event loop (the budget that keeps a background merge from
    # stalling commits sharing the loop).  Sized for single-digit-ms
    # slices at Python merge speed: a commit awaiting the WAL between
    # two slices waits at most one slice, so this IS the compaction
    # tail the commit path can see (perf_smoke --stage compact bounds
    # it at ≤20% of the monolithic twin's worst inline merge)
    LSM_COMPACT_SLICE_BYTES: int = 128 << 10
    # level capacity multiplier: level i >= 1 holds FANOUT**(i-1) x the
    # L0-equivalent byte budget before its fullness scores a compaction
    LSM_LEVEL_FANOUT: int = 8

    # --- device read serving (ISSUE 6) ---
    # serve get_values' missing-key pass (the keys the MVCC window does
    # not resolve) through a device-resident mirror of the engine's
    # PackedKeyIndex: one vectorized searchsorted over keycode-u64
    # prefixes per batch instead of a per-key host descent.  The mirror
    # refreshes on index merges; a stale mirror or a batch below the
    # threshold falls back to the engine path (identical results, tested)
    STORAGE_DEVICE_READ_SERVE: bool = True
    STORAGE_DEVICE_READ_MIN_BATCH: int = 64
    # per-chip sharded mirror (ISSUE 18, ROADMAP 1 (d)): split the
    # packed key index across this many device shards by key range —
    # one shard per chip when jax.devices() has that many, round-robin
    # replicas on one chip otherwise (the CPU tier-1 shape).  A base
    # mutation then re-uploads ONLY the shards whose key span it
    # touched (the index's change log names the span), so the mirror
    # partially refreshes inline and keeps serving where the
    # single-directory twin falls back to the engine for a full
    # re-upload.  0/1 = the single DeviceKeyDirectory, kept verbatim
    # as the A/B twin (byte-identical results either way, asserted in
    # situ by perf_smoke --stage devplane).
    STORAGE_DEVICE_READ_SHARDS: int = 0

    # --- client read path ---
    # same-tick point-read coalescing: concurrent Transaction.get calls
    # (across transactions sharing a read version too — GRV batching
    # makes shared versions the common case) group by owning shard into
    # ONE packed GetValuesRequest, single-flight per shard.  Off =
    # scalar one-RPC-per-key reads (the pre-714 path; equivalence tests
    # compare against it)
    CLIENT_COALESCE_READS: bool = True
    # replica-read spreading (ISSUE 7): how ReplicaGroup orders a team
    # for snapshot-safe reads.  "score" = the pre-heat policy (penalty,
    # outstanding, random tiebreak); "rotate" = round-robin across
    # healthy replicas (zipfian read fan-out); "least" = deterministic
    # least-outstanding.  Failover semantics are identical under every
    # policy — only the FIRST-choice order changes.
    CLIENT_READ_LOAD_BALANCE: str = "score"
    # range-read streaming: first fetch asks for this many rows per
    # shard, then DOUBLES each round (the iterator-mode growth of
    # REF:fdbclient/NativeAPI.actor.cpp getRange) until a reply would
    # exceed CLIENT_RANGE_CHUNK_BYTES at the observed mean row size
    CLIENT_RANGE_CHUNK_ROWS: int = 128
    CLIENT_RANGE_CHUNK_BYTES: int = 1 << 20
    # columnar range reads (ISSUE 9): CLIENT range fetches
    # (Transaction.get_range's snapshot stream) ride the packed
    # GetRangeRequest/Reply RPC (sorted key blob + cumulative u32
    # bounds, per-chunk status byte), the engines extract whole
    # block/leaf runs, and overlay-free scans bulk-extend reply pages
    # client-side.  Off = get_range's scalar pre-715 tuple-list path,
    # kept as the equivalence/A-B baseline (byte-identical results,
    # tested).  The knob gates ONLY that client fetch choice: fetchKeys
    # shard moves, Transaction.get_range_packed and the backup snapshot
    # writer are packed-native by design — like mutations on
    # MutationBatch, the packed struct IS their protocol (both peers
    # speak 715 or the version gate fences them), so there is no scalar
    # fallback to toggle.
    CLIENT_PACKED_RANGE_READS: bool = True

    # --- backup / point-in-time restore (ISSUE 8) ---
    # feed-native backup: the agent tails a WHOLE-DATABASE change feed
    # through ChangeFeedCursor (begin_version is the complete resume
    # token) and persists packed .mlog files into a BackupContainer.
    # None of these change cluster behavior unless an agent is running.
    BACKUP_LOG_FLUSH_ENTRIES: int = 2048      # feed entries per .mlog flush
    BACKUP_LOG_FLUSH_INTERVAL: float = 0.25   # max seconds entries sit unflushed
    # a quiet feed still advances the durable resume frontier once the
    # heartbeat has proven this many versions empty (bounds the resume
    # re-scan after an agent crash on an idle database)
    BACKUP_HEARTBEAT_VERSIONS: int = 1_000_000
    # periodic \xff/backup/progress/<name> state transactions so status
    # (cluster.backup) sees snapshot/log frontiers + agent liveness
    BACKUP_PROGRESS_PUBLISH: bool = True
    BACKUP_PROGRESS_INTERVAL: float = 1.0
    BACKUP_SNAPSHOT_ROWS: int = 1000          # rows per packed snapshot file

    # --- transaction limits (REF:fdbclient/ClientKnobs, Limits in docs) ---
    KEY_SIZE_LIMIT: int = 10_000
    VALUE_SIZE_LIMIT: int = 100_000
    TRANSACTION_SIZE_LIMIT: int = 10_000_000
    DEFAULT_RETRY_LIMIT: int = -1             # unlimited
    DEFAULT_TIMEOUT: float = 0.0              # disabled
    DEFAULT_MAX_RETRY_DELAY: float = 1.0

    # --- rpc / failure detection ---
    FAILURE_TIMEOUT: float = 1.0
    PING_INTERVAL: float = 0.25
    CONNECT_TIMEOUT: float = 2.0

    # --- coordination / recovery ---
    LEADER_LEASE_DURATION: float = 2.0
    LEADER_HEARTBEAT_INTERVAL: float = 0.5
    RECOVERY_RETRY_DELAY: float = 0.5
    NOMINATION_TIMEOUT: float = 1.0           # unrefreshed candidacies lapse
    ELECTION_TIMEOUT: float = 8.0             # one elect_leader call's budget
    ELECTION_BACKOFF: float = 0.15            # base inter-round retry delay

    # --- tlog ---
    TLOG_SPILL_THRESHOLD: int = 1 << 30
    DISK_QUEUE_PAGE_SIZE: int = 4096
    LOG_REPLICATION: int = 2                  # TLogs hosting each tag (min'd with log count)
    TLOG_PEEK_RETRY: float = 0.05             # cursor poll while a generation is being ended

    # --- data distribution ---
    DD_ENABLED: bool = False                  # auto split/move loop on the CC
    DD_INTERVAL: float = 2.0                  # stats sampling period
    DD_SHARD_SPLIT_BYTES: int = 1 << 24       # split threshold (logical bytes)
    DD_MOVE_TIMEOUT: float = 30.0             # live-move catch-up deadline

    # --- shard heat (ISSUE 7) ---
    # per-storage-server decayed read/write rate tracking + key
    # reservoir (core/shard_load.py): always on — a few float ops per
    # batch, no RNG from the global sim stream — shipped to DD and the
    # Ratekeeper via the shard_metrics RPC.  The CONSUMERS are each
    # knob-gated; DD's heat policy and the client read spread default
    # OFF so same-seed sims replay the pre-heat behavior bit-exactly.
    SHARD_HEAT_HALFLIFE: float = 10.0         # rate decay half-life, seconds
    SHARD_HEAT_SAMPLES: int = 64              # reservoir capacity (keys)
    SHARD_HEAT_KEY_SAMPLE: int = 8            # sample 1 key per N recorded ops
    # heat-driven relocation: a shard sustaining DD_SHARD_HOT_RW_PER_SEC
    # (reads summed over the team + writes) for DD_HEAT_SUSTAIN_ROUNDS
    # consecutive DD rounds splits at the reservoir's heat midpoint —
    # or MOVES to a fresh team when the heat straddles a single key —
    # then cools down for DD_HEAT_COOLDOWN_S so oscillating load cannot
    # thrash fetchKeys
    DD_SHARD_HEAT_SPLITS: bool = False
    DD_SHARD_HOT_RW_PER_SEC: float = 5000.0
    DD_HEAT_SUSTAIN_ROUNDS: int = 2
    DD_HEAT_COOLDOWN_S: float = 10.0
    # heat-driven RESOLVER boundary rebalance (ISSUE 16): DD rolls the
    # storage shard-heat reservoirs up into the resolver partitions;
    # when the hottest partition sustains >= RATIO x the mean heat for
    # SUSTAIN consecutive rounds, DD writes a desired boundary list
    # (split the hot partition at its heat midpoint, merge the coldest
    # adjacent pair — partition count preserved) to a system key that
    # the NEXT epoch's recruitment applies: a state-txn remap, with
    # each partition's conflict window rebuilt from the tlogs exactly
    # as any recovery rebuilds it.  Gated separately from the heat
    # split policy so sims can exercise one without the other.
    RESOLVER_REBALANCE: bool = False
    RESOLVER_REBALANCE_RATIO: float = 2.0
    RESOLVER_REBALANCE_SUSTAIN_ROUNDS: int = 2

    # --- consistency scrub (ISSUE 17) ---
    # the online replica-audit plane: a singleton scrubber on the
    # leading ClusterHost (the DD recruitment shape) continuously walks
    # the shard map, pins a read version per chunk via GRV, fans a
    # scrub_page digest request to EVERY replica in each shard's team
    # (degraded included — auditing them is the point), and bisects any
    # digest mismatch down to exact divergent rows via the packed range
    # read path (severity-40 ScrubMismatch).  A frontier invariant
    # watchdog rides the same role: per-tag version-order assertions
    # off the live metrics plane (severity-40 ScrubInvariantViolation).
    # Scrub reads are read-only and pacing rides the loop clock, so
    # same-seed sim traces are bit-identical with the knob either way.
    SCRUB_ENABLED: bool = False
    SCRUB_PAGES_PER_SEC: float = 50.0         # pass pacing budget
    SCRUB_PAGE_ROWS: int = 256                # rows per digest page
    SCRUB_MAX_PAGES_PER_REQUEST: int = 32     # pages per scrub_page RPC
    SCRUB_PASS_INTERVAL: float = 5.0          # idle between full passes
    SCRUB_WATCHDOG_INTERVAL: float = 2.0      # invariant-check cadence
    SCRUB_MAX_REPORTED_ROWS: int = 16         # ScrubMismatch events per page

    # --- layers (ISSUE 19) ---
    # the layer ecosystem (foundationdb_tpu/layers/): secondary indexes,
    # the invalidating read-through cache, and feed-riding key watches,
    # all client-side constructions over ordinary transactions and the
    # change-feed cursor.  NOTHING here runs unless a layer object is
    # constructed — the knobs only tune layers that a client explicitly
    # builds, so same-seed sim traces with no layers in the workload are
    # bit-identical regardless of these values (the determinism children
    # pin them BOTH ways to prove it).
    LAYER_FEED_POLL_INTERVAL: float = 0.05    # consumer idle re-poll pace
    LAYER_FEED_POP_LAG_VERSIONS: int = 1_000_000  # pop feed this far behind frontier
    LAYER_INDEX_TRANSACTIONAL: bool = True    # index mode: same-commit rows vs feed-driven
    LAYER_CACHE_CAPACITY: int = 4096          # read-through cache entries (LRU)
    LAYER_WATCH_LIMIT: int = 10_000           # pending watches per registry
    LAYER_PROGRESS_INTERVAL: float = 1.0      # \xff/layers/progress publish pace
    LAYER_CHECK_PAGE_ROWS: int = 256          # checker rows per packed page

    # --- observability ---
    METRICS_INTERVAL: float = 5.0             # role *Metrics emit period
    # the continuous metrics plane (ISSUE 15): every role registers its
    # counters/histograms/gauges in the hosting process's
    # MetricsRegistry, and ONE per-worker emitter actor drains them
    # every METRICS_INTERVAL on the loop clock (sim-deterministic).
    # Off = registry still populated (status snapshots work) but no
    # periodic *Metrics emission — the A/B twin the observe smoke and
    # the determinism children measure against.
    METRICS_EMITTER: bool = True

    # --- ratekeeper ---
    RATEKEEPER_UPDATE_INTERVAL: float = 0.25
    TARGET_STORAGE_QUEUE_BYTES: int = 1 << 30
    TARGET_TLOG_QUEUE_BYTES: int = 1 << 31
    TARGET_DURABILITY_LAG_VERSIONS: int = 20_000_000  # 4x the MVCC window: steady-state lag == window is healthy
    RATEKEEPER_MAX_TPS: float = 1e6
    RATEKEEPER_MIN_TPS: float = 10.0
    # a txn tag whose smoothed share of default-lane GRV demand reaches
    # this while the cluster is limited gets its own clamp (tag
    # throttling) instead of dragging the global rate down
    TAG_THROTTLE_DEMAND_SHARE: float = 0.5
    # heat-armed tag throttling (ISSUE 7): when ONE shard's write-byte
    # rate alone would fill TARGET_STORAGE_QUEUE_BYTES within
    # RATEKEEPER_HEAT_WEDGE_S (and its write op rate clears the floor
    # below), the dominant demand tag is clamped BEFORE the global
    # falloff engages — GRV sheds the hot tenant, cold tenants never
    # feel the storage queue wedge.  Arms only when a dominant tag
    # exists, so untagged workloads see no behavior change.
    RATEKEEPER_HEAT_THROTTLE: bool = True
    RATEKEEPER_HOT_SHARD_WRITES_PER_SEC: float = 20_000.0
    RATEKEEPER_HEAT_WEDGE_S: float = 30.0

    # --- simulation ---
    SIM_NETWORK_MIN_DELAY: float = 0.0005
    SIM_NETWORK_MAX_DELAY: float = 0.005
    SIM_CONNECT_DELAY: float = 0.01
    BUGGIFY_ENABLED: bool = False
    # --- simulated disk faults (ISSUE 12, the AsyncFileNonDurable
    # model): OFF by default so same-seed traces with faults off stay
    # bit-identical — arming draws the profile's seed from the sim rng.
    # DiskFaultWorkload arms per-machine profiles mid-run regardless of
    # the master knob; SIM_DISK_FAULTS=True arms every machine at boot.
    SIM_DISK_FAULTS: bool = False
    SIM_DISK_IO_ERROR_P: float = 0.01     # per-op IoError probability
    SIM_DISK_STALL_P: float = 0.02        # per-op random stall probability
    SIM_DISK_STALL_MAX_S: float = 0.05    # random stall upper bound
    SIM_DISK_TORN_P: float = 0.75         # per-kill torn-write probability
    SIM_DISK_CORRUPT_P: float = 0.25      # per-surviving-sector corruption
    SIM_DISK_SECTOR: int = 512            # tear granularity, bytes

    # --- gray-failure detection (ISSUE 12): decayed per-op disk latency
    # per machine; a sustained mean above the threshold marks the disk
    # degraded — published via role metrics, polled into the
    # FailureMonitor by the CC, deprioritized by recruitment and DD
    # move-destination picking.  Detection is passive arithmetic (no
    # RNG); the CC poll is its own RPC loop, gated by the interval knob
    # (0 disables).
    DISK_DEGRADED_LATENCY_MS: float = 25.0
    DISK_HEALTH_HALFLIFE_S: float = 5.0
    CC_DISK_HEALTH_INTERVAL: float = 1.0
    # un-degrade dwell (ROADMAP 6 (b), the _watch_region_preference
    # hysteresis shape): the CC clears a machine's degraded flag only
    # after its reports have stayed healthy for this long — a flapping
    # disk (decayed mean oscillating around the threshold) can no
    # longer thrash recruitment ordering / DD destination picking each
    # poll.  Degrading remains immediate.  0 restores flip-on-sample.
    CC_DISK_UNDEGRADE_DWELL_S: float = 5.0

    def override(self, **kv: Any) -> "Knobs":
        return dataclasses.replace(self, **kv)

    def set_from_strings(self, overrides: dict[str, str]) -> "Knobs":
        """Apply --knob_name=value style overrides with type coercion."""
        kv: dict[str, Any] = {}
        for name, sval in overrides.items():
            name = name.upper()
            field = self.__dataclass_fields__.get(name)
            if field is None:
                raise KeyError(f"unknown knob {name}")
            # field.type is a string under PEP 563; coerce by the type of the
            # class default, which is authoritative for every knob.
            t = type(field.default)
            if t is bool:
                kv[name] = sval.lower() in ("1", "true", "on", "yes")
            elif t is int:
                kv[name] = int(sval)
            elif t is float:
                kv[name] = float(sval)
            else:
                kv[name] = sval
        return self.override(**kv)


# Process-global default knobs (roles may carry their own copy).
KNOBS = Knobs()


def set_global_knobs(k: Knobs) -> None:
    global KNOBS
    KNOBS = k
