"""Commit-path latency instrumentation: per-stage stats + sampled per-txn
TraceBatch probes.

Reference: the reference attributes per-transaction stage latency with
``TraceBatch`` events (REF:flow/Trace.h TraceBatch; SURVEY §5.1 "latency
probes via TraceBatch for sampled transactions") and aggregates role-side
stage timings into rolled metrics.  Two instruments here:

- ``StageStats`` — a per-role accumulator of (stage -> seconds) samples;
  roles on the commit path (GrvProxy, CommitProxy, Resolver) record each
  stage's duration, and harnesses (bench/e2e.py) read ``summary()`` to
  put a GRV-wait / batch-fill / version-wait / resolve / push breakdown
  in the bench artifact (VERDICT r4 item 1a).
- ``TraceBatch`` — sampled per-transaction probes: roughly 1 in
  ``1/CLIENT_LATENCY_PROBE_SAMPLE`` transactions carries a probe; each
  stage appends a (name, t) pair and the flush emits ONE structured
  "TransactionTrace" TraceEvent with stage deltas in ms, so a single
  sampled txn's whole commit path can be read off one trace line.
"""

from __future__ import annotations

from typing import Optional

from .trace import TraceEvent


class StageStats:
    """Bounded per-stage duration accumulator (seconds in, ms out)."""

    __slots__ = ("name", "_samples", "_count", "_sum", "_max", "cap")

    def __init__(self, name: str, cap: int = 65536) -> None:
        self.name = name
        self.cap = cap
        self._samples: dict[str, list[float]] = {}
        self._count: dict[str, int] = {}
        self._sum: dict[str, float] = {}
        # running max, tracked OUTSIDE the bounded sample list: a stall
        # arriving after the cap fills must still move max_ms (the whole
        # point of the apply-path consumer)
        self._max: dict[str, float] = {}

    def record(self, stage: str, seconds: float) -> None:
        s = self._samples.setdefault(stage, [])
        n = self._count.get(stage, 0)
        self._count[stage] = n + 1
        self._sum[stage] = self._sum.get(stage, 0.0) + seconds
        # seed-or-raise, never strict-compare against a 0.0 default: a
        # virtual-time clock (SimEventLoop) measures synchronous work as
        # EXACTLY 0.0 seconds, and `0.0 > 0.0` left the stage out of
        # _max while _samples had it — summary() then KeyErrored
        m = self._max.get(stage)
        if m is None or seconds > m:
            self._max[stage] = seconds
        # ring overwrite, not first-N: percentiles must track the
        # TRAILING cap samples on a long-lived role, or a regression
        # arriving after the reservoir fills never moves p50/p99
        if len(s) < self.cap:
            s.append(seconds)
        else:
            s[n % self.cap] = seconds

    def reset(self) -> None:
        self._samples.clear()
        self._count.clear()
        self._sum.clear()
        self._max.clear()

    def summary(self) -> dict[str, dict[str, float]]:
        """{stage: {n, mean_ms, p50_ms, p99_ms, max_ms}} — percentiles
        over the (bounded) retained samples, mean over everything
        recorded.  ``max_ms`` names the worst single sample — the
        apply-path consumer wants the longest event-loop occupancy, not
        just the p99 (one 900ms index merge IS the r5 incident)."""
        out: dict[str, dict[str, float]] = {}
        for stage, s in self._samples.items():
            if not s:
                continue
            xs = sorted(s)
            n = self._count[stage]
            out[stage] = {
                "n": n,
                "mean_ms": round(self._sum[stage] / n * 1e3, 3),
                "p50_ms": round(xs[len(xs) // 2] * 1e3, 3),
                "p99_ms": round(xs[min(len(xs) - 1,
                                       int(len(xs) * 0.99))] * 1e3, 3),
                "max_ms": round(self._max[stage] * 1e3, 3),
            }
        return out


def merge_summaries(summaries: list[dict]) -> dict[str, dict[str, float]]:
    """Weighted-mean merge of several roles' summaries (percentiles take
    the max across roles — conservative for a breakdown artifact)."""
    out: dict[str, dict[str, float]] = {}
    for s in summaries:
        for stage, row in s.items():
            cur = out.get(stage)
            if cur is None:
                out[stage] = dict(row)
                continue
            n = cur["n"] + row["n"]
            cur["mean_ms"] = round((cur["mean_ms"] * cur["n"]
                                    + row["mean_ms"] * row["n"]) / n, 3)
            cur["p50_ms"] = max(cur["p50_ms"], row["p50_ms"])
            cur["p99_ms"] = max(cur["p99_ms"], row["p99_ms"])
            if "max_ms" in cur or "max_ms" in row:
                cur["max_ms"] = max(cur.get("max_ms", 0.0),
                                    row.get("max_ms", 0.0))
            cur["n"] = n
    return out


# process-wide probe-eviction rollup (ISSUE 17 satellite): per-instance
# ``evictions`` counts die with their owning client object, so probe
# loss under load was silent — role metrics() and the worker gauges
# read THIS.  Reset with span.reset_totals() (same determinism contract:
# a harness re-running a seeded sim in one process restarts the count).
EVICTIONS_TOTAL = {"probe_evictions": 0}


class TraceBatch:
    """Sampled per-transaction stage probes (one trace line per sampled
    txn).  ``attach()`` rolls the sampling dice; probes on unsampled ids
    are no-ops, so the fast path costs one dict lookup."""

    def __init__(self, sample_rate: float = 0.01, clock=None,
                 live_cap: int = 4096) -> None:
        # deterministic counter-based sampling (no RNG: the probe must
        # not perturb seeded simulation streams)
        self._every = max(1, int(round(1.0 / sample_rate))) \
            if sample_rate > 0 else 0
        self._n = 0
        self._live: dict[int, list[tuple[str, float]]] = {}
        self._clock = clock
        # bound the live table: a sampled txn abandoned without
        # flush/discard (client crash mid-retry, dropped task) would
        # otherwise leak its probe record forever.  Insertion order IS
        # age (dict semantics), so eviction drops the oldest probe.
        self._live_cap = max(1, live_cap)
        self.evictions = 0

    def _now(self) -> float:
        if self._clock is not None:
            return self._clock()
        import asyncio
        return asyncio.get_running_loop().time()

    def attach(self, txn_id: int) -> bool:
        """Maybe start a probe for this transaction; True if sampled."""
        if not self._every:
            return False
        self._n += 1
        if self._n % self._every:
            return False
        self._live[txn_id] = [("start", self._now())]
        if len(self._live) > self._live_cap:
            oldest = next(iter(self._live))
            del self._live[oldest]
            self.evictions += 1
            EVICTIONS_TOTAL["probe_evictions"] += 1
        return True

    def event(self, txn_id: int, name: str) -> None:
        rec = self._live.get(txn_id)
        if rec is not None:
            rec.append((name, self._now()))

    def discard(self, txn_id: int) -> None:
        self._live.pop(txn_id, None)

    def flush(self, txn_id: int, outcome: str = "committed") -> Optional[dict]:
        """Emit the sampled txn's stage deltas as one TransactionTrace
        event; returns the {stage: ms} dict (None if not sampled)."""
        rec = self._live.pop(txn_id, None)
        if rec is None:
            return None
        ev = TraceEvent("TransactionTrace")
        ev.detail("Txn", txn_id).detail("Outcome", outcome)
        deltas: dict[str, float] = {}
        for (prev_name, prev_t), (name, t) in zip(rec, rec[1:]):
            ms = round((t - prev_t) * 1e3, 3)
            deltas[name] = ms
            ev.detail(name.title().replace("_", "") + "Ms", ms)
        total = round((rec[-1][1] - rec[0][1]) * 1e3, 3)
        deltas["total"] = total
        ev.detail("TotalMs", total).log()
        return deltas
