"""Structured event logging — the sole observability substrate.

Reference: REF:flow/Trace.h/.cpp (TraceEvent with .detail(k,v) chaining,
Severity levels, rolled files, rate limiting) and REF:fdbrpc/Stats.h
(Counter/CounterCollection emitting periodic *Metrics events).

We emit JSON-lines. In simulation, time comes from the virtual clock so
logs are deterministic given a seed.
"""

from __future__ import annotations

import io
import json
import os
import sys
import threading
import time as _time
from typing import Any, Callable, Optional


def _default_clock() -> float:
    """Virtual time when called inside a running event loop, else wall time.

    This is what makes sim trace output deterministic by default: under
    run_simulation the running loop is a SimEventLoop whose time() is the
    virtual clock.
    """
    try:
        import asyncio
        return asyncio.get_running_loop().time()
    except RuntimeError:
        return _time.time()


def _next_roll_gen(path: str) -> int:
    """Continue the .N roll sequence past any files left by a previous run."""
    gen = 0
    d = os.path.dirname(path) or "."
    base = os.path.basename(path)
    try:
        for name in os.listdir(d):
            if name.startswith(base + "."):
                suffix = name[len(base) + 1:]
                if suffix.isdigit():
                    gen = max(gen, int(suffix))
    except OSError:
        pass
    return gen


class Severity:
    DEBUG = 5
    INFO = 10
    WARN = 20
    WARN_ALWAYS = 30
    ERROR = 40


class TraceLog:
    """Destination for trace events: a JSONL stream, optionally rolled."""

    def __init__(self, path: Optional[str] = None, min_severity: int = Severity.INFO,
                 clock: Optional[Callable[[], float]] = None, roll_bytes: int = 50 << 20):
        self.min_severity = min_severity
        self.clock = clock or _default_clock
        self.path = path
        self.roll_bytes = roll_bytes
        self._written = 0
        self._gen = _next_roll_gen(path) if path else 0
        self._lock = threading.Lock()
        self._fh: Optional[io.TextIOBase] = None
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            try:
                self._written = os.path.getsize(path)
            except OSError:
                pass
            self._fh = open(path, "a", buffering=1)
        self.event_count = 0
        self.sink: Optional[Callable[[dict], None]] = None  # test hook

    def emit(self, event: dict) -> None:
        self.event_count += 1
        if self.sink is not None:
            self.sink(event)
            return
        line = json.dumps(event, separators=(",", ":"), default=str)
        with self._lock:
            if self._fh is not None:
                self._fh.write(line + "\n")
                self._written += len(line) + 1
                if self._written >= self.roll_bytes:
                    self._roll()
            else:
                sys.stderr.write(line + "\n")

    def _roll(self) -> None:
        assert self._fh is not None and self.path is not None
        self._fh.close()
        self._gen += 1
        os.replace(self.path, f"{self.path}.{self._gen}")
        self._fh = open(self.path, "a", buffering=1)
        self._written = 0

    def close(self) -> None:
        # under the write lock: a concurrent emit() must never see a
        # closed-but-not-None handle (ValueError on a live thread)
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


_GLOBAL = TraceLog()


def set_trace_log(log: TraceLog) -> None:
    global _GLOBAL
    _GLOBAL = log


def get_trace_log() -> TraceLog:
    return _GLOBAL


class TraceEvent:
    """``TraceEvent("CommitBatch", sev=...).detail("Txns", n).log()``.

    Also logs automatically when used as a context-less statement via
    ``__del__``-free explicit ``log()`` (we do not rely on GC, unlike the
    C++ destructor-logging idiom).
    """

    def __init__(self, type_: str, severity: int = Severity.INFO,
                 log: Optional[TraceLog] = None):
        self._log = log or _GLOBAL
        self.severity = severity
        self.fields: dict[str, Any] = {"Type": type_}

    def detail(self, key: str, value: Any) -> "TraceEvent":
        self.fields[key] = value
        return self

    def error(self, e: BaseException) -> "TraceEvent":
        self.fields["Error"] = getattr(e, "name", type(e).__name__)
        self.fields["ErrorCode"] = getattr(e, "code", 0)
        self.severity = max(self.severity, Severity.WARN)
        return self

    def log(self) -> None:
        if self.severity < self._log.min_severity:
            return
        ev = {"Time": round(self._log.clock(), 6), "Severity": self.severity}
        ev.update(self.fields)
        self._log.emit(ev)


class Counter:
    """Monotonic counter with rate; emitted via CounterCollection (REF:fdbrpc/Stats.h)."""

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def add(self, n: int = 1) -> None:
        self.value += n

    def __iadd__(self, n: int) -> "Counter":
        self.value += n
        return self


class Histogram:
    """32-bucket power-of-two histogram (REF:flow/Histogram.h): bucket i
    counts samples in [2^i, 2^(i+1)) — microseconds for latency use.
    Emitted as one trace event per interval, like the reference's
    Histogram::writeToLog."""

    def __init__(self, group: str, op: str, unit: str = "microseconds"):
        self.group = group
        self.op = op
        self.unit = unit
        self.buckets = [0] * 32
        self.count = 0
        self.total = 0.0
        self.min: float | None = None
        self.max: float | None = None

    def sample(self, x: float) -> None:
        i = max(0, min(31, int(x).bit_length() - 1)) if x >= 1 else 0
        self.buckets[i] += 1
        self.count += 1
        self.total += x
        self.min = x if self.min is None else min(self.min, x)
        self.max = x if self.max is None else max(self.max, x)

    def sample_seconds(self, seconds: float) -> None:
        self.sample(seconds * 1e6)

    def percentile(self, p: float) -> float:
        """Upper bound of the bucket where the cumulative count crosses
        p (0..1); 0 when empty."""
        if self.count == 0:
            return 0.0
        target = p * self.count
        seen = 0
        for i, n in enumerate(self.buckets):
            seen += n
            if seen >= target:
                return float(1 << (i + 1))
        return float(1 << 32)

    def clear(self) -> None:
        self.buckets = [0] * 32
        self.count = 0
        self.total = 0.0
        self.min = self.max = None

    def log_metrics(self, log: Optional[TraceLog] = None,
                    id_: str = "") -> None:
        if self.count == 0:
            return
        ev = TraceEvent(f"Histogram{self.group}{self.op}", log=log or _GLOBAL)
        if id_:
            # instance id (the metrics plane passes its source id) so two
            # proxies' latency series don't merge in trace tooling
            ev.detail("ID", id_)
        ev.detail("Unit", self.unit).detail("Count", self.count) \
            .detail("Min", round(self.min or 0, 1)) \
            .detail("Max", round(self.max or 0, 1)) \
            .detail("Mean", round(self.total / self.count, 1)) \
            .detail("P50", self.percentile(0.5)) \
            .detail("P95", self.percentile(0.95)) \
            .detail("P99", self.percentile(0.99)).log()
        self.clear()


class CounterCollection:
    def __init__(self, name: str, id_: str = ""):
        self.name = name
        self.id = id_
        self.counters: dict[str, Counter] = {}
        self._last_values: dict[str, int] = {}
        self._last_time: Optional[float] = None

    def counter(self, name: str) -> Counter:
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter(name)
        return c

    def log_metrics(self, log: Optional[TraceLog] = None,
                    extra: Optional[dict] = None) -> None:
        """Emit one ``<Name>Metrics`` event: counter values + per-interval
        rates, plus ``extra`` details (the metrics plane folds gauge and
        meter samples in here so one series carries the whole source)."""
        lg = log or _GLOBAL
        now = lg.clock()
        ev = TraceEvent(f"{self.name}Metrics", log=lg).detail("ID", self.id)
        dt = (now - self._last_time) if self._last_time is not None else None
        for n, c in self.counters.items():
            ev.detail(n, c.value)
            if dt and dt > 0:
                ev.detail(f"{n}Rate", round((c.value - self._last_values.get(n, 0)) / dt, 3))
            self._last_values[n] = c.value
        self._last_time = now
        for k, v in (extra or {}).items():
            ev.detail(k, v)
        ev.log()
