"""L0 runtime: the Flow-analog layer (REF:flow/)."""

from .errors import FdbError, error_from_code
from .knobs import Knobs, KNOBS, set_global_knobs
from .rng import DeterministicRandom, deterministic_random, set_deterministic_random
from .simloop import SimEventLoop, SimQuiescenceError, run_simulation
from .trace import TraceEvent, TraceLog, Severity, Counter, CounterCollection, set_trace_log, get_trace_log
from .buggify import buggify, enable_buggify, buggify_enabled
from .actors import (Promise, PromiseStream, ActorCollection, wait_for_all,
                     timeout_error, delay, now)

__all__ = [
    "FdbError", "error_from_code", "Knobs", "KNOBS", "set_global_knobs",
    "DeterministicRandom", "deterministic_random", "set_deterministic_random",
    "SimEventLoop", "SimQuiescenceError", "run_simulation",
    "TraceEvent", "TraceLog", "Severity", "Counter", "CounterCollection",
    "set_trace_log", "get_trace_log",
    "buggify", "enable_buggify", "buggify_enabled",
    "Promise", "PromiseStream", "ActorCollection", "wait_for_all",
    "timeout_error", "delay", "now",
]
