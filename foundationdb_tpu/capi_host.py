"""The C-ABI host: a thread-confined client runtime with blocking entry
points, driven by bindings/c/fdbtpu_c.cpp through the CPython API.

Reference: the role of fdb_c's network thread (REF:bindings/c/fdb_c.cpp
runNetwork) — one background thread owns the event loop and every binding
call marshals onto it.  ``Host`` methods are called from arbitrary C
threads (under the GIL) and block on ``run_coroutine_threadsafe``;
``concurrent.futures.Future.result`` releases the GIL while waiting, so
callers never deadlock the loop thread.
"""

from __future__ import annotations

import asyncio
import itertools
import threading

from .client.transaction import Transaction
from .core.cluster_client import RecoveredClusterView, fetch_cluster_state
from .core.cluster_file import ClusterFile
from .rpc.stubs import CoordinatorClient
from .rpc.tcp_transport import TcpTransport
from .rpc.transport import NetworkAddress, WLTOKEN_COORDINATOR
from .runtime.errors import FdbError, error_from_code
from .runtime.knobs import Knobs

_C_CLIENT_PORT = itertools.count(1)


class Host:
    """One per process; owns the loop thread and the transaction table."""

    def __init__(self, cluster_file: str, connect_timeout: float = 30.0):
        self.knobs = Knobs()
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fdbtpu-c-network")
        self._thread.start()
        self._txns: dict[int, Transaction] = {}
        self._txn_ids = itertools.count(1)
        self._view = self._call(self._open(cluster_file, connect_timeout))

    def _run(self) -> None:
        asyncio.set_event_loop(self._loop)
        self._loop.run_forever()

    def _call(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self._loop).result()

    async def _open(self, cluster_file: str, timeout: float):
        cf = ClusterFile.parse(cluster_file) if "@" in cluster_file \
            else ClusterFile.load(cluster_file)
        t = TcpTransport(NetworkAddress("127.0.0.1", 0))
        self._coords = [CoordinatorClient(t, a, WLTOKEN_COORDINATOR)
                        for a in cf.coordinators]
        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            try:
                state = await fetch_cluster_state(self._coords)
                break
            except (FdbError, OSError):
                if asyncio.get_running_loop().time() > deadline:
                    raise
                await asyncio.sleep(0.5)
        return RecoveredClusterView(self.knobs, t, state)

    # --- the C surface (each returns (err_code, payload...)) ---

    def stop(self) -> None:
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)

    def create_transaction(self) -> int:
        tid = next(self._txn_ids)
        self._txns[tid] = Transaction(self._view)
        return tid

    def destroy_transaction(self, tid: int) -> None:
        self._txns.pop(tid, None)

    @staticmethod
    def _code(e: BaseException) -> int:
        return e.code if isinstance(e, FdbError) else 4100  # internal_error

    def txn_get(self, tid: int, key: bytes):
        """-> (err, present, value|b'')"""
        tr = self._txns[tid]
        try:
            v = self._call(tr.get(key))
        except BaseException as e:  # noqa: BLE001 — code crosses the ABI
            return self._code(e), 0, b""
        return 0, (1 if v is not None else 0), v or b""

    def txn_set(self, tid: int, key: bytes, value: bytes) -> int:
        try:
            self._call(self._sync(self._txns[tid].set, key, value))
        except BaseException as e:  # noqa: BLE001
            return self._code(e)
        return 0

    def txn_clear(self, tid: int, key: bytes) -> int:
        try:
            self._call(self._sync(self._txns[tid].clear, key))
        except BaseException as e:  # noqa: BLE001
            return self._code(e)
        return 0

    @staticmethod
    async def _sync(fn, *args):
        return fn(*args)

    def txn_get_range(self, tid: int, begin: bytes, end: bytes,
                      limit: int, reverse: int):
        """-> (err, packed, count); packed = ([u32 klen][key][u32 vlen]
        [value]) * count, little-endian — one flat buffer crossing the
        ABI (the fdb_c FDBKeyValue array analog)."""
        import struct
        tr = self._txns[tid]
        try:
            rows = self._call(tr.get_range(begin, end, limit=limit,
                                           reverse=bool(reverse)))
        except BaseException as e:  # noqa: BLE001 — code crosses the ABI
            return self._code(e), b"", 0
        out = bytearray()
        for k, v in rows:
            k, v = bytes(k), bytes(v)
            out += struct.pack("<I", len(k)) + k
            out += struct.pack("<I", len(v)) + v
        return 0, bytes(out), len(rows)

    def txn_atomic_op(self, tid: int, op: int, key: bytes,
                      operand: bytes) -> int:
        from .core.data import ATOMIC_TYPES, MutationType
        try:
            mt = MutationType(op)
        except ValueError:
            return 2007  # invalid_option (unknown mutation opcode)
        if mt not in ATOMIC_TYPES:
            # SET_VALUE/CLEAR_RANGE ride their own entry points, and
            # private opcodes (shard drops) must never cross the ABI —
            # a forged one would be client-triggered data loss
            return 2007
        try:
            self._call(self._sync(self._txns[tid].atomic_op, mt, key,
                                  operand))
        except BaseException as e:  # noqa: BLE001
            return self._code(e)
        return 0

    def txn_get_read_version(self, tid: int):
        """-> (err, version)"""
        try:
            v = self._call(self._txns[tid].get_read_version())
        except BaseException as e:  # noqa: BLE001
            return self._code(e), -1
        return 0, v

    def txn_set_option(self, tid: int, option: str) -> int:
        """fdb_transaction_set_option analog (named, no packed ints)."""
        if option == "lock_aware":
            self._txns[tid].lock_aware = True
            return 0
        return 2007  # invalid_option

    def txn_commit(self, tid: int):
        """-> (err, committed_version)"""
        tr = self._txns[tid]
        try:
            self._call(tr.commit())
            return 0, tr.get_committed_version()
        except BaseException as e:  # noqa: BLE001
            return self._code(e), -1

    def txn_on_error(self, tid: int, code: int) -> int:
        tr = self._txns[tid]
        try:
            self._call(tr.on_error(error_from_code(code)))
            return 0
        except BaseException as e:  # noqa: BLE001
            return self._code(e)

    def txn_reset(self, tid: int) -> int:
        self._txns[tid].reset()
        return 0


_HOST: Host | None = None


def init(cluster_file: str) -> int:
    """C entry: start the runtime.  Returns an error code (0 ok)."""
    global _HOST
    if _HOST is not None:
        return 2201  # network_already_setup
    try:
        _HOST = Host(cluster_file)
    except BaseException as e:  # noqa: BLE001
        return e.code if isinstance(e, FdbError) else 4100
    return 0


def stop() -> int:
    global _HOST
    if _HOST is not None:
        _HOST.stop()
        _HOST = None
    return 0


def host() -> Host:
    assert _HOST is not None, "fdbtpu_init() not called"
    return _HOST


def error_message(code: int) -> str:
    try:
        return error_from_code(code).name
    except Exception:  # noqa: BLE001
        return f"error_{code}"
