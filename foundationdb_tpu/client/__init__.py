"""Client library: Database / Transaction with read-your-writes.

Reference: REF:fdbclient/NativeAPI.actor.cpp (Transaction) wrapped by
REF:fdbclient/ReadYourWrites.actor.cpp (RYW cache + conflict-range
bookkeeping).  Here both collapse into one Transaction class because the
RYW layer is not optional in practice.
"""

from .database import Database
from .transaction import Transaction
from .change_feed import ChangeFeedCursor
from ..core.data import KeySelector
