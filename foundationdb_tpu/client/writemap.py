"""Per-transaction buffered-write state for read-your-writes.

Reference: REF:fdbclient/WriteMap.h — upstream keeps a PTree of write
entries (sets, clears, atomic-op stacks) merged on the fly with snapshot
data by RYWIterator.  Here: a dict of per-key operation stacks plus a
sorted list of disjoint cleared ranges; merging happens in
transaction.py's read path.

Per-key stack semantics (matching WriteMap's OperationStack):
  ('set', value)            — known value, stack resets
  ('clear',)                — known-missing, stack resets
  ('atomic', op, operand)*  — appended; base may be unknown (needs a
                              snapshot read to fold)
"""

from __future__ import annotations

import bisect

from ..core.data import Mutation, MutationType, apply_atomic, key_after


class WriteMap:
    def __init__(self) -> None:
        self._stacks: dict[bytes, list[tuple]] = {}
        self._clears: list[tuple[bytes, bytes]] = []  # disjoint, sorted
        self.mutations: list[Mutation] = []           # commit order preserved
        self.bytes = 0

    def __bool__(self) -> bool:
        return bool(self.mutations)

    # --- mutation entry points ---

    def set(self, key: bytes, value: bytes) -> None:
        self.mutations.append(Mutation.set(key, value))
        self.bytes += len(key) + len(value)
        self._stacks[key] = [("set", value)]

    def clear_range(self, begin: bytes, end: bytes) -> None:
        self.mutations.append(Mutation.clear_range(begin, end))
        self.bytes += len(begin) + len(end)
        for k in [k for k in self._stacks if begin <= k < end]:
            self._stacks[k] = [("clear",)]
        self._insert_clear(begin, end)

    def atomic(self, op: MutationType, key: bytes, operand: bytes) -> None:
        self.mutations.append(Mutation(op, key, operand))
        self.bytes += len(key) + len(operand)
        stack = self._stacks.get(key)
        if stack is None:
            # a prior clear_range covering the key pins the base to missing
            stack = [("clear",)] if self.range_cleared(key) else []
            self._stacks[key] = stack
        stack.append(("atomic", op, operand))

    def _insert_clear(self, begin: bytes, end: bytes) -> None:
        merged = []
        for b, e in self._clears:
            if e < begin or b > end:
                merged.append((b, e))
            else:
                begin, end = min(begin, b), max(end, e)
        merged.append((begin, end))
        merged.sort()
        self._clears = merged

    # --- read-your-writes queries ---

    def range_cleared(self, key: bytes) -> bool:
        i = bisect.bisect_right(self._clears, (key, b"\xff" * 64)) - 1
        return i >= 0 and self._clears[i][0] <= key < self._clears[i][1]

    def lookup(self, key: bytes) -> tuple[str, object]:
        """('value', v|None) if fully determined by writes;
        ('stack', ops) if atomics need a snapshot base;
        ('none', None) if untouched."""
        stack = self._stacks.get(key)
        if stack is None:
            return ("value", None) if self.range_cleared(key) else ("none", None)
        return self._fold(stack)

    @staticmethod
    def _fold(stack: list[tuple]) -> tuple[str, object]:
        base_known = False
        value: bytes | None = None
        pending: list[tuple] = []
        for op in stack:
            if op[0] == "set":
                base_known, value, pending = True, op[1], []
            elif op[0] == "clear":
                base_known, value, pending = True, None, []
            else:
                pending.append(op)
        if not base_known and pending:
            return ("stack", pending)
        for _, aop, operand in pending:
            value = apply_atomic(aop, value, operand)
        return ("value", value)

    @staticmethod
    def fold_with_base(pending: list[tuple], base: bytes | None) -> bytes | None:
        value = base
        for _, aop, operand in pending:
            value = apply_atomic(aop, value, operand)
        return value

    def written_keys_in(self, begin: bytes, end: bytes) -> list[bytes]:
        return sorted(k for k in self._stacks if begin <= k < end)

    def clears_in(self, begin: bytes, end: bytes) -> list[tuple[bytes, bytes]]:
        return [(max(b, begin), min(e, end)) for b, e in self._clears
                if b < end and e > begin]
