"""Transaction: snapshot reads + RYW + OCC commit.

Reference: REF:fdbclient/NativeAPI.actor.cpp (Transaction::get/getRange/
commit/onError) and REF:fdbclient/ReadYourWrites.actor.cpp (merging
buffered writes into reads, conflict-range bookkeeping).  The lifecycle
and retry contract match the C API: use once, ``on_error`` decides
retryability and resets, commit makes the txn immutable until reset.
"""

from __future__ import annotations

import asyncio
import contextlib

from ..core.cluster import Cluster
from ..core.data import (CommitTransactionRequest, KeySelector, MutationType,
                         Version, key_after)
from ..runtime import span as _span
from ..runtime.errors import (CommitUnknownResult, FdbError, InvalidOption,
                              IoError as _IoError, KeyOutsideLegalRange,
                              KeyTooLarge, RequestMaybeDelivered,
                              TransactionCancelled, TransactionTooLarge,
                              TransactionReadOnly, UsedDuringCommit,
                              ValueTooLarge)
from ..runtime.rng import deterministic_random
from .writemap import WriteMap

# client-side span events for sampled transactions (the NativeAPI.*
# locations of the reference's debugTransaction)
_SPANS = _span.SpanSink("client")


@contextlib.contextmanager
def _hop(ctx: _span.SpanContext | None, evtype: str = "",
         base: str = "", **details):
    """Activate a child span of the txn's root for one client→role hop;
    the active context rides the RPC envelope (rpc/transport.py) so the
    serving role's span events key to this transaction.  With a
    ``base`` location, emits ``{base}.Before`` on entry and pairs it
    with ``{base}.Error`` if the hop raises (the success site emits its
    own ``.After`` with result details) — the analyzer's consecutive-
    pair stats need every Before closed."""
    with _span.child_scope(ctx) as child:
        if child is None:
            yield None
            return
        if base:
            _SPANS.event(evtype, child, base + ".Before", **details)
        try:
            yield child
        except BaseException as e:
            if base:
                _SPANS.event(evtype, child, base + ".Error",
                             Error=type(e).__name__)
            raise


class Transaction:
    def __init__(self, cluster: Cluster) -> None:
        self._cluster = cluster
        self._knobs = cluster.knobs
        # LOCK_AWARE survives reset/on_error like an upstream persistent
        # transaction option (REF:fdbclient/NativeAPI.actor.cpp
        # TransactionOptions held across resets by the retry loop).
        # priority ("default" | "batch" | "immediate") and throttle_tag
        # are the GRV admission options (PRIORITY_BATCH /
        # PRIORITY_SYSTEM_IMMEDIATE / AUTO_THROTTLE_TAG upstream) —
        # enforced by the Ratekeeper through the GRV proxies.
        self.lock_aware = False
        self.priority = "default"
        self.throttle_tag: str | None = None
        # the C API's bounded-failure trio (ISSUE 12;
        # REF:fdbclient/NativeAPI.actor.cpp TransactionOptions TIMEOUT /
        # RETRY_LIMIT / MAX_RETRY_DELAY): enforced in on_error and on
        # the blocking surfaces, so a degraded cluster surfaces a
        # bounded transaction_timed_out instead of an unbounded hang.
        # Persistent across reset/on_error like lock_aware; the timeout
        # covers the transaction INCLUDING retries (upstream semantics).
        self.timeout = self._knobs.DEFAULT_TIMEOUT          # seconds; 0 off
        self.retry_limit = self._knobs.DEFAULT_RETRY_LIMIT  # -1 unlimited
        self.max_retry_delay = self._knobs.DEFAULT_MAX_RETRY_DELAY
        self._deadline: float | None = None
        # SPECIAL_KEY_SPACE_ENABLE_WRITES (REF: the transaction option
        # gating management writes through \xff\xff)
        self.special_key_space_enable_writes = False
        # layer commit hooks (ISSUE 19): async fn(tr) callables run at
        # the start of every commit() attempt, BEFORE _committing flips
        # — so a hook can still read (the pre-write values whose derived
        # rows it must clear) and write (the replacement derived rows)
        # into the SAME commit.  Persistent across reset/on_error like
        # lock_aware: db.run's retry loop re-runs the body, the body
        # re-buffers its writes, and the hook re-derives from them.
        self._commit_hooks: list = []
        self.reset()

    # --- bounded-failure options (the C API trio) ---

    def set_timeout(self, seconds: float) -> None:
        """Whole-transaction deadline, retries included; 0 disables.
        Validated BEFORE mutating: a rejected value must leave any
        previously armed deadline untouched."""
        seconds = float(seconds)
        if seconds < 0:
            raise InvalidOption("timeout must be >= 0")
        self.timeout = seconds
        self._deadline = None
        if self.timeout > 0:
            try:
                self._deadline = asyncio.get_running_loop().time() \
                    + self.timeout
            except RuntimeError:
                pass            # armed lazily at first use

    def set_retry_limit(self, limit: int) -> None:
        """on_error retries allowed before the error is re-raised;
        -1 = unlimited, 0 = never retry."""
        self.retry_limit = int(limit)

    def set_max_retry_delay(self, seconds: float) -> None:
        if seconds <= 0:
            raise InvalidOption("max_retry_delay must be > 0")
        self.max_retry_delay = float(seconds)

    def _remaining(self) -> float | None:
        """Seconds until the deadline (None = no timeout armed)."""
        if self.timeout <= 0:
            return None
        loop = asyncio.get_running_loop()
        if self._deadline is None:
            self._deadline = loop.time() + self.timeout
        return self._deadline - loop.time()

    def _check_deadline(self) -> None:
        """Cheap entry-point check: an op issued past the deadline fails
        NOW with transaction_timed_out instead of dialing the cluster
        (the blocking awaits themselves are raced via ``_bounded``)."""
        if self.timeout > 0:
            rem = self._remaining()
            if rem is not None and rem <= 0:
                from ..runtime.errors import TransactionTimedOut
                raise TransactionTimedOut()

    async def _bounded(self, coro):
        """Race one blocking await against the transaction deadline —
        what turns a wedged read/commit on a degraded cluster into a
        bounded transaction_timed_out."""
        rem = self._remaining()
        if rem is None:
            return await coro
        if rem <= 0:
            if asyncio.iscoroutine(coro):
                coro.close()
            else:                   # a Future (e.g. the shielded GRV)
                coro.cancel()
            from ..runtime.errors import TransactionTimedOut
            raise TransactionTimedOut()
        try:
            return await asyncio.wait_for(coro, rem)
        except asyncio.TimeoutError:
            from ..runtime.errors import TransactionTimedOut
            raise TransactionTimedOut() from None

    # --- lifecycle ---

    def reset(self) -> None:
        # watches never armed (txn reset before a successful commit) fail
        # like upstream rather than leaving their awaiters hung
        for fut in getattr(self, "_watch_futures", ()):
            if not fut.done():
                fut.set_exception(TransactionCancelled())
        self._writes = WriteMap()
        self._read_conflicts: list[tuple[bytes, bytes]] = []
        self._write_conflicts: list[tuple[bytes, bytes]] = []
        self._read_version: Version | None = None
        old_grv = getattr(self, "_grv_task", None)
        if old_grv is not None and not old_grv.done():
            old_grv.cancel()
        self._grv_task: asyncio.Task | None = None
        self._committed_version: Version | None = None
        self._versionstamp: bytes | None = None
        self._committing = False
        self._retry_count = 0
        self._watches_pending: list[tuple[bytes, bytes | None]] = []
        self._watch_futures: list[asyncio.Future] = []
        tb = getattr(self._cluster, "trace_batch", None)
        if tb is not None and getattr(self, "_probe_id", None) is not None:
            tb.discard(self._probe_id)
        self._probe_id: int | None = None
        self._span: _span.SpanContext | None = None
        self._special_error: bytes | None = None

    def _check_mutable(self) -> None:
        if self._committing:
            raise UsedDuringCommit()

    # --- read version ---

    _probe_counter = 0      # class-wide txn ids for TraceBatch probes

    async def get_read_version(self) -> Version:
        if self._read_version is not None:
            return self._read_version
        # single-flight: concurrent first reads must share ONE snapshot —
        # two GRV fetches would split the transaction's read version and
        # commit-time conflict checking would miss writes between them
        if self._grv_task is None:
            self._grv_task = asyncio.get_running_loop().create_task(
                self._fetch_read_version(), name="txn-grv")
        # the shield keeps the shared GRV fetch alive when the deadline
        # cancels this waiter (a sibling read may still be inside it)
        return await self._bounded(asyncio.shield(self._grv_task))

    async def _fetch_read_version(self) -> Version:
        # TraceBatch latency probe (REF:flow/Trace.h TraceBatch): a
        # sampled fraction of transactions carry per-stage probes
        # from GRV through commit, flushed as one TransactionTrace.
        # The same counter-based sampling decision roots the distributed
        # span (no extra RNG draw: seeded sim streams are unperturbed)
        tb = getattr(self._cluster, "trace_batch", None)
        if tb is not None and self._probe_id is None:
            Transaction._probe_counter += 1
            if tb.attach(Transaction._probe_counter):
                self._probe_id = Transaction._probe_counter
                self._span = _span.new_root(Transaction._probe_counter)
        proxy = deterministic_random().choice(self._cluster.grv_proxies)
        with _hop(self._span, "TransactionDebug",
                  "NativeAPI.getReadVersion") as h:
            self._read_version = await proxy.get_read_version(
                self.lock_aware, self.priority, self.throttle_tag)
            _SPANS.event("TransactionDebug", h,
                         "NativeAPI.getReadVersion.After",
                         Version=self._read_version)
        if self._probe_id is not None and tb is not None:
            tb.event(self._probe_id, "grv")
        return self._read_version

    def set_read_version(self, version: Version) -> None:
        self._read_version = version

    # --- reads ---

    async def get(self, key: bytes, snapshot: bool = False) -> bytes | None:
        self._check_mutable()
        self._check_deadline()
        if key.startswith(b"\xff\xff"):
            return await self._special_key(key)
        self._check_key(key)
        kind, payload = self._writes.lookup(key)
        if kind == "value" and not snapshot:
            # fully determined by this txn's writes; reads of your own
            # writes add no read conflict (RYW semantics)
            return payload
        version = await self.get_read_version()
        if kind == "value":
            return payload
        if not snapshot:
            self._read_conflicts.append((key, key_after(key)))
        with _hop(self._span, "TransactionDebug", "NativeAPI.get") as h:
            base = await self._bounded(self._storage_read(key, version))
            _SPANS.event("TransactionDebug", h, "NativeAPI.get.After")
        if kind == "stack":
            return WriteMap.fold_with_base(payload, base)
        return base

    async def _storage_read(self, key: bytes, version: Version
                            ) -> bytes | None:
        """One storage point read.  With CLIENT_COALESCE_READS (the
        default) it rides the cluster's multiget batcher: every
        concurrent point read landing in the same event-loop tick —
        this transaction's or any other's at any read version — groups
        by owning shard into one packed GetValuesRequest
        (client/read_coalescer.py).  Off, it is the scalar pre-714
        one-RPC-per-key path the equivalence tests compare against."""
        group = self._cluster.storage_for_key(key)
        if not getattr(self._knobs, "CLIENT_COALESCE_READS", True):
            return await group.get_value(key, version)
        co = getattr(self._cluster, "_read_coalescer", None)
        if co is None:
            from .read_coalescer import ReadCoalescer
            co = ReadCoalescer()
            self._cluster._read_coalescer = co
        return await co.submit(group, key, version)

    async def get_multi(self, keys: list[bytes], snapshot: bool = False
                        ) -> list[bytes | None]:
        """Batched point reads: the values of ``keys`` in input order
        (the fdb_transaction_get_multi surface ISSUE 5 adds).  Per-key
        semantics are EXACTLY a ``get`` loop's — RYW overlays fold,
        non-snapshot reads record one read-conflict range per key,
        special keys answer client-side — but the storage half ships
        as one packed multiget per owning shard, fanned out and
        reassembled in key order."""
        self._check_mutable()
        self._check_deadline()
        results: list[bytes | None] = [None] * len(keys)
        fetch: list[tuple[int, bytes, str, object]] = []
        for i, key in enumerate(keys):
            if key.startswith(b"\xff\xff"):
                results[i] = await self._special_key(key)
                continue
            self._check_key(key)
            kind, payload = self._writes.lookup(key)
            if kind == "value" and not snapshot:
                results[i] = payload    # RYW: fully determined
                continue
            fetch.append((i, key, kind, payload))
        if not fetch:
            return results
        version = await self.get_read_version()
        # group by owning shard — ONE packed GetValuesRequest per shard,
        # fanned out concurrently, no per-key task/future (the per-key
        # async overhead is exactly what this path amortizes away)
        from ..core.data import GetValuesRequest
        from ..runtime.errors import error_from_code
        per_shard: dict[object, list[bytes]] = {}
        waits: list[tuple[int, str, object, bytes]] = []
        for i, key, kind, payload in fetch:
            if kind == "value":         # snapshot read of a buffered set
                results[i] = payload
                continue
            if not snapshot:
                self._read_conflicts.append((key, key_after(key)))
            g = self._cluster.storage_for_key(key)
            per_shard.setdefault(g, []).append(key)
            waits.append((i, kind, payload, key))
        if not waits:
            return results
        reqs = [(g, sorted(set(ks))) for g, ks in per_shard.items()]
        with _hop(self._span, "TransactionDebug", "NativeAPI.getValues",
                  Keys=len(waits), Shards=len(reqs)) as h:
            replies = await self._bounded(asyncio.gather(
                *(g.get_values(GetValuesRequest.from_keys(sk, version))
                  for g, sk in reqs),
                return_exceptions=True))
            err = next((r for r in replies if isinstance(r, BaseException)),
                       None)
            if err is not None:
                raise err
            valmap: dict[bytes, bytes | None] = {}
            errcode: int | None = None
            for (_g, sk), rep in zip(reqs, replies):
                for j, k in enumerate(sk):
                    ec, valmap[k] = rep.unpack(j)
                    if errcode is None and ec is not None:
                        errcode = ec
            if errcode is not None:
                # one bad key fails the call exactly as it would have
                # failed the scalar get() loop — the txn's retry loop
                # owns recovery
                raise error_from_code(errcode)
            _SPANS.event("TransactionDebug", h, "NativeAPI.getValues.After",
                         Keys=len(waits))
        for i, kind, payload, key in waits:
            base = valmap[key]
            results[i] = (WriteMap.fold_with_base(payload, base)
                          if kind == "stack" else base)
        return results

    async def _special_key(self, key: bytes) -> bytes | None:
        """The ``\\xff\\xff`` special-key space (REF:fdbclient/
        SpecialKeySpace.actor.cpp): module-backed reads answered by the
        client, not storage.  No read conflict is taken.  Dispatch lives
        in client/special_keys.py's module registry."""
        from .special_keys import SPECIAL_KEY_SPACE
        return await SPECIAL_KEY_SPACE.get(self, key)

    async def get_addresses_for_key(self, key: bytes) -> list[str]:
        from .locality import get_addresses_for_key
        return await get_addresses_for_key(self, key)

    async def get_range(self, begin, end, limit: int = 0,
                        reverse: bool = False, snapshot: bool = False
                        ) -> list[tuple[bytes, bytes]]:
        """begin/end: bytes or KeySelector.  Returns up to ``limit`` pairs."""
        self._check_mutable()
        self._check_deadline()
        if isinstance(begin, bytes) and begin.startswith(b"\xff\xff"):
            # special-key range read: module-backed, may span modules
            from .special_keys import SPECIAL_KEY_SPACE
            if not isinstance(end, bytes):
                from ..runtime.errors import ClientInvalidOperation
                raise ClientInvalidOperation(
                    "key selectors are not supported in the special-key "
                    "space; pass byte bounds")
            return await SPECIAL_KEY_SPACE.get_range(
                self, begin, end, limit=limit, reverse=reverse)
        if isinstance(begin, KeySelector):
            begin = await self.get_key(begin, snapshot=True)
        if isinstance(end, KeySelector):
            end = await self.get_key(end, snapshot=True)
        if begin >= end:
            return []
        with _hop(self._span, "TransactionDebug", "NativeAPI.getRange") as h:
            # deadline-bounded (ISSUE 12): a wedged shard fetch on a
            # degraded cluster surfaces transaction_timed_out instead
            # of hanging the scan unboundedly
            out = await self._bounded(
                self._merged_range(begin, end, limit, reverse))
            _SPANS.event("TransactionDebug", h, "NativeAPI.getRange.After",
                         Rows=len(out))
        if not snapshot:
            # conflict range covers what was actually observed: the whole
            # requested range if exhausted, else up to the last-seen key
            if limit and len(out) >= limit:
                if reverse:
                    self._read_conflicts.append((out[-1][0], end))
                else:
                    self._read_conflicts.append((begin, key_after(out[-1][0])))
            else:
                self._read_conflicts.append((begin, end))
        return out

    async def _snapshot_stream(self, begin: bytes, end: bytes,
                               version: Version, reverse: bool,
                               chunk: int | None = None):
        """Yield storage rows of [begin, end) in key order (or reverse),
        following each shard's 'more' flag — no row is ever silently
        dropped by a fetch limit.

        With CLIENT_PACKED_RANGE_READS (the default) every fetch rides
        the packed GetRangeRequest/Reply RPC (ISSUE 9); off, the scalar
        pre-715 tuple-list RPC — byte-identical rows either way
        (tested).  The per-fetch row limit starts at
        CLIENT_RANGE_CHUNK_ROWS and DOUBLES after every truncated reply
        (the iterator-mode growth of REF:fdbclient/NativeAPI.actor.cpp
        getRange), capped where the next reply would exceed
        CLIENT_RANGE_CHUNK_BYTES at the observed mean row size — a long
        scan converges to few large fetches without letting huge rows
        blow the reply budget."""
        if getattr(self._knobs, "CLIENT_PACKED_RANGE_READS", True):
            async for page in self._snapshot_stream_packed(
                    begin, end, version, reverse, chunk):
                for kv in page:
                    yield kv
            return
        if chunk is None:
            chunk = self._knobs.CLIENT_RANGE_CHUNK_ROWS
        budget = self._knobs.CLIENT_RANGE_CHUNK_BYTES
        servers = self._cluster.storages_for_range(begin, end)
        servers.sort(key=lambda ss: ss.shard.begin, reverse=reverse)
        for ss in servers:
            b = max(begin, ss.shard.begin)
            e = min(end, ss.shard.end)
            while b < e:
                # budget rides positionally: RPC stubs are *args-only
                kvs, more = await ss.get_key_values(b, e, version, chunk,
                                                    reverse, budget)
                for kv in kvs:
                    yield kv
                if not more:
                    break
                if reverse:
                    e = kvs[-1][0]            # exclusive end: continue below
                else:
                    b = key_after(kvs[-1][0])
                nbytes = sum(len(k) + len(v) for k, v in kvs)
                avg = max(1, nbytes // max(1, len(kvs)))
                chunk = max(chunk, min(chunk * 2, max(1, budget // avg)))

    async def _snapshot_stream_packed(self, begin: bytes, end: bytes,
                                      version: Version, reverse: bool,
                                      chunk: int | None = None):
        """Yield PackedRows PAGES of [begin, end) in scan order over the
        packed range RPC (ISSUE 9) — the bulk twin of _snapshot_stream,
        one page per storage reply, same shard fan-out, continuation
        and adaptive chunk growth.  A refused chunk's status byte maps
        back to the error class the scalar path raised (after the
        replica group has already failed over lagging/compacted
        replicas), so every retry contract upstream is unchanged."""
        from ..core.data import GV_ERROR_CODES, GetRangeRequest
        from ..runtime.errors import error_from_code
        if chunk is None:
            chunk = self._knobs.CLIENT_RANGE_CHUNK_ROWS
        budget = self._knobs.CLIENT_RANGE_CHUNK_BYTES
        servers = self._cluster.storages_for_range(begin, end)
        servers.sort(key=lambda ss: ss.shard.begin, reverse=reverse)
        for ss in servers:
            b = max(begin, ss.shard.begin)
            e = min(end, ss.shard.end)
            while b < e:
                rep = await ss.get_key_values_packed(
                    GetRangeRequest(b, e, version, chunk, reverse, budget))
                if rep.status:
                    raise error_from_code(GV_ERROR_CODES[rep.status])
                page = rep.columns()
                n = len(page)
                if n:
                    yield page
                if not rep.more or not n:
                    break
                last = page.key(n - 1)
                if reverse:
                    e = last                  # exclusive end: continue below
                else:
                    b = key_after(last)
                avg = max(1, page.nbytes() // n)
                chunk = max(chunk, min(chunk * 2, max(1, budget // avg)))

    async def _merged_range(self, begin: bytes, end: bytes, limit: int,
                            reverse: bool) -> list[tuple[bytes, bytes]]:
        """Merge the snapshot stream with buffered writes (the RYWIterator
        analog, REF:fdbclient/RYWIterator.cpp): two sorted streams —
        storage rows (clears applied) and written keys — merged until
        ``limit`` rows are produced or both are exhausted."""
        version = await self.get_read_version()
        written = self._writes.written_keys_in(begin, end)
        if not written and not self._writes.clears_in(begin, end) \
                and getattr(self._knobs, "CLIENT_PACKED_RANGE_READS", True):
            # no buffered write touches the range: the merge is the
            # identity, so packed reply pages bulk-extend the result
            # instead of walking the per-row loop below (the scan-heavy
            # fast path, ISSUE 9)
            out = []
            async for page in self._snapshot_stream_packed(
                    begin, end, version, reverse):
                rows = page.rows()
                if limit and len(out) + len(rows) >= limit:
                    out.extend(rows[:limit - len(out)])
                    break
                out.extend(rows)
            return out
        if reverse:
            written = written[::-1]
        snap = self._snapshot_stream(begin, end, version, reverse)
        out: list[tuple[bytes, bytes]] = []
        wi = 0
        pending_snap: tuple[bytes, bytes] | None = None

        def before(a: bytes, b: bytes) -> bool:
            return a > b if reverse else a < b

        async def next_snap():
            async for k, v in snap:
                if not self._writes.range_cleared(k):
                    return (k, v)
            return None

        while not limit or len(out) < limit:
            if pending_snap is None:
                pending_snap = await next_snap()
            wkey = written[wi] if wi < len(written) else None
            if pending_snap is None and wkey is None:
                break
            use_write = wkey is not None and (
                pending_snap is None or not before(pending_snap[0], wkey))
            if use_write:
                base = None
                if pending_snap is not None and pending_snap[0] == wkey:
                    base = pending_snap[1]
                    pending_snap = None     # consumed as the fold base
                kind, payload = self._writes.lookup(wkey)
                v = (WriteMap.fold_with_base(payload, base)
                     if kind == "stack" else payload)
                if v is not None:
                    out.append((wkey, v))
                wi += 1
            else:
                out.append(pending_snap)
                pending_snap = None
        return out

    async def get_range_packed(self, begin: bytes, end: bytes,
                               limit: int = 0):
        """Columnar snapshot range read: up to ``limit`` rows of
        [begin, end) as ONE PackedRows — the reply pages' columns
        concatenated by blob join + vectorized bounds rebase, never a
        per-row tuple list (ISSUE 9).  Snapshot-only (no read conflict)
        and only legal while no buffered write overlaps the range: the
        RYW merge would force per-row re-materialization, which is
        exactly what this surface exists to delete.  The backup
        snapshot writer is the canonical consumer — its pages reach the
        ``.kvr`` frame byte-identical to the tuple path (tested)."""
        self._check_mutable()
        self._check_deadline()
        if self._writes.written_keys_in(begin, end) \
                or self._writes.clears_in(begin, end):
            from ..runtime.errors import ClientInvalidOperation
            raise ClientInvalidOperation(
                "get_range_packed on a range this transaction has "
                "buffered writes in — use get_range")
        from ..core.data import PackedRows
        version = await self.get_read_version()
        with _hop(self._span, "TransactionDebug", "NativeAPI.getRange") as h:
            parts: list[PackedRows] = []
            n = 0
            async for page in self._snapshot_stream_packed(
                    begin, end, version, False):
                if limit and n + len(page) >= limit:
                    parts.append(page.slice(0, limit - n))
                    n = limit
                    break
                parts.append(page)
                n += len(page)
            _SPANS.event("TransactionDebug", h, "NativeAPI.getRange.After",
                         Rows=n)
        return PackedRows.concat(parts)

    async def get_key(self, selector: KeySelector, snapshot: bool = False) -> bytes:
        """Resolve a KeySelector against the merged view
        (REF:fdbclient/NativeAPI.actor.cpp resolveKey).

        With no buffered write overlapping the probe span, resolution
        rides the packed ``get_key`` RPC (ISSUE 11, PROTOCOL_VERSION
        716): each shard answers with ONE key + a live-row count and
        the client walks shards carrying the residual offset — the
        legacy path row-probed up to ``offset`` full (key, value) rows
        through ``_merged_range``.  Resolved keys are identical by
        construction (the server locates rows with the same merged
        extraction the range read uses; equivalence tested on
        randomized selectors), and a transaction with overlapping RYW
        writes falls back to the legacy merge, which already handles
        them."""
        self._check_mutable()
        self._check_deadline()
        k, oe, off = selector.key, selector.or_equal, selector.offset
        if off > 0:
            # firstGreaterOrEqual(k)+n / firstGreaterThan(k)+n
            start = key_after(k) if oe else k
            result = await self._resolve_key(start, b"\xff", off,
                                             reverse=False)
            if result is None:
                result = b"\xff"  # off the end: clamp to keyspace end
        else:
            # lastLessOrEqual(k)-n / lastLessThan(k)-n
            stop = key_after(k) if oe else k
            result = await self._resolve_key(b"", stop, 1 - off,
                                             reverse=True)
            if result is None:
                result = b""
        if not snapshot:
            lo = min(result, k)
            hi = max(key_after(result), key_after(k) if oe else k)
            if lo < hi:
                self._read_conflicts.append((lo, hi))
        return result

    async def _resolve_key(self, begin: bytes, end: bytes, n: int,
                           reverse: bool) -> bytes | None:
        """The ``n``-th live key of [begin, end) in scan order (from
        the end when ``reverse``), or None when fewer than ``n`` rows
        exist.  Packed shard walk when no buffered write overlaps the
        span; the legacy ``_merged_range`` row-probe otherwise."""
        if self._writes.written_keys_in(begin, end) \
                or self._writes.clears_in(begin, end):
            rows = await self._merged_range(begin, end, n, reverse)
            return rows[n - 1][0] if len(rows) >= n else None
        from ..core.data import GV_ERROR_CODES, GetKeyRequest
        from ..runtime.errors import error_from_code
        version = await self.get_read_version()
        servers = self._cluster.storages_for_range(begin, end)
        servers.sort(key=lambda ss: ss.shard.begin, reverse=reverse)
        remaining = n
        for ss in servers:
            b = max(begin, ss.shard.begin)
            e = min(end, ss.shard.end)
            if b >= e:
                continue
            rep = await ss.get_key(
                GetKeyRequest(b, e, version, remaining, reverse))
            if rep.status:
                # every replica refused: surface the same error class
                # the legacy range fetch raised — retry discipline
                # upstream (on_error) is unchanged
                raise error_from_code(GV_ERROR_CODES[rep.status])
            if rep.count >= remaining:
                return bytes(rep.key)
            remaining -= rep.count
        return None

    # --- writes ---

    def set(self, key: bytes, value: bytes) -> None:
        self._check_mutable()
        if key.startswith(b"\xff\xff"):
            # special-key writes (REF: SpecialKeySpace RW modules) are
            # rewritten onto real system keys inside this txn; gated by
            # the SPECIAL_KEY_SPACE_ENABLE_WRITES option
            from .special_keys import SPECIAL_KEY_SPACE
            SPECIAL_KEY_SPACE.set(self, key, value)
            return
        self._check_key(key)
        if len(value) > self._knobs.VALUE_SIZE_LIMIT:
            raise ValueTooLarge()
        self._writes.set(key, value)
        self._write_conflicts.append((key, key_after(key)))

    def clear(self, key: bytes) -> None:
        self._check_mutable()
        if key.startswith(b"\xff\xff"):
            from .special_keys import SPECIAL_KEY_SPACE
            SPECIAL_KEY_SPACE.clear(self, key)
            return
        self._check_key(key)
        self._writes.clear_range(key, key_after(key))
        self._write_conflicts.append((key, key_after(key)))

    def clear_range(self, begin: bytes, end: bytes) -> None:
        self._check_mutable()
        if begin >= end:
            return
        if begin.startswith(b"\xff\xff"):
            from .special_keys import SPECIAL_KEY_SPACE
            SPECIAL_KEY_SPACE.clear(self, begin, end)
            return
        # both endpoints validated like any written key (upstream's
        # clear_range raises key_too_large / key_outside_legal_range the
        # same way); ``\xff`` as the exclusive end is legal — it means
        # "to the end of the user keyspace"
        if len(begin) > self._knobs.KEY_SIZE_LIMIT \
                or len(end) > self._knobs.KEY_SIZE_LIMIT:
            raise KeyTooLarge()
        if end.startswith(b"\xff\xff"):
            raise KeyOutsideLegalRange()
        self._writes.clear_range(begin, end)
        self._write_conflicts.append((begin, end))

    def atomic_op(self, op: MutationType, key: bytes, operand: bytes) -> None:
        self._check_mutable()
        self._check_key(key)
        self._writes.atomic(op, key, operand)
        self._write_conflicts.append((key, key_after(key)))

    # convenience named atomics (the C API's FDBMutationType surface)
    def add(self, key: bytes, operand: bytes) -> None:
        self.atomic_op(MutationType.ADD, key, operand)

    def bit_and(self, key: bytes, operand: bytes) -> None:
        self.atomic_op(MutationType.BIT_AND, key, operand)

    def bit_or(self, key: bytes, operand: bytes) -> None:
        self.atomic_op(MutationType.BIT_OR, key, operand)

    def bit_xor(self, key: bytes, operand: bytes) -> None:
        self.atomic_op(MutationType.BIT_XOR, key, operand)

    def max(self, key: bytes, operand: bytes) -> None:
        self.atomic_op(MutationType.MAX, key, operand)

    def min(self, key: bytes, operand: bytes) -> None:
        self.atomic_op(MutationType.MIN, key, operand)

    def byte_min(self, key: bytes, operand: bytes) -> None:
        self.atomic_op(MutationType.BYTE_MIN, key, operand)

    def byte_max(self, key: bytes, operand: bytes) -> None:
        self.atomic_op(MutationType.BYTE_MAX, key, operand)

    def append_if_fits(self, key: bytes, operand: bytes) -> None:
        self.atomic_op(MutationType.APPEND_IF_FITS, key, operand)

    def compare_and_clear(self, key: bytes, operand: bytes) -> None:
        self.atomic_op(MutationType.COMPARE_AND_CLEAR, key, operand)

    def set_versionstamped_key(self, key: bytes, value: bytes) -> None:
        self.atomic_op(MutationType.SET_VERSIONSTAMPED_KEY, key, value)

    def set_versionstamped_value(self, key: bytes, value: bytes) -> None:
        self.atomic_op(MutationType.SET_VERSIONSTAMPED_VALUE, key, value)

    # --- explicit conflict ranges ---

    def add_read_conflict_range(self, begin: bytes, end: bytes) -> None:
        if begin < end:
            self._read_conflicts.append((begin, end))

    def add_read_conflict_key(self, key: bytes) -> None:
        self.add_read_conflict_range(key, key_after(key))

    def add_write_conflict_range(self, begin: bytes, end: bytes) -> None:
        if begin < end:
            self._write_conflicts.append((begin, end))

    def add_write_conflict_key(self, key: bytes) -> None:
        self.add_write_conflict_range(key, key_after(key))

    # --- layer commit hooks (ISSUE 19) ---

    @property
    def write_map(self) -> WriteMap:
        """This transaction's buffered-write state, exposed read-only
        for commit hooks (layers/index.py walks written keys and
        cleared spans to derive index-row mutations)."""
        return self._writes

    def add_commit_hook(self, hook) -> None:
        """Register an async ``fn(tr)`` run at the start of every
        commit() attempt while the transaction still accepts reads and
        writes — the transactional secondary-index mode's atomicity
        point (layers/index.py is the canonical consumer).  Idempotent:
        re-adding the same callable is a no-op, so a hook installed
        inside a ``db.run`` body survives the retry loop without
        stacking."""
        if hook not in self._commit_hooks:
            self._commit_hooks.append(hook)

    async def get_prewrite_multi(self, keys: list[bytes],
                                 snapshot: bool = False
                                 ) -> list[bytes | None]:
        """The values of ``keys`` at this transaction's read version
        IGNORING buffered writes — the pre-transaction base a commit
        hook needs (RYW ``get`` would return the buffered value, hiding
        the derived rows that must be cleared).  Non-snapshot reads add
        per-key read conflicts, which is what makes hook-maintained
        derived state serializable: any concurrent writer of the same
        primary key conflicts here."""
        self._check_mutable()
        self._check_deadline()
        for k in keys:
            self._check_key(k)
        if not snapshot:
            for k in keys:
                self._read_conflicts.append((k, key_after(k)))
        version = await self.get_read_version()
        return list(await self._bounded(asyncio.gather(
            *(self._storage_read(k, version) for k in keys))))

    async def get_prewrite_range(self, begin: bytes, end: bytes,
                                 snapshot: bool = False
                                 ) -> list[tuple[bytes, bytes]]:
        """All rows of [begin, end) at the read version IGNORING
        buffered writes — what a commit hook scans to clear the derived
        rows of a buffered ``clear_range``.  Non-snapshot adds one read
        conflict over the whole range (a concurrent insert into the
        cleared span must conflict, or its derived row would leak)."""
        self._check_mutable()
        self._check_deadline()
        if not snapshot and begin < end:
            self._read_conflicts.append((begin, end))
        version = await self.get_read_version()
        out: list[tuple[bytes, bytes]] = []
        async for k, v in self._snapshot_stream(begin, end, version, False):
            out.append((bytes(k), bytes(v)))
        return out

    # --- watch ---

    async def watch(self, key: bytes) -> asyncio.Future:
        """Returns a future completing when key changes after commit
        (fdb_transaction_watch).  The watched baseline is the value at
        this txn's read version (snapshot; adds no conflict)."""
        value = await self.get(key, snapshot=True)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._watches_pending.append((key, value))
        self._watch_futures.append(fut)
        return fut

    # --- commit ---

    async def commit(self) -> Version:
        self._check_mutable()
        self._check_deadline()
        # layer commit hooks run while the txn is still mutable and only
        # when there is something to commit: a read-only txn derives
        # nothing, and hooking it would force a GRV onto the read-only
        # fast path below
        if self._commit_hooks and (self._writes or self._write_conflicts):
            for hook in list(self._commit_hooks):
                await hook(self)
        if not self._writes and not self._write_conflicts:
            # read-only txn commits trivially at its read version
            self._committed_version = self._read_version if self._read_version is not None else 0
            self._arm_watches(self._committed_version)
            if self._probe_id is not None:
                tb0 = getattr(self._cluster, "trace_batch", None)
                if tb0 is not None:
                    tb0.flush(self._probe_id, "read_only")
                _SPANS.event("CommitDebug", self._span,
                             "NativeAPI.commit.ReadOnly",
                             Version=self._committed_version)
                self._probe_id = None
                self._span = None
            return self._committed_version
        if self._writes.bytes > self._knobs.TRANSACTION_SIZE_LIMIT:
            raise TransactionTooLarge()
        read_snapshot = await self.get_read_version()
        tb = getattr(self._cluster, "trace_batch", None)
        if self._probe_id is not None and tb is not None:
            tb.event(self._probe_id, "commit_submit")
        req = CommitTransactionRequest(
            read_conflict_ranges=_coalesce(self._read_conflicts),
            write_conflict_ranges=_coalesce(self._write_conflicts),
            mutations=list(self._writes.mutations),
            read_snapshot=read_snapshot,
            lock_aware=self.lock_aware,
        )
        self._committing = True
        try:
            proxy = deterministic_random().choice(self._cluster.commit_proxies)
            with _hop(self._span, "CommitDebug", "NativeAPI.commit",
                      Mutations=len(req.mutations)) as h:
                # deadline-bounded (ISSUE 12): a commit cut off by the
                # transaction timeout surfaces transaction_timed_out —
                # like an unknown result, the commit MAY have landed;
                # on_error refuses to spin past the deadline either way
                result = await self._bounded(proxy.commit(req))
                _SPANS.event("CommitDebug", h, "NativeAPI.commit.After",
                             Version=result.version)
        except (RequestMaybeDelivered, _IoError):
            # the commit reached the proxy but its reply was lost — or a
            # server-side disk error surfaced AFTER the batch may have
            # landed on some logs (ISSUE 12: io_error is retryable for
            # idempotent ops, but a commit is not one): the outcome is
            # unknown and retrying blindly could double-commit
            if self._probe_id is not None and tb is not None:
                tb.event(self._probe_id, "commit_done")
                tb.flush(self._probe_id, "unknown_result")
                self._probe_id = None
            _SPANS.event("CommitDebug", self._span,
                         "NativeAPI.commit.UnknownResult")
            self._span = None
            raise CommitUnknownResult() from None
        except BaseException:
            if self._probe_id is not None and tb is not None:
                tb.event(self._probe_id, "commit_done")
                tb.flush(self._probe_id, "aborted")
                self._probe_id = None
            # no extra event: the _hop already paired the commit hop
            # with NativeAPI.commit.Error
            self._span = None
            raise
        finally:
            self._committing = False
        if self._probe_id is not None and tb is not None:
            tb.event(self._probe_id, "commit_done")
            tb.flush(self._probe_id, "committed")
            self._probe_id = None
        self._span = None
        self._committed_version = result.version
        self._versionstamp = result.versionstamp
        self._arm_watches(result.version)
        return result.version

    def _arm_watches(self, commit_version: Version) -> None:
        loop = asyncio.get_running_loop()
        for (key, value), fut in zip(self._watches_pending, self._watch_futures):
            ss = self._cluster.storage_for_key(key)

            async def run(ss=ss, key=key, value=value, fut=fut):
                try:
                    await ss.watch_value(key, value, commit_version)
                    if not fut.done():
                        fut.set_result(None)
                except Exception as e:
                    if not fut.done():
                        fut.set_exception(e)
            t = loop.create_task(run(), name="watch")
            fut.add_done_callback(lambda _f, t=t: None)  # keep task referenced
        self._watches_pending.clear()
        self._watch_futures.clear()

    def get_committed_version(self) -> Version:
        if self._committed_version is None:
            from ..runtime.errors import VersionInvalid
            raise VersionInvalid()
        return self._committed_version

    def get_versionstamp(self) -> bytes:
        if self._versionstamp is None:
            from ..runtime.errors import VersionInvalid
            raise VersionInvalid()
        return self._versionstamp

    # --- error handling / retry (REF: Transaction::onError) ---

    async def on_error(self, e: BaseException) -> None:
        # a NON-retryable error re-raises unchanged even past the
        # deadline: it carries a definite outcome (e.g. a too-large
        # commit provably never landed), and replacing it with
        # transaction_timed_out — which is maybe-committed — would
        # inflate a known result into ambiguity
        if not isinstance(e, FdbError) or not e.retryable:
            raise e
        # bounded failure (ISSUE 12, the C API trio): a transaction past
        # its deadline never RETRIES — the caller gets
        # transaction_timed_out now instead of an unbounded retry loop
        # against a degraded cluster
        rem = self._remaining() if self.timeout > 0 else None
        if rem is not None and rem <= 0:
            from ..runtime.errors import TransactionTimedOut
            raise TransactionTimedOut() from \
                (e if not isinstance(e, TransactionTimedOut) else None)
        self._retry_count += 1
        if self.retry_limit >= 0 and self._retry_count > self.retry_limit:
            raise e
        backoff = min(0.001 * (2 ** min(self._retry_count, 10)),
                      self.max_retry_delay)
        await asyncio.sleep(backoff * (0.5 + deterministic_random().random() * 0.5))
        retry_count = self._retry_count
        self.reset()
        self._retry_count = retry_count

    # --- helpers ---

    def _check_key(self, key: bytes) -> None:
        if len(key) > self._knobs.KEY_SIZE_LIMIT:
            raise KeyTooLarge()
        if key.startswith(b"\xff\xff"):
            # the special-key space is module-backed and never stored
            # (REF: keys at or above \xff\xff are outside the legal
            # range); writes here would be unreachable through get()
            raise KeyOutsideLegalRange()


def _coalesce(ranges: list[tuple[bytes, bytes]]) -> list[tuple[bytes, bytes]]:
    """Sort + merge overlapping conflict ranges (the reference coalesces in
    CommitTransactionRef::read_conflict_ranges construction)."""
    if not ranges:
        return []
    rs = sorted(ranges)
    out = [rs[0]]
    for b, e in rs[1:]:
        lb, le = out[-1]
        if b <= le:
            out[-1] = (lb, max(le, e))
        else:
            out.append((b, e))
    return out
