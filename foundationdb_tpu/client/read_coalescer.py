"""Same-tick client read coalescing — the multiget batcher.

Reference: REF:fdbclient/NativeAPI.actor.cpp getValues +
REF:fdbserver/storageserver.actor.cpp getValueQ — the reference batches
point reads at the storage server; the client half here makes sure
batches actually FORM: every concurrent ``Transaction.get`` that lands
in the same event-loop tick — across transactions as well as within
one, since GRV batching hands concurrent transactions the same read
version — groups by owning shard and ships as ONE packed
``GetValuesRequest`` per (shard, read version) instead of one RPC per
key.

Discipline:

- RYW lookups and conflict-range bookkeeping happen in the Transaction
  BEFORE a key reaches this module, so snapshot and non-snapshot reads
  coalesce into the same wire batch while recording conflicts
  independently;
- single-flight per shard: while a batch is on the wire, later
  arrivals queue and ride the NEXT flush — a hot shard sees a steady
  stream of maximal batches, never a convoy of tiny ones;
- per-key failures come back as status codes in the reply and are
  re-raised per waiter, so one too-old key fails exactly the reads
  that asked for it;
- scheduling is deterministic: no RNG, no timers — the flush task is
  an ordinary ``create_task`` whose body runs one ready-queue
  iteration after the submissions that scheduled it (virtual-time sim
  loops included), which is the "deterministic batch boundary" the
  seeded sims rely on.
"""

from __future__ import annotations

import asyncio

from ..core.data import GetValuesRequest
from ..runtime import span as _span
from ..runtime.errors import error_from_code

__all__ = ["ReadCoalescer"]


class _ShardQueue:
    __slots__ = ("group", "items", "task")

    def __init__(self, group) -> None:
        self.group = group
        # (key, future, span ctx) in arrival order
        self.items: list = []
        self.task: asyncio.Task | None = None


class ReadCoalescer:
    """One per cluster view (attached lazily, like the TraceBatch):
    Transaction point reads funnel through ``submit``.

    Queues key on (shard team, read version): single-flight applies PER
    VERSION, so a batch parked in the storage future-version wait (a
    client racing ahead of a lagging replica) head-of-line-blocks only
    reads at that same stuck version — other transactions' immediately
    servable reads on the shard flush independently.  A drained queue
    deletes itself, so dead ReplicaGroups from shard splits and view
    rebuilds are never retained."""

    def __init__(self) -> None:
        self._queues: dict[tuple[int, int], _ShardQueue] = {}
        # observability: batch formation stats (status rollups and the
        # perf smoke read off these)
        self.batches = 0
        self.keys_batched = 0
        self.max_batch = 0

    def submit(self, group, key: bytes, version: int) -> asyncio.Future:
        """Enqueue one point read against ``group`` (the key's replica
        team); resolves to the value (or None) or raises the per-key
        error."""
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        qkey = (id(group), version)
        q = self._queues.get(qkey)
        if q is None or q.group is not group:
            # id() reuse after a view rebuild re-keys the slot; the
            # old queue object keeps draining its own in-flight batch
            q = self._queues[qkey] = _ShardQueue(group)
        # the submitter's active span (the txn's NativeAPI.get hop):
        # the flush task runs outside every submitter's context, so
        # wire propagation needs the context captured HERE
        q.items.append((key, fut, _span.current_span()))
        if q.task is None:
            q.task = loop.create_task(self._drain(qkey, q, version),
                                      name="multiget-flush")
        return fut

    async def _drain(self, qkey: tuple[int, int], q: _ShardQueue,
                     version: int) -> None:
        try:
            while q.items:
                items, q.items = q.items, []
                keymap: dict[bytes, list] = {}
                ctx = None
                for k, f, c in items:
                    keymap.setdefault(k, []).append(f)
                    if ctx is None and c is not None:
                        ctx = c
                await self._fetch(q.group, version, keymap, ctx)
        finally:
            # no await between the loop's emptiness check and this
            # cleanup, so a submit can never race into a dead task —
            # and the drained queue leaves the map (no growth across
            # view rebuilds / version churn)
            q.task = None
            if q.items:
                # the drain died mid-flight (cancellation): waiters
                # queued behind the in-flight batch must not hang
                # forever-pending on a task that no longer exists
                items, q.items = q.items, []
                for _k, f, _c in items:
                    if not f.done():
                        f.cancel()
            if self._queues.get(qkey) is q:
                del self._queues[qkey]

    async def _fetch(self, group, version: int, keymap: dict[bytes, list],
                     ctx=None) -> None:
        skeys = sorted(keymap)          # the wire contract: sorted keys
        self.batches += 1
        self.keys_batched += len(skeys)
        if len(skeys) > self.max_batch:
            self.max_batch = len(skeys)
        try:
            # re-activate the first sampled submitter's span around the
            # wire hop: a batch answers many transactions, but a trace
            # that follows ONE sampled read to its serving storage span
            # (the scalar path's behavior) beats attributing to nobody
            token = _span.activate(ctx) if ctx is not None else None
            try:
                reply = await group.get_values(
                    GetValuesRequest.from_keys(skeys, version))
            finally:
                if token is not None:
                    _span.deactivate(token)
        except BaseException as e:
            first = True
            for futs in keymap.values():
                for f in futs:
                    if f.done():
                        continue
                    # fresh instance per waiter past the first (same
                    # discipline as the per-key branch below): a shared
                    # exception object accretes every waiter's re-raise
                    # frames onto one traceback
                    if first:
                        err, first = e, False
                    else:
                        try:
                            err = type(e)(*e.args)
                            if "code" in e.__dict__:
                                err.code = e.code   # instance-level code
                        except Exception:  # noqa: BLE001 — exotic ctor
                            err = e
                    f.set_exception(err)
            if isinstance(e, asyncio.CancelledError):
                raise
            return
        for i, k in enumerate(skeys):
            err, value = reply.unpack(i)
            for f in keymap[k]:
                if f.done():
                    continue
                if err is not None:
                    # a fresh instance per waiter: shared exception
                    # objects accrete each other's tracebacks
                    f.set_exception(error_from_code(err))
                else:
                    f.set_result(value)

    def stats(self) -> dict:
        mean = (self.keys_batched / self.batches) if self.batches else 0.0
        return {"read_batches": self.batches,
                "read_keys_batched": self.keys_batched,
                "read_batch_mean": round(mean, 2),
                "read_batch_max": self.max_batch}
