"""Database handle + the retry-loop helper every binding exposes.

Reference: REF:fdbclient/NativeAPI.actor.h (Database/DatabaseContext) and
the ``db.run``/``@fdb.transactional`` pattern from
REF:bindings/python/fdb/impl.py — run a function against a fresh
transaction, commit, and loop through ``on_error`` on retryable failures.
"""

from __future__ import annotations

from typing import Any, Awaitable, Callable

from ..core.cluster import Cluster
from ..core.data import Version
from .transaction import Transaction


class Database:
    def __init__(self, cluster: Cluster) -> None:
        self.cluster = cluster

    def create_transaction(self) -> Transaction:
        return Transaction(self.cluster)

    async def run(self, fn: Callable[[Transaction], Awaitable[Any]],
                  max_retries: int | None = None) -> Any:
        """The @transactional retry loop: fn(tr) then commit; retryable
        errors reset and re-run fn.  fn must be idempotent across retries
        (same contract as the reference)."""
        tr = self.create_transaction()
        attempts = 0
        while True:
            try:
                result = await fn(tr)
                await tr.commit()
                return result
            except BaseException as e:
                attempts += 1
                if max_retries is not None and attempts > max_retries:
                    raise
                await tr.on_error(e)   # re-raises if not retryable

    # --- one-shot conveniences ---

    async def get(self, key: bytes) -> bytes | None:
        return await self.run(lambda tr: tr.get(key))

    async def set(self, key: bytes, value: bytes) -> Version:
        async def go(tr: Transaction):
            tr.set(key, value)
        await self.run(go)
        return 0

    async def clear(self, key: bytes) -> None:
        async def go(tr: Transaction):
            tr.clear(key)
        await self.run(go)

    async def clear_range(self, begin: bytes, end: bytes) -> None:
        async def go(tr: Transaction):
            tr.clear_range(begin, end)
        await self.run(go)

    async def get_range(self, begin, end, limit: int = 0,
                        reverse: bool = False) -> list[tuple[bytes, bytes]]:
        return await self.run(lambda tr: tr.get_range(begin, end, limit, reverse))

    # --- change feeds (ISSUE 4; see client/change_feed.py) ---

    async def create_change_feed(self, feed_id: bytes, begin: bytes,
                                 end: bytes) -> Version:
        """Register a feed over [begin, end); returns the registration's
        commit version (mutations strictly above it flow in)."""
        from .change_feed import create_change_feed
        return await create_change_feed(self, feed_id, begin, end)

    async def destroy_change_feed(self, feed_id: bytes) -> None:
        from .change_feed import destroy_change_feed
        await destroy_change_feed(self, feed_id)

    async def pop_change_feed(self, feed_id: bytes, version: Version) -> None:
        """Durably release feed data at or below ``version``."""
        from .change_feed import pop_change_feed
        await pop_change_feed(self, feed_id, version)

    def read_change_feed(self, feed_id: bytes, begin_version: Version = 0,
                         begin: bytes | None = None,
                         end: bytes | None = None):
        """A ChangeFeedCursor resuming at ``begin_version`` (exclusive of
        already-processed versions; pass 0 to start from registration)."""
        from .change_feed import ChangeFeedCursor
        return ChangeFeedCursor(self, feed_id, begin_version, begin, end)
