"""Database handle + the retry-loop helper every binding exposes.

Reference: REF:fdbclient/NativeAPI.actor.h (Database/DatabaseContext) and
the ``db.run``/``@fdb.transactional`` pattern from
REF:bindings/python/fdb/impl.py — run a function against a fresh
transaction, commit, and loop through ``on_error`` on retryable failures.
"""

from __future__ import annotations

import asyncio
from typing import Any, Awaitable, Callable

from ..core.cluster import Cluster
from ..core.data import Version
from .transaction import Transaction


class Database:
    def __init__(self, cluster: Cluster) -> None:
        self.cluster = cluster

    def create_transaction(self) -> Transaction:
        return Transaction(self.cluster)

    async def run(self, fn: Callable[[Transaction], Awaitable[Any]],
                  max_retries: int | None = None) -> Any:
        """The @transactional retry loop: fn(tr) then commit; retryable
        errors reset and re-run fn.  fn must be idempotent across retries
        (same contract as the reference)."""
        tr = self.create_transaction()
        attempts = 0
        while True:
            try:
                result = await fn(tr)
                await tr.commit()
                return result
            except BaseException as e:
                attempts += 1
                if max_retries is not None and attempts > max_retries:
                    raise
                await tr.on_error(e)   # re-raises if not retryable

    # --- one-shot conveniences ---

    async def get(self, key: bytes) -> bytes | None:
        return await self.run(lambda tr: tr.get(key))

    async def set(self, key: bytes, value: bytes) -> Version:
        async def go(tr: Transaction):
            tr.set(key, value)
        await self.run(go)
        return 0

    async def clear(self, key: bytes) -> None:
        async def go(tr: Transaction):
            tr.clear(key)
        await self.run(go)

    async def clear_range(self, begin: bytes, end: bytes) -> None:
        async def go(tr: Transaction):
            tr.clear_range(begin, end)
        await self.run(go)

    async def get_range(self, begin, end, limit: int = 0,
                        reverse: bool = False) -> list[tuple[bytes, bytes]]:
        return await self.run(lambda tr: tr.get_range(begin, end, limit, reverse))

    # --- change feeds (ISSUE 4; see client/change_feed.py) ---

    async def create_change_feed(self, feed_id: bytes, begin: bytes = b"",
                                 end: bytes = b"\xff") -> Version:
        """Register a feed over [begin, end); returns the registration's
        commit version (mutations strictly above it flow in).  The
        default range is the WHOLE user keyspace, \\xff-exclusive
        (ISSUE 8): system writes never enter a feed."""
        from .change_feed import create_change_feed
        return await create_change_feed(self, feed_id, begin, end)

    async def destroy_change_feed(self, feed_id: bytes) -> None:
        from .change_feed import destroy_change_feed
        await destroy_change_feed(self, feed_id)

    async def pop_change_feed(self, feed_id: bytes, version: Version) -> None:
        """Durably release feed data at or below ``version``."""
        from .change_feed import pop_change_feed
        await pop_change_feed(self, feed_id, version)

    def read_change_feed(self, feed_id: bytes, begin_version: Version = 0,
                         begin: bytes | None = None,
                         end: bytes | None = None):
        """A ChangeFeedCursor resuming at ``begin_version`` (exclusive of
        already-processed versions; pass 0 to start from registration)."""
        from .change_feed import ChangeFeedCursor
        return ChangeFeedCursor(self, feed_id, begin_version, begin, end)

    # --- feed-native backup / point-in-time restore (ISSUE 8) ---

    def _backup_agents(self) -> dict:
        agents = getattr(self, "_backup_agents_by_dir", None)
        if agents is None:
            agents = self._backup_agents_by_dir = {}
        return agents

    async def start_backup(self, fs, directory: str,
                           snapshot: bool = True):
        """Start a feed-native backup into ``directory`` on ``fs``: arm
        the whole-database change-feed tail (the continuous mutation
        log) and, with ``snapshot``, write an initial consistent
        snapshot under it.  Returns the BackupAgent (kept on this
        handle for stop_backup).  A container holding a prior agent's
        mutation log is RESUMED exactly-once from its durable frontier
        instead of restarted."""
        from ..backup.agent import BackupAgent
        agent = BackupAgent(self, fs, directory)
        meta = await agent.container.load_log_manifest()
        if meta is not None and not meta.get("stopped", False):
            await agent.resume_continuous()
        else:
            await agent.start_continuous()
        # registered BEFORE the snapshot so a failed snapshot never
        # leaves a running tail the API cannot reach
        self._backup_agents()[agent.dir] = agent
        if snapshot:
            try:
                await agent.backup()
            except BaseException:
                # unwind the tail WITHOUT destroying the feed or the
                # manifest: the container stays resumable (a retry of
                # start_backup resumes it exactly-once) and the feed's
                # retention is released then — destroying here would
                # hole a resumed log irrecoverably
                if agent._pull_task is not None:
                    agent._pull_task.cancel()
                    try:
                        await agent._pull_task
                    except (asyncio.CancelledError, Exception):  # noqa: BLE001
                        pass
                    agent._pull_task = None
                self._backup_agents().pop(agent.dir, None)
                raise
        return agent

    async def stop_backup(self, directory: str,
                          drain_timeout: float = 10.0) -> Version:
        """Drain and stop the backup running into ``directory``;
        returns the drained log frontier (every commit at or below it
        is durably in the container)."""
        agent = self._backup_agents().get(directory.rstrip("/"))
        if agent is None:
            from ..backup.agent import RestoreError
            raise RestoreError(f"no backup running into {directory!r}")
        return await agent.stop_continuous(drain_timeout=drain_timeout)

    async def restore(self, fs, directory: str,
                      to_version: Version | None = None,
                      resume: bool = False):
        """Point-in-time restore from the container in ``directory``:
        the newest snapshot at or below ``to_version`` plus the .mlog
        replay window above it (see BackupAgent.restore)."""
        from ..backup.agent import BackupAgent
        agent = BackupAgent(self, fs, directory)
        return await agent.restore(to_version=to_version, resume=resume)
