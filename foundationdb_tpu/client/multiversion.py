"""Multi-version client — API-version selection and client switching.

Reference: REF:fdbclient/MultiVersionTransaction.actor.cpp +
REF:bindings/c (fdb_select_api_version) — the reference client dlopens
several ``libfdb_c`` versions so one process can talk to clusters running
any protocol version, and every binding must call
``fdb_select_api_version`` exactly once before anything else: the chosen
version gates features and pins compatibility semantics.

The analog here: ``api_version(N)`` must be called once, validates N
against [MIN_API_VERSION, MAX_API_VERSION], and feature-gates the
surface; ``MultiVersionDatabase`` fronts one of the interchangeable
client implementations (the native asyncio client, or the ctypes-over-C
binding) and re-resolves on cluster upgrades (epoch changes) the way the
reference re-dlopens on protocol changes.
"""

from __future__ import annotations

from typing import Any

from ..runtime.errors import FdbError, _err

MIN_API_VERSION = 200
MAX_API_VERSION = 710

ApiVersionInvalid = _err(2200, "api_version_invalid",
                         "API version is not supported")
ApiVersionAlreadySet = _err(2201, "api_version_already_set",
                            "API version may be set only once")
ApiVersionUnset = _err(2202, "api_version_unset",
                       "API version must be set before any other call")

_selected: int | None = None


def api_version(version: int) -> None:
    """Select the API version for this process (fdb_select_api_version).
    Must be called exactly once, before ``open``."""
    global _selected
    if _selected is not None:
        if version == _selected:
            return
        raise ApiVersionAlreadySet()
    if not MIN_API_VERSION <= version <= MAX_API_VERSION:
        raise ApiVersionInvalid()
    _selected = version


def selected_api_version() -> int | None:
    return _selected


def _reset_api_version_for_tests() -> None:
    global _selected
    _selected = None


class FeatureGate:
    """What the selected API version permits — consulted by surfaces that
    changed across versions (the reference hides/renames options the
    same way)."""

    def __init__(self, version: int) -> None:
        self.version = version

    @property
    def versionstamps(self) -> bool:
        return self.version >= 520       # modern 4-byte-offset format

    @property
    def snapshot_ryw(self) -> bool:
        return self.version >= 300


class MultiVersionDatabase:
    """Database facade delegating to a selected client implementation.

    ``flavor`` picks the backing client:
      - "native": foundationdb_tpu.client (asyncio, in-process stubs)
      - "c":      the ctypes binding over libfdbtpu_c (bindings/python)
    """

    def __init__(self, flavor: str, target: Any) -> None:
        if _selected is None:
            raise ApiVersionUnset()
        self.features = FeatureGate(_selected)
        self.flavor = flavor
        if flavor == "native":
            self._db = target        # a Database/RefreshingDatabase
        elif flavor == "c":
            import importlib.util
            import os
            path = os.path.join(os.path.dirname(__file__), "..", "..",
                                "bindings", "python", "fdbtpu.py")
            spec = importlib.util.spec_from_file_location("fdbtpu", path)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            self._db = mod.open(target)      # target = cluster file path
        else:
            raise ValueError(f"unknown client flavor {flavor!r}")

    def create_transaction(self):
        tr = self._db.create_transaction()
        if self.flavor == "native" and not self.features.versionstamps:
            # feature gate: old API versions had a different stamp format
            # we do not implement — surface a clean error instead of a
            # silently wrong encoding
            def _no_stamp(*a, **kw):
                raise ApiVersionInvalid(
                    "versionstamped operations need api_version >= 520")
            tr.set_versionstamped_key = _no_stamp
            tr.set_versionstamped_value = _no_stamp
        return tr

    async def _call(self, name, *args, **kwargs):
        """Delegate to the inner database, surviving CLUSTER UPGRADES:
        when the cluster publishes a new protocol version, the pinned
        native view raises cluster_version_changed; re-resolve (the
        analog of dlopening the matching libfdb_c) and retry
        (REF:fdbclient/MultiVersionTransaction.actor.cpp
        MultiVersionDatabase protocol-version monitor).  Accepts both
        the native client's coroutines and the ctypes-over-C binding's
        synchronous methods, preserving each one's return value."""
        import asyncio
        while True:
            try:
                r = getattr(self._db, name)(*args, **kwargs)
                return await r if asyncio.iscoroutine(r) else r
            except FdbError as e:
                if e.code != 1039 or self.flavor != "native":
                    raise
                await self._re_resolve()

    # run + the convenience surface all route through _call, so every
    # entry point — not just explicit run() callers — survives upgrades

    def run(self, fn):
        return self._call("run", fn)

    def get(self, key):
        return self._call("get", key)

    def set(self, key, value):
        return self._call("set", key, value)

    def clear(self, key):
        return self._call("clear", key)

    def clear_range(self, begin, end):
        return self._call("clear_range", begin, end)

    def get_range(self, begin, end, **kwargs):
        return self._call("get_range", begin, end, **kwargs)

    async def _re_resolve(self) -> None:
        """Adopt the cluster's published protocol: re-pin the view's
        knobs to it and rebuild the stub set from the fresh state."""
        from ..core.cluster_client import fetch_cluster_state
        from ..runtime.trace import TraceEvent
        state = await fetch_cluster_state(self._db.coordinators)
        old = self._db.view.knobs.PROTOCOL_VERSION
        self._db.view.knobs = self._db.view.knobs.override(
            PROTOCOL_VERSION=state.get("protocol", old))
        self._db.view.update(state)
        TraceEvent("MultiVersionClientSwitched").detail("From", old) \
            .detail("To", self._db.view.knobs.PROTOCOL_VERSION).log()

    def __getattr__(self, name: str):
        return getattr(self._db, name)
