"""Client surface for change feeds: create / read / pop / destroy.

Reference: REF:fdbclient/NativeAPI.actor.cpp (createChangeFeed /
getChangeFeedStreamActor / popChangeFeedMutations) — feed lifecycle is
ordinary transactions against the ``\\xff/changeFeeds`` system keyspace
(so registration is replicated, recovered and exactly-versioned like
any commit), and consumption is a merged cursor over the storage
servers owning the feed's range.

Exactly-once resume: the cursor's ``version`` field is the full resume
state.  Every ``next()`` long-polls all owning shards, delivers only
entries below the MINIMUM of the shards' heartbeat end-versions, and
advances the cursor to that minimum — so a consumer that crashes and
reconstructs a cursor from its last processed version re-reads nothing
and skips nothing, across storage failovers and range moves.
"""

from __future__ import annotations

import asyncio

from ..core.change_feed import ChangeFeedStreamRequest
from ..core.data import MutationBatch, Version
from ..core.system_data import change_feed_key, change_feed_pop_key
from ..runtime.errors import (ChangeFeedDestroyed, ChangeFeedNotRegistered,
                              ChangeFeedPopped, FdbError, InvertedRange,
                              KeyOutsideLegalRange)

__all__ = ["create_change_feed", "destroy_change_feed", "pop_change_feed",
           "ChangeFeedCursor"]


async def create_change_feed(db, feed_id: bytes, begin: bytes,
                             end: bytes) -> Version:
    """Register feed ``feed_id`` over [begin, end); returns the commit
    version — mutations strictly above it flow into the feed.
    Idempotent: re-creating an existing feed is a no-op server-side."""
    if begin >= end:
        raise InvertedRange()
    if end > b"\xff":
        raise KeyOutsideLegalRange("change feeds cover user keys only")
    from ..rpc.wire import encode
    blob = encode({"b": bytes(begin), "e": bytes(end)})
    tr = db.create_transaction()
    while True:
        try:
            tr.set(change_feed_key(feed_id), blob)
            return await tr.commit()
        except BaseException as e:
            await tr.on_error(e)   # re-raises if not retryable


async def destroy_change_feed(db, feed_id: bytes) -> None:
    """Unregister the feed; owning storage servers release every
    retained segment at the destroy's exact commit version."""
    async def go(tr):
        tr.clear(change_feed_key(feed_id))
    await db.run(go)


async def pop_change_feed(db, feed_id: bytes, version: Version) -> None:
    """Advance the feed's durable low-water mark: entries at or below
    ``version`` are released on every owning storage server (a resumed
    cursor below it fails with change_feed_popped)."""
    from ..rpc.wire import encode
    blob = encode(int(version))

    async def go(tr):
        tr.set(change_feed_pop_key(feed_id), blob)
    await db.run(go)


async def _feed_range(db, feed_id: bytes) -> tuple[bytes, bytes]:
    from ..rpc.wire import decode
    tr = db.create_transaction()
    try:
        raw = await tr.get(change_feed_key(feed_id), snapshot=True)
    finally:
        tr.reset()
    if not raw:
        raise ChangeFeedNotRegistered()
    info = decode(bytes(raw))
    return bytes(info["b"]), bytes(info["e"])


def _covers(begin: bytes, end: bytes,
            pieces: list[tuple[bytes, bytes]]) -> bool:
    """True when the union of ``pieces`` covers [begin, end)."""
    cur = begin
    for b, e in sorted((bytes(b), bytes(e)) for b, e in pieces):
        if b > cur:
            return False
        cur = max(cur, e)
        if cur >= end:
            return True
    return cur >= end


class ChangeFeedCursor:
    """Version-merged consumer over every shard of a feed's range.

    ``next()`` returns [(version, MutationBatch)] in non-decreasing
    version order (a version appears once per owning shard — shards
    carry disjoint keys) and advances ``self.version`` past everything
    returned; an empty list is a heartbeat (the range is proven quiet
    below the advanced cursor).  Construct with the last processed
    cursor to resume exactly-once.
    """

    def __init__(self, db, feed_id: bytes, begin_version: Version = 0,
                 begin: bytes | None = None, end: bytes | None = None,
                 byte_limit: int = 0) -> None:
        self._db = db
        self.feed_id = feed_id
        self.version = max(1, begin_version)   # next unseen version
        self._begin = begin
        self._end = end
        self._byte_limit = byte_limit
        self.popped_version: Version = 0
        self.entries_read = 0

    def _cluster(self):
        # Database wraps an in-process Cluster; RefreshingDatabase wraps
        # a RecoveredClusterView — both expose storages_for_range
        return getattr(self._db, "view", None) or self._db.cluster

    async def _refresh(self) -> None:
        refresh = getattr(self._db, "refresh", None)
        if refresh is not None:
            await refresh()

    async def next(self) -> list[tuple[Version, MutationBatch]]:
        not_registered = 0
        stale_map = 0
        while True:
            groups = self._cluster().storages_for_range(
                self._begin, self._end) if self._begin is not None else None
            if groups is None:
                self._begin, self._end = await _feed_range(self._db,
                                                           self.feed_id)
                continue
            req = ChangeFeedStreamRequest(self.feed_id, self.version,
                                          self._byte_limit)
            try:
                replies = await asyncio.gather(
                    *(g.change_feed_stream(req) for g in groups))
            except ChangeFeedPopped:
                raise
            except FdbError as e:
                if isinstance(e, ChangeFeedNotRegistered):
                    # racing a range handoff (the destination has not
                    # applied its REGISTER yet) — or genuinely destroyed;
                    # the replicated registration row distinguishes the
                    # two: a handoff leaves it intact, a destroy clears
                    # it, so a consumer gets the typed terminal error
                    # instead of a raw lookup failure after 50 retries
                    try:
                        await _feed_range(self._db, self.feed_id)
                    except ChangeFeedNotRegistered:
                        raise ChangeFeedDestroyed(
                            "change feed %r destroyed mid-drain at cursor "
                            "version %d" % (self.feed_id, self.version)
                        ) from e
                    except FdbError as probe:
                        if not probe.retryable:
                            raise
                        # row unreadable right now: stay in the bounded
                        # handoff retry rather than misclassifying
                    not_registered += 1
                    if not_registered > 50:
                        raise
                elif not e.retryable:
                    raise
                await self._refresh()
                await asyncio.sleep(0.1)
                continue
            # COVERAGE gate: after a range split/move the old owner keeps
            # answering for the keys it kept — no error ever fires — so
            # the cursor must prove the polled shards jointly cover the
            # feed range before advancing, else the moved half's
            # mutations would be silently skipped
            pieces: list[tuple[bytes, bytes]] = []
            known = True
            for r in replies:
                if r.ranges is None:      # pre-coverage peer: trust it
                    known = False
                    break
                pieces.extend(r.ranges)
            if known and not _covers(self._begin, self._end, pieces):
                stale_map += 1
                if stale_map > 100:
                    raise FdbError(
                        "change feed range %r-%r not fully served after "
                        "repeated refreshes" % (self._begin, self._end))
                await self._refresh()
                await asyncio.sleep(0.1)
                continue
            end = min(r.end_version for r in replies)
            self.popped_version = max(r.popped_version for r in replies)
            if end <= self.version:
                return []      # heartbeat with no progress: re-poll
            out: list[tuple[Version, MutationBatch]] = []
            for r in replies:          # group order == shard key order
                for v, batch in r.entries:
                    if self.version <= v < end:
                        out.append((v, batch))
            out.sort(key=lambda e: e[0])   # stable: shard order per version
            self.version = end
            self.entries_read += len(out)
            return out

    async def drain_through(self, version: Version,
                            deadline: float | None = None
                            ) -> list[tuple[Version, MutationBatch]]:
        """Poll until the cursor has proven everything at or below
        ``version`` delivered; returns the accumulated entries."""
        loop = asyncio.get_running_loop()
        out: list[tuple[Version, MutationBatch]] = []
        while self.version <= version:
            if deadline is not None and loop.time() > deadline:
                raise TimeoutError(
                    f"feed cursor stalled at {self.version} < {version}")
            out.extend(await self.next())
        return out
