"""Subspace — tuple-addressed key prefixes.

Reference: REF:bindings/python/fdb/subspace_impl.py — a Subspace wraps a
byte prefix; keys inside it are ``prefix + tuple.pack(t)``, so the
ordered tuple encoding gives each subspace a contiguous, nestable key
range.  The API (pack/unpack/range/contains/subscript) is the
cross-binding standard surface layers build on (Directory, queues,
indexes).
"""

from __future__ import annotations

from . import tuple as tuplelayer


class Subspace:
    def __init__(self, prefix_tuple: tuple = (), raw_prefix: bytes = b"") -> None:
        self._prefix = bytes(raw_prefix) + tuplelayer.pack(tuple(prefix_tuple))

    @classmethod
    def from_raw(cls, raw_prefix: bytes) -> "Subspace":
        return cls((), raw_prefix)

    def key(self) -> bytes:
        return self._prefix

    def pack(self, t: tuple = ()) -> bytes:
        return self._prefix + tuplelayer.pack(tuple(t))

    def unpack(self, key: bytes) -> tuple:
        if not self.contains(key):
            raise ValueError("key is not in this subspace")
        return tuplelayer.unpack(key[len(self._prefix):])

    def range(self, t: tuple = ()) -> tuple[bytes, bytes]:
        """[begin, end) covering every key packed under tuple ``t`` in this
        subspace (strict: the bare ``pack(t)`` key itself is excluded,
        matching the reference's ``Subspace.range``)."""
        p = self.pack(t)
        return p + b"\x00", p + b"\xff"

    def contains(self, key: bytes) -> bool:
        return key.startswith(self._prefix)

    def subspace(self, t: tuple) -> "Subspace":
        return Subspace.from_raw(self.pack(t))

    def __getitem__(self, item) -> "Subspace":
        return self.subspace((item,))

    def __repr__(self) -> str:
        return f"Subspace(raw_prefix={self._prefix!r})"

    def __eq__(self, other) -> bool:
        return isinstance(other, Subspace) and self._prefix == other._prefix

    def __hash__(self) -> int:
        return hash(self._prefix)
