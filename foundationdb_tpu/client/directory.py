"""Directory layer — hierarchical namespaces over short allocated prefixes.

Reference: REF:bindings/python/fdb/directory_impl.py — directories map
path tuples like ("app", "users") to short, allocator-assigned key
prefixes, stored in a node tree under ``\\xfe``; applications get a
DirectorySubspace per path and never embed long paths in keys.  The
cross-binding contract (same node tree layout, same allocator behavior)
is what lets every binding open the same directories.

Differences from the reference, driven by this client being async:
every operation takes an explicit transaction and is ``await``-ed; the
reference's transactional decorators become the caller's ``db.run``.

Components:

- ``HighContentionAllocator`` — windowed prefix allocator.  Counters
  advance a window; candidates are drawn uniformly from it and claimed
  with an OCC read+write of the candidate key, so concurrent allocators
  conflict on the claim (one retries) instead of on a single hot counter
  key.
- ``DirectoryLayer`` — create/open/move/remove/list over the node tree.
- ``DirectorySubspace`` — a Subspace bound to its path + layer, with
  directory methods relative to it.
- partitions (layer=b"partition") — a subtree whose nodes AND content
  live under the partition's own prefix, so the whole subtree moves as
  one unit.
"""

from __future__ import annotations

import struct

from . import tuple as tuplelayer
from ..runtime.rng import deterministic_random
from .subspace import Subspace

_SUBDIRS = 0
_VERSION = (1, 0, 0)


class DirectoryError(Exception):
    pass


class HighContentionAllocator:
    """REF:bindings/python/fdb/directory_impl.py::HighContentionAllocator.

    State: ``counters[start] -> allocation count`` (windows) and
    ``recent[candidate] -> b''`` (claims).  The window with the highest
    start is current; when it is half-consumed the window advances and
    older state is cleared.
    """

    def __init__(self, subspace: Subspace, rng=None) -> None:
        self.counters = subspace[0]
        self.recent = subspace[1]
        # injectable for the bindingtester (two implementations must draw
        # identical candidate sequences); defaults to the process RNG
        self._rng = rng

    @staticmethod
    def _window_size(start: int) -> int:
        if start < 255:
            return 64
        if start < 65535:
            return 1024
        return 8192

    async def _current_start(self, tr) -> int:
        rows = await tr.get_range(self.counters.key(),
                                  self.counters.key() + b"\xff",
                                  limit=1, reverse=True, snapshot=True)
        if not rows:
            return 0
        return self.counters.unpack(bytes(rows[0][0]))[0]

    async def allocate(self, tr) -> bytes:
        """Returns a packed integer never allocated before (and never
        again), usable as a key prefix shorter than a path tuple."""
        while True:
            start = await self._current_start(tr)
            window_advanced = False
            while True:
                if window_advanced:
                    tr.clear_range(self.counters.key(),
                                   self.counters.pack((start,)))
                    tr.clear_range(self.recent.key(),
                                   self.recent.pack((start,)))
                tr.add(self.counters.pack((start,)),
                       struct.pack("<q", 1))
                raw = await tr.get(self.counters.pack((start,)),
                                   snapshot=True)
                count = struct.unpack("<q", raw.ljust(8, b"\x00"))[0] \
                    if raw else 0
                window = self._window_size(start)
                if count * 2 < window:
                    break
                start += window
                window_advanced = True
            while True:
                # the process RNG, NOT os.urandom: every source of
                # randomness must flow through the seeded generator or
                # simulation replay loses bit-for-bit determinism
                rng = self._rng if self._rng is not None \
                    else deterministic_random()
                candidate = start + rng.random_int(0, window - 1)
                latest = await self._current_start(tr)
                if latest > start:
                    break       # window moved under us: restart outer
                # OCC claim: both contenders read the key and write it, so
                # each one's read conflicts with the other's write and
                # exactly one commits (the reference does the same with an
                # explicit write-conflict key)
                taken = await tr.get(self.recent.pack((candidate,)))
                tr.set(self.recent.pack((candidate,)), b"")
                if taken is None:
                    return tuplelayer.pack((candidate,))


class DirectorySubspace(Subspace):
    """A directory's content subspace, carrying its path and layer and
    offering directory ops relative to itself."""

    def __init__(self, path: tuple, prefix: bytes,
                 directory_layer: "DirectoryLayer", layer: bytes = b"") -> None:
        super().__init__((), prefix)
        self.path = tuple(path)
        self.layer = layer
        self._dl = directory_layer

    def _partition_subpath(self, path):
        return self.path[len(self._dl._path):] + tuple(path)

    def _effective_dl(self) -> "DirectoryLayer":
        return self._dl

    async def create_or_open(self, tr, path, layer: bytes = b""):
        return await self._effective_dl().create_or_open(
            tr, self._partition_subpath(path), layer)

    async def open(self, tr, path, layer: bytes = b""):
        return await self._effective_dl().open(
            tr, self._partition_subpath(path), layer)

    async def create(self, tr, path, layer: bytes = b"",
                     prefix: bytes | None = None):
        return await self._effective_dl().create(
            tr, self._partition_subpath(path), layer, prefix)

    async def list(self, tr, path=()):
        return await self._effective_dl().list(
            tr, self._partition_subpath(path))

    async def move_to(self, tr, new_path):
        return await self._dl.move(tr, self.path, tuple(new_path))

    async def move(self, tr, old_sub, new_sub):
        return await self._effective_dl().move(
            tr, self._partition_subpath(old_sub),
            self._partition_subpath(new_sub))

    async def remove(self, tr, path=()):
        return await self._effective_dl().remove(
            tr, self._partition_subpath(path))

    async def exists(self, tr, path=()) -> bool:
        return await self._effective_dl().exists(
            tr, self._partition_subpath(path))

    def __repr__(self) -> str:
        return f"DirectorySubspace(path={self.path}, prefix={self.key()!r})"


class DirectoryPartition(DirectorySubspace):
    """layer=b"partition": a subtree whose node metadata lives inside its
    own prefix, so moving/removing the partition moves everything.  Using
    a partition as a raw subspace is an error in the reference, and here."""

    def __init__(self, path: tuple, prefix: bytes,
                 parent_dl: "DirectoryLayer") -> None:
        super().__init__(path, prefix, parent_dl, b"partition")
        self._contents_dl = DirectoryLayer(
            node_subspace=Subspace.from_raw(prefix + b"\xfe"),
            content_subspace=Subspace.from_raw(prefix))
        self._contents_dl._path = tuple(path)

    def _effective_dl(self) -> "DirectoryLayer":
        return self._contents_dl

    def _partition_subpath(self, path):
        return tuple(path)

    def _raw_used(self, what: str):
        raise DirectoryError(
            f"cannot {what} a directory partition's raw subspace")

    def key(self):                    # noqa: D102 — guard, not accessor
        self._raw_used("key()")

    def pack(self, t=()):
        self._raw_used("pack()")

    def range(self, t=()):
        self._raw_used("range()")


class DirectoryLayer:
    def __init__(self,
                 node_subspace: Subspace | None = None,
                 content_subspace: Subspace | None = None,
                 rng=None) -> None:
        self._nodes = node_subspace if node_subspace is not None \
            else Subspace.from_raw(b"\xfe")
        self._content = content_subspace if content_subspace is not None \
            else Subspace()
        # the root node's key prefix is the node subspace's own prefix
        self._root = self._nodes[self._nodes.key()]
        self._allocator = HighContentionAllocator(self._root[b"hca"], rng)
        self._path: tuple = ()

    # --- node helpers.  A node is nodes[prefix]; children live at
    # node[_SUBDIRS][name] -> child_prefix; the layer id at node[b"layer"].

    def _node(self, prefix: bytes) -> Subspace:
        return self._nodes[prefix]

    def _prefix_of(self, node: Subspace) -> bytes:
        return self._nodes.unpack(node.key())[0]

    async def _check_version(self, tr, write: bool) -> None:
        raw = await tr.get(self._root.pack((b"version",)))
        if raw is None:
            if write:
                tr.set(self._root.pack((b"version",)),
                       struct.pack("<III", *_VERSION))
            return
        major, minor, _ = struct.unpack("<III", raw)
        if major != _VERSION[0]:
            raise DirectoryError(
                f"directory version {major}.{minor} unreadable")

    async def _route(self, tr, path: tuple):
        """Resolve partition crossings: a path whose PROPER ancestor is a
        partition belongs to that partition's own directory layer (its
        nodes live under the partition prefix, not this layer's \\xfe
        tree).  Returns (layer, subpath) — possibly (self, path)."""
        node = self._root
        for i, name in enumerate(path[:-1]):
            child = await tr.get(node.pack((_SUBDIRS, name)))
            if child is None:
                return self, path
            node = self._node(bytes(child))
            raw = await tr.get(node.pack((b"layer",)))
            if raw == b"partition":
                part = DirectoryPartition(
                    self._path + tuple(path[:i + 1]),
                    self._prefix_of(node), self)
                return await part._contents_dl._route(tr, path[i + 1:])
        return self, path

    async def _find(self, tr, path: tuple):
        """Walk the node tree; returns (node | None, layer) for path."""
        node = self._root
        layer = b""
        for name in path:
            child = await tr.get(node.pack((_SUBDIRS, name)))
            if child is None:
                return None, b""
            node = self._node(bytes(child))
            raw = await tr.get(node.pack((b"layer",)))
            layer = bytes(raw) if raw is not None else b""
        return node, layer

    def _contents(self, path: tuple, node: Subspace,
                  layer: bytes) -> DirectorySubspace:
        prefix = self._prefix_of(node)
        full = self._path + tuple(path)
        if layer == b"partition":
            return DirectoryPartition(full, prefix, self)
        return DirectorySubspace(full, prefix, self, layer)

    async def _node_containing_key(self, tr, key: bytes):
        """The deepest existing node whose prefix contains key, if any —
        the prefix-freedom probe (REF directory_impl.py NodeFinder)."""
        if key.startswith(self._nodes.key()):
            return self._root
        rows = await tr.get_range(self._nodes.key(),
                                  self._nodes.pack((key,)) + b"\x00",
                                  limit=1, reverse=True, snapshot=True)
        for k, _ in rows:
            prev = self._nodes.unpack(bytes(k))[0]
            if key.startswith(prev):
                return self._node(prev)
        return None

    async def _is_prefix_free(self, tr, prefix: bytes) -> bool:
        if not prefix:
            return False
        if await self._node_containing_key(tr, prefix) is not None:
            return False
        rows = await tr.get_range(self._nodes.pack((prefix,)),
                                  self._nodes.pack((prefix + b"\xff",)),
                                  limit=1, snapshot=True)
        return not rows

    # --- public surface ---

    async def create_or_open(self, tr, path, layer: bytes = b""):
        return await self._create_or_open(tr, tuple(path), layer,
                                          prefix=None, allow_create=True,
                                          allow_open=True)

    async def open(self, tr, path, layer: bytes = b""):
        return await self._create_or_open(tr, tuple(path), layer,
                                          prefix=None, allow_create=False,
                                          allow_open=True)

    async def create(self, tr, path, layer: bytes = b"",
                     prefix: bytes | None = None):
        return await self._create_or_open(tr, tuple(path), layer,
                                          prefix=prefix, allow_create=True,
                                          allow_open=False)

    async def _create_or_open(self, tr, path: tuple, layer: bytes,
                              prefix: bytes | None, allow_create: bool,
                              allow_open: bool):
        await self._check_version(tr, write=False)
        if not path:
            raise DirectoryError("the root directory cannot be opened")
        dl, sub = await self._route(tr, path)
        if dl is not self:
            return await dl._create_or_open(tr, sub, layer, prefix,
                                            allow_create, allow_open)
        existing, found_layer = await self._find(tr, path)
        if existing is not None:
            if not allow_open:
                raise DirectoryError(f"directory {path} already exists")
            if layer and found_layer != layer:
                raise DirectoryError(
                    f"{path}: layer mismatch ({found_layer!r} != {layer!r})")
            return self._contents(path, existing, found_layer)
        if not allow_create:
            raise DirectoryError(f"directory {path} does not exist")
        await self._check_version(tr, write=True)

        if prefix is None:
            alloc = await self._allocator.allocate(tr)
            prefix = self._content.key() + alloc
            rows = await tr.get_range(prefix, prefix + b"\xff", limit=1,
                                      snapshot=True)
            if rows:
                raise DirectoryError(
                    f"allocated prefix {prefix!r} is not empty")
            if not await self._is_prefix_free(tr, prefix):
                raise DirectoryError(
                    f"allocated prefix {prefix!r} is already in use")
        elif not await self._is_prefix_free(tr, prefix):
            raise DirectoryError(f"prefix {prefix!r} is already in use")

        # parent must exist (created recursively, layerless)
        if len(path) > 1:
            parent = await self._create_or_open(
                tr, path[:-1], b"", None, allow_create=True, allow_open=True)
            parent_node = self._node(
                parent.key() if not isinstance(parent, DirectoryPartition)
                else self._prefix_of_partition(parent))
        else:
            parent_node = self._root
        node = self._node(prefix)
        tr.set(parent_node.pack((_SUBDIRS, path[-1])), prefix)
        tr.set(node.pack((b"layer",)), layer)
        return self._contents(path, node, layer)

    @staticmethod
    def _prefix_of_partition(p: DirectoryPartition) -> bytes:
        return Subspace.key(p)      # bypass the raw-use guard internally

    async def exists(self, tr, path) -> bool:
        await self._check_version(tr, write=False)
        dl, sub = await self._route(tr, tuple(path))
        if dl is not self:
            return await dl.exists(tr, sub)
        node, _ = await self._find(tr, tuple(path))
        return node is not None

    async def list(self, tr, path=()) -> list:
        await self._check_version(tr, write=False)
        path = tuple(path)
        if path:
            dl, sub = await self._route(tr, path)
            if dl is not self:
                return await dl.list(tr, sub)
            node, layer = await self._find(tr, path)
            if node is None:
                raise DirectoryError(f"directory {path} does not exist")
            if layer == b"partition":
                return await self._contents(path, node, layer) \
                    ._effective_dl().list(tr, ())
        else:
            node = self._root
        rows = await tr.get_range(*node.range((_SUBDIRS,)), limit=0)
        return [node.unpack(bytes(k))[1] for k, _ in rows]

    async def move(self, tr, old_path, new_path):
        await self._check_version(tr, write=True)
        old_path, new_path = tuple(old_path), tuple(new_path)
        if new_path[:len(old_path)] == old_path:
            raise DirectoryError("cannot move a directory into itself")
        dl_old, sub_old = await self._route(tr, old_path)
        dl_new, sub_new = await self._route(tr, new_path)
        if dl_old._nodes.key() != dl_new._nodes.key():
            raise DirectoryError(
                "cannot move between directory partitions")
        if dl_old is not self:
            return await dl_old.move(tr, sub_old, sub_new)
        old_node, layer = await self._find(tr, old_path)
        if old_node is None:
            raise DirectoryError(f"directory {old_path} does not exist")
        if await self.exists(tr, new_path):
            raise DirectoryError(f"directory {new_path} already exists")
        if len(new_path) > 1:
            parent_node, _ = await self._find(tr, new_path[:-1])
        else:
            parent_node = self._root
        if parent_node is None:
            raise DirectoryError(
                f"new parent {new_path[:-1]} does not exist")
        prefix = self._prefix_of(old_node)
        tr.set(parent_node.pack((_SUBDIRS, new_path[-1])), prefix)
        await self._remove_from_parent(tr, old_path)
        return self._contents(new_path, old_node, layer)

    async def _remove_from_parent(self, tr, path: tuple) -> None:
        if len(path) > 1:
            parent, _ = await self._find(tr, path[:-1])
        else:
            parent = self._root
        tr.clear(parent.pack((_SUBDIRS, path[-1])))

    async def remove(self, tr, path) -> bool:
        """Remove the directory, its contents, and its whole subtree."""
        await self._check_version(tr, write=True)
        path = tuple(path)
        if not path:
            raise DirectoryError("the root directory cannot be removed")
        dl, sub = await self._route(tr, path)
        if dl is not self:
            return await dl.remove(tr, sub)
        node, _ = await self._find(tr, path)
        if node is None:
            return False
        await self._remove_recursive(tr, node)
        await self._remove_from_parent(tr, path)
        return True

    async def _remove_recursive(self, tr, node: Subspace) -> None:
        rows = await tr.get_range(*node.range((_SUBDIRS,)), limit=0)
        for _k, child_prefix in rows:
            await self._remove_recursive(tr, self._node(bytes(child_prefix)))
        prefix = self._prefix_of(node)
        tr.clear_range(prefix, prefix + b"\xff")        # content
        tr.clear_range(*node.range())                   # node metadata
        tr.clear(node.key())
