"""Locality API — where keys physically live.

Reference: REF:bindings/python/fdb/locality.py +
REF:fdbclient/NativeAPI.actor.cpp (getAddressesForKey) and the
boundary-keys reader over ``\\xff/keyServers``.  Applications use these
to colocate computation with data and to partition scans along shard
boundaries.
"""

from __future__ import annotations


def _shard_map(db_or_cluster):
    c = getattr(db_or_cluster, "cluster", db_or_cluster)
    return c.shard_map


async def get_addresses_for_key(tr, key: bytes) -> list[str]:
    """Public addresses of the storage replicas serving ``key`` (the
    fdb_transaction_get_addresses_for_key analog).  Takes no read
    conflict, like the reference.  In-process storages (no transport)
    report as "local"."""
    group = tr._cluster.storage_for_key(key)
    out = []
    for r in getattr(group, "replicas", [group]):
        a = getattr(r, "_address", None)
        out.append(f"{a.ip}:{a.port}" if a is not None else "local")
    return out


async def get_boundary_keys(db, begin: bytes, end: bytes) -> list[bytes]:
    """Shard start keys inside [begin, end): the keys at which the
    serving storage team changes.  Scan ranges split on these boundaries
    never cross a shard (REF: fdb.locality.get_boundary_keys)."""
    sm = _shard_map(db)
    starts = [b""] + list(sm.boundaries)
    return [k for k in starts if begin <= k < end]
