"""The tuple layer — order-preserving typed key encoding.

Reference: REF:bindings/python/fdb/tuple.py (+ the cross-binding tuple
spec in REF:design/tuple.md) — every FDB binding ships the same tuple
encoding so keys packed in one language sort and decode identically in
every other.  The byte comparison of ``pack(a)`` and ``pack(b)`` matches
the elementwise comparison of ``a`` and ``b``.

Typecodes (the stable cross-binding surface):

  0x00        null               (escaped as 00 FF inside nested tuples)
  0x01        byte string        (terminated 00; embedded 00 -> 00 FF)
  0x02        unicode string     (utf-8, same escaping)
  0x05        nested tuple       (terminated 00)
  0x0C..0x13  negative int, 8..1 bytes (big-endian of v + 2^(8n) - 1)
  0x14        integer zero
  0x15..0x1C  positive int, 1..8 bytes (big-endian)
  0x20        float  (IEEE754 big-endian, sign-transformed)
  0x21        double (IEEE754 big-endian, sign-transformed)
  0x26        false
  0x27        true
  0x30        UUID (16 raw bytes)
  0x33        versionstamp (12 bytes: 10 txn + 2 user)
"""

from __future__ import annotations

import struct
import uuid as _uuid
from typing import Any

NULL = 0x00
BYTES = 0x01
STRING = 0x02
NESTED = 0x05
INT_ZERO = 0x14
FLOAT = 0x20
DOUBLE = 0x21
FALSE = 0x26
TRUE = 0x27
UUID = 0x30
VERSIONSTAMP = 0x33


class Versionstamp:
    """An 80-bit transaction versionstamp + 16-bit user order."""

    __slots__ = ("bytes",)

    def __init__(self, raw: bytes = b"\xff" * 10, user: int = 0) -> None:
        if len(raw) == 12:
            self.bytes = raw
        elif len(raw) == 10:
            self.bytes = raw + struct.pack(">H", user)
        else:
            raise ValueError("versionstamp needs 10 or 12 bytes")

    def __eq__(self, other) -> bool:
        return isinstance(other, Versionstamp) and self.bytes == other.bytes

    def __hash__(self) -> int:
        return hash(self.bytes)

    def __repr__(self) -> str:
        return f"Versionstamp({self.bytes!r})"


def _escape_nul(data: bytes) -> bytes:
    return data.replace(b"\x00", b"\x00\xff")


def _float_transform(raw: bytes) -> bytes:
    """Sign-transform so byte order == numeric order: negative numbers
    flip every bit, non-negative flip only the sign bit."""
    if raw[0] & 0x80:
        return bytes(b ^ 0xFF for b in raw)
    return bytes([raw[0] ^ 0x80]) + raw[1:]


def _float_untransform(raw: bytes) -> bytes:
    if raw[0] & 0x80:
        return bytes([raw[0] ^ 0x80]) + raw[1:]
    return bytes(b ^ 0xFF for b in raw)


def _encode_one(item: Any, nested: bool, out: bytearray) -> None:
    if item is None:
        out.append(NULL)
        if nested:
            out.append(0xFF)
    elif item is True:
        out.append(TRUE)
    elif item is False:
        out.append(FALSE)
    elif isinstance(item, (bytes, bytearray)):
        out.append(BYTES)
        out += _escape_nul(bytes(item))
        out.append(0x00)
    elif isinstance(item, str):
        out.append(STRING)
        out += _escape_nul(item.encode("utf-8"))
        out.append(0x00)
    elif isinstance(item, int):
        if item == 0:
            out.append(INT_ZERO)
        elif item > 0:
            n = (item.bit_length() + 7) // 8
            if n > 8:
                raise ValueError("tuple ints limited to 8 bytes")
            out.append(INT_ZERO + n)
            out += item.to_bytes(n, "big")
        else:
            n = ((-item).bit_length() + 7) // 8
            if n > 8:
                raise ValueError("tuple ints limited to 8 bytes")
            out.append(INT_ZERO - n)
            out += (item + (1 << (8 * n)) - 1).to_bytes(n, "big")
    elif isinstance(item, float):
        out.append(DOUBLE)
        out += _float_transform(struct.pack(">d", item))
    elif isinstance(item, _uuid.UUID):
        out.append(UUID)
        out += item.bytes
    elif isinstance(item, Versionstamp):
        out.append(VERSIONSTAMP)
        out += item.bytes
    elif isinstance(item, (tuple, list)):
        out.append(NESTED)
        for x in item:
            _encode_one(x, True, out)
        out.append(0x00)
    else:
        raise TypeError(f"cannot pack {type(item).__name__} into a tuple key")


def pack(t: tuple | list) -> bytes:
    """Pack a tuple into an order-preserving byte string."""
    out = bytearray()
    for item in t:
        _encode_one(item, False, out)
    return bytes(out)


def _find_terminator(data: bytes, pos: int) -> int:
    """Index of the unescaped 0x00 terminating a string at ``pos``."""
    while True:
        i = data.index(b"\x00", pos)
        if i + 1 < len(data) and data[i + 1] == 0xFF:
            pos = i + 2
            continue
        return i


def _decode_one(data: bytes, pos: int, nested: bool) -> tuple[Any, int]:
    code = data[pos]
    if code == NULL:
        if nested and pos + 1 < len(data) and data[pos + 1] == 0xFF:
            return None, pos + 2
        return None, pos + 1
    if code == BYTES or code == STRING:
        end = _find_terminator(data, pos + 1)
        raw = data[pos + 1:end].replace(b"\x00\xff", b"\x00")
        return (raw if code == BYTES else raw.decode("utf-8")), end + 1
    if code == NESTED:
        items: list[Any] = []
        p = pos + 1
        while True:
            if data[p] == 0x00:
                if p + 1 < len(data) and data[p + 1] == 0xFF:
                    items.append(None)
                    p += 2
                    continue
                return tuple(items), p + 1
            item, p = _decode_one(data, p, True)
            items.append(item)
    if INT_ZERO - 8 <= code <= INT_ZERO + 8:
        n = code - INT_ZERO
        if n == 0:
            return 0, pos + 1
        if n > 0:
            return int.from_bytes(data[pos + 1:pos + 1 + n], "big"), pos + 1 + n
        n = -n
        v = int.from_bytes(data[pos + 1:pos + 1 + n], "big")
        return v - (1 << (8 * n)) + 1, pos + 1 + n
    if code == DOUBLE:
        raw = _float_untransform(data[pos + 1:pos + 9])
        return struct.unpack(">d", raw)[0], pos + 9
    if code == FLOAT:
        raw = _float_untransform(data[pos + 1:pos + 5])
        return struct.unpack(">f", raw)[0], pos + 5
    if code == TRUE:
        return True, pos + 1
    if code == FALSE:
        return False, pos + 1
    if code == UUID:
        return _uuid.UUID(bytes=data[pos + 1:pos + 17]), pos + 17
    if code == VERSIONSTAMP:
        return Versionstamp(data[pos + 1:pos + 13]), pos + 13
    raise ValueError(f"unknown tuple typecode 0x{code:02x} at {pos}")


def unpack(data: bytes) -> tuple:
    """Inverse of pack."""
    items: list[Any] = []
    pos = 0
    while pos < len(data):
        item, pos = _decode_one(data, pos, False)
        items.append(item)
    return tuple(items)


def range_of(t: tuple | list) -> tuple[bytes, bytes]:
    """The key range containing exactly the tuples extending ``t``
    (fdb.tuple.range): [pack(t)+\\x00, pack(t)+\\xff)."""
    p = pack(t)
    return p + b"\x00", p + b"\xff"
