"""Special-key-space — the ``\\xff\\xff`` module registry.

Reference: REF:fdbclient/SpecialKeySpace.actor.cpp — the reference maps
ranges under ``\\xff\\xff`` to SpecialKeyRangeReadImpl/RWImpl modules:
reads are answered by the client (status json, worker interfaces) or
rewritten onto real system keys (management: exclusions), and writes are
gated behind the SPECIAL_KEY_SPACE_ENABLE_WRITES transaction option.
Same architecture here: a sorted registry of prefix-scoped modules, each
with get/get_range and (for management modules) set/clear handlers that
translate onto ``\\xff`` system keys inside the SAME transaction — so a
special-key exclusion commits atomically with the rest of the txn.
"""

from __future__ import annotations

from ..runtime.errors import ClientInvalidOperation

SPECIAL_PREFIX = b"\xff\xff"


class SpecialKeyModule:
    """One registered range: [prefix, prefix+\\xff)."""

    prefix: bytes = b""
    writable: bool = False

    async def get(self, tr, key: bytes) -> bytes | None:
        rows = await self.get_range(tr, key, key + b"\x00", limit=1)
        for k, v in rows:
            if k == key:
                return v
        return None

    async def get_range(self, tr, begin: bytes, end: bytes,
                        limit: int = 0, reverse: bool = False
                        ) -> list[tuple[bytes, bytes]]:
        raise ClientInvalidOperation(
            f"special-key module {self.prefix!r} is not range-readable")

    def set(self, tr, key: bytes, value: bytes) -> None:
        raise ClientInvalidOperation(
            f"special-key range {self.prefix!r} is read-only")

    def clear(self, tr, begin: bytes, end: bytes | None = None) -> None:
        raise ClientInvalidOperation(
            f"special-key range {self.prefix!r} is read-only")


class StatusJsonModule(SpecialKeyModule):
    """\\xff\\xff/status/json — the cluster status document."""

    prefix = b"\xff\xff/status/json"

    async def get(self, tr, key: bytes) -> bytes | None:
        if key != self.prefix:
            return None
        import json

        from ..core.status import cluster_status
        rdb = getattr(tr, "_rdb", None)
        if rdb is None:
            raise ClientInvalidOperation(
                "status json needs a coordinator-backed database")
        doc = await cluster_status(tr._cluster.knobs, tr._cluster.transport,
                                   rdb.coordinators)
        return json.dumps(
            doc, sort_keys=True,
            default=lambda o: (o.hex() if isinstance(o, (bytes, bytearray))
                               else str(o))).encode()

    async def get_range(self, tr, begin, end, limit=0, reverse=False):
        v = await self.get(tr, self.prefix)
        rows = [(self.prefix, v)] if v is not None \
            and begin <= self.prefix < end else []
        return rows


class ConnectionStringModule(SpecialKeyModule):
    """\\xff\\xff/connection_string — the cluster file line."""

    prefix = b"\xff\xff/connection_string"

    async def get(self, tr, key: bytes) -> bytes | None:
        if key != self.prefix:
            return None
        rdb = getattr(tr, "_rdb", None)
        if rdb is None or not getattr(rdb, "connection_string", None):
            return None
        return rdb.connection_string.encode()

    async def get_range(self, tr, begin, end, limit=0, reverse=False):
        v = await self.get(tr, self.prefix)
        return [(self.prefix, v)] if v is not None \
            and begin <= self.prefix < end else []


class ExcludedServersModule(SpecialKeyModule):
    """\\xff\\xff/management/excluded/<ip:port> — rewrites onto the
    ``\\xff/conf/excluded/`` system keys inside the SAME transaction
    (REF:fdbclient/SpecialKeySpace.actor.cpp ExcludeServersRangeImpl):
    a special-key exclusion commits atomically with the txn and takes
    effect at the next recovery, exactly like the management API."""

    prefix = b"\xff\xff/management/excluded/"
    writable = True

    def _real(self, key: bytes) -> bytes:
        from ..core.management import EXCLUDED_PREFIX
        return EXCLUDED_PREFIX + key[len(self.prefix):]

    def _special(self, real_key: bytes) -> bytes:
        from ..core.management import EXCLUDED_PREFIX
        return self.prefix + real_key[len(EXCLUDED_PREFIX):]

    async def get(self, tr, key: bytes) -> bytes | None:
        return await tr.get(self._real(key))

    async def get_range(self, tr, begin, end, limit=0, reverse=False):
        from ..core.management import EXCLUDED_PREFIX
        lo = self._real(begin) if begin > self.prefix else EXCLUDED_PREFIX
        hi = self._real(end) if end.startswith(self.prefix) \
            else EXCLUDED_PREFIX + b"\xff"
        rows = await tr.get_range(lo, hi, limit=limit, reverse=reverse)
        return [(self._special(k), v) for k, v in rows]

    def set(self, tr, key: bytes, value: bytes) -> None:
        tr.set(self._real(key), value or b"1")

    def clear(self, tr, begin: bytes, end: bytes | None = None) -> None:
        if end is None:
            tr.clear(self._real(begin))
        else:
            from ..core.management import EXCLUDED_PREFIX
            lo = self._real(begin) if begin > self.prefix else EXCLUDED_PREFIX
            hi = self._real(end) if end.startswith(self.prefix) \
                else EXCLUDED_PREFIX + b"\xff"
            tr.clear_range(lo, hi)


class WorkerInterfacesModule(SpecialKeyModule):
    """\\xff\\xff/worker_interfaces/<ip:port> — the registered workers'
    addresses from the published cluster state
    (REF:fdbclient/SpecialKeySpace.actor.cpp WorkerInterfacesSpecialKeyImpl)."""

    prefix = b"\xff\xff/worker_interfaces/"

    async def get_range(self, tr, begin, end, limit=0, reverse=False):
        state = getattr(tr._cluster, "state", None) \
            or getattr(tr._cluster, "last_state", None)
        rows: list[tuple[bytes, bytes]] = []
        addrs = set()
        if isinstance(state, dict):
            for section in ("storage", "commit_proxies", "grv_proxies",
                            "resolvers"):
                for ent in state.get(section, []):
                    a = ent.get("addr")
                    if a:
                        addrs.add(f"{a[0]}:{a[1]}")
            for a in state.get("workers", []):
                addrs.add(f"{a[0]}:{a[1]}" if isinstance(a, (list, tuple))
                          else str(a))
        for a in sorted(addrs):
            k = self.prefix + a.encode()
            if begin <= k < end:
                rows.append((k, b""))
        if reverse:
            rows.reverse()
        if limit:
            rows = rows[:limit]
        return rows


class ErrorMessageModule(SpecialKeyModule):
    """\\xff\\xff/error_message — the last special-key error explanation
    recorded on this transaction (REF: SpecialKeySpace's errorMsg)."""

    prefix = b"\xff\xff/error_message"

    async def get(self, tr, key: bytes) -> bytes | None:
        if key != self.prefix:
            return None
        return getattr(tr, "_special_error", None)

    async def get_range(self, tr, begin, end, limit=0, reverse=False):
        v = await self.get(tr, self.prefix)
        return [(self.prefix, v)] if v is not None \
            and begin <= self.prefix < end else []


class SpecialKeySpace:
    """The registry: longest-prefix dispatch over sorted modules."""

    def __init__(self, modules: list[SpecialKeyModule] | None = None) -> None:
        self.modules = sorted(modules if modules is not None
                              else DEFAULT_MODULES(),
                              key=lambda m: m.prefix)

    def module_for(self, key: bytes) -> SpecialKeyModule | None:
        best = None
        for m in self.modules:
            if key.startswith(m.prefix) or key == m.prefix:
                if best is None or len(m.prefix) > len(best.prefix):
                    best = m
        return best

    async def get(self, tr, key: bytes) -> bytes | None:
        m = self.module_for(key)
        if m is None:
            self._err(tr, f"unknown special key {key!r}")
            raise ClientInvalidOperation(f"unknown special key {key!r}")
        return await m.get(tr, key)

    async def get_range(self, tr, begin: bytes, end: bytes,
                        limit: int = 0, reverse: bool = False
                        ) -> list[tuple[bytes, bytes]]:
        """Range reads span modules (the reference's cross-module read):
        each module contributes its rows clipped to [begin, end)."""
        out: list[tuple[bytes, bytes]] = []
        for m in self.modules:      # sorted by prefix = key order
            mend = m.prefix + b"\xff"
            if mend <= begin or m.prefix >= end:
                continue
            # push the REMAINING limit down so a bounded read never
            # materializes (or RPCs for) rows it will throw away; early
            # termination is only valid forward (modules ascend)
            sub_limit = max(0, limit - len(out)) if limit and not reverse \
                else 0
            try:
                rows = await m.get_range(tr, max(begin, m.prefix),
                                         min(end, mend), limit=sub_limit)
            except ClientInvalidOperation:
                # a module that cannot serve THIS client (e.g. status
                # json without coordinators) contributes nothing to a
                # cross-module read; point reads still surface the error
                continue
            out.extend(rows)
            if limit and not reverse and len(out) >= limit:
                break
        out.sort(key=lambda kv: kv[0], reverse=reverse)
        if limit:
            out = out[:limit]
        return out

    def set(self, tr, key: bytes, value: bytes) -> None:
        m = self._writable(tr, key)
        m.set(tr, key, value)

    def clear(self, tr, begin: bytes, end: bytes | None = None) -> None:
        m = self._writable(tr, begin)
        m.clear(tr, begin, end)

    def _writable(self, tr, key: bytes) -> SpecialKeyModule:
        if not getattr(tr, "special_key_space_enable_writes", False):
            self._err(tr, "special-key writes require the "
                          "SPECIAL_KEY_SPACE_ENABLE_WRITES option")
            raise ClientInvalidOperation(
                "special-key writes require the "
                "SPECIAL_KEY_SPACE_ENABLE_WRITES option")
        m = self.module_for(key)
        if m is None or not m.writable:
            self._err(tr, f"special key {key!r} is not writable")
            raise ClientInvalidOperation(
                f"special key {key!r} is not writable")
        return m

    @staticmethod
    def _err(tr, msg: str) -> None:
        tr._special_error = msg.encode()


def DEFAULT_MODULES() -> list[SpecialKeyModule]:
    return [StatusJsonModule(), ConnectionStringModule(),
            ExcludedServersModule(), WorkerInterfacesModule(),
            ErrorMessageModule()]


SPECIAL_KEY_SPACE = SpecialKeySpace()
