"""Invalidating read-through cache — the millions-of-users read tier.

Entries are **versioned**: ``key -> (value, fill_version)`` where the
fill version is the read version the value was fetched at.  The feed
sink evicts an entry the moment any committed mutation touching its key
is delivered, so a surviving entry is valid through the consumer's
freshness frontier — the entry's effective version is
``max(fill_version, frontier)``, which is exactly what ``get(key,
at_least=V)`` checks: a hit is served only when the entry is provably
fresh at or above the caller's read-version floor, otherwise the cache
reads through and refills.

The fill path closes the obvious race: a mutation delivered BETWEEN the
read-through's snapshot and its store (the asyncio interleave) marks
the in-flight fill, and a fill whose read version is below the marking
mutation's version is discarded instead of cached — the feed's eviction
already ran and must not be undone by a stale store.

Capacity is a plain LRU (``LAYER_CACHE_CAPACITY``); hit/miss/
invalidation counts feed the metrics plane and the zipf hit-rate floor
the perf-smoke stage asserts.
"""

from __future__ import annotations

import collections

from ..core.data import MutationType, Version

__all__ = ["ReadThroughCache"]


class ReadThroughCache:
    def __init__(self, db, consumer, capacity: int | None = None,
                 name: str = "cache") -> None:
        self.db = db
        self.consumer = consumer
        self.name = name
        knobs = db.cluster.knobs
        self.capacity = capacity if capacity is not None \
            else knobs.LAYER_CACHE_CAPACITY
        self._entries: collections.OrderedDict[bytes, tuple] = \
            collections.OrderedDict()           # key -> (value, fill_version)
        self._filling: dict[bytes, Version] = {}  # key -> invalidation ver
        self._fill_refs: dict[bytes, int] = {}    # concurrent fills in flight
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self.evictions = 0
        self.discarded_fills = 0
        self._msource = None
        consumer.add_sink(self)

    # --- read surface ---

    def effective_version(self, key: bytes) -> Version | None:
        """The version a cached entry is provably valid through, or
        None when the key is not cached."""
        e = self._entries.get(key)
        if e is None:
            return None
        return max(e[1], self.consumer.frontier)

    async def get(self, key: bytes, at_least: Version | None = None
                  ) -> bytes | None:
        """The value of ``key``, served from cache when the entry is
        fresh at or above ``at_least`` (default: any cached entry —
        still never stale beyond the feed frontier)."""
        return (await self.get_versioned(key, at_least))[0]

    async def get_versioned(self, key: bytes,
                            at_least: Version | None = None
                            ) -> tuple[bytes | None, Version]:
        """``(value, valid_through)``: the value plus the version it is
        provably valid at — a hit's ``max(fill_version, frontier)``, a
        read-through's fill version.  The staleness proof the workloads
        and the bench stage assert rides this pair."""
        e = self._entries.get(key)
        if e is not None:
            valid_through = max(e[1], self.consumer.frontier)
            if at_least is None or valid_through >= at_least:
                self.hits += 1
                self._entries.move_to_end(key)
                return e[0], valid_through
        self.misses += 1
        # read through, guarding against an invalidation delivered
        # while the fetch is in flight; the marker is refcounted so
        # concurrent fills of the same key each see it
        self._fill_refs[key] = self._fill_refs.get(key, 0) + 1
        self._filling.setdefault(key, 0)
        try:
            tr = self.db.create_transaction()
            try:
                fill_version = await tr.get_read_version()
                value = await tr.get(key, snapshot=True)
            finally:
                tr.reset()
            invalidated_at = self._filling.get(key, 0)
        finally:
            self._fill_refs[key] -= 1
            if self._fill_refs[key] <= 0:
                del self._fill_refs[key]
                self._filling.pop(key, None)
        if invalidated_at > fill_version:
            self.discarded_fills += 1
            return value, fill_version
        self._entries[key] = (value, fill_version)
        self._entries.move_to_end(key)
        while len(self._entries) > self.capacity:
            self._entries.popitem(last=False)
            self.evictions += 1
        return value, fill_version

    def __len__(self) -> int:
        return len(self._entries)

    def snapshot_entries(self) -> list[tuple[bytes, bytes | None, Version]]:
        """(key, value, fill_version) triples — the checker's view;
        taken synchronously so it is atomic w.r.t. the feed sink."""
        return [(k, v, ver) for k, (v, ver) in self._entries.items()]

    # --- feed sink ---

    def _invalidate(self, key: bytes, version: Version) -> None:
        if key in self._entries:
            e = self._entries[key]
            if version > e[1]:
                del self._entries[key]
                self.invalidations += 1
        if key in self._filling:
            self._filling[key] = max(self._filling[key], version)

    def on_mutations(self, version: Version, batch) -> None:
        for m in batch:
            t = int(m.type)
            if t == MutationType.CLEAR_RANGE:
                b, e = m.param1, m.param2
                for k in [k for k in self._entries if b <= k < e]:
                    self._invalidate(k, version)
                for k in [k for k in self._filling if b <= k < e]:
                    self._filling[k] = max(self._filling[k], version)
            else:
                self._invalidate(m.param1, version)

    # --- metrics / status surface ---

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def metrics_source(self):
        if self._msource is None:
            from ..runtime.metrics import MetricsSource
            s = MetricsSource("LayerCache", self.name)
            s.gauge("Entries", lambda: len(self._entries))
            s.gauge("Hits", lambda: self.hits)
            s.gauge("Misses", lambda: self.misses)
            s.gauge("Invalidations", lambda: self.invalidations)
            s.gauge("Evictions", lambda: self.evictions)
            s.gauge("HitRate", lambda: round(self.hit_rate, 4))
            self._msource = s
        return self._msource

    def stats(self) -> dict:
        return {"kind": "cache", "entries": len(self._entries),
                "capacity": self.capacity, "hits": self.hits,
                "misses": self.misses,
                "invalidations": self.invalidations,
                "evictions": self.evictions,
                "discarded_fills": self.discarded_fills,
                "hit_rate": round(self.hit_rate, 4)}
