"""``watch(key)`` on change feeds — upstream's watches (SURVEY §2.3),
rebuilt as a layer.

The core already has a storage-side watch (``Transaction.watch`` →
``ss.watch_value``); this surface is its feed-riding sibling: a watch
registers at a read version, and fires on the FIRST committed mutation
touching its key at or after that version — delivered by the shared
:class:`~..layers.feed_consumer.LayerFeedConsumer`, whose cursor
re-routes across shard moves, failovers and recoveries by construction.
Fire semantics are **at-least-once**: a reconnect replays the
undelivered span exactly-once, so a fire is never lost, and the
registry is allowed to fire spuriously (e.g. when its bounded mutation
memory cannot prove a quiet history) but never to miss.

Immediate fire: registration consults the registry's per-key
last-mutation memory (and the recorded ``clear_range`` spans) — a watch
registered at a version at or below an already-delivered mutation fires
on the spot, without waiting for new feed traffic.  Both memories are
bounded: pruning raises a conservative floor below which registration
fires immediately rather than guessing (spurious, never missed).
"""

from __future__ import annotations

import asyncio

from ..core.data import MutationType, Version
from ..runtime.errors import ClientInvalidOperation

__all__ = ["WatchRegistry", "Watch"]

# bounded mutation memory: prune the per-key map beyond this many
# entries (oldest versions first), raising the conservative floor
_MUTATION_MEMORY = 65536


class Watch:
    """One pending watch: resolved with the firing version."""

    __slots__ = ("key", "version", "baseline", "baseline_version",
                 "future", "registered_at")

    def __init__(self, key: bytes, version: Version,
                 baseline: bytes | None, baseline_version: Version,
                 future: asyncio.Future, registered_at: float) -> None:
        self.key = key
        self.version = version            # fire on mutations >= this
        self.baseline = baseline          # value at baseline_version
        self.baseline_version = baseline_version
        self.future = future
        self.registered_at = registered_at


class WatchRegistry:
    def __init__(self, db, consumer, name: str = "watches",
                 limit: int | None = None) -> None:
        self.db = db
        self.consumer = consumer
        self.name = name
        knobs = db.cluster.knobs
        self.limit = limit if limit is not None else knobs.LAYER_WATCH_LIMIT
        self._pending: dict[bytes, list[Watch]] = {}
        self._pending_count = 0
        # per-key last delivered mutation version + recorded range
        # clears, both with a conservative pruning floor
        self._last_mutation: dict[bytes, Version] = {}
        self._range_clears: list[tuple[bytes, bytes, Version]] = []
        self._memory_floor: Version = 0
        self.registered = 0
        self.fired = 0
        self.immediate_fires = 0
        self.fire_latency_total = 0.0
        self.fire_latency_max = 0.0
        self._msource = None
        consumer.add_sink(self)

    # --- registration ---

    async def watch(self, key: bytes, version: Version | None = None
                    ) -> asyncio.Future:
        """Register a watch on ``key``; the returned future resolves
        with the version of the first mutation at or after the watch
        version (default: a fresh read version).  The baseline value is
        read at the same version for the checker's missed-fire audit."""
        if self._pending_count >= self.limit:
            raise ClientInvalidOperation(
                f"watch registry {self.name!r} at its limit ({self.limit})")
        loop = asyncio.get_running_loop()
        tr = self.db.create_transaction()
        try:
            if version is not None:
                tr.set_read_version(version)
            baseline_version = await tr.get_read_version()
            baseline = await tr.get(key, snapshot=True)
        finally:
            tr.reset()
        watch_version = version if version is not None else baseline_version
        fut: asyncio.Future = loop.create_future()
        self.registered += 1
        fired_at = self._already_fired(key, watch_version)
        if fired_at:
            self.immediate_fires += 1
            self.fired += 1
            fut.set_result(fired_at)
            return fut
        w = Watch(key, watch_version, baseline, baseline_version, fut,
                  loop.time())
        self._pending.setdefault(key, []).append(w)
        self._pending_count += 1
        return fut

    def _already_fired(self, key: bytes, watch_version: Version
                       ) -> Version:
        """The version of an already-delivered mutation at or after
        ``watch_version``, or 0.  Below the pruning floor the history is
        unknowable — fire spuriously (at-least-once allows it; missing
        would not be allowed)."""
        if watch_version <= self._memory_floor:
            return max(self._memory_floor, 1)
        last = self._last_mutation.get(key, 0)
        if last >= watch_version:
            return last
        for b, e, v in self._range_clears:
            if b <= key < e and v >= watch_version:
                return v
        return 0

    def pending_watches(self) -> list[Watch]:
        """Flat snapshot of unfired watches — the checker's view; taken
        synchronously so it is atomic w.r.t. the feed sink."""
        return [w for ws in self._pending.values() for w in ws]

    @property
    def pending_count(self) -> int:
        return self._pending_count

    # --- feed sink ---

    def _fire(self, key: bytes, version: Version) -> None:
        ws = self._pending.get(key)
        if not ws:
            return
        keep: list[Watch] = []
        loop = asyncio.get_running_loop()
        for w in ws:
            if version >= w.version:
                if not w.future.done():
                    w.future.set_result(version)
                lat = loop.time() - w.registered_at
                self.fired += 1
                self.fire_latency_total += lat
                self.fire_latency_max = max(self.fire_latency_max, lat)
                self._pending_count -= 1
            else:
                keep.append(w)
        if keep:
            self._pending[key] = keep
        else:
            del self._pending[key]

    def on_mutations(self, version: Version, batch) -> None:
        for m in batch:
            t = int(m.type)
            if t == MutationType.CLEAR_RANGE:
                b, e = m.param1, m.param2
                self._range_clears.append((b, e, version))
                for k in [k for k in self._pending if b <= k < e]:
                    self._fire(k, version)
            else:
                self._last_mutation[m.param1] = version
                self._fire(m.param1, version)
        self._prune()

    def _prune(self) -> None:
        if len(self._last_mutation) > _MUTATION_MEMORY:
            by_version = sorted(self._last_mutation.items(),
                                key=lambda kv: kv[1])
            drop = by_version[:len(by_version) - _MUTATION_MEMORY // 2]
            for k, v in drop:
                self._memory_floor = max(self._memory_floor, v)
                del self._last_mutation[k]
        if len(self._range_clears) > _MUTATION_MEMORY // 16:
            keep = len(self._range_clears) // 2
            for _b, _e, v in self._range_clears[:-keep]:
                self._memory_floor = max(self._memory_floor, v)
            self._range_clears = self._range_clears[-keep:]

    # --- metrics / status surface ---

    @property
    def fire_latency_mean(self) -> float:
        return self.fire_latency_total / self.fired if self.fired else 0.0

    def metrics_source(self):
        if self._msource is None:
            from ..runtime.metrics import MetricsSource
            s = MetricsSource("LayerWatch", self.name)
            s.gauge("Pending", lambda: self._pending_count)
            s.gauge("Registered", lambda: self.registered)
            s.gauge("Fired", lambda: self.fired)
            s.gauge("ImmediateFires", lambda: self.immediate_fires)
            s.gauge("FireLatencyMeanMs",
                    lambda: round(self.fire_latency_mean * 1000, 3))
            s.gauge("FireLatencyMaxMs",
                    lambda: round(self.fire_latency_max * 1000, 3))
            self._msource = s
        return self._msource

    def stats(self) -> dict:
        return {"kind": "watches", "pending": self._pending_count,
                "registered": self.registered, "fired": self.fired,
                "immediate_fires": self.immediate_fires,
                "fire_latency_mean_ms":
                    round(self.fire_latency_mean * 1000, 3),
                "fire_latency_max_ms":
                    round(self.fire_latency_max * 1000, 3)}
