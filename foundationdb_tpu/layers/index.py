"""Secondary index maintenance — the canonical derived-state layer.

An index row is ``index_subspace.pack((ival, pkey)) -> b""`` where
``ival`` is one of the values the ``extractor(pkey, value)`` callback
derives from a primary row and ``pkey`` is the primary key itself — the
standard FDB index encoding (the tuple order makes ``lookup(ival)`` one
contiguous range read, and embedding ``pkey`` makes rows per-entry
unique so blind clears/sets are exact).

Two maintenance modes share the class:

**Transactional** (``LAYER_INDEX_TRANSACTIONAL``, the default): a
transaction commit hook (client/transaction.py ``add_commit_hook``)
translates the transaction's buffered primary-subspace writes into
index-row mutations inside the SAME commit.  The hook reads each
written key's pre-transaction value (``get_prewrite_multi`` — a
conflicted read, which is what serializes concurrent writers of the
same primary key against each other's index updates) to clear stale
rows, and scans buffered ``clear_range`` spans (``get_prewrite_range``)
to clear every covered row.  The index is never observably stale: rows
are bit-identical to a rebuild-from-scan at any pinned version.

**Async**: a feed sink applies mutations in version order against an
in-memory ``pkey -> ivals`` map (seeded by a one-time scan at start and
re-derivable from the index subspace itself on restart), flushing the
resulting index-row mutations in one transaction per cursor round.  The
layer exposes a **freshness frontier** — reads serve at-or-below it and
``lookup(..., at_least=V)`` falls back to a primary scan when the
frontier lags V.  After each flush the (frontier, flush commit version)
pair is a consistent **checkpoint**: the index subspace read at any
version in [commit, next flush) is exactly the rebuild-from-scan of the
primary at the frontier — the invariant the consistency checker pins.
"""

from __future__ import annotations

import asyncio

from ..client.subspace import Subspace
from ..client.writemap import WriteMap
from ..core.change_feed import WHOLE_DB_END
from ..core.data import MutationType, Version

__all__ = ["SecondaryIndex"]

# one flush transaction per chunk of this many index-row mutations: a
# cursor round folding a big backlog must not exceed the txn size limit
_FLUSH_CHUNK = 1000


def _default_extractor(key: bytes, value: bytes) -> list[bytes]:
    """Index primary rows by their full value (the simplest useful
    index: value -> keys holding it)."""
    return [value]


class SecondaryIndex:
    def __init__(self, db, index: Subspace, extractor=None,
                 primary_begin: bytes = b"",
                 primary_end: bytes = WHOLE_DB_END,
                 mode: str | None = None, name: str = "index",
                 consumer=None, knobs=None) -> None:
        self.db = db
        self.index = index
        self.extractor = extractor or _default_extractor
        self.primary_begin = primary_begin
        self.primary_end = primary_end
        self.knobs = knobs if knobs is not None else db.cluster.knobs
        if mode is None:
            mode = "transactional" if self.knobs.LAYER_INDEX_TRANSACTIONAL \
                else "async"
        if mode not in ("transactional", "async"):
            raise ValueError(f"unknown index mode {mode!r}")
        ib, ie = index.key(), index.range(())[1]
        if ib < primary_end and ie > primary_begin:
            # a self-feeding index would loop: its own rows re-enter the
            # maintenance path as primary mutations
            raise ValueError("index subspace overlaps the primary range")
        self.mode = mode
        self.name = name
        self.consumer = consumer
        # async-mode state
        self._map: dict[bytes, tuple] = {}      # pkey -> sorted ivals
        self._buffer: list[tuple] = []          # raw feed ops this round
        self._pending_ops: list[tuple] = []     # folded, not yet committed
        self._frontier: Version = 0             # applied-through version
        self._commit_version: Version = 0       # last flush's commit
        self._scan_version: Version = 0         # initial build's read version
        self._ready = False
        self._flushing = False
        # counters
        self.rows_set = 0
        self.rows_cleared = 0
        self.lookups = 0
        self.fallback_scans = 0
        self.resolve_fallbacks = 0
        self._msource = None

    # --- shared helpers ---

    def row_key(self, ival: bytes, pkey: bytes) -> bytes:
        return self.index.pack((ival, pkey))

    def _extract(self, key: bytes, value: bytes | None) -> set:
        if value is None:
            return set()
        return set(self.extractor(key, value))

    def _in_primary(self, key: bytes) -> bool:
        return self.primary_begin <= key < self.primary_end

    # --- transactional mode: the commit hook ---

    def install(self, tr) -> None:
        """Arm this index's commit hook on ``tr`` (idempotent)."""
        tr.add_commit_hook(self._commit_hook)

    async def run(self, fn):
        """``db.run`` with the hook armed on every attempt's txn."""
        async def body(tr):
            self.install(tr)
            return await fn(tr)
        return await self.db.run(body)

    async def _commit_hook(self, tr) -> None:
        wm = tr.write_map
        pb, pe = self.primary_begin, self.primary_end
        # buffered clear_range spans: every pre-txn row they cover loses
        # its index rows (the scan takes a read conflict over the span —
        # a concurrent insert into it must conflict or its row leaks)
        for cb, ce in wm.clears_in(pb, pe):
            for k, v in await tr.get_prewrite_range(cb, ce):
                for iv in sorted(self._extract(k, v)):
                    tr.clear(self.row_key(iv, k))
                    self.rows_cleared += 1
        written = wm.written_keys_in(pb, pe)
        need_old = [k for k in written if not wm.range_cleared(k)]
        olds = dict(zip(need_old, await tr.get_prewrite_multi(need_old))) \
            if need_old else {}
        for k in written:
            kind, payload = wm.lookup(k)
            old_v = olds.get(k)         # None: absent or range-cleared above
            new_v = WriteMap.fold_with_base(payload, old_v) \
                if kind == "stack" else payload
            old_ivals = self._extract(k, old_v)
            new_ivals = self._extract(k, new_v)
            for iv in sorted(old_ivals - new_ivals):
                tr.clear(self.row_key(iv, k))
                self.rows_cleared += 1
            for iv in sorted(new_ivals - old_ivals):
                tr.set(self.row_key(iv, k), b"")
                self.rows_set += 1

    # --- async mode: build + feed sink + flush ---

    async def start_async(self) -> None:
        """Register as a sink and build the initial map/rows by scanning
        the primary range.  The scan's read version may exceed the feed
        registration version; replaying the overlap through the map is
        convergent (old == new folds to a no-op), and the checkpoint is
        withheld until the frontier passes the scan version, so the
        checker never observes the catch-up window."""
        if self.mode != "async":
            raise ValueError("start_async on a transactional index")
        if self.consumer is None:
            raise ValueError("async index needs a LayerFeedConsumer")
        self.consumer.add_sink(self)
        page = self.knobs.LAYER_CHECK_PAGE_ROWS
        tr = self.db.create_transaction()
        scan_version = await tr.get_read_version()
        rows_buf: list[tuple[bytes, bytes]] = []
        cursor = self.primary_begin
        while True:
            rows = await tr.get_range(cursor, self.primary_end,
                                      limit=page, snapshot=True)
            for k, v in rows:
                ivals = sorted(self._extract(k, v))
                self._map[k] = tuple(ivals)
                rows_buf.extend((self.row_key(iv, k), b"") for iv in ivals)
            if len(rows) < page:
                break
            cursor = rows[-1][0] + b"\x00"
        tr.reset()
        for start in range(0, len(rows_buf), _FLUSH_CHUNK):
            chunk = [(rk, rv) for rk, rv in
                     rows_buf[start:start + _FLUSH_CHUNK]]
            self._commit_version = await self._commit_ops(chunk)
            self.rows_set += len(chunk)
        self._scan_version = scan_version
        self._ready = True

    async def _commit_ops(self, ops) -> Version:
        """Commit (row_key, b""|None) ops in one retried transaction and
        return the COMMIT VERSION (db.run returns fn's result, not the
        version — the checkpoint needs the version)."""
        tr = self.db.create_transaction()
        try:
            while True:
                try:
                    for rk, rv in ops:
                        if rv is None:
                            tr.clear(rk)
                        else:
                            tr.set(rk, rv)
                    return await tr.commit()
                except BaseException as e:
                    await tr.on_error(e)   # re-raises if not retryable
        finally:
            tr.reset()

    def on_mutations(self, version: Version, batch) -> None:
        # buffer raw ops; folding + flushing happens per cursor round in
        # on_frontier so one transaction carries the whole round
        for m in batch:
            t = int(m.type)
            if t == MutationType.CLEAR_RANGE:
                b = max(m.param1, self.primary_begin)
                e = min(m.param2, self.primary_end)
                if b < e:
                    self._buffer.append((t, b, e, version))
            elif self._in_primary(m.param1):
                self._buffer.append((t, m.param1, m.param2, version))

    async def on_frontier(self, frontier: Version) -> None:
        if self._buffer or self._pending_ops:
            # the checkpoint is withheld while a flush is in flight: a
            # multi-chunk flush commits incrementally, and a checker
            # reading between chunks would see a half-applied round
            self._flushing = True
            await self._flush(frontier)
        if self._ready and frontier >= self._scan_version:
            self._frontier = frontier
        self._flushing = False

    async def _flush(self, frontier: Version) -> None:
        """Fold this round's buffered ops and commit the row diffs.

        Failure-ordered for chaos: atomic operands are RESOLVED before
        any in-memory state changes (a resolution failure re-queues the
        untouched buffer and re-raises — the pull loop reconnects and a
        later round retries), the fold itself is synchronous (cannot
        fail mid-way), and folded-but-uncommitted ops persist in
        ``_pending_ops`` across a failed commit, with the checkpoint
        withheld (``_flushing``) until the drain completes."""
        # pass 1 (sync): which keys still carry an unresolved atomic
        # after this round's later sets/clears supersede earlier ops
        unresolved: dict[bytes, Version] = {}
        for t, p1, p2, v in self._buffer:
            if t == MutationType.SET_VALUE:
                unresolved.pop(p1, None)
            elif t == MutationType.CLEAR_RANGE:
                for k in [k for k in unresolved if p1 <= k < p2]:
                    del unresolved[k]
            else:
                # the feed carries the operand, not the folded value —
                # resolve by reading the key at the frontier below
                unresolved[p1] = v
        resolved: dict[bytes, bytes | None] = {}
        if unresolved:
            keys = sorted(unresolved)
            tr = self.db.create_transaction()
            try:
                tr.set_read_version(frontier)
                vals = await tr.get_multi(keys, snapshot=True)
            except Exception:  # noqa: BLE001 — frontier out of the MVCC
                # window (a long stall): read current instead; any
                # mutation between frontier and now is also in the feed
                # and will re-apply, so the map converges.  db.get rides
                # the full retry loop — a recovery mid-resolution waits
                # it out instead of losing the round.
                self.resolve_fallbacks += 1
                vals = await asyncio.gather(
                    *(self.db.get(k) for k in keys))
            finally:
                tr.reset()
            resolved = dict(zip(keys, vals))

        # pass 2 (sync, infallible): fold into the map, emit row diffs
        buffer, self._buffer = self._buffer, []
        ops = self._pending_ops

        def apply(k: bytes, new_ivals: set) -> None:
            old = set(self._map.get(k, ()))
            for iv in sorted(old - new_ivals):
                ops.append((self.row_key(iv, k), None))
                self.rows_cleared += 1
            for iv in sorted(new_ivals - old):
                ops.append((self.row_key(iv, k), b""))
                self.rows_set += 1
            if new_ivals:
                self._map[k] = tuple(sorted(new_ivals))
            else:
                self._map.pop(k, None)

        pending_atomics: set = set()
        for t, p1, p2, v in buffer:
            if t == MutationType.SET_VALUE:
                pending_atomics.discard(p1)
                apply(p1, self._extract(p1, p2))
            elif t == MutationType.CLEAR_RANGE:
                for k in [k for k in self._map if p1 <= k < p2]:
                    pending_atomics.discard(k)
                    apply(k, set())
            else:
                pending_atomics.add(p1)
        for k in sorted(pending_atomics):
            apply(k, self._extract(k, resolved.get(k)))

        # pass 3: drain; a failed chunk leaves the rest queued and the
        # checkpoint withheld — the next round resumes the drain
        while ops:
            chunk = ops[:_FLUSH_CHUNK]
            self._commit_version = await self._commit_ops(chunk)
            del ops[:len(chunk)]

    # --- read surface ---

    @property
    def frontier(self) -> Version:
        return self._frontier

    def checkpoint(self) -> tuple[Version, Version] | None:
        """(frontier, flush commit version) — None until the initial
        scan has been overtaken.  While no flush commits, the index
        subspace at any read version >= the commit version equals the
        rebuild-from-scan of the primary at the frontier."""
        if self.mode != "async" or not self._ready or self._flushing \
                or self._frontier < self._scan_version:
            return None
        return self._frontier, self._commit_version

    async def lookup(self, ival: bytes, at_least: Version | None = None
                     ) -> tuple[list[bytes], Version]:
        """Primary keys whose extracted values include ``ival``, plus
        the version the answer is fresh through.  Async mode serves the
        index subspace at its frontier — NEVER above it — and falls
        back to a primary scan when ``at_least`` outruns the frontier;
        transactional mode reads at the transaction's own version."""
        self.lookups += 1
        if self.mode == "async":
            ck = self.checkpoint()
            if ck is None or (at_least is not None and ck[0] < at_least):
                self.fallback_scans += 1
                return await self._scan_lookup(ival)
            frontier = ck[0]
            rows = await self.db.get_range(*self.index.range((ival,)))
            return [self.index.unpack(k)[1] for k, _ in rows], frontier
        tr = self.db.create_transaction()
        try:
            version = await tr.get_read_version()
            rows = await tr.get_range(*self.index.range((ival,)))
        finally:
            tr.reset()
        return [self.index.unpack(k)[1] for k, _ in rows], version

    async def _scan_lookup(self, ival: bytes
                           ) -> tuple[list[bytes], Version]:
        """The fallback: scan the primary range at a fresh read version
        and filter through the extractor."""
        page = self.knobs.LAYER_CHECK_PAGE_ROWS
        tr = self.db.create_transaction()
        try:
            version = await tr.get_read_version()
            out: list[bytes] = []
            cursor = self.primary_begin
            while True:
                rows = await tr.get_range(cursor, self.primary_end,
                                          limit=page, snapshot=True)
                for k, v in rows:
                    if ival in self._extract(k, v):
                        out.append(k)
                if len(rows) < page:
                    break
                cursor = rows[-1][0] + b"\x00"
        finally:
            tr.reset()
        return out, version

    # --- metrics / status surface ---

    def metrics_source(self):
        if self._msource is None:
            from ..runtime.metrics import MetricsSource
            s = MetricsSource("LayerIndex", self.name)
            s.gauge("Mode", lambda: self.mode)
            s.gauge("FrontierVersion", lambda: self._frontier)
            s.gauge("RowsSet", lambda: self.rows_set)
            s.gauge("RowsCleared", lambda: self.rows_cleared)
            s.gauge("Lookups", lambda: self.lookups)
            s.gauge("FallbackScans", lambda: self.fallback_scans)
            self._msource = s
        return self._msource

    def stats(self) -> dict:
        return {"kind": "index", "mode": self.mode,
                "frontier": self._frontier,
                "rows_set": self.rows_set,
                "rows_cleared": self.rows_cleared,
                "lookups": self.lookups,
                "fallback_scans": self.fallback_scans,
                "resolve_fallbacks": self.resolve_fallbacks}
