"""The layer ecosystem (ISSUE 19) — production layers over the core contract.

Reference: SURVEY §5 (FDB is a substrate; real products are LAYERS built
on the transactional contract) and §2.3 (watches).  Everything here is a
CLIENT-side construction: ordinary transactions, the tuple/subspace
encoding, and ONE shared whole-database change-feed consumption core
(:mod:`.feed_consumer`) — no new server role, no new RPC.  Three layers
ride that core:

- :class:`.index.SecondaryIndex` — keeps a secondary-index subspace
  current, either transactionally (index rows written in the SAME commit
  via a transaction commit hook) or asynchronously (feed-driven, with an
  exposed freshness frontier; reads serve at-or-below the frontier and
  fall back to a primary scan when asked for fresher data);
- :class:`.cache.ReadThroughCache` — an invalidating read-through cache
  of versioned entries, evicted by the feed the moment a newer committed
  mutation lands (the millions-of-users read tier);
- :class:`.watches.WatchRegistry` — a ``watch(key)`` client surface with
  at-least-once fire semantics that survives shard moves and recoveries
  because the underlying cursor does.

All three are audited by :class:`.checker.LayerConsistencyChecker`: the
scrubber discipline (core/scrubber.py) applied to derived state — pin a
version, page the authoritative keyspace via packed range reads,
cross-verify index rows / cache entries / pending watches against it,
and name every divergent key exactly (severity-40 ``LayerMismatch``).
Refusals are never mismatches.

Nothing in this package runs unless a layer object is constructed, so
same-seed sim traces with no layers in the workload are bit-identical
regardless of the ``LAYER_*`` knobs (proven by the determinism suite).
"""

from .cache import ReadThroughCache
from .checker import LayerConsistencyChecker
from .feed_consumer import LayerFeedConsumer
from .index import SecondaryIndex
from .watches import WatchRegistry

__all__ = ["LayerFeedConsumer", "SecondaryIndex", "ReadThroughCache",
           "WatchRegistry", "LayerConsistencyChecker"]
