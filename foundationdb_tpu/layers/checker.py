"""Layer consistency checker — the scrubber discipline applied to
derived state.

The scrubber (core/scrubber.py) audits REPLICAS of authoritative state;
this checker audits DERIVATIONS of it: index rows, cache entries and
pending watches, cross-verified against the primary keyspace read
through the ordinary transactional path.  Same rules of engagement:

- **pin a version** before comparing anything, and read both sides of
  every comparison at pinned versions so concurrent commits can never
  manufacture a diff;
- **page the authoritative keyspace** via packed range reads
  (``LAYER_CHECK_PAGE_ROWS`` rows per page);
- **name divergent keys exactly** — one severity-40 ``LayerMismatch``
  per divergent key, carrying the layer, the key, the pinned version
  and both sides' evidence;
- **refusals are never mismatches** — a checkpoint that moved mid-read,
  a frontier that will not catch up, a version fallen out of the MVCC
  window all count as refusals and end the sub-check without a verdict.

Per-layer invariant:

- transactional index: rows at pinned version V are BIT-IDENTICAL to a
  rebuild-from-scan of the primary range at V;
- async index: at a stable checkpoint (frontier F, flush commit C), the
  index subspace read at any version >= C equals the rebuild at F;
- cache: every entry with fill version <= pinned V, once the feed
  frontier passes V, byte-equals the authoritative value at V;
- watches: once the frontier passes pinned V, no watch registered at or
  below V may still be pending if the authoritative value at V differs
  from its registration baseline (one-sided: ABA flips are invisible to
  a value check and at-least-once semantics do not require catching
  them).
"""

from __future__ import annotations

import asyncio

from ..core.data import Version
from ..runtime.trace import TraceEvent

__all__ = ["LayerConsistencyChecker"]

# stable-checkpoint attempts before the async-index sub-check refuses
_MAX_CHECK_RETRIES = 8


class LayerConsistencyChecker:
    """One pass = one ``check()`` call; returns the verdict dict and
    emits key-exact ``LayerMismatch`` events for every divergence."""

    def __init__(self, db, index=None, cache=None, watches=None,
                 name: str = "layer-check", knobs=None) -> None:
        self.db = db
        self.index = index
        self.cache = cache
        self.watches = watches
        self.name = name
        self.knobs = knobs if knobs is not None else db.cluster.knobs
        self.passes = 0
        self.divergences = 0
        self.refusals = 0
        self.rows_checked = 0
        self._msource = None

    # --- evidence ---

    def _mismatch(self, layer: str, key: bytes, version: Version,
                  expected, actual) -> None:
        self.divergences += 1
        TraceEvent("LayerMismatch", severity=40) \
            .detail("Layer", layer) \
            .detail("Key", key.hex()) \
            .detail("Version", version) \
            .detail("Expected", "<missing>" if expected is None
                    else bytes(expected)[:64].hex()) \
            .detail("Actual", "<missing>" if actual is None
                    else bytes(actual)[:64].hex()) \
            .log()

    def _refuse(self, layer: str, why: str) -> None:
        self.refusals += 1
        TraceEvent("LayerCheckRefused", severity=20) \
            .detail("Layer", layer).detail("Why", why).log()

    # --- paged pinned reads ---

    async def _page_range(self, begin: bytes, end: bytes,
                          version: Version) -> list[tuple[bytes, bytes]]:
        """Every row of [begin, end) at pinned ``version`` (snapshot,
        paged).  Raises on refusal (too-old, moved) — callers convert
        to a refusal verdict."""
        page = self.knobs.LAYER_CHECK_PAGE_ROWS
        tr = self.db.create_transaction()
        out: list[tuple[bytes, bytes]] = []
        try:
            tr.set_read_version(version)
            cursor = begin
            while True:
                rows = await tr.get_range(cursor, end, limit=page,
                                          snapshot=True)
                out.extend(rows)
                self.rows_checked += len(rows)
                if len(rows) < page:
                    return out
                cursor = rows[-1][0] + b"\x00"
        finally:
            tr.reset()

    async def _pin(self) -> Version:
        tr = self.db.create_transaction()
        try:
            return await tr.get_read_version()
        finally:
            tr.reset()

    def _rebuild_rows(self, index, primary_rows) -> set:
        """The expected index row-key set for a primary snapshot."""
        expected: set = set()
        for k, v in primary_rows:
            for iv in index._extract(k, v):
                expected.add(index.row_key(iv, k))
        return expected

    # --- sub-checks ---

    async def _check_index(self) -> dict:
        index = self.index
        ib, ie = index.index.key(), index.index.range(())[1]
        if index.mode == "transactional":
            # one pinned version serves both sides: the hook keeps rows
            # atomic with the primary, so ANY version must agree
            version = await self._pin()
            try:
                primary = await self._page_range(index.primary_begin,
                                                 index.primary_end, version)
                actual = await self._page_range(ib, ie, version)
            except Exception as e:  # noqa: BLE001
                self._refuse("index", repr(e)[:200])
                return {"checked": 0, "divergences": 0, "refused": True}
            return self._diff_index(version, primary, actual)
        # async mode: compare at a STABLE checkpoint — unchanged across
        # the whole read, else the flush that moved it explains any diff
        for _ in range(_MAX_CHECK_RETRIES):
            ck = index.checkpoint()
            if ck is None:
                await asyncio.sleep(self.knobs.LAYER_FEED_POLL_INTERVAL)
                continue
            frontier, commit = ck
            version = await self._pin()     # >= commit by GRV contract
            try:
                actual = await self._page_range(ib, ie, version)
                primary = await self._page_range(index.primary_begin,
                                                 index.primary_end, frontier)
            except Exception as e:  # noqa: BLE001
                self._refuse("index", repr(e)[:200])
                return {"checked": 0, "divergences": 0, "refused": True}
            if index.checkpoint() != ck:
                continue                    # moved mid-read: no verdict
            return self._diff_index(frontier, primary, actual)
        self._refuse("index", "no stable checkpoint after %d attempts"
                     % _MAX_CHECK_RETRIES)
        return {"checked": 0, "divergences": 0, "refused": True}

    def _diff_index(self, version: Version, primary_rows,
                    actual_rows) -> dict:
        expected = self._rebuild_rows(self.index, primary_rows)
        actual = {k for k, _v in actual_rows}
        before = self.divergences
        for rk in sorted(expected - actual):
            self._mismatch("index", rk, version, b"", None)
        for rk in sorted(actual - expected):
            self._mismatch("index", rk, version, None, b"")
        return {"checked": len(expected | actual),
                "divergences": self.divergences - before, "refused": False}

    async def _check_cache(self) -> dict:
        cache = self.cache
        version = await self._pin()
        try:
            await cache.consumer.wait_frontier(version)
        except TimeoutError:
            self._refuse("cache", "frontier stalled below pin")
            return {"checked": 0, "divergences": 0, "refused": True}
        # snapshot AFTER the frontier passes the pin, synchronously:
        # every mutation at or below the pin has already run the sink
        entries = [(k, v, ver) for k, v, ver in cache.snapshot_entries()
                   if ver <= version]
        if not entries:
            return {"checked": 0, "divergences": 0, "refused": False}
        keys = [k for k, _v, _ver in entries]
        tr = self.db.create_transaction()
        try:
            tr.set_read_version(version)
            truth = await tr.get_multi(keys, snapshot=True)
        except Exception as e:  # noqa: BLE001
            self._refuse("cache", repr(e)[:200])
            return {"checked": 0, "divergences": 0, "refused": True}
        finally:
            tr.reset()
        before = self.divergences
        self.rows_checked += len(entries)
        for (k, v, _ver), auth in zip(entries, truth):
            if v != auth:
                self._mismatch("cache", k, version, auth, v)
        return {"checked": len(entries),
                "divergences": self.divergences - before, "refused": False}

    async def _check_watches(self) -> dict:
        watches = self.watches
        version = await self._pin()
        try:
            await watches.consumer.wait_frontier(version)
        except TimeoutError:
            self._refuse("watches", "frontier stalled below pin")
            return {"checked": 0, "divergences": 0, "refused": True}
        pending = [w for w in watches.pending_watches()
                   if w.baseline_version <= version]
        if not pending:
            return {"checked": 0, "divergences": 0, "refused": False}
        keys = [w.key for w in pending]
        tr = self.db.create_transaction()
        try:
            tr.set_read_version(version)
            truth = await tr.get_multi(keys, snapshot=True)
        except Exception as e:  # noqa: BLE001
            self._refuse("watches", repr(e)[:200])
            return {"checked": 0, "divergences": 0, "refused": True}
        finally:
            tr.reset()
        before = self.divergences
        self.rows_checked += len(pending)
        for w, auth in zip(pending, truth):
            if auth != w.baseline and not w.future.done():
                # the value changed at or below the pin, the change was
                # delivered (frontier >= pin), yet the watch never fired
                self._mismatch("watches", w.key, version, w.baseline, auth)
        return {"checked": len(pending),
                "divergences": self.divergences - before, "refused": False}

    # --- the pass ---

    async def check(self) -> dict:
        """One full pass over every attached layer."""
        out: dict = {"divergences_before": self.divergences}
        if self.index is not None:
            out["index"] = await self._check_index()
        if self.cache is not None:
            out["cache"] = await self._check_cache()
        if self.watches is not None:
            out["watches"] = await self._check_watches()
        self.passes += 1
        out["divergences"] = self.divergences - out.pop("divergences_before")
        out["refusals"] = self.refusals
        out["rows_checked"] = self.rows_checked
        out["passes"] = self.passes
        return out

    # --- metrics / status surface ---

    def metrics_source(self):
        if self._msource is None:
            from ..runtime.metrics import MetricsSource
            s = MetricsSource("LayerCheck", self.name)
            s.gauge("Passes", lambda: self.passes)
            s.gauge("Divergences", lambda: self.divergences)
            s.gauge("Refusals", lambda: self.refusals)
            s.gauge("RowsChecked", lambda: self.rows_checked)
            self._msource = s
        return self._msource

    def stats(self) -> dict:
        return {"kind": "checker", "passes": self.passes,
                "divergences": self.divergences,
                "refusals": self.refusals,
                "rows_checked": self.rows_checked}
