"""The shared feed-consumption core every layer rides.

One :class:`LayerFeedConsumer` owns one whole-database change feed
(client/change_feed.py's exactly-once cursor) and fans each delivered
``(version, MutationBatch)`` entry to its registered sinks in
registration order.  The consumer's **freshness frontier** is the
highest version proven fully delivered to every sink: the cursor
advances only past versions all owning shards have heartbeated, and the
frontier advances only after every sink has returned for every entry at
or below it — so a layer that finished ``on_mutations`` for frontier F
has seen EVERY committed mutation at or below F, across shard moves,
failovers and recoveries (the cursor's coverage gate and min-heartbeat
merge provide that; this module adds nothing to the delivery contract).

The consumer also:

- pops the feed ``LAYER_FEED_POP_LAG_VERSIONS`` behind the frontier so
  retention stays bounded (the backup agent's pop discipline);
- publishes ``\\xff/layers/progress/<name>`` every
  ``LAYER_PROGRESS_INTERVAL`` seconds so ``cluster.layers`` in status
  can report frontier lag without an RPC surface to the client;
- registers one MetricsSource (frontier, entries, reconnects) when
  handed a registry.

Sink protocol (duck-typed): ``on_mutations(version, batch)`` per feed
entry, optional ``on_frontier(version)`` after each cursor round; either
may be a plain function or a coroutine function.  Sinks run in
registration order and a sink exception tears the consumer down loudly
(a layer silently skipping mutations would corrupt derived state — the
checker would catch it, but the consumer must not make it easy).
"""

from __future__ import annotations

import asyncio
import inspect

from ..core.change_feed import WHOLE_DB_BEGIN, WHOLE_DB_END
from ..core.data import Version
from ..core.system_data import layer_progress_key
from ..runtime.errors import ChangeFeedDestroyed
from ..runtime.trace import TraceEvent

__all__ = ["LayerFeedConsumer"]


class LayerFeedConsumer:
    """One whole-db feed, many layer sinks, one freshness frontier."""

    def __init__(self, db, name: str = "layers",
                 feed_id: bytes | None = None, knobs=None) -> None:
        self.db = db
        self.name = name
        self.feed_id = feed_id if feed_id is not None \
            else b"layers/" + name.encode()
        self.knobs = knobs if knobs is not None else db.cluster.knobs
        self._sinks: list = []
        self._task: asyncio.Task | None = None
        self.registration_version: Version = 0
        self.frontier: Version = 0        # proven-delivered version
        self.entries_delivered = 0
        self.batches_delivered = 0
        self.reconnects = 0
        self.pops = 0
        self.destroyed = False
        self._last_pop: Version = 0
        self._last_publish = 0.0
        self._msource = None

    # --- sink registration ---

    def add_sink(self, sink) -> None:
        if sink not in self._sinks:
            self._sinks.append(sink)

    # --- lifecycle ---

    async def start(self) -> Version:
        """Destroy-then-create the feed (the backup agent's fresh
        registration discipline: the commit version of the CREATE is the
        layer's time zero) and begin pulling.  Returns the registration
        version — the frontier starts there."""
        await self.db.destroy_change_feed(self.feed_id)
        vb = await self.db.create_change_feed(self.feed_id, WHOLE_DB_BEGIN,
                                              WHOLE_DB_END)
        self.registration_version = vb
        self.frontier = vb
        self._last_pop = vb
        loop = asyncio.get_running_loop()
        self._task = loop.create_task(self._pull_loop(),
                                      name=f"layer-feed-{self.name}")
        return vb

    async def stop(self, destroy: bool = False) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._task = None
        if destroy and not self.destroyed:
            try:
                await self.db.destroy_change_feed(self.feed_id)
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass

    async def wait_frontier(self, version: Version,
                            timeout: float = 30.0) -> Version:
        """Block until the frontier proves everything at or below
        ``version`` delivered to every sink (loop-clock deadline)."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while self.frontier < version:
            if self._task is not None and self._task.done():
                self._task.result()     # surface the pull loop's death
            if loop.time() > deadline:
                raise TimeoutError(
                    f"layer feed {self.name!r} frontier stalled at "
                    f"{self.frontier} < {version}")
            await asyncio.sleep(self.knobs.LAYER_FEED_POLL_INTERVAL)
        return self.frontier

    # --- metrics / status surface ---

    def metrics_source(self):
        if self._msource is None:
            from ..runtime.metrics import MetricsSource
            s = MetricsSource("LayerFeed", self.name)
            s.gauge("Frontier", lambda: self.frontier)
            s.gauge("RegistrationVersion",
                    lambda: self.registration_version)
            s.gauge("EntriesDelivered", lambda: self.entries_delivered)
            s.gauge("Reconnects", lambda: self.reconnects)
            s.gauge("Pops", lambda: self.pops)
            self._msource = s
        return self._msource

    def stats(self) -> dict:
        return {"kind": "feed", "frontier": self.frontier,
                "registration_version": self.registration_version,
                "entries": self.entries_delivered,
                "batches": self.batches_delivered,
                "reconnects": self.reconnects, "pops": self.pops,
                "destroyed": self.destroyed}

    # --- the pull loop ---

    async def _dispatch(self, method: str, *args) -> None:
        for sink in self._sinks:
            fn = getattr(sink, method, None)
            if fn is None:
                continue
            r = fn(*args)
            if inspect.isawaitable(r):
                await r

    async def _pull_loop(self) -> None:
        cursor = self.db.read_change_feed(self.feed_id, self.frontier + 1)
        while True:
            try:
                entries = await cursor.next()
            except asyncio.CancelledError:
                raise
            except ChangeFeedDestroyed:
                # terminal: the feed's retained segments are gone — a
                # rebuilt cursor could silently skip, so don't
                self.destroyed = True
                TraceEvent("LayerFeedDestroyed", severity=30) \
                    .detail("Name", self.name) \
                    .detail("Frontier", self.frontier).log()
                return
            except Exception as e:  # noqa: BLE001 — rebuild off the frontier
                self.reconnects += 1
                TraceEvent("LayerFeedReconnect", severity=20) \
                    .detail("Name", self.name) \
                    .detail("Frontier", self.frontier) \
                    .detail("Error", repr(e)[:200]).log()
                await asyncio.sleep(self.knobs.LAYER_FEED_POLL_INTERVAL)
                cursor = self.db.read_change_feed(self.feed_id,
                                                  self.frontier + 1)
                continue
            for v, batch in entries:
                await self._dispatch("on_mutations", v, batch)
                self.entries_delivered += 1
                self.batches_delivered += len(batch)
            # the cursor owns everything below cursor.version across
            # every shard — only NOW is that span proven delivered
            self.frontier = max(self.frontier, cursor.version - 1)
            await self._dispatch("on_frontier", self.frontier)
            await self._maintain()

    async def _maintain(self) -> None:
        """Retention pop + progress publish, both best-effort: a locked
        or briefly headless cluster costs a skipped round, never the
        pull loop."""
        pop_to = self.frontier - self.knobs.LAYER_FEED_POP_LAG_VERSIONS
        if pop_to > self._last_pop:
            try:
                await self.db.pop_change_feed(self.feed_id, pop_to)
                self._last_pop = pop_to
                self.pops += 1
            except Exception:  # noqa: BLE001
                pass
        loop = asyncio.get_running_loop()
        if loop.time() - self._last_publish \
                >= self.knobs.LAYER_PROGRESS_INTERVAL:
            self._last_publish = loop.time()
            try:
                await self.publish_progress()
            except Exception:  # noqa: BLE001
                pass

    async def publish_progress(self, extra: dict | None = None) -> None:
        """Write the ``\\xff/layers/progress/<name>`` row status reads
        back (the backup-progress discipline; see core/system_data.py)."""
        from ..rpc.wire import encode
        stats = self.stats()
        # splat each sink's own stats alongside the feed's so the
        # cluster.layers rollup shows index/cache/watch state per
        # consumer without any of them publishing separately
        stats["sinks"] = [s.stats() for s in self._sinks
                          if hasattr(s, "stats")]
        if extra:
            stats.update(extra)
        blob = encode(stats)

        async def go(tr):
            tr.lock_aware = True
            tr.set(layer_progress_key(self.name), blob)
        await self.db.run(go, max_retries=3)
