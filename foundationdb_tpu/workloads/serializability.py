"""Serializability-oracle workload — commit-order replay equality.

Reference: the idea of REF:fdbserver/workloads/ConflictRange.actor.cpp and
SerializabilityWorkload — random concurrent transactions whose *committed*
effects, replayed sequentially in commit order against a brute-force model,
must reproduce the exact final database state.  Catches: writes surviving
an abort verdict, lost committed writes, wrong commit ordering, RYW
leaking uncommitted state.

Tie-break within a commit version uses the versionstamp's batch-order
field — the same total order the proxy applied mutations in.
"""

from __future__ import annotations

import asyncio

from ..core.data import MutationType, apply_atomic
from ..runtime.errors import FdbError
from .workload import TestWorkload, register_workload


@register_workload
class SerializabilityWorkload(TestWorkload):
    name = "Serializability"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.n_keys = int(self.opt("keyCount", 32))
        self.txns = int(self.opt("transactionsPerClient", 25))
        self.prefix = bytes(self.opt("prefix", b"ser/"))
        # shared across clients via options dict (tester merges metrics,
        # but the committed-op log must be global)
        self.log = self.ctx.options.setdefault("_committed_log", [])
        self.committed = 0
        self.aborted = 0

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%06d" % i

    async def start(self) -> None:
        for _ in range(self.txns):
            ops = self._random_ops()
            tr = self.db.create_transaction()
            while True:
                try:
                    for op in ops:
                        kind = op[0]
                        if kind == "get":
                            await tr.get(op[1])
                        elif kind == "range":
                            await tr.get_range(op[1], op[2], limit=10)
                        elif kind == "set":
                            tr.set(op[1], op[2])
                        elif kind == "clear":
                            tr.clear_range(op[1], op[2])
                        elif kind == "atomic":
                            tr.atomic_op(op[1], op[2], op[3])
                    await tr.commit()
                    if any(op[0] in ("set", "clear", "atomic") for op in ops):
                        # read-only txns have no versionstamp and no effects
                        self.log.append((tr.get_versionstamp(), ops))
                    self.committed += 1
                    break
                except FdbError as e:
                    if e.code == 1020:   # not_committed: abort, don't retry
                        self.aborted += 1
                        break
                    await tr.on_error(e)

    def _random_ops(self):
        ops = []
        for _ in range(self.rng.random_int(1, 6)):
            r = self.rng.random()
            k = self._key(self.rng.random_int(0, self.n_keys))
            if r < 0.25:
                ops.append(("get", k))
            elif r < 0.35:
                k2 = self._key(self.rng.random_int(0, self.n_keys))
                ops.append(("range", min(k, k2), max(k, k2) + b"\x00"))
            elif r < 0.70:
                ops.append(("set", k, b"v%d" % self.rng.random_int(0, 1 << 30)))
            elif r < 0.80:
                k2 = self._key(self.rng.random_int(0, self.n_keys))
                ops.append(("clear", min(k, k2), max(k, k2) + b"\x00"))
            else:
                ops.append(("atomic", MutationType.ADD, k,
                            self.rng.random_int(1, 100).to_bytes(8, "little")))
        return ops

    async def check(self) -> bool:
        # replay committed txns in (version, batch-order) order
        model: dict[bytes, bytes] = {}
        for _stamp, ops in sorted(self.log, key=lambda e: e[0]):
            for op in ops:
                if op[0] == "set":
                    model[op[1]] = op[2]
                elif op[0] == "clear":
                    for k in [k for k in model if op[1] <= k < op[2]]:
                        del model[k]
                elif op[0] == "atomic":
                    new = apply_atomic(op[1], model.get(op[2]), op[3])
                    if new is None:
                        model.pop(op[2], None)
                    else:
                        model[op[2]] = new
        actual = dict(await self.db.get_range(self.prefix, self.prefix + b"\xff"))
        return actual == model

    def metrics(self):
        return {"committed": self.committed, "aborted": self.aborted}
