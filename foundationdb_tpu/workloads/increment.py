"""Increment + VersionStamp workloads — atomic-op and ordering checks.

Reference: REF:fdbserver/workloads/Increment.actor.cpp (every atomic
add lands exactly once across faults) and
REF:fdbserver/workloads/VersionStamp.actor.cpp (versionstamped keys
embed the true commit version/order, so their sort order IS the commit
order).
"""

from __future__ import annotations

from ..runtime.errors import FdbError
from .workload import TestWorkload, register_workload


@register_workload
class IncrementWorkload(TestWorkload):
    """Each client atomically adds 1 to a shared counter N times through
    the retry loop; commit_unknown_result makes exactly-once accounting
    subtle, so the workload tracks a per-client ledger key in the SAME
    transaction — at check time counter == sum of ledgers, proving no
    add was lost or double-applied relative to its ledger entry."""

    name = "Increment"
    KEY = b"incr/counter"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.n = int(self.opt("incrementsPerClient", 20))

    def _ledger(self, cid: int) -> bytes:
        return b"incr/ledger/%d" % cid

    async def start(self) -> None:
        cid = self.ctx.client_id
        for i in range(self.n):
            async def bump(tr, i=i):
                tr.add(self.KEY, (1).to_bytes(8, "little"))
                tr.add(self._ledger(cid), (1).to_bytes(8, "little"))
            await self.db.run(bump)

    async def check(self) -> bool:
        if self.ctx.client_id != 0:
            return True
        tr = self.db.create_transaction()
        while True:
            try:
                total = await tr.get(self.KEY)
                ledgers = await tr.get_range(b"incr/ledger/",
                                             b"incr/ledger0", limit=0)
                break
            except FdbError as e:
                await tr.on_error(e)
        got = int.from_bytes(total or b"\x00" * 8, "little")
        ledger_sum = sum(int.from_bytes(bytes(v), "little")
                         for _, v in ledgers)
        assert got == ledger_sum, (
            f"counter {got} != ledger sum {ledger_sum} — an atomic add "
            f"was lost or double-applied relative to its own transaction")
        return True

    def metrics(self):
        return {"increments": self.n}


@register_workload
class VersionStampWorkload(TestWorkload):
    """Versionstamped keys embed (commit version, batch order): after
    the run, the stamps' byte order must agree with the value sequence
    each client observed committing — commit order IS key order."""

    name = "VersionStamp"
    PREFIX = b"vs/"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.n = int(self.opt("stampsPerClient", 15))
        self.shared = ctx.options.setdefault("_vs_pool", {"committed": []})
        self.local_stamped = 0

    async def start(self) -> None:
        cid = self.ctx.client_id
        for i in range(self.n):
            tr = self.db.create_transaction()
            while True:
                try:
                    key = (self.PREFIX + b"\x00" * 10
                           + len(self.PREFIX).to_bytes(4, "little"))
                    tr.set_versionstamped_key(key, b"%d:%d" % (cid, i))
                    await tr.commit()
                    stamp = tr.get_versionstamp()
                    self.shared["committed"].append(
                        (bytes(stamp), b"%d:%d" % (cid, i)))
                    self.local_stamped += 1
                    break
                except FdbError as e:
                    # an unknown result may or may not have stamped a
                    # key; drop the sample rather than guess (the
                    # ordering check tolerates extras in the db)
                    if e.maybe_committed:
                        break
                    await tr.on_error(e)

    async def check(self) -> bool:
        if self.ctx.client_id != 0:
            return True
        tr = self.db.create_transaction()
        while True:
            try:
                rows = await tr.get_range(self.PREFIX,
                                          self.PREFIX + b"\xff", limit=0)
                break
            except FdbError as e:
                await tr.on_error(e)
        in_db = {bytes(k)[len(self.PREFIX):]: bytes(v) for k, v in rows}
        # every acked stamp exists at exactly its stamped key
        for stamp, val in self.shared["committed"]:
            assert in_db.get(stamp) == val, (
                f"stamp {stamp.hex()} expected {val!r}, "
                f"got {in_db.get(stamp)!r}")
        # stamps are unique, and within one client (whose commits are
        # strictly sequential) stamp byte-order equals commit order
        stamps = [s for s, _ in self.shared["committed"]]
        assert len(set(stamps)) == len(stamps), "duplicate versionstamps"
        per_client: dict[bytes, list[tuple[int, bytes]]] = {}
        for stamp, val in self.shared["committed"]:
            cid, i = val.split(b":")
            per_client.setdefault(cid, []).append((int(i), stamp))
        for cid, seq in per_client.items():
            seq.sort()
            raw = [s for _, s in seq]
            assert raw == sorted(raw), (
                f"client {cid!r}: versionstamp order diverges from "
                f"commit order")
        return True

    def metrics(self):
        # per-client count: the runner SUMS metrics across clients, and
        # the committed pool is shared
        return {"stamped": self.local_stamped}
