"""ConsistencyCheck + AtomicOps — reusable invariant workloads.

Reference: REF:fdbserver/workloads/ConsistencyCheck.actor.cpp (every
replica of every shard must return identical data at one read version)
and REF:fdbserver/workloads/AtomicOps.actor.cpp (concurrent atomic adds
must sum exactly — lost updates or double-applies shift the total).
"""

from __future__ import annotations

import asyncio
import struct

from ..core.data import MutationType
from ..runtime.trace import TraceEvent
from .workload import TestWorkload, register_workload


@register_workload
class ConsistencyCheckWorkload(TestWorkload):
    """check(): for every shard, read the full range from EACH replica at
    one read version and require bit-identical results."""

    name = "ConsistencyCheck"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.shards_checked = 0
        self.rows_checked = 0

    async def check(self) -> bool:
        if self.ctx.client_id != 0:
            return True
        from ..runtime.errors import FdbError
        last: Exception | None = None
        for _ in range(5):
            try:
                return await self._check_once()
            except FdbError as e:
                # the view can be stale after live moves / engine
                # migration (same epoch, seq bump): the retired source
                # roles answer endpoint_not_found to the raw replica
                # reads — refresh the view and retry
                last = e
                refresh = getattr(self.db, "refresh", None)
                if refresh is not None:
                    await refresh()
                await asyncio.sleep(0.25)
        raise last  # type: ignore[misc]

    async def _check_once(self) -> bool:
        tr = self.db.create_transaction()
        while True:
            try:
                version = await tr.get_read_version()
                break
            except Exception as e:  # noqa: BLE001 — retryable path
                await tr.on_error(e)
        cluster = getattr(self.db, "view", None) or self.db.cluster
        shard_map = cluster.shard_map
        ok = True
        for rng, _tags in shard_map.ranges():
            group = cluster.storage_for_key(rng.begin)
            replicas = getattr(group, "replicas", [group])
            results = []
            for rep in replicas:
                rows = []
                b = rng.begin
                while True:
                    kvs, more = await rep.get_key_values(
                        b, rng.end, version, 1000)
                    rows.extend((bytes(k), bytes(v)) for k, v in kvs)
                    if not more or not kvs:
                        break
                    b = bytes(kvs[-1][0]) + b"\x00"
                results.append(rows)
            for other in results[1:]:
                if other != results[0]:
                    TraceEvent("ConsistencyCheckFailed", severity=40) \
                        .detail("Begin", rng.begin).log()
                    ok = False
            self.shards_checked += 1
            self.rows_checked += len(results[0]) if results else 0
        return ok

    def metrics(self):
        return {"shards_checked": self.shards_checked,
                "rows_checked": self.rows_checked}


@register_workload
class AtomicOpsWorkload(TestWorkload):
    """Concurrent little-endian ADDs to shared counters; check() sums the
    per-client intents against the stored totals."""

    name = "AtomicOps"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.counters = int(self.opt("counters", 4))
        self.adds = int(self.opt("addsPerClient", 20))
        self.added: dict[int, int] = {}

    def _key(self, i: int) -> bytes:
        return b"atomic/%02d" % i

    def _intent_key(self) -> bytes:
        return b"atomic-intent/%02d" % self.ctx.client_id

    async def start(self) -> None:
        total_by_counter = {i: 0 for i in range(self.counters)}
        for _ in range(self.adds):
            i = int(self.rng.random_int(0, self.counters))
            n = int(self.rng.random_int(1, 10))

            async def do(tr, i=i, n=n):
                # the intent ledger rides the SAME transaction as the add,
                # so a maybe-committed retry can't double-count intents
                tr.add(self._key(i), struct.pack("<q", n))
                tr.add(self._intent_key(), struct.pack("<q", n))
            await self.db.run(do)
            total_by_counter[i] += n
        self.added = total_by_counter

    async def check(self) -> bool:
        if self.ctx.client_id != 0:
            return True
        async def read(tr):
            stored = 0
            for i in range(self.counters):
                v = await tr.get(self._key(i))
                stored += struct.unpack("<q", v)[0] if v else 0
            intents = 0
            rows = await tr.get_range(b"atomic-intent/", b"atomic-intent0",
                                      limit=0)
            for _k, v in rows:
                intents += struct.unpack("<q", v)[0]
            return stored, intents
        stored, intents = await self.db.run(read)
        if stored != intents:
            TraceEvent("AtomicOpsMismatch", severity=40) \
                .detail("Stored", stored).detail("Intents", intents).log()
        return stored == intents

    def metrics(self):
        return {"adds": float(self.adds)}
