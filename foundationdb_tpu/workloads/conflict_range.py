"""ConflictRange workload — OCC verdict correctness under contention.

Reference: REF:fdbserver/workloads/ConflictRange.actor.cpp — hammer a
tiny keyspace with range reads + writes and prove the resolver's
verdicts are CORRECT, not merely convergent:

- **no false commits** (the serializability half): a transaction that
  committed with a strict range read must not have any OTHER committed
  write inside its read range between its read version and its commit
  version.  Every write also appends to a per-key versionstamped log
  subspace in the same transaction, so the exact global write history is
  reconstructible after quiescence and the check is exhaustive;
- **snapshot reads take no read conflicts**: snapshot-read transactions
  whose writes are disjoint by construction must never abort with
  not_committed.
"""

from __future__ import annotations

from ..core.data import MutationType
from ..runtime.errors import FdbError, NotCommitted
from .workload import TestWorkload, register_workload

KEYS = b"cr/"          # the contended keyspace: cr/00 .. cr/NN
LOG = b"crlog/"        # crlog/<key>/<versionstamp> -> commit marker


def _key(i: int) -> bytes:
    return KEYS + b"%02d" % i


@register_workload
class ConflictRangeWorkload(TestWorkload):
    name = "ConflictRange"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.n_keys = int(self.opt("nodeCount", 8))
        self.ops = int(self.opt("opsPerClient", 25))
        # pooled across clients (the options dict is shared per spec, and
        # only client 0 runs check): (read_version, commit_version,
        # begin_idx, end_idx) per strict-read commit
        self.shared = ctx.options.setdefault(
            "_pool", {"reads": [], "snapshot_aborts": 0})
        self.commits = 0
        self.conflicts = 0

    async def setup(self) -> None:
        if self.ctx.client_id != 0:
            return

        async def init(tr):
            for i in range(self.n_keys):
                tr.set(_key(i), b"0")
        await self.db.run(init)

    async def start(self) -> None:
        for op in range(self.ops):
            b = int(self.rng.random_int(0, self.n_keys))
            e = b + 1 + int(self.rng.random_int(0, self.n_keys - b))
            wk = int(self.rng.random_int(0, self.n_keys))
            snapshot_only = self.rng.coinflip(0.3)
            tr = self.db.create_transaction()
            while True:
                try:
                    rv = await tr.get_read_version()
                    await tr.get_range(_key(b), _key(e),
                                       snapshot=snapshot_only)
                    # the write: bump the key and append to its history
                    # log in the SAME transaction (versionstamped key =
                    # exact commit version, unique order suffix)
                    tr.set(_key(wk), b"%d-%d" % (self.ctx.client_id, op))
                    stamp_key = (LOG + _key(wk) + b"/"
                                 + b"\x00" * 10
                                 + len(LOG + _key(wk) + b"/").to_bytes(
                                     4, "little"))
                    tr.atomic_op(MutationType.SET_VERSIONSTAMPED_KEY,
                                 stamp_key, b"1")
                    if snapshot_only:
                        # disjoint write-conflict space per client makes
                        # a not_committed abort provably a FALSE read
                        # conflict — snapshot reads must not create any
                        tr.add_write_conflict_range(
                            b"wcr/%d" % self.ctx.client_id,
                            b"wcr/%d\x00" % self.ctx.client_id)
                    cv = await tr.commit()
                    self.commits += 1
                    if not snapshot_only:
                        self.shared["reads"].append((rv, cv, b, e))
                    break
                except NotCommitted as err:
                    if snapshot_only:
                        # a snapshot-only txn has no read conflict ranges
                        # at all (writes never abort their own txn), so
                        # ANY not_committed on it is a false conflict
                        self.shared["snapshot_aborts"] += 1
                    self.conflicts += 1
                    await tr.on_error(err)
                except FdbError as err:
                    await tr.on_error(err)

    async def check(self) -> bool:
        if self.ctx.client_id != 0:
            return True
        aborts = self.shared["snapshot_aborts"]
        assert aborts == 0, (
            f"{aborts} snapshot-read txns aborted with not_committed — "
            f"snapshot reads must take no read conflicts")
        # reconstruct the exact write history per key from the log
        history: dict[bytes, list[int]] = {}
        tr = self.db.create_transaction()
        while True:
            try:
                rows = await tr.get_range(LOG, LOG + b"\xff", limit=0,
                                          snapshot=True)
                break
            except FdbError as e:
                await tr.on_error(e)
        for k, _v in rows:
            body = bytes(k)[len(LOG):]
            # layout: <key> b"/" <10-byte versionstamp> — the stamp may
            # itself contain 0x2f, so split positionally, not by rsplit
            key, stamp = body[:-11], body[-10:]
            version = int.from_bytes(stamp[:8], "big")
            history.setdefault(key, []).append(version)
        for vs in history.values():
            vs.sort()
        # no false commits: no committed write to a strictly-read key in
        # (read_version, commit_version)
        import bisect
        for rv, cv, b, e in self.shared["reads"]:
            for i in range(b, e):
                vs = history.get(_key(i), [])
                lo = bisect.bisect_right(vs, rv)
                assert lo >= len(vs) or vs[lo] >= cv, (
                    f"FALSE COMMIT: read [{b},{e}) at rv={rv} committed "
                    f"at cv={cv}, but {_key(i)} was written at {vs[lo]}")
        return True

    def metrics(self):
        return {"commits": self.commits, "conflicts": self.conflicts}
