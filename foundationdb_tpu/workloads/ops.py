"""Operational workloads: backup/DR under chaos, live-move storms,
lock cycling, directory churn, region failover, engine migration.

Reference: REF:fdbserver/workloads/ — BackupCorrectness.actor.cpp,
BackupToDBCorrectness.actor.cpp (DR), RandomMoveKeys.actor.cpp,
LockDatabase*.actor.cpp, Directory test workloads — the operational
machinery must keep its own invariants while attrition/clogging
workloads supply the chaos in the same run.
"""

from __future__ import annotations

import asyncio

from ..runtime.trace import TraceEvent
from .workload import TestWorkload, register_workload


@register_workload
class BackupUnderAttritionWorkload(TestWorkload):
    """Continuous mutation-log backup running through the whole chaotic
    run.  Check: the stream stayed live (pulled past the final commit)
    and a snapshot backup taken at quiescence reads back byte-identical
    to the database (REF:fdbserver/workloads/BackupCorrectness)."""

    name = "BackupUnderAttrition"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.agent = None
        self.snapshots = 0

    async def setup(self) -> None:
        if self.ctx.client_id != 0:
            return
        from ..backup.agent import BackupAgent
        from ..runtime.files import SimFileSystem
        self.agent = BackupAgent(self.db, SimFileSystem(),
                                 "backup-chaos", rows_per_file=50)
        await self.agent.start_continuous()

    async def start(self) -> None:
        if self.agent is None:
            return
        # periodic snapshot backups while the cluster is under fire;
        # transient failures retry next round (the agent's transactions
        # already follow recoveries)
        for _ in range(int(self.opt("snapshots", 3))):
            await asyncio.sleep(float(self.opt("secondsBetween", 3.0)))
            try:
                await self.agent.backup()
                self.snapshots += 1
            except Exception as e:  # noqa: BLE001 — chaos mid-backup
                TraceEvent("BackupChaosSnapshotFailed", severity=30) \
                    .detail("Error", repr(e)[:120]).log()

    async def check(self) -> bool:
        if self.agent is None:
            return True
        from ..core.data import SYSTEM_PREFIX
        await self.agent.stop_continuous()
        manifest = await self.agent.backup()     # final quiescent snapshot
        rows = []
        for name in manifest.range_files:
            _v, page = await self.agent.container.read_snapshot_page(name)
            rows.extend((bytes(k), bytes(v)) for k, v in page)
        tr = self.db.create_transaction()
        while True:
            try:
                live = await tr.get_range(b"", SYSTEM_PREFIX, limit=0)
                break
            except Exception as e:  # noqa: BLE001
                await tr.on_error(e)
        live = [(bytes(k), bytes(v)) for k, v in live]
        assert rows == live, \
            f"backup diverged: {len(rows)} backup rows vs {len(live)} live"
        return True

    def metrics(self):
        return {"snapshots": self.snapshots}


@register_workload
class DRUnderAttritionWorkload(TestWorkload):
    """Cluster-to-cluster DR running through the chaos: the destination
    (a lightweight in-process cluster) must converge to a byte-identical
    copy once the source quiesces
    (REF:fdbserver/workloads/BackupToDBCorrectness)."""

    name = "DRUnderAttrition"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.dr = None
        self._dest_cluster = None

    async def setup(self) -> None:
        if self.ctx.client_id != 0:
            return
        from ..backup.dr import DRAgent
        from ..client.database import Database
        from ..core.cluster import Cluster, ClusterConfig
        from ..runtime.knobs import Knobs
        self._dest_cluster = Cluster(ClusterConfig(), Knobs())
        await self._dest_cluster.__aenter__()
        dest = Database(self._dest_cluster)
        self.dr = DRAgent(self.db, dest)
        await self.dr.start()

    async def check(self) -> bool:
        if self.dr is None:
            return True
        from ..core.data import SYSTEM_PREFIX
        await self.dr.drain()
        src_tr = self.db.create_transaction()
        while True:
            try:
                src_rows = await src_tr.get_range(b"", SYSTEM_PREFIX,
                                                  limit=0)
                break
            except Exception as e:  # noqa: BLE001
                await src_tr.on_error(e)
        dest_tr = self.dr.dest.create_transaction()
        dest_tr.lock_aware = True
        dest_rows = await dest_tr.get_range(b"", SYSTEM_PREFIX, limit=0)
        a = [(bytes(k), bytes(v)) for k, v in src_rows]
        b = [(bytes(k), bytes(v)) for k, v in dest_rows]
        assert a == b, f"DR diverged: {len(a)} src rows vs {len(b)} dest"
        await self.dr.stop()
        await self._dest_cluster.__aexit__(None, None, None)
        return True


@register_workload
class LiveMoveStormWorkload(TestWorkload):
    """Force a storm of live shard splits (fat writes across widening
    prefixes with DD's split threshold low) — every split must happen
    LIVE (epoch unchanged unless other chaos recovers) and the other
    workloads' invariants must hold (REF:RandomMoveKeys intent)."""

    name = "LiveMoveStorm"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.sim = self.opt("sim", None)
        self.rows = int(self.opt("rows", 150))
        self.value_bytes = int(self.opt("valueBytes", 60))
        self.splits_seen = 0

    async def start(self) -> None:
        cid = self.ctx.client_id
        for i in range(self.rows):
            key = b"storm%02d%05d" % (cid, i)

            async def do(tr, key=key):
                tr.set(key, b"v" * self.value_bytes)
            await self.db.run(do)
            if i % 10 == 0:
                await asyncio.sleep(0.05)

    async def check(self) -> bool:
        if self.ctx.client_id != 0 or self.sim is None:
            return True
        state = await self.sim.wait_state(
            lambda s: len(s["shard_teams"]) > 2)
        self.splits_seen = len(state["shard_teams"]) - 2
        tr = self.db.create_transaction()
        while True:
            try:
                rows = await tr.get_range(b"storm", b"stoso", limit=0)
                break
            except Exception as e:  # noqa: BLE001
                await tr.on_error(e)
        expect = self.rows * self.ctx.client_count
        assert len(rows) == expect, \
            f"rows lost across the move storm: {len(rows)}/{expect}"
        return True

    def metrics(self):
        return {"splits": self.splits_seen}


@register_workload
class LockCyclingWorkload(TestWorkload):
    """Cycle the database lock: while locked, plain commits must be
    refused and lock-aware ones admitted; after unlock everything flows
    (REF:fdbserver/workloads/LockDatabase.actor.cpp).  Run it with
    lock-tolerant company only — plain-writer workloads in the same spec
    would see database_locked by design."""

    name = "LockCycling"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.rounds = int(self.opt("rounds", 3))
        self.cycles = 0

    async def start(self) -> None:
        if self.ctx.client_id != 0:
            return
        from ..core.management import lock_database, unlock_database
        from ..runtime.errors import DatabaseLocked
        uid = b"lock-cycling"
        for i in range(self.rounds):
            await lock_database(self.db, uid)
            # plain commit refused
            tr = self.db.create_transaction()
            tr.set(b"lockprobe", b"%d" % i)
            try:
                await tr.commit()
                raise AssertionError("commit admitted under lock")
            except DatabaseLocked:
                pass
            # lock-aware commit admitted
            tr = self.db.create_transaction()
            tr.lock_aware = True
            tr.set(b"lockaware", b"%d" % i)
            await tr.commit()
            await unlock_database(self.db, uid)
            # unlocked: plain commit flows again
            async def do(tr, i=i):
                tr.set(b"lockprobe", b"%d" % i)
            await self.db.run(do)
            self.cycles += 1
            await asyncio.sleep(float(self.opt("secondsBetween", 0.5)))

    async def check(self) -> bool:
        if self.ctx.client_id != 0:
            return True
        v = await self.db.get(b"lockaware")
        assert v == b"%d" % (self.rounds - 1)
        return True

    def metrics(self):
        return {"lock_cycles": self.cycles}


@register_workload
class DirectoryOpsWorkload(TestWorkload):
    """Directory-layer churn against a model: create/open/move/remove
    random paths; check the layer's listing matches the model exactly
    (REF:bindings directory tests as a server-side workload)."""

    name = "DirectoryOps"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.ops = int(self.opt("ops", 25))
        self.done = 0

    async def start(self) -> None:
        from ..client.directory import DirectoryLayer
        cid = self.ctx.client_id
        dl = DirectoryLayer()
        root = ("dirops", "c%d" % cid)
        self.model: set[tuple] = set()
        for i in range(self.ops):
            op = self.rng.random_int(0, 3)
            name = "d%d" % self.rng.random_int(0, 6)
            path = root + (name,)

            async def do(tr, op=op, path=path, dl=dl):
                if op == 0:
                    await dl.create_or_open(tr, path)
                    return "add"
                if op == 1 and await dl.exists(tr, path):
                    await dl.remove(tr, path)
                    return "del"
                if op == 2 and await dl.exists(tr, path):
                    dst = path[:-1] + (path[-1] + "m",)
                    if not await dl.exists(tr, dst):
                        await dl.move(tr, path, dst)
                        return "mv"
                return None
            from ..runtime.errors import DatabaseLocked
            while True:
                try:
                    res = await self.db.run(do)
                    break
                except DatabaseLocked:
                    # an operator lock cycle (LockCycling) is in force:
                    # back off like a real app and retry after unlock
                    await asyncio.sleep(0.3)
            if res == "add":
                self.model.add(path)
            elif res == "del":
                self.model.discard(path)
            elif res == "mv":
                self.model.discard(path)
                self.model.add(path[:-1] + (path[-1] + "m",))
            self.done += 1

    async def check(self) -> bool:
        from ..client.directory import DirectoryLayer
        dl = DirectoryLayer()
        root = ("dirops", "c%d" % self.ctx.client_id)

        async def ls(tr):
            if not await dl.exists(tr, root):
                return []
            return await dl.list(tr, root)
        names = sorted(await self.db.run(ls))
        want = sorted(p[-1] for p in self.model)
        assert names == want, f"directory mismatch: {names} != {want}"
        return True

    def metrics(self):
        return {"dir_ops": self.done}


@register_workload
class RegionFailoverWorkload(TestWorkload):
    """Kill the whole primary region mid-run, verify failover to the
    secondary, reboot the region, verify failback — while the other
    workloads in the spec keep their invariants
    (REF:fdbserver/TagPartitionedLogSystem region failover paths)."""

    name = "RegionFailover"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.sim = self.opt("sim", None)
        self.dc = str(self.opt("primaryDc", "dc1"))
        self.rounds_done = 0

    async def start(self) -> None:
        if self.ctx.client_id != 0 or self.sim is None:
            return
        await asyncio.sleep(float(self.opt("secondsBefore", 3.0)))
        state0 = await self.sim.wait_state(
            lambda s: s.get("primary_dc") == self.dc)
        victims = await self.sim.kill_dc(self.dc)
        state1 = await self.sim.wait_state(
            lambda s: s["epoch"] > state0["epoch"]
            and s.get("primary_dc") not in (None, self.dc))
        TraceEvent("RegionFailoverWorkload").detail("To",
                                                    state1["primary_dc"]) \
            .log()
        await asyncio.sleep(float(self.opt("secondsFailedOver", 2.0)))
        for m in victims:
            await m.reboot()
        await self.sim.wait_state(
            lambda s: s["epoch"] > state1["epoch"]
            and s.get("primary_dc") == self.dc)
        self.rounds_done = 1

    async def check(self) -> bool:
        return self.ctx.client_id != 0 or self.sim is None \
            or self.rounds_done == 1

    def metrics(self):
        return {"failover_rounds": self.rounds_done}


@register_workload
class EngineMigrationWorkload(TestWorkload):
    """`configure storage_engine=` mid-run: every shard must live-move
    onto the new engine while the other workloads keep committing
    (REF:fdbclient/ManagementAPI changeStorageType)."""

    name = "EngineMigration"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.sim = self.opt("sim", None)
        self.engine = str(self.opt("engine", "btree"))
        self.migrated = 0

    async def start(self) -> None:
        if self.ctx.client_id != 0 or self.sim is None:
            return
        from ..core.management import configure
        await asyncio.sleep(float(self.opt("secondsBefore", 2.0)))
        await configure(self.db, storage_engine=self.engine)
        state = await self.sim.wait_state(
            lambda s: s["storage"]
            and all(e.get("engine") == self.engine for e in s["storage"]))
        self.migrated = len(state["storage"])

    async def check(self) -> bool:
        return self.ctx.client_id != 0 or self.sim is None \
            or self.migrated > 0

    def metrics(self):
        return {"migrated_replicas": self.migrated}
