"""Fault-injection workloads: machine attrition and random clogging.

Reference: REF:fdbserver/workloads/MachineAttrition.actor.cpp and
RandomClogging.actor.cpp — run CONCURRENTLY with invariant workloads
(Cycle, Serializability): they supply the chaos, the others prove the
database survived it.  Both need the SimulatedCluster handle, passed via
the ``sim`` option.
"""

from __future__ import annotations

import asyncio

from ..runtime.trace import TraceEvent
from .workload import TestWorkload, register_workload


@register_workload
class MachineAttritionWorkload(TestWorkload):
    """Kill + reboot machines while others do real work.

    Only txn-role machines are eligible (storage re-replication needs
    DataDistribution; the reference's protectedAddresses plays the same
    role for coordinators)."""

    name = "MachineAttrition"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.sim = self.opt("sim", None)
        self.kills = int(self.opt("machinesToKill", 2))
        self.between = float(self.opt("secondsBetweenKills", 3.0))
        self.reboot_after = float(self.opt("rebootAfter", 1.5))
        self.killed = 0

    async def start(self) -> None:
        if self.ctx.client_id != 0 or self.sim is None:
            return
        for i in range(self.kills):
            await asyncio.sleep(self.between)
            # re-derive victims from the CURRENT epoch's placement: after a
            # round, the rebooted machine usually hosts nothing until the
            # next recovery recruits on it
            victims = [m for m in await self.sim.txn_only_machines()
                       if m.alive]
            if not victims:
                continue
            m = victims[int(self.rng.random_int(0, len(victims)))]
            epoch_before = (await self.sim.wait_epoch(1))["epoch"]
            await m.kill()
            self.killed += 1
            # the cluster must publish a NEW epoch (recovery ran)
            await self.sim.wait_epoch(epoch_before + 1)
            await asyncio.sleep(self.reboot_after)
            await m.reboot()
            TraceEvent("AttritionRound").detail("Machine", m.ip) \
                .detail("Epoch", epoch_before + 1).log()

    def metrics(self):
        return {"machines_killed": self.killed}


@register_workload
class SwizzleWorkload(TestWorkload):
    """The simulator's swizzle: kill a random SUBSET of txn-role
    machines near-simultaneously, then reboot them in a DIFFERENT
    shuffled order (REF:fdbrpc/sim2.actor.cpp swizzle /
    RebootProcessAndSwitch) — the worst-case correlated failure the
    single-kill attrition workload never produces."""

    name = "Swizzle"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.sim = self.opt("sim", None)
        self.rounds = int(self.opt("rounds", 1))
        self.delay = float(self.opt("secondsBefore", 3.0))
        self.swizzled = 0

    async def start(self) -> None:
        if self.ctx.client_id != 0 or self.sim is None:
            return
        for _ in range(self.rounds):
            await asyncio.sleep(self.delay)
            victims = [m for m in await self.sim.txn_only_machines()
                       if m.alive]
            if len(victims) < 2:
                continue
            # a random subset of >= 2, killed in one burst
            k = 2 + int(self.rng.random_int(0, len(victims) - 1))
            picks = list(victims)
            self.rng.shuffle(picks)
            subset = picks[:k]
            epoch_before = (await self.sim.wait_epoch(1))["epoch"]
            for m in subset:
                await m.kill()
                await asyncio.sleep(self.rng.random() * 0.05)
            # reboot in a DIFFERENT shuffled order
            order = list(subset)
            self.rng.shuffle(order)
            await asyncio.sleep(0.5)
            for m in order:
                await m.reboot()
                await asyncio.sleep(self.rng.random() * 0.1)
            # the cluster must recover to a NEW epoch with everyone back
            await self.sim.wait_epoch(epoch_before + 1)
            self.swizzled += len(subset)
            TraceEvent("SwizzleRound").detail("Killed", len(subset)) \
                .detail("Epoch", epoch_before + 1).log()

    def metrics(self):
        return {"machines_swizzled": self.swizzled}


@register_workload
class RandomCloggingWorkload(TestWorkload):
    """Randomly clog and partition (then heal) network links."""

    name = "RandomClogging"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.sim = self.opt("sim", None)
        self.duration = float(self.opt("testDuration", 10.0))
        self.clogs = 0

    async def start(self) -> None:
        if self.ctx.client_id != 0 or self.sim is None:
            return
        loop = asyncio.get_running_loop()
        end = loop.time() + self.duration
        machines = self.sim.machines
        while loop.time() < end:
            await asyncio.sleep(0.5 + self.rng.random() * 1.0)
            a = machines[int(self.rng.random_int(0, len(machines)))]
            b = machines[int(self.rng.random_int(0, len(machines)))]
            if a is b:
                continue
            if self.rng.coinflip(0.7):
                self.sim.net.clog_pair(a.addr, b.addr,
                                       0.2 + self.rng.random() * 1.0)
            else:
                self.sim.net.partition(a.addr, b.addr)
                await asyncio.sleep(0.3 + self.rng.random() * 0.7)
                self.sim.net.heal(a.addr, b.addr)
            self.clogs += 1

    def metrics(self):
        return {"clogs": self.clogs}
