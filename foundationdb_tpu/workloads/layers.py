"""Layer-ecosystem drivers (ISSUE 19): the zipf read tier + index churn.

Two workloads exercise the layers package under the sim's fault mix:

- ``LayerReadTier`` — the millions-of-users shape: zipf-skewed point
  reads through a :class:`~..layers.cache.ReadThroughCache`, with a
  configurable writer fraction committing invalidating updates.  The
  check phase asserts the cache never went stale past the feed frontier
  (every workload-observed value is re-verified against a pinned read).
- ``LayerIndexChurn`` — sustained primary churn (sets, overwrites,
  deletes, occasional ``clear_range``) under a maintained
  :class:`~..layers.index.SecondaryIndex`; the layer consistency
  checker (driven by the test, not this workload) owns the verdict.

Layer objects are passed through workload ``options`` (they are live
client-side objects, not names) so a test builds the layer stack once
and lets several workload clients drive it concurrently.
"""

from __future__ import annotations

from .workload import TestWorkload, register_workload


def zipf_cdf(n: int, s: float) -> list[float]:
    """Cumulative zipf(s) distribution over ranks 1..n."""
    weights = [1.0 / (i ** s) for i in range(1, n + 1)]
    total = sum(weights)
    cdf, acc = [], 0.0
    for w in weights:
        acc += w / total
        cdf.append(acc)
    return cdf


def zipf_pick(cdf: list[float], u: float) -> int:
    """Rank (0-based) for uniform draw ``u`` via binary search."""
    lo, hi = 0, len(cdf) - 1
    while lo < hi:
        mid = (lo + hi) // 2
        if cdf[mid] < u:
            lo = mid + 1
        else:
            hi = mid
    return lo


@register_workload
class LayerReadTierWorkload(TestWorkload):
    name = "LayerReadTier"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.cache = self.opt("cache", None)
        self.n_keys = int(self.opt("nodeCount", 500))
        self.ops = int(self.opt("opsPerClient", 200))
        self.write_fraction = float(self.opt("writeFraction", 0.1))
        self.zipf_s = float(self.opt("zipfS", 0.99))
        self.prefix = bytes(self.opt("prefix", b"tier/"))
        self._cdf = zipf_cdf(self.n_keys, self.zipf_s)
        self.reads = 0
        self.writes = 0
        self.stale_reads = 0

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%08d" % i

    async def setup(self) -> None:
        BATCH = 250
        for start in range(0, self.n_keys, BATCH):
            async def fill(tr, start=start):
                for i in range(start, min(start + BATCH, self.n_keys)):
                    tr.set(self._key(i), b"v0-%08d" % i)
            await self.db.run(fill)

    async def start(self) -> None:
        assert self.cache is not None, "pass the ReadThroughCache in options"
        gen = 0
        for _ in range(self.ops):
            i = zipf_pick(self._cdf, self.rng.random())
            key = self._key(i)
            if self.rng.coinflip(self.write_fraction):
                gen += 1
                value = b"v%d-c%d-%08d" % (gen, self.ctx.client_id, i)

                async def body(tr, key=key, value=value):
                    tr.set(key, value)
                await self.db.run(body)
                self.writes += 1
            else:
                value, valid_through = await self.cache.get_versioned(key)
                self.reads += 1
                # the staleness proof, inline while the claimed version
                # is still inside the MVCC window: the cache says the
                # value is valid through ``valid_through``, so the
                # authoritative read pinned there must byte-match
                tr = self.db.create_transaction()
                try:
                    tr.set_read_version(valid_through)
                    truth = await tr.get(key, snapshot=True)
                    if truth != value:
                        self.stale_reads += 1
                except Exception:  # noqa: BLE001 — aged out mid-probe:
                    pass           # unverifiable, not stale
                finally:
                    tr.reset()

    async def check(self) -> bool:
        return self.stale_reads == 0

    def metrics(self):
        return {"reads": self.reads, "writes": self.writes,
                "stale_reads": self.stale_reads,
                "hit_rate": self.cache.hit_rate if self.cache else 0.0}


@register_workload
class LayerIndexChurnWorkload(TestWorkload):
    name = "LayerIndexChurn"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.index = self.opt("index", None)
        self.n_keys = int(self.opt("nodeCount", 300))
        self.ops = int(self.opt("opsPerClient", 100))
        self.clear_fraction = float(self.opt("clearFraction", 0.05))
        self.delete_fraction = float(self.opt("deleteFraction", 0.15))
        self.prefix = bytes(self.opt("prefix", b"churn/"))
        self.committed = 0

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%08d" % i

    def _value(self, i: int) -> bytes:
        # a small value population so index entries collide across keys
        # (the interesting shape for (ival, pkey) row maintenance)
        return b"bucket-%02d" % (i % 17)

    async def setup(self) -> None:
        async def fill(tr):
            for i in range(0, self.n_keys, 3):
                tr.set(self._key(i), self._value(i))
        await self._run(fill)

    async def _run(self, fn) -> None:
        if self.index is not None and self.index.mode == "transactional":
            await self.index.run(fn)
        else:
            await self.db.run(fn)
        self.committed += 1

    async def start(self) -> None:
        for n in range(self.ops):
            i = self.rng.random_int(0, self.n_keys - 1)
            if self.rng.coinflip(self.clear_fraction):
                b = self._key(i)
                e = self._key(min(self.n_keys, i + 8))

                async def body(tr, b=b, e=e):
                    tr.clear_range(b, e)
                await self._run(body)
            elif self.rng.coinflip(self.delete_fraction):
                async def body(tr, key=self._key(i)):
                    tr.clear(key)
                await self._run(body)
            else:
                v = self._value(self.rng.random_int(0, 10_000))

                async def body(tr, key=self._key(i), v=v):
                    tr.set(key, v)
                await self._run(body)

    async def check(self) -> bool:
        return True      # the LayerConsistencyChecker owns the verdict

    def metrics(self):
        return {"committed": self.committed}
