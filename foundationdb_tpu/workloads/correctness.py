"""API-correctness workloads: randomized ops vs. an exact model,
causal-consistency sideband checking, and invariant-sum bank transfers.

Reference: REF:fdbserver/workloads/ApiCorrectness.actor.cpp (random API
calls shadowed by an in-memory model store), Sideband.actor.cpp
(external-consistency: a commit announced out-of-band must be visible
to any later read version), and the DDBalance/bank-style invariant
workloads — the sum over a family of keys is conserved by every
transaction, so any snapshot that reads a different total caught a
non-serializable read.
"""

from __future__ import annotations

import asyncio
import bisect

from ..core.data import KeySelector, MutationType, apply_atomic
from ..runtime.errors import FdbError
from .workload import TestWorkload, register_workload


@register_workload
class ApiCorrectnessWorkload(TestWorkload):
    """Random set/clear/clear_range/atomics/get/get_range/get_key against
    a per-client key region, shadowed by an exact in-memory model.  Every
    read inside a transaction must match the model's merged (RYW) view;
    after quiescence the database region must equal the model exactly.
    Unknown commit results are settled with a per-transaction sentinel
    key, the reference workload's trick for keeping the model exact
    through commit_unknown_result."""

    name = "ApiCorrectness"

    MUTATIONS = ("set", "clear", "clear_range", "add", "byte_min",
                 "byte_max", "compare_and_clear")
    READS = ("get", "get_range", "get_key")

    def __init__(self, ctx):
        super().__init__(ctx)
        self.prefix = b"api/%02d/" % ctx.client_id
        self.keyspace = int(self.opt("keyCount", 32))
        self.txns = int(self.opt("transactionsPerClient", 25))
        self.ops_per_txn = int(self.opt("opsPerTransaction", 8))
        self.model: dict[bytes, bytes] = {}
        self.committed = 0
        self.reads_checked = 0

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%04d" % i

    def _rand_key(self) -> bytes:
        return self._key(self.rng.random_int(0, self.keyspace))

    def _rand_val(self) -> bytes:
        return b"v%016x" % self.rng.next_u64()

    def _gen_ops(self) -> list[tuple]:
        ops = []
        for _ in range(self.ops_per_txn):
            if self.rng.random() < 0.55:
                kind = self.MUTATIONS[self.rng.random_int(
                    0, len(self.MUTATIONS))]
            else:
                kind = self.READS[self.rng.random_int(0, len(self.READS))]
            if kind == "clear_range":
                a, b = sorted((self._rand_key(), self._rand_key()))
                ops.append((kind, a, b))
            elif kind == "get_range":
                a, b = sorted((self._rand_key(), self._rand_key()))
                ops.append((kind, a, b, self.rng.random_int(0, 10)))
            elif kind == "get_key":
                ops.append((kind, self._rand_key(),
                            self.rng.random() < 0.5,
                            self.rng.random_int(-3, 4)))
            elif kind in ("add", "byte_min", "byte_max",
                          "compare_and_clear"):
                ops.append((kind, self._rand_key(),
                            self.rng.next_u64().to_bytes(8, "little")))
            elif kind == "set":
                ops.append((kind, self._rand_key(), self._rand_val()))
            else:   # clear
                ops.append((kind, self._rand_key()))
        return ops

    async def _apply(self, tr, shadow: dict[bytes, bytes],
                     op: tuple) -> None:
        kind = op[0]
        if kind in self.MUTATIONS:
            self._mutate_model(shadow, op)
        if kind == "set":
            _, k, v = op
            tr.set(k, v)
        elif kind == "clear":
            _, k = op
            tr.clear(k)
        elif kind == "clear_range":
            _, a, b = op
            tr.clear_range(a, b)
        elif kind in ("add", "byte_min", "byte_max", "compare_and_clear"):
            _, k, operand = op
            tr.atomic_op(self._MT[kind], k, operand)
        elif kind == "get":
            _, k = op
            got = await tr.get(k)
            assert got == shadow.get(k), \
                f"get({k!r}) = {got!r}, model {shadow.get(k)!r}"
            self.reads_checked += 1
        elif kind == "get_range":
            _, a, b, limit = op
            got = [(bytes(k), bytes(v))
                   for k, v in await tr.get_range(a, b, limit=limit)]
            want = sorted((k, v) for k, v in shadow.items() if a <= k < b)
            if limit:
                want = want[:limit]
            assert got == want, \
                f"get_range({a!r},{b!r},{limit}) diverged from model"
            self.reads_checked += 1
        else:   # get_key
            _, anchor, or_equal, offset = op
            got = await tr.get_key(KeySelector(anchor, or_equal, offset))
            want = self._model_selector(shadow, anchor, or_equal, offset)
            if want is not None:
                assert got == want, (
                    f"get_key({anchor!r},{or_equal},{offset}) = {got!r}, "
                    f"model {want!r}")
                self.reads_checked += 1

    _MT = {"add": MutationType.ADD,
           "byte_min": MutationType.BYTE_MIN,
           "byte_max": MutationType.BYTE_MAX,
           "compare_and_clear": MutationType.COMPARE_AND_CLEAR}

    def _mutate_model(self, shadow: dict[bytes, bytes], op: tuple) -> None:
        """Apply a mutation op to the model only — also used to REPLAY a
        landed-but-unknown transaction's ops into the adopted shadow
        (the database applied them; a model that skips them diverges
        forever)."""
        kind = op[0]
        if kind == "set":
            _, k, v = op
            shadow[k] = v
        elif kind == "clear":
            _, k = op
            shadow.pop(k, None)
        elif kind == "clear_range":
            _, a, b = op
            for k in [k for k in shadow if a <= k < b]:
                del shadow[k]
        elif kind in self._MT:
            _, k, operand = op
            new = apply_atomic(self._MT[kind], shadow.get(k), operand)
            if new is None:
                shadow.pop(k, None)
            else:
                shadow[k] = new

    def _model_selector(self, shadow: dict[bytes, bytes], anchor: bytes,
                        or_equal: bool, offset: int) -> bytes | None:
        """Resolve the selector against the model, or None when the
        resolution steps outside this client's region (foreign keys
        would then decide the answer — unverifiable from here).  Mirrors
        Transaction.get_key's forward/backward split exactly."""
        from ..core.data import key_after
        keys = sorted(shadow)
        if offset > 0:
            start = key_after(anchor) if or_equal else anchor
            cands = keys[bisect.bisect_left(keys, start):]
            if len(cands) < offset:
                return None                      # runs past our region
            return cands[offset - 1]
        stop = key_after(anchor) if or_equal else anchor
        cands = keys[:bisect.bisect_left(keys, stop)]
        n = 1 - offset
        if len(cands) < n:
            return None                          # runs before our region
        return cands[-n]

    async def start(self) -> None:
        sentinel = self.prefix + b"~txn"         # sorts after data keys
        try:
            await self._run_txns(sentinel)
        finally:
            # only client 0's check() runs (tester convention), so every
            # client publishes its final model through the shared options
            self.ctx.options.setdefault("_api_models", {})[
                self.ctx.client_id] = (self.prefix, self.model)

    async def _run_txns(self, sentinel: bytes) -> None:
        for txn_id in range(self.txns):
            ops = self._gen_ops()
            marker = b"%d" % txn_id
            tr = self.db.create_transaction()
            while True:
                shadow = dict(self.model)
                shadow[sentinel] = marker
                try:
                    # settle INSIDE the transaction: reading the sentinel
                    # both detects an earlier unknown-result attempt that
                    # landed AND serializes against one still in flight —
                    # if that attempt commits after this read, this retry
                    # conflicts at the resolver instead of double-applying
                    # the non-idempotent atomics (the reference
                    # ApiCorrectness trick; a bare db.get() settle races
                    # the proxy's repair path)
                    if await tr.get(sentinel) == marker:
                        # the earlier attempt landed: the database holds
                        # its mutations, so the adopted shadow must too
                        for op in ops:
                            self._mutate_model(shadow, op)
                        self.model = shadow
                        self.committed += 1
                        break
                    tr.set(sentinel, marker)
                    for op in ops:
                        await self._apply(tr, shadow, op)
                    await tr.commit()
                    self.model = shadow
                    self.committed += 1
                    break
                except FdbError as e:
                    if e.maybe_committed:
                        tr = self.db.create_transaction()
                        continue
                    await tr.on_error(e)   # re-raises if not retryable

    async def check(self) -> bool:
        # every client's region must equal its final model (published by
        # each client at the end of start())
        if self.ctx.client_id != 0:
            return True
        models = self.ctx.options.setdefault("_api_models", {})
        assert len(models) == self.ctx.client_count, \
            f"only {len(models)}/{self.ctx.client_count} models published"
        for cid in range(self.ctx.client_count):
            prefix, model = models.get(cid, (None, None))
            if prefix is None:
                continue
            tr = self.db.create_transaction()
            while True:
                try:
                    rows = await tr.get_range(prefix, prefix + b"\xff",
                                              limit=0)
                    break
                except FdbError as e:
                    await tr.on_error(e)
            got = {bytes(k): bytes(v) for k, v in rows}
            assert got == model, (
                f"client {cid}: db has {len(got)} rows vs model "
                f"{len(model)} — divergent keys "
                f"{sorted(set(got) ^ set(model))[:5]}")
        return True

    def metrics(self):
        return {"committed": self.committed,
                "reads_checked": self.reads_checked}


@register_workload
class SidebandWorkload(TestWorkload):
    """External consistency: client 1 commits a key, then announces it
    over a side channel that bypasses the database.  Client 0, upon
    hearing the announcement, takes a FRESH read version — which must be
    >= the announced commit version and must see the key.  Any GRV that
    could run behind an already-acknowledged commit breaks strict
    serializability (REF:fdbserver/workloads/Sideband.actor.cpp)."""

    name = "Sideband"
    PREFIX = b"sideband/"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.n = int(self.opt("messages", 20))
        self.checked = 0

    def _q(self) -> asyncio.Queue:
        q = self.ctx.options.get("_sideband_q")
        if q is None:
            q = self.ctx.options["_sideband_q"] = asyncio.Queue()
        return q

    async def start(self) -> None:
        if self.ctx.client_count < 2:
            return          # needs a producer and a checker
        q = self._q()
        if self.ctx.client_id == 1:
            for i in range(self.n):
                key, val = self.PREFIX + b"%06d" % i, b"m%d" % i
                committed_version = None
                while committed_version is None:
                    tr = self.db.create_transaction()
                    unknown = False
                    while True:
                        try:
                            tr.set(key, val)
                            await tr.commit()
                            committed_version = tr.get_committed_version()
                            break
                        except FdbError as e:
                            if e.maybe_committed:
                                unknown = True
                                break
                            await tr.on_error(e)
                    if unknown:
                        # settle before announcing: an announcement for a
                        # commit that never landed is a false alarm, not
                        # an external-consistency violation
                        if await self.db.get(key) == val:
                            committed_version = 0   # landed, version unknown
                await q.put((i, committed_version))
            await q.put(None)
        elif self.ctx.client_id == 0:
            while True:
                msg = await q.get()
                if msg is None:
                    return
                i, commit_version = msg
                tr = self.db.create_transaction()
                while True:
                    try:
                        rv = await tr.get_read_version()
                        got = await tr.get(self.PREFIX + b"%06d" % i)
                        break
                    except FdbError as e:
                        await tr.on_error(e)
                assert rv >= commit_version, (
                    f"GRV {rv} ran behind announced commit "
                    f"{commit_version}")
                assert got == b"m%d" % i, (
                    f"announced key {i} invisible at version {rv}")
                self.checked += 1

    def metrics(self):
        return {"causally_checked": self.checked}


@register_workload
class BankTransferWorkload(TestWorkload):
    """Contended read-modify-write transfers over a shared account pool:
    every transaction conserves the total, so a whole-pool scan inside
    one transaction must always read the exact initial sum, and no
    account may go negative.  High inter-client contention makes this a
    resolver workout; the mid-run scans make it a snapshot-isolation
    detector."""

    name = "BankTransfer"
    PREFIX = b"bank/"
    INITIAL = 100

    def __init__(self, ctx):
        super().__init__(ctx)
        self.accounts = int(self.opt("accounts", 12))
        self.txns = int(self.opt("transfersPerClient", 20))
        self.scan_every = int(self.opt("scanEvery", 5))
        self.transfers = 0
        self.scans = 0
        self.retries = 0

    def _key(self, i: int) -> bytes:
        return self.PREFIX + b"%04d" % i

    async def setup(self) -> None:
        if self.ctx.client_id != 0:
            return

        async def fill(tr):
            for i in range(self.accounts):
                tr.set(self._key(i), b"%d" % self.INITIAL)
        await self.db.run(fill)

    async def _scan_total(self) -> None:
        """Chunked whole-pool read inside ONE transaction (single read
        version): the sum must be exact."""
        tr = self.db.create_transaction()
        while True:
            try:
                total, count = 0, 0
                cursor = self.PREFIX
                while True:
                    rows = await tr.get_range(cursor, self.PREFIX + b"\xff",
                                              limit=5)
                    if not rows:
                        break
                    for k, v in rows:
                        total += int(v)
                        count += 1
                    cursor = bytes(rows[-1][0]) + b"\x00"
                break
            except FdbError as e:
                await tr.on_error(e)
        assert count == self.accounts, \
            f"scan saw {count} accounts, expected {self.accounts}"
        assert total == self.accounts * self.INITIAL, (
            f"sum {total} != conserved {self.accounts * self.INITIAL} — "
            f"non-serializable snapshot")
        self.scans += 1

    async def start(self) -> None:
        for t in range(self.txns):
            a = self.rng.random_int(0, self.accounts)
            b = self.rng.random_int(0, self.accounts)
            if a == b:
                b = (b + 1) % self.accounts
            amount = self.rng.random_int(1, 20)
            tr = self.db.create_transaction()
            while True:
                try:
                    va = int(await tr.get(self._key(a)))
                    vb = int(await tr.get(self._key(b)))
                    moved = min(amount, va)    # never go negative
                    tr.set(self._key(a), b"%d" % (va - moved))
                    tr.set(self._key(b), b"%d" % (vb + moved))
                    await tr.commit()
                    break
                except FdbError as e:
                    self.retries += 1
                    await tr.on_error(e)
            self.transfers += 1
            if (t + 1) % self.scan_every == 0:
                await self._scan_total()

    async def check(self) -> bool:
        if self.ctx.client_id != 0:
            return True
        await self._scan_total()
        rows = await self.db.get_range(self.PREFIX, self.PREFIX + b"\xff")
        assert all(int(v) >= 0 for _, v in rows), "negative balance"
        return True

    def metrics(self):
        return {"transfers": self.transfers, "scans": self.scans,
                "retries": self.retries}
