"""Cycle workload — the canonical lost-write detector.

Reference: REF:fdbserver/workloads/Cycle.actor.cpp — keys form a ring
(key i stores the index of its successor); transactions rotate three
adjacent nodes; the check phase walks the ring and asserts it is still a
single cycle visiting every node exactly once.  Any lost, phantom, or
non-serializable write breaks the permutation.
"""

from __future__ import annotations

import asyncio

from .workload import TestWorkload, register_workload


def _key(prefix: bytes, i: int) -> bytes:
    return prefix + b"%08d" % i


@register_workload
class CycleWorkload(TestWorkload):
    name = "Cycle"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.n = int(self.opt("nodeCount", 16))
        self.txns = int(self.opt("transactionsPerClient", 20))
        self.prefix = bytes(self.opt("prefix", b"cycle/"))
        self.ops_done = 0
        self.retries = 0

    async def setup(self) -> None:
        async def fill(tr):
            for i in range(self.n):
                tr.set(_key(self.prefix, i), b"%08d" % ((i + 1) % self.n))
        await self.db.run(fill)

    async def start(self) -> None:
        for _ in range(self.txns):
            a = self.rng.random_int(0, self.n)
            tr = self.db.create_transaction()
            while True:
                try:
                    ka = _key(self.prefix, a)
                    b = int(await tr.get(ka))
                    kb = _key(self.prefix, b)
                    c = int(await tr.get(kb))
                    kc = _key(self.prefix, c)
                    d = int(await tr.get(kc))
                    # rotate b out: a→c, c→b, b→d  (still one cycle)
                    tr.set(ka, b"%08d" % c)
                    tr.set(kc, b"%08d" % b)
                    tr.set(kb, b"%08d" % d)
                    await tr.commit()
                    break
                except BaseException as e:
                    await tr.on_error(e)   # re-raises if not retryable
                    self.retries += 1
            self.ops_done += 1

    async def check(self) -> bool:
        rows = await self.db.get_range(self.prefix, self.prefix + b"\xff")
        if len(rows) != self.n:
            return False
        succ = {int(k[len(self.prefix):]): int(v) for k, v in rows}
        seen = set()
        cur = 0
        for _ in range(self.n):
            if cur in seen:
                return False
            seen.add(cur)
            cur = succ[cur]
        return cur == 0 and len(seen) == self.n

    def metrics(self):
        return {"transactions": self.ops_done, "retries": self.retries}
