"""Simulation workloads — invariant-checking test drivers.

Reference: REF:fdbserver/workloads/ (~100 TestWorkload classes driven by
.toml specs, REF:fdbserver/tester.actor.cpp).  Each workload has
setup/start/check phases; fault-injection workloads run concurrently with
functional ones, and check() asserts a database invariant that would be
violated by lost/phantom/reordered writes.
"""

from .workload import (TestWorkload, WorkloadContext, register_workload,
                       make_workload, run_workloads, run_workloads_on)
from . import (api_fuzz, attrition, change_feed,  # noqa: F401  (register)
               conflict_range, consistency, correctness, cycle, disk_fault,
               dynamic, increment, layers, ops, ops2, random_rw,
               serializability)
